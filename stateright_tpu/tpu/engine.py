"""``TpuBfsChecker``: breadth-first model checking as device frontier waves.

The TPU-native inversion of the reference's `src/checker/bfs.rs`: instead
of worker threads pulling one state at a time through virtual dispatch
(`bfs.rs:75-152`), each *wave* advances the whole frontier as one jitted
XLA program:

1. vmapped property predicates over the frontier batch (`bfs.rs:192-226`),
2. vmapped successor generation (``DeviceModel.step``) with a static
   max-fanout and validity mask (`bfs.rs:231-244`),
3. device fingerprinting of every successor (`lib.rs:307-311`),
4. dedup: intra-wave first-occurrence via a sort over the (small) wave
   array, cross-wave membership + insertion via an HBM-resident
   open-addressing ``uint64`` hash table (the analog of the amortized-O(1)
   ``DashMap`` visited set, `bfs.rs:26,245-259`): a ``lax.while_loop`` of
   gather / claim-scatter / re-gather rounds resolves every candidate in
   O(probe-chain) steps, so per-wave cost is independent of table
   occupancy (no re-sorting of the resident set),
5. frontier compaction via a stable argsort so surviving successors keep
   host-BFS enqueue order (this preserves the reference's level order and
   therefore its exact discovery traces).

The host keeps the parent-pointer map (fingerprint -> parent fingerprint,
`bfs.rs:26`) fed by a per-wave stream of new states, so discovery paths are
reconstructed by model replay exactly as the reference does
(`bfs.rs:314-342`) — using the *device* fingerprint function.

Eventually-property bits ride along as a per-row ``uint32`` bitmask
(`EventuallyBits`, `checker.rs:340-347`), cleared on device-evaluated
satisfaction and converted to counterexamples at terminal states
(`bfs.rs:265-272`), preserving the reference's documented revisit caveats
(`bfs.rs:239-259`).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import warnings
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..checker.base import Checker
from ..checker.path import Path
from ..checker.visitor import as_visitor
from ..model import Expectation, Model
from ..obs import (prof_from_env, recorder_from_env, tracer_from_env,
                   wave_obs_from_env)
from ..resilience.faults import fault_plan_from_env, is_oom
from ..store.tiered import FrontierRef, store_from_config
from .device_model import DeviceModel
from .hashing import SENTINEL, device_fp64, host_fp64
from .matmul_wave import matmul_expand

__all__ = ["TpuBfsChecker", "build_wave", "build_mux_wave",
           "build_regather", "batch_bucket_ladder", "pick_bucket",
           "succ_bucket_ladder", "wave_kernel_impl"]


def batch_bucket_ladder(base: int, max_batch: Optional[int]) -> tuple:
    """The adaptive scheduler's dispatch widths: ``base`` followed by
    doublings up to ``max_batch`` (inclusive, rounded up to the next
    power of two). With ``max_batch`` unset the ladder is the single
    rung ``(base,)`` — the fixed-width behavior, zero extra compiles.

    Wave results are independent of the dispatch width (the
    first-occurrence dedup rule preserves global queue order whatever
    the wave composition — see the cross-B parity suite), so the ladder
    is purely a performance schedule: each rung costs one compile of
    the wave/dispatch program, amortized across every dispatch at that
    width.
    """
    base = max(1, int(base))
    if not max_batch or int(max_batch) <= base:
        return (base,)
    top = 1 << max(0, int(max_batch) - 1).bit_length()
    ladder = [base]
    while ladder[-1] * 2 <= top:
        ladder.append(ladder[-1] * 2)
    if ladder[-1] < int(max_batch):
        # Non-power-of-two base: doublings alone stop short of the
        # requested width; cap the ladder with it so the bulk phase
        # dispatches as wide as configured.
        ladder.append(top)
    return tuple(ladder)


def pick_bucket(ladder: tuple, width: int) -> int:
    """Smallest ladder rung that covers ``width`` frontier rows (the
    widest rung when none does — the frontier then drains over several
    full-width waves)."""
    for b in ladder:
        if width <= b:
            return b
    return ladder[-1]


def succ_bucket_ladder(full: int, base: int = 256) -> tuple:
    """The successor-side output ladder: how many compacted novel rows a
    wave program emits. Rungs are ``base`` times powers of FOUR, capped
    by ``full`` (= the wave's B*F successor space, always the last rung
    so a worst-case wave fits). The x4 spacing bounds the extra compiles
    at O(log4 full) per batch bucket while still letting the common
    small-novel-set wave skip most of the full-width compaction gather
    and output traffic (GPUexplore's successor-collapse observation:
    most of a wave's candidate stream is duplicate or already visited).
    """
    full = max(1, int(full))
    if full <= base:
        return (full,)
    rungs = []
    k = base
    while k < full:
        rungs.append(k)
        k *= 4
    rungs.append(full)
    return tuple(rungs)


class TpuBfsChecker(Checker):
    """Runs BFS waves on the default JAX device (TPU when present)."""

    #: wave-event ``engine`` id (obs schema); one per engine class.
    _ENGINE_ID = "classic"

    #: whether this engine can bound its wave outputs with the successor
    #: ladder (per-wave engines: outputs cross to the host, so K-bounded
    #: gathers and transfers pay off; the fused engines append on device
    #: with a full window — narrowing it breaks the donated arena's
    #: in-place aliasing, see fused.py — and opt out).
    _SUCC_LADDER_CAPABLE = True

    #: whether this engine's single-kernel wave is the table-less
    #: SENDER megakernel (the sharded engines: the visited table is
    #: partitioned across the mesh, so the probe stays owner-side and
    #: the kernel-path gate drops the table term).
    _SENDER_KERNEL = False

    #: whether jobs targeting this engine shape can be admitted into a
    #: shared multiplexed wave group (service/mux.py). Requires the
    #: per-wave host boundary: the mux splits every wave's outputs per
    #: tenant on the host before they reach counts/queues/discoveries.
    #: The fused engines keep frontiers and stats device-resident
    #: across multi-wave dispatches — there is no per-wave boundary to
    #: split at — and opt out (they still share compiled programs via
    #: the jit cache, just not dispatches).
    _MUX_CAPABLE = True

    #: whether the tiered store may evict visited partitions out of
    #: this engine's device table (stateright_tpu.store). Requires the
    #: per-wave host boundary — each wave's novel block is filtered
    #: against the spilled partitions BEFORE it reaches counts/queues.
    #: The fused engines dedup entirely on device across multi-wave
    #: dispatches (a host filter would come too late: re-admitted rows
    #: would already be re-expanded) and opt out; their device relief
    #: valve is the arena-span spill instead (see fused.py).
    _VISITED_SPILL_CAPABLE = True

    def __init__(self, builder, batch_size: int = 1024,
                 device_model: Optional[DeviceModel] = None,
                 table_capacity: int = 1 << 16,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every_waves: int = 64,
                 resume_from: Optional[str] = None,
                 pipeline: Optional[bool] = None,
                 table_impl: str = "xla",
                 max_batch_size: Optional[int] = None,
                 succ_ladder: Optional[bool] = None,
                 pack_arena: Optional[bool] = None,
                 tier_device_bytes: Optional[int] = None,
                 tier_host_bytes: Optional[int] = None,
                 tier_dir: Optional[str] = None,
                 tier_partitions: Optional[int] = None,
                 program_cache=None,
                 program_key: Optional[tuple] = None,
                 trace_path: Optional[str] = None,
                 wave_kernel: Optional[bool] = None,
                 wave_matmul: Optional[bool] = None,
                 async_io: Optional[bool] = None):
        model = builder._model
        # Cross-instance compiled-program sharing (jit_cache.
        # WaveProgramCache): armed only when BOTH a cache and a model
        # key are supplied — the key certifies that two engines' device
        # models are semantically identical (the job service derives it
        # from the corpus registry name + canonical params), which is
        # the safety condition for sharing a traced program. Ad-hoc
        # models never share.
        self._prog_cache = program_cache if program_key is not None \
            else None
        self._prog_key = tuple(program_key) if program_key is not None \
            else None
        self._prog_hits = 0
        self._prog_misses = 0
        # Per-run trace destination override: the job service gives
        # every job its own JSONL file (GET /jobs/<id>/trace streams
        # it); None follows the process-global STpu_TRACE env.
        self._trace_path = trace_path
        # Cooperative preemption (the job service's DELETE /jobs/<id>):
        # the wave loop checks the event at its dispatch boundary,
        # drains any in-flight wave, and stops — a safe point, so the
        # end-of-run checkpoint is a valid resume image.
        self._preempt_evt = threading.Event()
        self.preempted = False
        # Software-pipeline one wave deep on accelerators (hides the
        # host-side processing behind device compute); on the CPU backend
        # host and "device" share cores, so overlap only adds overhead.
        self._pipeline = (jax.default_backend() != "cpu"
                          if pipeline is None else bool(pipeline))
        if device_model is None:
            factory = getattr(model, "device_model", None)
            if factory is None:
                raise TypeError(
                    f"{type(model).__name__} does not define device_model(); "
                    "the TPU engine needs a DeviceModel (fixed-width state "
                    "encoding + jittable step). Use spawn_bfs()/spawn_dfs() "
                    "for host-only models.")
            device_model = factory()
        self._model = model
        self._dm = device_model
        self._properties = model.properties()
        self._use_symmetry = builder._symmetry is not None
        if self._use_symmetry:
            zero = jnp.zeros((device_model.state_width,), jnp.uint32)
            if device_model.representative(zero) is None:
                raise NotImplementedError(
                    "symmetry() on the TPU engine requires "
                    "DeviceModel.representative()")
        self._target_state_count = builder._target_state_count
        self._visitor = (as_visitor(builder._visitor)
                         if builder._visitor else None)
        self._B = batch_size
        self._buckets = batch_bucket_ladder(batch_size, max_batch_size)
        self._B_max = self._buckets[-1]
        self._F = device_model.max_fanout
        self._W = device_model.state_width
        # Packed storage row format (tpu/packing.py): states are
        # COMPUTED as uint32[W] registers but STORED (frontier blocks,
        # arena, shard exchange, checkpoints) as uint32[Wrow] packed
        # rows when the model declares narrow lanes. Like the pipeline
        # knob, the default is backend-aware: on accelerators the rows
        # live in HBM and the codec buys back 2-4x the bytes per state;
        # on the XLA:CPU fallback the working set is cache-resident and
        # the codec is pure compute overhead (measured ~15% on the
        # classic paxos headline — MEASUREMENTS round 9), so auto means
        # off there. pack_arena=True/False forces either way (a
        # performance schedule, never semantics: the wave unpacks to
        # the exact same registers either way).
        from .packing import compile_layout

        # getattr: bring-your-own device models duck-type the contract
        # and may predate the lane_bits hook — no declaration means the
        # conservative 32-bits-per-lane identity layout.
        lane_bits = getattr(device_model, "lane_bits", lambda: None)()
        self._layout = compile_layout(lane_bits, self._W)
        if pack_arena is None:
            pack_arena = jax.default_backend() != "cpu"
        self._pack_on = bool(pack_arena) and self._layout.packs
        self._Wrow = self._layout.packed_width if self._pack_on else self._W
        if table_impl not in ("xla", "pallas"):
            raise ValueError(f"table_impl must be 'xla' or 'pallas', "
                             f"got {table_impl!r}")
        self._table_impl = table_impl
        # Single-kernel wave (ISSUE 10): run the whole successor path —
        # unpack, expand, fingerprint, local dedup, global probe/claim,
        # re-pack — as one Pallas megakernel per wave instead of the
        # XLA op ladder. Unset follows the STpu_WAVE_KERNEL env knob;
        # the VMEM budget gate is re-checked per wave-program build, so
        # mid-run growth degrades to the XLA path (once-warned) instead
        # of killing the run. Bit-identical either way (the kernel
        # traces the same stage functions; tests/test_wave_kernel.py).
        if wave_kernel is None:
            wave_kernel = os.environ.get(
                "STpu_WAVE_KERNEL", "") not in ("", "0")
        self._wave_kernel_on = bool(wave_kernel)
        if self._wave_kernel_on:
            from .pallas_table import PALLAS_AVAILABLE

            if not PALLAS_AVAILABLE:
                warnings.warn(
                    "wave_kernel requested but pallas is unavailable "
                    "in this jax build; using the XLA wave path",
                    RuntimeWarning)
                self._wave_kernel_on = False
        # MXU-shaped successor generation (ISSUE 15): compile a
        # *regular* model's expand stage to one-hot x transition-table
        # matmuls (tpu/matmul_wave.py) and swap it in wherever the wave
        # programs call expand_frontier — including inside the
        # megakernel. Unset follows the STpu_WAVE_MATMUL env knob. The
        # capability gate keeps irregular models (undeclared lane_bits,
        # sentinel lanes, oversized key domains) on the vmapped step
        # path and reports why through scheduler_stats()["wave_matmul"].
        # Bit-identical either way (tests/test_matmul_wave.py).
        if wave_matmul is None:
            wave_matmul = os.environ.get(
                "STpu_WAVE_MATMUL", "") not in ("", "0")
        self._wave_matmul_on = bool(wave_matmul)
        self._matmul_plan = None
        self._matmul_reason = None
        if self._wave_matmul_on:
            from .matmul_wave import classify as matmul_classify

            cls = matmul_classify(device_model)
            self._matmul_plan = cls.plan
            self._matmul_reason = cls.reason
            if not cls.regular:
                key = type(device_model).__name__
                if key not in _WAVE_MATMUL_GATE_WARNED:
                    _WAVE_MATMUL_GATE_WARNED.add(key)
                    warnings.warn(
                        f"wave_matmul requested but {key} is not "
                        f"matmul-regular ({cls.reason}); using the "
                        "vmapped step path", RuntimeWarning)
        # Successor-side output ladder (classic per-wave engines only:
        # the fused engines keep full-window arena appends — see
        # _SUCC_LADDER_CAPABLE). Results are K-independent (overflowed
        # waves regather losslessly), so this is purely a performance
        # schedule, like the input bucket ladder.
        self._succ_ladder_on = (self._SUCC_LADDER_CAPABLE
                                and (True if succ_ladder is None
                                     else bool(succ_ladder)))
        #: recent (batch bucket, novel rows) pairs — the history the
        #: scheduler sizes the next wave's output rung from.
        self._succ_hist: deque = deque(maxlen=8)
        if len(self._properties) > 32:
            raise NotImplementedError("at most 32 properties on device")

        # Which properties evaluate on device vs. host-side fallback.
        device_props = device_model.device_properties()
        self._prop_fns = [device_props.get(p.name)
                          for p in self._properties]
        # Subclass support veto (e.g. the fused engine cannot host-eval)
        # runs BEFORE the warning and the heavy table/checkpoint work, so
        # an engine fallback neither warns twice nor initializes twice.
        self._check_support()
        for p, fn in zip(self._properties, self._prop_fns):
            if fn is None:
                warnings.warn(
                    f"property {p.name!r} has no device predicate; "
                    "falling back to host evaluation per wave (slow)",
                    stacklevel=2)

        self._ckpt_path = checkpoint_path
        self._ckpt_every = max(1, int(checkpoint_every_waves))
        self._discoveries: Dict[str, int] = {}
        self._ebits_all = 0
        self._eventually_idx: List[int] = []
        for i, p in enumerate(self._properties):
            if p.expectation is Expectation.EVENTUALLY:
                self._ebits_all |= 1 << i
                self._eventually_idx.append(i)
        self._pending: deque = deque()
        self._parents: Dict[int, Optional[int]] = {}
        self._parents_consumed = 0

        # Tiered state store (stateright_tpu.store): armed by explicit
        # kwargs or the STpu_TIER_* env knobs, the shared NULL_STORE
        # otherwise (one attribute check per wave — the tracer/faults
        # contract). Created BEFORE any checkpoint load: a v5 resume
        # re-attaches cold segments through it.
        self._store = store_from_config(
            device_bytes=tier_device_bytes, host_bytes=tier_host_bytes,
            segment_dir=tier_dir, n_partitions=tier_partitions,
            owner=self, meta={"model_name": type(model).__name__,
                              "state_width": self._W,
                              "use_symmetry": self._use_symmetry})

        # Asynchronous host I/O (round 17): ONE bounded background
        # writer per engine — checkpoint generations and the store's
        # cold-segment spills share it, so the safe-point join rule
        # (`_write_checkpoint` joins before capturing the next
        # snapshot) covers every off-thread write at once. Unset
        # follows the STpu_ASYNC_IO env knob (wave_kernel precedent);
        # knob-off is the inline SyncWriter and every path behaves
        # exactly as before.
        from ..io.async_io import writer_from_config

        self._aio = writer_from_config(
            async_io, name=f"stpu-aio-{self._ENGINE_ID}")
        self._store.attach_async(self._aio)
        #: seconds the wave loop spent blocked on host I/O since the
        #: last wave event (joins + inline write time) — drained into
        #: the v10 ``io_stall_s`` wave gauge by ``_take_io_stall``.
        self._io_stall_s = 0.0
        self._ckpt_gen = 0

        if resume_from is not None:
            visited_fps = self._load_checkpoint(resume_from)
        else:
            # Seed from init states (bfs.rs:43-66).
            init_states = [s for s in model.init_states()
                           if model.within_boundary(s)]
            self._state_count = len(init_states)
            init_rep_fps = set()
            init_vecs: List[np.ndarray] = []
            init_fps: List[int] = []
            for s in init_states:
                vec = np.asarray(device_model.encode(s), np.uint32)
                fp = host_fp64(vec)
                if self._use_symmetry:
                    rep = np.asarray(
                        device_model.representative(jnp.asarray(vec)),
                        np.uint32)
                    rep_fp = host_fp64(rep)
                else:
                    rep_fp = fp
                if rep_fp in init_rep_fps:
                    continue
                init_rep_fps.add(rep_fp)
                init_vecs.append(vec)
                init_fps.append(fp)
            # Pending is a queue of BLOCKS (vecs, fps, ebits arrays); the
            # parent log mirrors it per wave and materializes into a dict
            # only when a path is reconstructed.
            fps_arr = np.array(init_fps, np.uint64)
            if init_vecs:
                seed = np.stack(init_vecs).astype(np.uint32)
                if self._pack_on:
                    # Cold-path contract check: a wrong lane_bits()
                    # declaration dies here, not as silent truncation.
                    self._layout.check_fits(seed)
                self._pending.append((
                    self._pack_np(seed), fps_arr,
                    np.full(len(init_fps), self._ebits_all, np.uint32)))
            self._unique_count = len(init_fps)
            self._parent_log: List = [(fps_arr, None)]
            visited_fps = np.fromiter(
                init_rep_fps, np.uint64, len(init_rep_fps))

        # Device-resident visited table: open-addressing uint64 hash
        # table, padded with SENTINEL. Capacity rounds UP so a caller
        # pre-sizing for a known run (bench.py) never recompiles mid-run.
        self._capacity = 1 << max(12, (int(table_capacity) - 1).bit_length())
        visited_fps = self._spill_seed(visited_fps)
        while self._capacity < (4 * len(visited_fps)
                                + 2 * self._B_max * self._F):
            self._capacity *= 2
        self._visited = self._new_table(visited_fps)
        self._wave_cache: dict = {}

        self._lock = threading.Lock()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        #: (monotonic time, cumulative state_count) samples: one at run
        #: start, then one per wave. Waves after a table growth recompile,
        #: so steady-state throughput is best measured with a pre-sized
        #: table over entries [2:] (see bench.py).
        self.wave_log: list = []
        #: one dict per processed dispatch: ``{"t", "states", "bucket",
        #: "compiled", "waves", "inflight"}``. ``compiled`` marks an
        #: entry whose wall-clock interval contained a first-use XLA
        #: compile — under pipelined dispatch a new bucket's compile
        #: runs on the host BETWEEN stats reads, so the flag is
        #: interval-attributed (``_note_compile``/``_take_compile``),
        #: not launch-attributed; bench.py excludes flagged intervals
        #: from the steady rate. See ``scheduler_stats``.
        self.dispatch_log: list = []
        self._compile_dirty = False
        #: wall seconds spent in ahead-of-time XLA compiles (``_aot``) —
        #: the scheduler's bucket-ladder compile budget, reported by
        #: ``scheduler_stats`` so bench runs can attribute it.
        self.compile_sec = 0.0
        #: (end time, duration) per AOT compile; compiles run on the
        #: host thread between stats reads, so each lies inside exactly
        #: one dispatch_log interval — bench.py subtracts them from that
        #: interval's wall when computing the steady rate.
        self.compile_log: list = []
        #: run tracer (obs subsystem): a live JSONL writer when
        #: ``STpu_TRACE`` is set, the shared null tracer otherwise. Hot
        #: paths guard every emit with ``.enabled`` so the disabled
        #: subsystem costs one attribute check per dispatch.
        self._tracer = tracer_from_env(self._ENGINE_ID, path=self._trace_path, meta={
            "model": type(model).__name__,
            "batch_size": self._B,
            "bucket_ladder": list(self._buckets),
            "table_capacity": self._capacity,
            "table_impl": self._table_impl,
            "max_fanout": self._F,
            "state_width": self._W})
        if self._tracer.enabled and self._matmul_plan is not None:
            # Static per-frontier-row MAC count of the compiled plan
            # (obs schema v12) — one gauge at run start; the per-wave
            # attribution rides as the wave events' expand_impl.
            self._tracer.event("gauge", name="matmul_ops",
                               value=float(self._matmul_plan.matmul_ops))
        #: fault-injection plan (resilience subsystem): the live
        #: ``STpu_FAULTS`` plan, or the shared disarmed NULL_PLAN —
        #: every hook is guarded by ``.active``, so the unarmed
        #: subsystem costs one attribute check per dispatch (same
        #: contract as the tracer; MEASUREMENTS round-10).
        self._faults = fault_plan_from_env()
        #: always-on flight recorder (obs subsystem): the ring holds a
        #: reference to each dispatch_log entry — which this engine
        #: builds regardless of tracing — so recording is one guarded
        #: append, and a failed run dumps the last events to a
        #: postmortem file the Supervisor attaches to its retry/abort
        #: events. ``STpu_FLIGHT=0`` disarms it to the shared null.
        self._flight = recorder_from_env(
            f"{self._ENGINE_ID}-{os.getpid()}")
        #: the newest postmortem dump path (a failed run sets it).
        self.flight_dump: Optional[str] = None
        #: service observability facade (obs/hist.py): latency
        #: histograms + SLO burn windows + the slow-wave anomaly
        #: detector, fed with the dispatch_log entry the wave loop
        #: already builds. Disarmed (no ``STpu_HIST``/``STpu_SLO``/
        #: ``STpu_ANOMALY``) it is the shared NULL_OBS — one attribute
        #: check per dispatch, same contract as the tracer.
        self._wave_obs = wave_obs_from_env(self._ENGINE_ID)
        if self._wave_obs.enabled and self._flight.armed:
            # Postmortems carry the latency distribution at death.
            self._flight.set_hist_source(
                self._wave_obs.final_snapshot_event)
        #: continuous wave profiler (obs/prof.py): static XLA cost
        #: capture at compile + sampled roofline timing at dispatch.
        #: Disarmed (``STpu_PROF`` unset) it is the shared NULL_PROF —
        #: one attribute check per dispatch, same contract as the
        #: tracer.
        self._prof = prof_from_env(self._ENGINE_ID)
        self._pre_spawn_check()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- Packed row helpers (tpu/packing.py) ------------------------------

    def _pack_np(self, rows: np.ndarray) -> np.ndarray:
        """Host-side pack to the storage row format (identity with
        packing off)."""
        return self._layout.pack_np(rows) if self._pack_on else rows

    def _unpack_np(self, rows: np.ndarray) -> np.ndarray:
        """Host-side unpack from the storage row format (identity with
        packing off)."""
        return self._layout.unpack_np(rows) if self._pack_on else rows

    def _wave_layout(self):
        """The layout the wave programs pack/unpack with (None = rows
        are stored unpacked and the programs skip the codec)."""
        return self._layout if self._pack_on else None

    def _check_support(self) -> None:
        """Subclass hook: veto unsupported configurations cheaply, before
        any heavy initialization (table build, checkpoint load)."""

    def _pre_spawn_check(self) -> None:
        """Subclass hook: validate configuration before the worker starts."""

    # -- Checkpoint / resume ----------------------------------------------
    #
    # The reference has no checkpointing (a killed run restarts from
    # scratch); here the (visited fingerprints, pending frontier blocks,
    # discoveries, parent map) tuple IS the whole checker state — states
    # are reconstructible by replay, so checkpoints are small and
    # engine-agnostic: a snapshot from the single-device engine can
    # resume onto the sharded engine and vice versa (each rebuilds its
    # own table layout and ownership split from the same data).

    def _pending_blocks(self) -> list:
        """The not-yet-expanded frontier as (vecs, fps, ebits) blocks
        (subclasses with their own queue layout override this).
        Paged-out blocks are materialized non-destructively — the
        snapshot needs the rows, the queue keeps the ref."""
        return [self._store.load_ref(b) if isinstance(b, FrontierRef)
                else b for b in self._pending]

    def _snapshot(self) -> dict:
        """Collects checkpoint arrays. Only call at a safe point: between
        waves inside the worker, or after the worker has stopped."""
        from ..checkpoint_format import make_header

        parents = self._parent_map()
        n = len(parents)
        child = np.fromiter(parents.keys(), np.uint64, n)
        parent = np.fromiter((0 if v is None else v
                              for v in parents.values()), np.uint64, n)
        rooted = np.fromiter((v is None for v in parents.values()), bool, n)
        blocks = self._pending_blocks()
        if blocks:
            vecs = np.concatenate([b[0] for b in blocks])
            fps = np.concatenate([b[1] for b in blocks])
            ebits = np.concatenate([b[2] for b in blocks])
        else:
            vecs = np.zeros((0, self._Wrow), np.uint32)
            fps = np.zeros(0, np.uint64)
            ebits = np.zeros(0, np.uint32)
        visited = np.asarray(self._visited).reshape(-1)
        visited = visited[visited != SENTINEL]
        # Tiered store (checkpoint format v5): the snapshot's visited
        # section carries hot + warm; COLD segments travel by content
        # hash — a checkpoint of a spilled run moves only hot+warm
        # bytes, the segments already on disk are not rewritten.
        store_refs = None
        if self._store.active:
            warm = self._store.warm_fps()
            if len(warm):
                visited = np.concatenate([visited, warm])
            store_refs = self._store.checkpoint_refs()
        # Canonical order (round 16): the table scan above reflects
        # probe-slot placement, which depends on capacity growth
        # history — sorting makes the section a pure function of the
        # visited SET. Resume reinserts via host_table_insert, so the
        # on-disk order was never semantic; canonicalizing it is what
        # lets a multiplexed tenant's checkpoint match its solo twin
        # byte for byte.
        visited = np.sort(visited)
        # Pending rows persist in the storage row format; the header
        # self-describes the layout so ANY engine (packed or not, device
        # or native) can unpack on resume (checkpoint_format v2).
        header = make_header(
            model_name=type(self._model).__name__, state_width=self._W,
            state_count=self._state_count,
            unique_count=self._unique_count,
            use_symmetry=self._use_symmetry,
            discoveries=self._discoveries,
            row_format="packed" if self._pack_on else "u32",
            lane_bits=self._layout.specs if self._pack_on else None,
            packed_width=self._Wrow if self._pack_on else None,
            store=store_refs)
        return dict(header=header,
                    visited=visited, pending_vecs=vecs, pending_fps=fps,
                    pending_ebits=ebits, parent_child=child,
                    parent_parent=parent, parent_rooted=rooted)

    def _write_checkpoint(self, path: str) -> None:
        """Writes one checkpoint generation at a safe point. Async
        (round 17): join any still-pending write FIRST — a failure
        injected on the writer thread (``torn_ckpt``, ``ckpt_crc``,
        ``disk_full``) re-raises here, on the wave-loop thread, where
        the Supervisor/flight machinery expects it — then capture the
        snapshot arrays synchronously (content stays bit-identical to
        a sync write) and hand only the CRC/compress/rotate/rename to
        the writer. One FIFO thread + join-before-next-submit keeps
        generation ordering and keep-last-2 rotation exactly as the
        sync path. Sync (knob off): ``submit`` runs inline and this is
        byte-for-byte the pre-round-17 write."""
        from ..checkpoint_format import write_atomic

        t0 = time.monotonic()
        self._aio.join()
        payload = self._snapshot()
        self._ckpt_gen += 1
        gen = self._ckpt_gen
        tracer = self._tracer
        if tracer.enabled:
            tracer.event("ckpt_begin", gen=gen, path=path,
                         **{"async": bool(self._aio.enabled)})

        def _land() -> None:
            w0 = time.monotonic()
            write_atomic(path, payload)
            if tracer.enabled:
                tracer.event("ckpt_done", gen=gen, path=path,
                             write_s=round(time.monotonic() - w0, 6))

        self._aio.submit(_land, kind="checkpoint")
        self._io_stall_s += time.monotonic() - t0

    def _take_io_stall(self):
        """Drains the accumulated wave-loop I/O stall into one wave
        event (v10 ``io_stall_s``)."""
        s, self._io_stall_s = self._io_stall_s, 0.0
        return round(s, 6)

    def checkpoint(self, path: str) -> None:
        """Writes a resumable snapshot. Valid once the run has stopped
        (done, all-discovered, or target_state_count reached); while
        running, use the ``checkpoint_path`` knob for periodic safe-point
        snapshots instead."""
        if not self._done.is_set():
            raise RuntimeError(
                "checkpoint() while the checker is running would race the "
                "wave loop; pass checkpoint_path=... to spawn_tpu_bfs for "
                "periodic snapshots, or join() first")
        if self._error is not None:
            # A wave died after taking a batch but before streaming its
            # successors back: those states are in the visited table but
            # not in pending, so a snapshot now would permanently lose
            # their subtrees on resume. restart_from() clears this flag
            # on a successful in-place resume.
            raise RuntimeError(
                "checkpoint() after a failed run would snapshot a torn "
                "frontier; resume from the last periodic checkpoint "
                "(restart_from) instead") from self._error
        self._write_checkpoint(path)
        # Durability contract: the file exists (or the failure raised
        # here) when this returns, knob on or off.
        self._aio.join()

    def restart_from(self, path: str) -> "TpuBfsChecker":
        """In-place crash recovery: discards the failed run's (torn)
        in-memory state, reloads the snapshot at ``path``, CLEARS the
        failed-run flag, and restarts the worker — on this same
        instance, so the compiled wave-program cache survives and a
        recovery costs zero recompiles. This is the supervisor's
        preferred retry path (``resilience.supervisor``). Only valid
        once the worker has stopped; a successful restarted run makes
        ``checkpoint()`` usable again."""
        if not self._done.is_set():
            raise RuntimeError(
                "restart_from() while the checker is running; join() "
                "(or wait for the failure) first")
        self._thread.join()
        # The failed-run flag: cleared here, re-set only if the
        # restarted run fails again. The background writer drains and
        # drops any still-captured failure the same way — the resume
        # supersedes whatever generation died mid-flight.
        self._aio.reset()
        self._io_stall_s = 0.0
        self._error = None
        self._discoveries = {}
        self._pending = deque()
        self._parents = {}
        self._parent_log = []
        self._parents_consumed = 0
        self._succ_hist.clear()
        self.wave_log = []
        self.dispatch_log = []
        self._compile_dirty = False
        self._reset_engine_state()
        if self._store.active:
            # Warm/cold tiers rebuild from the checkpoint's v5 refs
            # (attached by _load_checkpoint), not the failed run's.
            self._store.reset()
        visited_fps = self._load_checkpoint(path)
        visited_fps = self._spill_seed(visited_fps)
        while self._capacity < (4 * len(visited_fps)
                                + 2 * self._B_max * self._F):
            self._capacity *= 2
        self._visited = self._new_table(visited_fps)
        self._tracer = tracer_from_env(
            self._ENGINE_ID, path=self._trace_path, meta={
                "model": type(self._model).__name__,
                "restarted_from": path})
        # The preempt EVENT survives a restart on purpose: a preempt
        # that raced a crash (requested while the failed run was down)
        # still targets the JOB, so the recovered run must honor it at
        # its first wave boundary — drain, checkpoint, stop — instead
        # of silently running to completion. Only the outcome flag
        # resets.
        self.preempted = False
        self._done = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _reset_engine_state(self) -> None:
        """Subclass hook: drop engine-specific run state (device
        arenas, per-shard queues) before a restart_from reload."""

    def _load_checkpoint(self, path: str) -> np.ndarray:
        """Restores pending/counts/discoveries/parents; returns the
        visited fingerprints for table seeding."""
        from ..checkpoint_format import (load_checkpoint, pending_rows,
                                         validate_header)

        with load_checkpoint(path) as data:
            header = validate_header(
                data, model_name=type(self._model).__name__,
                state_width=self._W, use_symmetry=self._use_symmetry)
            self._state_count = int(header["state_count"])
            self._unique_count = int(header["unique_count"])
            self._discoveries = {k: int(v) for k, v
                                 in header["discoveries"].items()}
            # pending_rows unpacks whatever row format the WRITER used
            # (self-described in the header); re-pack to THIS engine's
            # storage format — cross-format resume is how v1 unpacked
            # snapshots land on packed engines and vice versa. The
            # cold-path contract check runs first: a snapshot from an
            # engine without this model's lane_bits() bounds must fail
            # loudly here, not resume from silently truncated rows.
            vecs = pending_rows(data, header, self._W)
            if self._pack_on:
                self._layout.check_fits(vecs)
            vecs = self._pack_np(vecs)
            fps = data["pending_fps"]
            ebits = data["pending_ebits"]
            if len(fps):
                self._pending.append((vecs, fps, ebits))
            child = data["parent_child"]
            parent = data["parent_parent"]
            rooted = data["parent_rooted"]
            self._parents = {
                int(c): (None if r else int(p))
                for c, p, r in zip(child.tolist(), parent.tolist(),
                                   rooted.tolist())}
            self._parent_log = []
            visited = data["visited"]
            refs = header.get("store")
            if refs:
                if self._store.active and self._VISITED_SPILL_CAPABLE:
                    # v5 resume: re-attach the referenced cold segments
                    # (CRC + content-hash verified, with the rotation-
                    # predecessor fallback) — only hot+warm rows enter
                    # the device table.
                    self._store.attach_refs(
                        refs, base_dir=os.path.dirname(
                            os.path.abspath(path)))
                else:
                    # No store on this side (or an engine that cannot
                    # host-filter): materialize the cold rows into the
                    # device tier — slower, never wrong.
                    from ..store.tiered import load_cold_refs

                    cold = load_cold_refs(refs, base_dir=os.path.dirname(
                        os.path.abspath(path)))
                    if len(cold):
                        visited = np.concatenate(
                            [np.asarray(visited, np.uint64), cold])
            return visited

    # -- Device wave program ---------------------------------------------

    def _new_table(self, fps) -> jax.Array:
        table = np.full(self._capacity, SENTINEL, np.uint64)
        host_table_insert(table, np.fromiter(
            (int(f) for f in fps), np.uint64, len(fps)))
        # Device-tier occupancy (== unique_count unless the tiered
        # store has evicted partitions): what growth/load-factor gate
        # on.
        self._resident = len(fps)
        return jax.device_put(jnp.asarray(table))

    def _cached_program(self, key: tuple, build):
        """Two-level compiled-program lookup: the per-instance
        ``_wave_cache`` first, then — when the engine carries a
        registry-certified ``program_key`` — the process-wide shared
        cache (``jit_cache.WaveProgramCache``), so the Nth same-model
        job reuses the first job's executables instead of recompiling.
        ``build()`` must return a ready (AOT-compiled where supported)
        callable; the shared cache serializes concurrent builders per
        key. Hits cost no compile, so neither ``compile_sec`` nor the
        dispatch-interval ``compiled`` flags move — the cold/warm
        difference is exactly what job latency A/Bs measure."""
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached
        if self._prog_cache is not None:
            # wave_kernel rides in the shared key: a megakernel program
            # and an XLA-ladder program are different executables even
            # at identical shapes (the service's cross-job sharing must
            # never hand one job the other's path).
            shared_key = (self._prog_key, self._ENGINE_ID,
                          self._table_impl, self._pack_on,
                          self._use_symmetry, self._wave_kernel_on,
                          self._matmul_plan is not None) + key
            prog, hit = self._prog_cache.get_or_build(shared_key, build)
            if hit:
                self._prog_hits += 1
            else:
                self._prog_misses += 1
        else:
            prog = build()
        if self._prof.enabled:
            # Static cost capture (obs/prof.py): reads the compiled
            # executable's cost/memory analysis at most once per
            # program per process — a shared-cache hit finds the first
            # builder's record through the same key, so hits pay a
            # dict lookup, never a re-lower.
            self._prof.capture(self._prof_key(key), prog)
        self._wave_cache[key] = prog
        return prog

    def _prof_key(self, key: tuple) -> str:
        """The profiler's canonical program identity (obs/prof.py):
        engine id + a short digest of the shared-cache prefix (the
        model's program key and the executable-determining knobs) +
        the instance key. Process-stable, so every engine instance of
        one model/config derives the same string and shared-cache hits
        find the first builder's cost record."""
        prefix = (self._prog_key, self._table_impl, self._pack_on,
                  self._use_symmetry, self._wave_kernel_on,
                  self._matmul_plan is not None)
        digest = hashlib.blake2s(repr(prefix).encode(),
                                 digest_size=4).hexdigest()
        return f"{self._ENGINE_ID}|{digest}|{key!r}"

    def _wave_fn(self, capacity: int, batch: Optional[int] = None,
                 out_rows: Optional[int] = None):
        """Builds (and caches) the jitted wave program for a (batch,
        table size, output rung) bucket."""
        B = self._B if batch is None else batch
        K = B * self._F if out_rows is None else out_rows

        def build():
            jitted = build_wave(self._dm, B, capacity, self._prop_fns,
                                self._use_symmetry,
                                table_impl=self._table_impl, out_rows=K,
                                layout=self._wave_layout(),
                                wave_kernel=self._wave_kernel_on,
                                matmul_plan=self._matmul_plan)
            sds = jax.ShapeDtypeStruct
            return self._aot(jitted, (
                sds((B, self._Wrow), jnp.uint32), sds((B,), jnp.bool_),
                sds((capacity,), jnp.uint64)))

        return self._cached_program((B, capacity, K), build)

    def _succ_full_rows(self, B: int) -> int:
        """The wave's full successor space — the output ladder's top
        rung (per shard on the sharded engine, which overrides this)."""
        return B * self._F

    def _kernel_path(self, capacity: int, batch: int) -> str:
        """Which successor-path implementation a wave program at this
        (batch, capacity) resolves to — built from the SAME gate
        predicates the program builders call (``wave_kernel_impl`` /
        ``sender_kernel_impl``), so the recorded path is the executed
        path: ``megakernel`` (the single-kernel wave, TPU lowering),
        ``interpret`` (the same kernel in Pallas interpret mode —
        correct, not fast; the CPU parity arm), ``pallas_probe`` (the
        round-7 VMEM table kernel only), or ``xla`` (the op ladder).
        The sharded engines set ``_SENDER_KERNEL`` (their megakernel is
        the table-less per-shard sender; the probe stays owner-side, so
        the pallas probe table never applies there)."""
        from .matmul_wave import plan_bytes
        from .pallas_table import (PALLAS_AVAILABLE, default_interpret,
                                   pallas_table_capacity_ok,
                                   sender_kernel_ok, wave_kernel_ok)

        # wave_matmul rides every path as a "+matmul" suffix: the
        # expand stage swaps implementation inside whichever program
        # the other gates pick, so attribution must carry both axes.
        suffix = "+matmul" if self._matmul_plan is not None else ""
        extra = plan_bytes(self._matmul_plan, batch)
        if self._wave_kernel_on and PALLAS_AVAILABLE:
            ok = (sender_kernel_ok(batch, self._F, self._W, self._Wrow,
                                   extra_bytes=extra)
                  if self._SENDER_KERNEL
                  else wave_kernel_ok(capacity, batch, self._F,
                                      self._W, self._Wrow,
                                      extra_bytes=extra))
            if ok:
                return ("interpret" if default_interpret()
                        else "megakernel") + suffix
        if (not self._SENDER_KERNEL and self._table_impl == "pallas"
                and pallas_table_capacity_ok(capacity)):
            return "pallas_probe" + suffix
        return "xla" + suffix

    def kernel_path(self) -> str:
        """The active kernel path at the current capacity and widest
        dispatch bucket (per-dispatch values ride the wave events)."""
        return self._kernel_path(self._capacity, self._B_max)

    def _expand_impl(self) -> str:
        """Which expand-stage implementation the wave programs embed:
        ``matmul`` (the compiled transition-table form) or ``step``
        (the vmapped ``DeviceModel.step`` path — also what an
        irregular model falls back to with the knob on)."""
        return "matmul" if self._matmul_plan is not None else "step"

    def _pick_out_rows(self, B: int) -> int:
        """Picks the output rung for the next wave at batch bucket
        ``B`` from the novel-count history: twice the worst recent
        novel set (scaled when the history was measured at a narrower
        batch), rounded up the ladder. Until the history WINDOW fills —
        or with the ladder disabled — the full width is used: a sub-full
        rung costs one XLA compile per (B, K), which only a run long
        enough to have filled the window will amortize. Correctness
        never depends on the guess (an overflowed wave regathers
        losslessly); this only sets how often the regather path is
        paid."""
        full = self._succ_full_rows(B)
        if (not self._succ_ladder_on
                or len(self._succ_hist) < self._succ_hist.maxlen):
            return full
        ladder = succ_bucket_ladder(full)
        if len(ladder) == 1:
            return full
        want = 0
        for b, novel in self._succ_hist:
            want = max(want, novel * -(-B // b))
        return pick_bucket(ladder, 2 * want + 16)

    def _regather_fn(self, batch: int, out_rows: int):
        """The overflow-recovery program for a (batch, rung) pair: a
        pure re-expansion + mask-driven compaction at a rung that fits
        (no table access — the wave already inserted every novel
        candidate; only the truncated outputs are recomputed)."""
        def build():
            jitted = build_regather(self._dm, batch, out_rows,
                                    self._use_symmetry,
                                    layout=self._wave_layout(),
                                    matmul_plan=self._matmul_plan)
            sds = jax.ShapeDtypeStruct
            return self._aot(jitted, (
                sds((batch, self._Wrow), jnp.uint32),
                sds((batch,), jnp.bool_),
                sds((batch * self._F,), jnp.bool_)))

        return self._cached_program(("regather", batch, out_rows), build)

    def _note_compile(self, compiled: bool) -> None:
        """Marks the current processing interval compile-contaminated."""
        if compiled:
            self._compile_dirty = True

    def _take_compile(self) -> bool:
        dirty = self._compile_dirty
        self._compile_dirty = False
        return dirty

    def _aot(self, jitted, arg_specs):
        """Ahead-of-time compiles a jitted program from
        ``ShapeDtypeStruct`` specs, so LAUNCHES never carry an XLA
        compile: under pipelined dispatch a lazy first call would embed
        the compile in whatever processing interval happens to be open,
        corrupting the steady-rate attribution. The compile cost is
        accounted in ``compile_sec`` instead. Falls back to the lazy
        jitted callable (interval-flagged via ``_note_compile``) where
        lowering is unsupported (e.g. some pallas paths)."""
        t0 = time.monotonic()
        try:
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                compiled = jitted.lower(*arg_specs).compile()
        except Exception:  # noqa: BLE001 — lazy path stays correct
            self._note_compile(True)
            return jitted
        now = time.monotonic()
        self.compile_sec += now - t0
        self.compile_log.append((now, now - t0))
        return compiled

    def scheduler_stats(self) -> dict:
        """The adaptive wave scheduler's run telemetry: the configured
        bucket ladder, how many dispatches each bucket served, how many
        paid a first-use compile, and the deepest dispatch pipelining
        achieved (0 = fully synchronous).

        Every figure is a VIEW over the wave-event stream
        (``dispatch_log`` — the same unified per-dispatch records the
        obs tracer serializes under ``STpu_TRACE``); there is no
        parallel bookkeeping to drift out of sync."""
        with self._lock:
            log = list(self.dispatch_log)
        succ_total = sum(e["successors"] for e in log)
        cand_total = sum(e["candidates"] for e in log)
        overflows = sum(1 for e in log if e["overflow"])
        # Kernel occupancy: frontier rows actually processed vs the
        # padded rows the wave programs dispatched (bucket width x BFS
        # levels) — the figure the ladder's K choice and the megakernel
        # A/Bs are judged against (a half-empty wave pays full kernel
        # time either way). A zero-wave entry (a pipelined fused
        # dispatch that no-opped at a rest point) contributes nothing
        # to either side — it ran no kernel.
        rows_total = sum(e.get("rows") or 0 for e in log)
        # bucket is PER SHARD on the sharded engines while rows counts
        # every shard's valid slots, so the padded denominator scales
        # by the mesh (slots = 1 on the single-device engines).
        slots = int(getattr(self, "_n_shards", getattr(self, "_n", 1)))
        padded_total = sum(e["bucket"] * e["waves"] * slots
                           for e in log)
        buckets: Dict[str, int] = {}
        out_rows: Dict[str, int] = {}
        for e in log:
            k = str(e["bucket"])
            buckets[k] = buckets.get(k, 0) + 1
            if e.get("out_rows") is not None:
                r = str(e["out_rows"])
                out_rows[r] = out_rows.get(r, 0) + 1
        return {
            "bucket_ladder": list(self._buckets),
            "bucket_dispatches": buckets,
            "dispatches": len(log),
            "bucket_compiles": sum(1 for e in log if e["compiled"]),
            "compile_sec": round(self.compile_sec, 3),
            "max_inflight": max((e["inflight"] for e in log), default=0),
            # Successor-path telemetry (ISSUE 2): which output rungs the
            # ladder dispatched, how often a wave's novel set overflowed
            # its rung (and paid the logged regather), and how much of
            # the candidate stream the intra-wave local dedup collapsed
            # before the global table probe.
            "succ_ladder": {
                "enabled": self._succ_ladder_on,
                "out_rows_dispatches": out_rows,
                "overflow_redispatches": overflows,
                "occupancy": (round(rows_total / padded_total, 4)
                              if padded_total else 0.0),
            },
            # Single-kernel wave telemetry (ISSUE 10): which successor-
            # path implementation the run dispatches, and how many BFS
            # levels one host round-trip covers (the fused engines'
            # device-resident multi-wave loop; 1 on the per-wave
            # engines). Occupancy lives under succ_ladder — one
            # canonical key, shared numerator/denominator.
            "wave_kernel": {
                "enabled": self._wave_kernel_on,
                "path": self.kernel_path(),
                "waves_per_round_trip": int(getattr(self, "_K", 1)),
            },
            # Matmul-form expand telemetry (ISSUE 15): whether the
            # transition compiler classified the model regular, which
            # implementation the programs embed, and the per-row MXU
            # work the compiled plan carries (0 on the step path).
            "wave_matmul": {
                "enabled": self._wave_matmul_on,
                "active": self._matmul_plan is not None,
                "expand_impl": self._expand_impl(),
                "reason": self._matmul_reason,
                "matmul_ops": (self._matmul_plan.matmul_ops
                               if self._matmul_plan is not None else 0),
            },
            "local_dedup": {
                "successors": succ_total,
                "distinct_candidates": cand_total,
                "collapse_ratio": (round(1.0 - cand_total
                                         / max(succ_total, 1), 4)
                                   if succ_total else 0.0),
            },
            # Packed-arena telemetry (ISSUE 4): the storage row format
            # and the byte high-water marks, read off the same wave
            # event stream as everything else.
            "packing": {
                "enabled": self._pack_on,
                "state_width": self._W,
                # What the layout CAN pack to (reported even when the
                # knob resolved off, so a CPU bench still records the
                # achievable cut) vs what this run actually stored.
                "packed_width": self._layout.packed_width,
                "row_width": self._Wrow,
                "bytes_per_state": 4 * self._Wrow,
                "bytes_per_state_packed": 4 * self._layout.packed_width,
                "bytes_per_state_unpacked": 4 * self._W,
                "ratio": round(self._W / self._Wrow, 3),
                "packable_ratio": round(
                    self._W / self._layout.packed_width, 3),
                "arena_bytes_high_water": max(
                    (e.get("arena_bytes") or 0 for e in log),
                    default=0) or None,
                "table_bytes_high_water": max(
                    (e.get("table_bytes") or 0 for e in log),
                    default=0) or None,
            },
            # Tiered-store telemetry (ISSUE 8): per-tier occupancy,
            # spill/page-in counters, and the resident ratio — the
            # graceful-degradation record.
            "store": self.store_stats(),
            # Cross-job compiled-program sharing (ISSUE 9): how many of
            # this run's program lookups the process-wide cache served
            # vs built. A warm-cache job shows hits > 0 and
            # bucket_compiles == 0 — the service's amortization story.
            "program_cache": {
                "shared": self._prog_cache is not None,
                "hits": self._prog_hits,
                "misses": self._prog_misses,
            },
            # Asynchronous host I/O (ISSUE 13): the background writer's
            # ledger — pending writes, safe-point joins and their wait,
            # and the overlap seconds the knob bought (writer busy time
            # the wave loop did not wait for).
            "async_io": self._aio.stats(),
            # Service-level observability (ISSUE 14): rolling SLO
            # burn-window status (None when ``STpu_SLO`` is unset) and
            # the recent slow-wave anomaly verdicts (empty when
            # ``STpu_ANOMALY`` is unset).
            "slo": self._wave_obs.slo_status(),
            "anomalies": self._wave_obs.anomalies(),
            # Continuous wave profiler (ISSUE 18): sampled roofline
            # snapshots per compiled program (None when ``STpu_PROF``
            # is unset).
            "prof": (self._prof.stats() if self._prof.enabled
                     else None),
        }



    # -- Host orchestration loop -----------------------------------------

    def _run(self) -> None:
        try:
            self._run_waves()
            if self._ckpt_path is not None:
                self._write_checkpoint(self._ckpt_path)
            # Final safe point: the last generation (and any spill
            # still in flight) lands — or surfaces its writer-thread
            # failure as an ordinary engine error — before done.
            self._aio.join()
        except BaseException as e:  # surfaced at join()
            self._error = e
            if self._flight.armed:
                # The always-on postmortem: the ring's last waves,
                # dumped where a dark (untraced) run would otherwise
                # die without a trail. The Supervisor attaches this
                # path to its retry/abort events.
                self.flight_dump = self._flight.dump(
                    f"{type(e).__name__}: {e}")
        finally:
            if self._wave_obs.enabled:
                # A short run may never cross the snapshot cadence:
                # land the final histogram snapshot before run_end.
                self._wave_obs.close(self._tracer)
            self._tracer.close()
            self._done.set()

    def _take_batch(self, pending: deque, rows: int):
        """Assembles up to ``rows`` frontier rows from the block queue.

        The pending queue holds whole *blocks* (vecs, fps, ebits arrays) —
        one per producing wave — rather than per-state tuples, so batch
        assembly and new-state streaming are pure array ops with no
        per-state Python in the hot loop.
        """
        parts = []
        taken = 0
        while pending and taken < rows:
            if isinstance(pending[0], FrontierRef):
                # Page the block back in before it can dispatch; the
                # NEXT paged-out blocks (scanning a few entries deep)
                # go to the background reader so their disk reads
                # overlap this dispatch. With async_io on the window
                # widens from one-block-ahead to several (round 17:
                # the store-level prefetcher dedups by path, so the
                # same ref surfacing twice costs nothing).
                width = 4 if self._aio.enabled else 1
                depth = 32 if self._aio.enabled else 8
                ahead = []
                for i in range(1, min(len(pending), depth)):
                    if isinstance(pending[i], FrontierRef):
                        ahead.append(pending[i])
                        if len(ahead) >= width:
                            break
                pending[0] = self._store.fetch_frontier(
                    pending[0], prefetch=ahead or None)
            vecs, fps, ebits = pending[0]
            k = len(fps)
            take = min(k, rows - taken)
            if take == k:
                pending.popleft()
                parts.append((vecs, fps, ebits))
            else:
                parts.append((vecs[:take], fps[:take], ebits[:take]))
                pending[0] = (vecs[take:], fps[take:], ebits[take:])
            taken += take
        return parts, taken

    def _eval_host_conds(self, conds_out, batch_vecs, rows):
        """Reattaches device-evaluated conditions to property slots and
        fills host-fallback slots by decoding the batch rows in ``rows``.

        Decoding a row into a Python state object is the expensive part
        of the host fallback, so it happens lazily — only when at least
        one fallback slot exists — and at most ONCE per wave, with the
        decoded list shared across every fallback property (three
        host-only properties cost one decode pass, not three)."""
        model = self._model
        conds: List[np.ndarray] = []
        it = iter(conds_out)
        decoded: Optional[list] = None
        for i, fn in enumerate(self._prop_fns):
            if fn is not None:
                conds.append(np.asarray(next(it)))
                continue
            if decoded is None:
                decode = self._dm.decode
                # The batch rides in the storage row format; decode
                # needs real lanes — one unpack pass, shared across
                # every fallback property (like the decode itself).
                unpacked = self._unpack_np(batch_vecs)
                decoded = [(r, decode(unpacked[r])) for r in rows]
            cond = np.zeros(len(batch_vecs), bool)
            prop_cond = self._properties[i].condition
            for r, state in decoded:
                cond[r] = bool(prop_cond(model, state))
            conds.append(cond)
        return conds

    def _run_waves(self) -> None:
        """The host orchestration loop, software-pipelined one wave deep:
        while the device computes wave k, the host finishes processing
        wave k-1's outputs. Dispatch-ahead only happens when a FULL batch
        is already queued, so wave composition — and therefore BFS visit
        order, counts, and discovery identities — is bit-identical to a
        sequential loop (children always land at the queue tail; a
        partial batch means the loop drains first, exactly like the
        unpipelined schedule). Growth and checkpoints force a drain:
        both need the frontier + table at rest.

        Batch width is adaptive: each dispatch picks the smallest bucket
        of the power-of-two ladder that covers the queued frontier rows
        (``batch_bucket_ladder``), so a 40-row tail stops paying a
        full-width padded expand. Results are bucket-independent (the
        cross-B parity suite pins this)."""
        F = self._F
        properties = self._properties
        pending = self._pending
        self.wave_log.append((time.monotonic(), self._state_count))
        wave_index = 0
        last_ckpt = 0
        inflight = None

        while pending or inflight is not None:
            if self._preempt_evt.is_set():
                # Preemption (job service): drain the in-flight wave —
                # its table insertions are real, dropping its outputs
                # would tear the frontier — then stop at this safe
                # point; _run writes the resumable checkpoint.
                if inflight is not None:
                    self._process_wave(inflight)
                self.preempted = True
                return
            with self._lock:
                done = (len(self._discoveries) == len(properties)
                        # all properties discovered (bfs.rs:117)
                        or (self._target_state_count is not None
                            and self._state_count
                            >= self._target_state_count))
            if done:
                if inflight is not None:
                    # Drain: the dispatched wave's insertions are already
                    # in the visited table; dropping its outputs would
                    # tear the frontier (states visited but their
                    # subtrees never queued — fatal for checkpoints).
                    self._process_wave(inflight)
                return
            ckpt_due = (self._ckpt_path is not None
                        and wave_index - last_ckpt >= self._ckpt_every)
            # Two waves of headroom — see _needs_growth.
            growth_due = self._needs_growth()
            if inflight is None:
                if ckpt_due:
                    self._write_checkpoint(self._ckpt_path)  # safe point
                    last_ckpt = wave_index
                    ckpt_due = False
                if growth_due:
                    # Grow the table before it can overflow mid-wave.
                    self._grow_table()
                    growth_due = False

            # Count queued rows only until the dispatch threshold: O(1)
            # amortized instead of walking every pending block per wave.
            queued = 0
            for b in pending:
                queued += b.rows if isinstance(b, FrontierRef) \
                    else len(b[1])
                if queued >= self._B_max:
                    break
            next_wave = None
            # Dispatch-ahead only with a full widest-bucket batch queued
            # (wave composition then matches the sequential schedule).
            may_dispatch = (inflight is None
                            or (self._pipeline and queued >= self._B_max))
            if queued and may_dispatch and not growth_due and not ckpt_due:
                wave_index += 1
                next_wave = self._dispatch_wave(
                    pick_bucket(self._buckets, queued),
                    inflight=0 if inflight is None else 1)
            if inflight is not None:
                self._process_wave(inflight)
            inflight = next_wave

    def _dispatch_wave(self, batch: Optional[int] = None,
                       inflight: int = 0) -> tuple:
        """Assembles a batch and launches the wave program; returns the
        dispatch context with the (still device-resident, possibly
        unmaterialized) outputs."""
        B, W = (self._B if batch is None else batch), self._Wrow
        parts, n = self._take_batch(self._pending, B)
        batch_vecs = np.zeros((B, W), np.uint32)
        batch_fps = np.zeros(B, np.uint64)
        batch_ebits = np.zeros(B, np.uint32)
        row = 0
        for vecs, fps, ebits in parts:
            k = len(fps)
            batch_vecs[row:row + k] = vecs
            batch_fps[row:row + k] = fps
            batch_ebits[row:row + k] = ebits
            row += k
        valid = np.arange(B) < n

        K = self._pick_out_rows(B)
        prog = self._wave_fn(self._capacity, B, K)
        pkey = prof_s = t0 = None
        if self._prof.enabled:
            pkey = self._prof_key((B, self._capacity, K))
            if self._prof.should_sample(pkey):
                t0 = time.monotonic()
        outs = prog(
            jnp.asarray(batch_vecs), jnp.asarray(valid), self._visited)
        if t0 is not None:
            # Rest-point timing (obs/prof.py): forcing materialization
            # serializes this one dispatch against the pipeline — the
            # sampled 1/N price of a real device-time measurement.
            jax.block_until_ready(outs)
            prof_s = time.monotonic() - t0
        (conds_out, succ_count, cand_count, terminal, new_count,
         new_vecs, new_fps, new_parent, new_mask, overflow,
         self._visited) = outs
        meta = {"bucket": B, "inflight": inflight, "out_rows": K,
                "rows": n,
                "kernel_path": self._kernel_path(self._capacity, B),
                "expand_impl": self._expand_impl()}
        if pkey is not None:
            # Internal riders for _process_wave — popped there before
            # the entry reaches the schema'd streams.
            meta["_prof_key"] = pkey
            if prof_s is not None:
                meta["_prof_s"] = prof_s
        return (conds_out, succ_count, cand_count, terminal, new_count,
                new_vecs, new_fps, new_parent, new_mask, overflow,
                batch_vecs, batch_fps, batch_ebits, valid, n, meta)

    def _process_wave(self, wave: tuple) -> None:
        """Materializes a dispatched wave's outputs and applies them to
        counts, discoveries, the parent log, and the frontier queue."""
        model = self._model
        properties = self._properties
        eventually_idx = self._eventually_idx
        (conds_out, succ_count, cand_count, terminal, new_count,
         new_vecs, new_fps, new_parent, new_mask, overflow, batch_vecs,
         batch_fps, batch_ebits, valid, n, meta) = wave
        if self._faults.active:
            # Before any count/queue mutation: a crash here models the
            # worst case (the dispatched wave's table insertions are
            # real, its outputs are lost — a torn frontier only a
            # checkpoint resume can repair).
            self._faults.crash("wave_crash", self._tracer,
                               wave=len(self.dispatch_log))

        conds = self._eval_host_conds(conds_out, batch_vecs, range(n))

        if self._visitor is not None:
            for r in range(n):
                self._visitor.visit(
                    model, self._reconstruct_path(int(batch_fps[r])))

        terminal = np.asarray(terminal)
        k = int(new_count)
        if bool(overflow):
            # The wave's novel set outgrew its output rung: the table
            # insertions are complete and the full novelty mask is an
            # output, so recover the truncated rows with a pure
            # regather at a rung that fits (logged — the scheduler's
            # history sizing is judged by how rarely this path runs).
            B = meta["bucket"]
            k2 = pick_bucket(succ_bucket_ladder(self._succ_full_rows(B)),
                             k)
            (new_vecs, new_fps, new_parent) = self._regather_fn(B, k2)(
                jnp.asarray(batch_vecs), jnp.asarray(valid), new_mask)
            meta = dict(meta, out_rows=k2, overflowed=True)
            if self._tracer.enabled:
                self._tracer.event("overflow_redispatch", bucket=B,
                                   out_rows=k2, novel=k)
        # Power-of-two slice lengths bound the number of
        # shape-specialized dispatch cache entries at O(log S).
        kb = min(max(1, 1 << (k - 1).bit_length()) if k else 0,
                 int(new_fps.shape[0]))
        new_vecs = np.asarray(new_vecs[:kb])[:k]
        new_fps = np.asarray(new_fps[:kb])[:k]
        parent_rows = np.asarray(new_parent[:kb])[:k]
        self._check_error_lane(new_vecs)

        # Tiered store: the device table only knows its RESIDENT rows,
        # so a spilled state that got re-generated looks novel on
        # device (and was re-admitted to the table). The batched probe
        # against the warm/cold partitions filters it here, BEFORE it
        # can touch counts, the parent log, or the queue — that filter
        # is what keeps a spilled run bit-identical to an all-in-device
        # run.
        k_dev = k
        if self._store.active and k and self._store.spilled_rows:
            present = self._store.probe(
                self._store_probe_fps(new_vecs, new_fps))
            if present.any():
                keep = ~present
                new_vecs = new_vecs[keep]
                new_fps = new_fps[keep]
                parent_rows = parent_rows[keep]
                k = len(new_fps)

        with self._lock:
            self._state_count += int(succ_count)
            self._resident += k_dev
            self._succ_hist.append((meta["bucket"], k_dev))
            now = time.monotonic()
            self.wave_log.append((now, self._state_count))
            # One unified wave event per dispatch (obs schema): the
            # in-memory dispatch_log entry IS the record the tracer
            # serializes, so scheduler_stats/bench read the same stream
            # a trace consumer does.
            entry = dict(
                meta, t=now, states=self._state_count,
                unique=self._unique_count + k, waves=1,
                compiled=self._take_compile(),
                successors=int(succ_count), candidates=int(cand_count),
                novel=k, capacity=self._capacity,
                # Occupancy is the DEVICE-resident count: with the
                # tiered store armed it can lag unique_count (spilled
                # partitions live warm/cold); without it they are
                # equal.
                load_factor=round(self._resident / self._capacity, 4),
                overflow=bool(meta.get("overflowed", False)),
                # Bandwidth gauges (obs schema v2): state-row bytes as
                # stored, plus the table footprint; the classic engine
                # keeps its frontier host-side, so arena_bytes is null.
                bytes_per_state=4 * self._Wrow, arena_bytes=None,
                table_bytes=self._capacity * 8,
                # v10: wave-loop host-I/O stall since the last wave
                # event (safe-point joins + inline write time).
                io_stall_s=self._take_io_stall())
            if self._store.active:
                # Tier occupancy gauges (obs schema v6).
                entry.update(self._store.gauges(),
                             tier_device_rows=self._resident,
                             tier_device_bytes=self._table_bytes(
                                 self._capacity))
            entry.pop("overflowed", None)
            if self._prof.enabled:
                # v13 cost stamping + (on sampled dispatches) the
                # profile_snapshot roofline event. The internal riders
                # never reach the dispatch log or the trace.
                self._prof.wave(entry, entry.pop("_prof_key", None),
                                entry.pop("_prof_s", None),
                                self._tracer, self._flight)
            self.dispatch_log.append(entry)
            if self._flight.armed:
                self._flight.record(entry)
            # Always/Sometimes discoveries: first failing/matching state
            # in queue order (bfs.rs:196-211).
            for i, prop in enumerate(properties):
                if prop.name in self._discoveries:
                    continue
                if prop.expectation is Expectation.ALWAYS:
                    hits = valid & ~conds[i]
                elif prop.expectation is Expectation.SOMETIMES:
                    hits = valid & conds[i]
                else:
                    continue
                rows = np.flatnonzero(hits)
                if rows.size:
                    self._discoveries[prop.name] = int(
                        batch_fps[rows[0]])
            # Eventually bits: clear satisfied, then flag terminal
            # states with remaining bits (bfs.rs:212-226, 265-272).
            ebits_after = batch_ebits.copy()
            for i in eventually_idx:
                ebits_after &= ~np.where(
                    conds[i], np.uint32(1 << i), np.uint32(0))
            for r in np.flatnonzero(terminal[:n] & (ebits_after[:n] != 0)):
                for i in eventually_idx:
                    prop = properties[i]
                    if (ebits_after[r] >> i) & 1 \
                            and prop.name not in self._discoveries:
                        self._discoveries[prop.name] = int(batch_fps[r])
            # Stream the new block into the queue + parent log — all
            # array ops, no per-state Python (bfs.rs:262 enqueue).
            if k:
                self._parent_log.append((new_fps, batch_fps[parent_rows]))
                self._unique_count += k
                self._pending.append(
                    (new_vecs, new_fps, ebits_after[parent_rows]))
        if self._store.active and k:
            # Host-tier frontier budget: page tail blocks out to disk
            # (they dispatch last; they page back in with prefetch).
            self._store.balance_frontier((self._pending,))
        if self._tracer.enabled:
            self._tracer.wave(entry)
        if self._wave_obs.enabled:
            self._wave_obs.wave(entry, self._tracer, self._flight)

    def _check_error_lane(self, new_vecs: np.ndarray) -> None:
        """Raises if any generated state tripped the model's error lane
        (e.g. a bounded-network overflow in an actor encoding)."""
        lane = self._dm.error_lane
        if lane is None or not new_vecs.size:
            return
        col = (self._layout.lane_np(new_vecs, lane) if self._pack_on
               else new_vecs[:, lane])
        if col.any():
            raise RuntimeError(
                f"device model error lane {lane} is set in a generated "
                "state: an encoding capacity was exceeded (for actor "
                "models: raise net_slots)")

    def _needs_growth(self) -> bool:
        """Whether the visited table needs to grow before the next
        dispatch: two waves of headroom against the load-factor-1/2
        bound (with one wave in flight, the resident count lags its
        unprocessed insertions by up to ``B_max*F``, and the next
        dispatch adds up to ``B_max*F`` more). ``_resident`` is the
        DEVICE-tier occupancy — equal to ``_unique_count`` until the
        tiered store evicts partitions."""
        return self._needs_growth_at(self._capacity)

    def _needs_growth_at(self, capacity: int) -> bool:
        """The growth predicate at a hypothetical capacity (shared by
        the real check, the grow-target simulation, and the tiered
        store's spill-vs-grow decision)."""
        return (self._resident + 2 * self._B_max * self._F
                > capacity // 2)

    def _simulate_grow_capacity(self) -> int:
        """The capacity ``_grow_table_impl`` would grow to right now —
        what the spill-vs-grow decision budgets against, and what a
        failed growth's ``degrade`` event records as ``requested``."""
        cap = self._capacity
        while self._needs_growth_at(cap):
            cap *= 2
        return cap

    def _table_bytes(self, capacity: int) -> int:
        """Device bytes the visited table occupies at ``capacity``
        (the sharded engines multiply by the mesh)."""
        return capacity * 8

    # -- Tiered store hooks (stateright_tpu.store) -------------------------

    def _spill_enough(self, keep_fps: np.ndarray) -> bool:
        """Whether keeping only ``keep_fps`` device-resident satisfies
        the growth predicate at the CURRENT capacity (the spill
        target: evict just enough partitions that no growth is
        needed)."""
        return (len(keep_fps) + 2 * self._B_max * self._F
                <= self._capacity // 2)

    def _spill_seed(self, visited_fps: np.ndarray) -> np.ndarray:
        """Budget gate at table-build time (fresh runs and resumes): if
        seeding every fingerprint would size the table past the device
        budget, spill whole partitions to the warm tier first and seed
        only the survivors."""
        store = self._store
        if (not store.active or store.device_budget is None
                or not self._VISITED_SPILL_CAPABLE
                or not len(visited_fps)):
            return visited_fps
        visited_fps = np.asarray(visited_fps, np.uint64)

        def cap_for(n_rows: int) -> int:
            cap = self._capacity
            while cap < 4 * n_rows + 2 * self._B_max * self._F:
                cap *= 2
            return cap

        if self._table_bytes(cap_for(len(visited_fps))) \
                <= store.device_budget:
            return visited_fps
        mask = store.spill_mask(
            visited_fps,
            lambda keep: self._table_bytes(cap_for(len(keep)))
            <= store.device_budget)
        if not mask.any():
            return visited_fps
        store.spill_visited(visited_fps[mask])
        return visited_fps[~mask]

    def _spill_for_headroom(self) -> bool:
        """The spill-instead-of-grow arm of ``_grow_table``: when the
        table's next growth would exceed the device byte budget, evict
        whole ``fp % P`` partitions to the warm tier (membership stays
        covered by the per-wave host probe) and rebuild the table at
        the SAME capacity. Returns True when something spilled; when
        even a full eviction cannot restore headroom the device tier
        must exceed its budget and a ``pressure`` event records why."""
        store = self._store
        if (not store.active or store.device_budget is None
                or not self._VISITED_SPILL_CAPABLE):
            return False
        target = self._simulate_grow_capacity()
        if self._table_bytes(target) <= store.device_budget:
            return False  # normal growth stays inside the budget
        if not self._spill_enough(np.zeros(0, np.uint64)):
            # Even a fully-evicted table cannot satisfy the dispatch
            # headroom at this capacity: spilling would only buy
            # per-wave probe cost, not memory — the device tier must
            # exceed its budget (recorded, not fatal).
            store.note_device_pressure(self._table_bytes(target),
                                       store.device_budget)
            return False
        real = np.asarray(self._visited).reshape(-1)
        real = real[real != SENTINEL]
        mask = store.spill_mask(real, self._spill_enough)
        if not mask.any():
            store.note_device_pressure(self._table_bytes(target),
                                       store.device_budget)
            return False
        store.spill_visited(real[mask])
        self._visited = self._new_table(real[~mask])
        return True

    def _store_probe_fps(self, new_vecs: np.ndarray,
                         new_fps: np.ndarray) -> np.ndarray:
        """The fingerprints the spilled-partition membership probe
        keys on: the wave outputs PATH fingerprints, which under
        symmetry differ from the dedup (representative) fingerprints
        the table — and therefore the spilled partitions — hold, so
        the symmetric case recomputes them (one small jitted program
        per power-of-two block shape)."""
        if not self._use_symmetry:
            return new_fps
        k = len(new_vecs)
        if not k:
            return new_fps
        kb = 1 << max(0, (k - 1).bit_length())
        key = ("repfp", kb)
        fn = self._wave_cache.get(key)
        if fn is None:
            layout = self._wave_layout()
            dm = self._dm

            def rep_fp(vecs):
                if layout is not None:
                    vecs = layout.unpack(vecs)
                return device_fp64(jax.vmap(dm.representative)(vecs))

            fn = self._wave_cache[key] = jax.jit(rep_fp)
        pad = np.zeros((kb, new_vecs.shape[1]), np.uint32)
        pad[:k] = new_vecs
        return np.asarray(fn(jnp.asarray(pad)))[:k]

    def store_stats(self) -> dict:
        """The tiered store's occupancy/telemetry summary (also the
        ``scheduler_stats()["store"]`` payload and the Supervisor's
        abort high-water record)."""
        stats = self._store.stats()
        if self._store.active:
            with self._lock:
                resident = int(getattr(self, "_resident",
                                       self._unique_count))
                unique = self._unique_count
            stats["device"] = {
                "rows": resident,
                "table_bytes": self._table_bytes(self._capacity),
                "budget": self._store.device_budget,
            }
            stats["resident_ratio"] = round(
                resident / max(1, unique), 4)
        return stats

    def _degrade_bucket(self) -> bool:
        """OOM graceful degradation: drops the top rung of the batch
        bucket ladder — narrower dispatches need proportionally less
        table/arena headroom, so a failed growth is retried against a
        smaller requirement before the run gives up. Returns False when
        already at the narrowest rung (nothing left to shed)."""
        if len(self._buckets) <= 1:
            return False
        old = self._B_max
        self._buckets = self._buckets[:-1]
        self._B_max = self._buckets[-1]
        warnings.warn(
            f"table/arena growth hit an allocation failure; degrading "
            f"the dispatch bucket ladder {old} -> {self._B_max} and "
            "retrying", RuntimeWarning)
        if self._tracer.enabled:
            # requested/kept: the capacity the failed growth asked for
            # vs what actually exists — postmortems need to see WHY
            # memory ran out, not just that a bucket was shed.
            self._tracer.event(
                "degrade", kind="batch_bucket", old=old,
                new=self._B_max,
                requested=int(getattr(self, "_grow_requested", 0)),
                kept=int(self._capacity), _flush=True)
        return True

    def _handle_grow_failure(self, e: BaseException) -> None:
        """The shared OOM-degrade arm for every engine's growth site
        (call from the ``except`` clause): a non-OOM failure, or an OOM
        with nothing left to shed, re-raises; otherwise the ladder is
        degraded and one paired ``recover`` event is emitted — the lint
        pairs fault->recover 1:1 in stream order, and each caught
        OOM here pairs with exactly one fault/real-OOM."""
        if not is_oom(e) or not self._degrade_bucket():
            raise
        if self._tracer.enabled:
            self._tracer.event("recover", attempt=1, backoff_s=0.0,
                               resumed_from=None, kind="grow_degrade",
                               _flush=True)

    def _grow_table(self) -> None:
        """Growth with OOM graceful degradation: an allocation failure
        (real RESOURCE_EXHAUSTED/MemoryError, or the injected
        ``grow_oom`` fault) sheds the top batch bucket and retries; the
        smaller headroom requirement may even make the growth
        unnecessary. Only when the ladder is down to its base rung does
        the failure propagate (and the supervisor takes over)."""
        while True:
            try:
                self._grow_requested = self._simulate_grow_capacity()
                if self._faults.active:
                    self._faults.crash("grow_oom", self._tracer)
                # Tiered store: when the growth target would exceed the
                # device byte budget, evict cold visited partitions to
                # the warm tier instead (spill-instead-of-grow) — the
                # growth may then be unnecessary at this capacity.
                if self._spill_for_headroom() \
                        and not self._needs_growth():
                    return
                self._grow_table_impl()
            except Exception as e:  # noqa: BLE001 — non-OOM re-raised
                self._handle_grow_failure(e)
                if self._needs_growth():
                    continue
            return

    def _grow_table_impl(self) -> None:
        real = np.asarray(self._visited)
        real = real[real != SENTINEL]
        old = self._capacity
        while self._needs_growth():
            self._capacity *= 2
        if self._tracer.enabled:
            self._tracer.event("grow", kind="table", old=old,
                               new=self._capacity)
        try:
            self._visited = self._new_table(real)
        except BaseException:
            # A failed allocation must leave capacity describing the
            # table that actually exists, or the degrade-retry path
            # would dispatch against a phantom size.
            self._capacity = old
            raise

    # -- Path reconstruction (bfs.rs:314-342) ----------------------------

    def _fingerprint_state(self, state) -> int:
        return host_fp64(np.asarray(self._dm.encode(state), np.uint32))

    def _parent_map(self) -> Dict[int, Optional[int]]:
        """Materializes fingerprint -> parent fingerprint from the per-wave
        parent log (built lazily: the hot loop only appends arrays)."""
        with self._lock:
            log = self._parent_log
            while self._parents_consumed < len(log):
                child_fps, parent_fps = log[self._parents_consumed]
                if parent_fps is None:
                    for f in child_fps:
                        self._parents.setdefault(int(f), None)
                else:
                    for f, p in zip(child_fps.tolist(), parent_fps.tolist()):
                        self._parents.setdefault(f, p)
                # The dict now owns this block; drop the arrays.
                log[self._parents_consumed] = None
                self._parents_consumed += 1
        return self._parents

    def _reconstruct_path(self, fp: int) -> Path:
        parents = self._parent_map()
        fingerprints: deque = deque()
        next_fp = fp
        while next_fp in parents:
            source = parents[next_fp]
            fingerprints.appendleft(next_fp)
            if source is None:
                break
            next_fp = source
        return Path.from_fingerprints(
            self._model, fingerprints, fingerprint_fn=self._fingerprint_state)

    # -- Checker API -----------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        with self._lock:
            return self._state_count

    def unique_state_count(self) -> int:
        with self._lock:
            return self._unique_count

    def discoveries(self) -> Dict[str, Path]:
        with self._lock:
            found = list(self._discoveries.items())
        return {name: self._reconstruct_path(fp) for name, fp in found}

    def preempt(self) -> None:
        """Requests a cooperative stop: the wave loop drains any
        in-flight dispatch at its next boundary, writes the end-of-run
        checkpoint (when ``checkpoint_path`` is set — a safe point, so
        the image is a valid resume source), and stops with
        ``self.preempted`` True. The run is NOT failed: ``join()``
        returns normally and a later run resumes from the checkpoint
        bit-identically. Idempotent; a no-op once the run finished.
        (The single-process sharded engines don't poll the flag — the
        job service only schedules onto the classic/fused engines.)"""
        self._preempt_evt.set()

    def join(self) -> "TpuBfsChecker":
        self._thread.join()
        if self._error is not None:
            raise self._error
        return self

    def is_done(self) -> bool:
        return self._done.is_set()


#: capacities whose pallas->XLA degrade has already been announced —
#: the warning fires once per capacity, not once per compiled (B, K)
#: wave program (the successor ladder multiplies program builds).
_PALLAS_DEGRADE_WARNED: set = set()


def dedup_impl(table_impl: str, capacity: int):
    """Resolves the visited-table implementation for a wave program:
    ``"xla"`` (the while_loop probe over the HBM-resident table) or
    ``"pallas"`` (the VMEM-staged kernel, ``pallas_table.py``). A pallas
    request a capacity can't satisfy degrades to XLA with a warning
    (once per capacity, not per compiled wave program) — mid-run table
    growth must not kill a checker.

    The returned function runs BOTH dedup levels —
    ``fn(fps, visited) -> (new_mask, new_count, cand_count, merged)``:
    the intra-wave local collapse (``first_occurrence_candidates``)
    first, then the global probe (``global_insert``) over the distinct
    survivors only, with ``cand_count`` (how many candidates reached
    the global probe) surfaced for the collapse-ratio telemetry."""
    if table_impl == "pallas":
        from .pallas_table import (dedup_and_insert_pallas,
                                   pallas_table_capacity_ok)

        if pallas_table_capacity_ok(capacity):
            return lambda fps, visited: dedup_and_insert_pallas(
                fps, visited, capacity)
        if capacity not in _PALLAS_DEGRADE_WARNED:
            _PALLAS_DEGRADE_WARNED.add(capacity)
            warnings.warn(
                f"pallas visited table unavailable at capacity "
                f"{capacity} (VMEM budget or pallas missing); using "
                "the XLA table", RuntimeWarning)

    def xla(fps, visited):
        candidate = first_occurrence_candidates(fps)
        cand_count = jnp.sum(candidate, dtype=jnp.int32)
        new_mask, new_count, merged = global_insert(
            fps, candidate, visited, capacity)
        return new_mask, new_count, cand_count, merged

    return xla


#: (batch, capacity) shapes whose megakernel->XLA degrade has already
#: been announced — once per shape, not per compiled wave program.
_WAVE_KERNEL_DEGRADE_WARNED: set = set()
#: Device-model type names whose wave_matmul capability-gate rejection
#: has already been announced — once per model type, not per spawn.
_WAVE_MATMUL_GATE_WARNED: set = set()


def wave_kernel_impl(wave_kernel: bool, dm: DeviceModel, batch: int,
                     capacity: int, use_sym: bool, layout,
                     matmul_plan=None):
    """Resolves the single-kernel-wave implementation for one wave
    program build: the Pallas megakernel when requested and the VMEM
    working-set gate passes at this (batch, capacity), else ``None``
    (the caller keeps the XLA op ladder). Degrades with a once-per-
    shape warning — mid-run table growth must not kill a checker,
    mirroring ``dedup_impl``'s pallas gate."""
    if not wave_kernel:
        return None
    from .matmul_wave import plan_bytes
    from .pallas_table import (PALLAS_AVAILABLE, build_wave_megakernel,
                               wave_kernel_ok)

    W = dm.state_width
    Wr = layout.packed_width if layout is not None else W
    if PALLAS_AVAILABLE and wave_kernel_ok(
            capacity, batch, dm.max_fanout, W, Wr,
            extra_bytes=plan_bytes(matmul_plan, batch)):
        return build_wave_megakernel(dm, batch, capacity,
                                     use_sym=use_sym, layout=layout,
                                     matmul_plan=matmul_plan)
    key = (batch, capacity)
    if key not in _WAVE_KERNEL_DEGRADE_WARNED:
        _WAVE_KERNEL_DEGRADE_WARNED.add(key)
        warnings.warn(
            f"wave megakernel unavailable at batch {batch} x capacity "
            f"{capacity} (VMEM working-set budget or pallas missing); "
            "using the XLA wave path", RuntimeWarning)
    return None


def sender_kernel_impl(wave_kernel: bool, dm: DeviceModel, batch: int,
                       use_sym: bool, layout, local_dedup: bool,
                       matmul_plan=None):
    """The sharded engines' single-kernel-wave resolver: the table-less
    SENDER megakernel (in-kernel unpack → expand → fingerprint →
    sender-side local dedup → re-pack), run per shard under
    ``shard_map``; the global probe/claim stays owner-side on the
    partitioned XLA table after the all-to-all. Returns ``None`` (the
    XLA path) when disabled or past the VMEM gate, with the same
    once-per-shape degrade warning as ``wave_kernel_impl``."""
    if not wave_kernel:
        return None
    from .matmul_wave import plan_bytes
    from .pallas_table import (PALLAS_AVAILABLE,
                               build_sender_megakernel,
                               sender_kernel_ok)

    W = dm.state_width
    Wr = layout.packed_width if layout is not None else W
    if PALLAS_AVAILABLE and sender_kernel_ok(
            batch, dm.max_fanout, W, Wr,
            extra_bytes=plan_bytes(matmul_plan, batch)):
        return build_sender_megakernel(dm, batch, use_sym=use_sym,
                                       layout=layout,
                                       local_dedup=local_dedup,
                                       matmul_plan=matmul_plan)
    key = ("sender", batch)
    if key not in _WAVE_KERNEL_DEGRADE_WARNED:
        _WAVE_KERNEL_DEGRADE_WARNED.add(key)
        warnings.warn(
            f"sender wave megakernel unavailable at batch {batch} "
            "(VMEM working-set budget or pallas missing); using the "
            "XLA wave path", RuntimeWarning)
    return None


def build_wave(dm: DeviceModel, batch_size: int, capacity: int,
               prop_fns=(), use_sym: bool = False,
               table_impl: str = "xla", out_rows: Optional[int] = None,
               layout=None, wave_kernel: bool = False,
               matmul_plan=None):
    """The single-device wave program (jitted): one BFS level expansion.

    Exposed as a standalone builder so the wave can be compiled and
    benchmarked without spawning a checker (see ``__graft_entry__``).
    Signature of the returned function::

        wave(vecs: uint32[B, W], valid: bool[B], visited: uint64[C])
          -> (conds, succ_count, cand_count, terminal, new_count,
              new_vecs, new_fps, new_parent, new_mask, overflow,
              merged_visited)

    ``visited`` is donated (the table is updated in place on device).

    ``out_rows`` (default B*F) is the successor ladder's output rung:
    ``new_vecs``/``new_fps``/``new_parent`` carry only the first
    ``out_rows`` compacted novel rows, so small-novel-set waves skip
    most of the full-width compaction gather and output traffic. The
    full novelty mask ``new_mask`` and the device-computed ``overflow``
    flag (``new_count > out_rows``) are always emitted, so an
    overflowed wave is recovered losslessly by ``build_regather`` —
    the table insertions are already complete and order-identical.

    ``layout`` (a :class:`~stateright_tpu.tpu.packing.PackedLayout`)
    switches the STORAGE row format: input ``vecs`` and output
    ``new_vecs`` are then packed ``uint32[.., Wp]`` rows, unpacked to
    real lanes at wave start and re-packed after compaction — compute
    (step, properties, fingerprints, symmetry) always runs on the exact
    unpacked registers, so results are layout-independent.

    ``wave_kernel`` (ISSUE 10) swaps the expand → fingerprint → local
    dedup → probe/claim middle for ONE Pallas megakernel
    (``pallas_table.build_wave_megakernel``) when the VMEM working-set
    gate admits this (batch, capacity); property evaluation and the
    ladder's K-row compaction stay XLA-side around it. The kernel
    traces the same stage functions, so outputs are bit-identical to
    the ladder (counts, discoveries, checkpoints — the test_wave_kernel
    differential suite pins this).

    ``matmul_plan`` (ISSUE 15, a compiled
    :class:`~stateright_tpu.tpu.matmul_wave.MatmulPlan`) swaps the
    expand stage for the one-hot x transition-table matmul form — in
    the XLA ladder and inside the megakernel alike; everything
    downstream of ``(succ, valid)`` is untouched, so outputs stay
    bit-identical to the vmapped ``step`` path.
    """
    B, F, W = batch_size, dm.max_fanout, dm.state_width
    S = B * F
    K = S if out_rows is None else min(max(1, int(out_rows)), S)
    prop_fns = list(prop_fns)
    dedup = dedup_impl(table_impl, capacity)
    mega = wave_kernel_impl(wave_kernel, dm, B, capacity, use_sym,
                            layout, matmul_plan=matmul_plan)

    def wave(vecs, valid, visited):
        reg = vecs if layout is None else layout.unpack(vecs)
        conds = eval_properties(prop_fns, reg)
        if mega is not None:
            # Single-kernel wave: the successor path runs as one
            # pallas_call on the PACKED rows (in-kernel unpack); only
            # the cheap reductions and the K-row compaction remain out
            # here. succ_count/terminal derive from the kernel's
            # validity mask exactly as expand_frontier derives them.
            (succ_store, path_fps, sflat, new_mask, cand_mask,
             merged) = mega(vecs, valid, visited)
            succ_count = jnp.sum(sflat, dtype=jnp.int64)
            terminal = valid & ~sflat.reshape(B, F).any(axis=1)
            new_count = jnp.sum(new_mask, dtype=jnp.int32)
            cand_count = jnp.sum(cand_mask, dtype=jnp.int32)
            comp = compaction_order(new_mask)[:K]
            # Successor rows leave the kernel already in storage form;
            # the gather moves K packed rows, like the ladder's
            # pack-after-gather moves K packed rows.
            new_vecs = succ_store[comp]
        else:
            succ_flat, sflat, succ_count, terminal = (
                matmul_expand(dm, matmul_plan, reg, valid)
                if matmul_plan is not None
                else expand_frontier(dm, reg, valid))
            dedup_fps, path_fps = fingerprint_successors(
                dm, succ_flat, sflat, use_sym)
            new_mask, new_count, cand_count, merged = dedup(dedup_fps,
                                                            visited)
            # Compact new successors to the front, preserving (frontier
            # row, action) order — the host enqueue order of bfs.rs:262
            # — and gather only the ladder's K rows (packing AFTER the
            # gather: only the K surviving rows pay the codec).
            comp = compaction_order(new_mask)[:K]
            new_vecs = succ_flat[comp]
            if layout is not None:
                new_vecs = layout.pack(new_vecs)
        new_fps = path_fps[comp]
        new_parent = (comp // F).astype(jnp.int32)
        overflow = new_count > K
        conds_out = [c for c in conds if c is not None]
        return (conds_out, succ_count, cand_count, terminal, new_count,
                new_vecs, new_fps, new_parent, new_mask, overflow,
                merged)

    return jax.jit(wave, donate_argnums=(2,))


def build_mux_wave(dm: DeviceModel, batch_size: int, capacity: int,
                   prop_fns=(), use_sym: bool = False,
                   max_jobs: int = 8, layout=None,
                   pack_on: bool = False):
    """The multi-tenant wave program (jitted): one BFS level expansion
    over a batch drawn from SEVERAL jobs' frontiers at once (round 16).

    Input rows carry a trailing tenant lane (``layout`` must be a
    :meth:`~stateright_tpu.tpu.packing.PackedLayout.with_tenant_lane`
    derivation; when ``pack_on`` is False the model part is raw
    ``uint32[W]`` registers and only the tenant word is appended).
    Signature of the returned function::

        mux_wave(vecs: uint32[B, Wr+1], valid: bool[B],
                 tag_fps: uint64[J], visited: uint64[C])
          -> (conds, terminal, seg_succ[J], seg_cand[J], seg_novel[J],
              new_count, new_vecs, new_fps, new_dedup, new_parent,
              merged_visited)

    ``visited`` is donated and SHARED between tenants: each tenant's
    dedup fingerprints are XORed with its 64-bit ``tag_fps`` slot mask
    before probing, so the one open-addressing table holds per-
    (tenant, state) entries and tenants never dedup against each other
    (the shared-table-with-attribution design of arXiv:1004.2772). Path
    fingerprints stay untagged — parent maps and discoveries read real
    state fingerprints; ``new_dedup`` returns the UNtagged dedup
    (representative) fingerprints of the novel rows so the host can
    keep each tenant's visited set for its checkpoint.

    Per-tenant stats come back as segment sums over the tenant lane
    (``seg_succ``/``seg_cand``/``seg_novel``, fixed ``J = max_jobs``
    slots), which is what splits the dispatch-log totals per job.

    Bit-identity with solo runs falls out of the same two properties
    the B-independence suite pins: ``first_occurrence_candidates``
    resolves intra-wave duplicates to the earliest row (tenant rows are
    assembled contiguously in each tenant's own queue order, and
    cross-tenant fps never collide by construction), and
    ``compaction_order`` is stable, so each tenant's novel rows come
    back in exactly the order its solo engine would have enqueued.

    No successor ladder, no megakernel, no multi-wave pipelining here:
    the output rung is always the full ``B*F`` (an overflow path would
    complicate the per-tenant split for no gain at multiplexing's
    target shape — many SMALL frontiers sharing one dispatch)."""
    B, F = batch_size, dm.max_fanout
    S = B * F
    J = int(max_jobs)
    prop_fns = list(prop_fns)
    if layout is None or layout.tenant_lane is None:
        raise ValueError("build_mux_wave needs a tenant-lane layout")

    def mux_wave(vecs, valid, tag_fps, visited):
        slots = jnp.clip(layout.tenant(vecs).astype(jnp.int32), 0,
                         J - 1)
        reg = (layout.unpack(vecs) if pack_on
               else vecs[..., :layout.packed_width - 1])
        conds = eval_properties(prop_fns, reg)
        succ_flat, sflat, _, terminal = expand_frontier(dm, reg, valid)
        if use_sym:
            dedup_raw = device_fp64(jax.vmap(dm.representative)(
                succ_flat))
            path_fps = device_fp64(succ_flat)
        else:
            dedup_raw = device_fp64(succ_flat)
            path_fps = dedup_raw
        flat_slots = jnp.repeat(slots, F)
        tagged = jnp.where(sflat, dedup_raw ^ tag_fps[flat_slots],
                           jnp.uint64(SENTINEL))
        candidate = first_occurrence_candidates(tagged)
        new_mask, new_count, merged = global_insert(
            tagged, candidate, visited, capacity)
        seg_succ = jax.ops.segment_sum(
            sflat.astype(jnp.int64), flat_slots, num_segments=J)
        seg_cand = jax.ops.segment_sum(
            candidate.astype(jnp.int32), flat_slots, num_segments=J)
        seg_novel = jax.ops.segment_sum(
            new_mask.astype(jnp.int32), flat_slots, num_segments=J)
        comp = compaction_order(new_mask)[:S]
        new_reg = succ_flat[comp]
        new_parent = (comp // F).astype(jnp.int32)
        new_slots = slots[new_parent]
        if pack_on:
            new_vecs = layout.pack_tenant(new_reg, new_slots)
        else:
            new_vecs = jnp.concatenate(
                [new_reg, new_slots[:, None].astype(jnp.uint32)],
                axis=-1)
        conds_out = [c for c in conds if c is not None]
        return (conds_out, terminal, seg_succ, seg_cand, seg_novel,
                new_count, new_vecs, path_fps[comp], dedup_raw[comp],
                new_parent, merged)

    return jax.jit(mux_wave, donate_argnums=(3,))


def build_regather(dm: DeviceModel, batch_size: int, out_rows: int,
                   use_sym: bool = False, layout=None,
                   matmul_plan=None):
    """The successor ladder's overflow recovery (jitted, pure): re-runs
    the deterministic expand + fingerprint of the SAME batch and
    compacts with the wave's own novelty mask at a rung that fits::

        regather(vecs: uint32[B, W], valid: bool[B], new_mask: bool[B*F])
          -> (new_vecs, new_fps, new_parent)

    No table access and no novelty decisions happen here — the
    overflowed wave already inserted every novel candidate — so the
    recovered rows are bit-identical to what a full-width wave would
    have emitted (the differential suite pins this). Property
    evaluation and the dedup fingerprints are dead code under XLA DCE:
    only ``path_fps`` and the gather survive."""
    F = dm.max_fanout
    K = min(max(1, int(out_rows)), batch_size * F)

    def regather(vecs, valid, new_mask):
        if layout is not None:
            vecs = layout.unpack(vecs)
        succ_flat, sflat, _, _ = (
            matmul_expand(dm, matmul_plan, vecs, valid)
            if matmul_plan is not None
            else expand_frontier(dm, vecs, valid))
        _, path_fps = fingerprint_successors(dm, succ_flat, sflat,
                                             use_sym)
        comp = compaction_order(new_mask)[:K]
        new_vecs = succ_flat[comp]
        if layout is not None:
            new_vecs = layout.pack(new_vecs)
        return new_vecs, path_fps[comp], (comp // F).astype(
            jnp.int32)

    return jax.jit(regather)


# -- Wave building blocks (shared with the sharded engine) ----------------

def eval_properties(prop_fns, vecs):
    """Property predicates at "pop time" (bfs.rs:192-226); ``None`` slots
    are host-fallback properties."""
    return [None if fn is None else jax.vmap(fn)(vecs) for fn in prop_fns]


def expand_frontier(dm: DeviceModel, vecs, valid):
    """Successor generation with boundary pruning (bfs.rs:231-244).

    Returns ``(succ_flat [B*F, W], valid_flat [B*F], succ_count,
    terminal [B])``; terminal rows have no in-boundary successor
    (bfs.rs:265-272).
    """
    has_boundary = dm.boundary(
        jnp.zeros((dm.state_width,), jnp.uint32)) is not None
    succ, sv = jax.vmap(dm.step)(vecs)
    sv = sv & valid[:, None]
    if has_boundary:
        sv = sv & jax.vmap(jax.vmap(dm.boundary))(succ)
    succ_count = jnp.sum(sv, dtype=jnp.int64)
    terminal = valid & ~sv.any(axis=1)
    s = sv.size
    return succ.reshape(s, dm.state_width), sv.reshape(s), succ_count, terminal


def fingerprint_successors(dm: DeviceModel, succ_flat, valid_flat,
                           use_sym: bool):
    """``(dedup_fps, path_fps)``: under symmetry, dedup by the
    representative's fingerprint but continue paths with the original
    state's (the dfs.rs:258-267 rule). Invalid rows carry the sentinel."""
    if use_sym:
        dedup_fps = device_fp64(jax.vmap(dm.representative)(succ_flat))
        path_fps = device_fp64(succ_flat)
    else:
        dedup_fps = device_fp64(succ_flat)
        path_fps = dedup_fps
    dedup_fps = jnp.where(valid_flat, dedup_fps, jnp.uint64(SENTINEL))
    return dedup_fps, path_fps


def compaction_order(mask):
    """Indices that bring ``mask``'s True rows to the front, both halves
    in original order (what a stable argsort of ~mask computes, via two
    prefix sums instead of a sort)."""
    n = mask.shape[0]
    kept = jnp.cumsum(mask) - 1                 # target slot if True
    dropped = jnp.cumsum(~mask) - 1             # after all kept rows
    total_kept = kept[-1] + 1
    slot = jnp.where(mask, kept, total_kept + dropped)
    return (jnp.zeros((n,), jnp.int32).at[slot].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"))


# Fibonacci mixing constant (2^64 / golden ratio). The *high* bits of
# fp * MIX index the table: under the sharded engine a shard only holds
# fingerprints with a fixed residue mod n_shards, so low bits of fp are
# correlated — the multiply-shift decorrelates the slot from them.
_TABLE_MIX = 0x9E3779B97F4A7C15
# Second mixer for the double-hashing step. The probe sequence is
# home + i*step (step odd, so it tours the whole power-of-two table):
# the while_loop in dedup_and_insert runs for the LONGEST chain among
# all candidates, and linear probing's clusters make that tail long —
# per-key step sequences keep the max chain near the O(log n / log log n)
# balls-in-bins bound instead.
_STEP_MIX = 0xC2B2AE3D27D4EB4F


def _probe_step_host(fps: np.ndarray, capacity: int) -> np.ndarray:
    shift = np.uint64(64 - (capacity.bit_length() - 1))
    with np.errstate(over="ignore"):
        step = ((fps.astype(np.uint64) * np.uint64(_STEP_MIX)) >> shift)
    return (step.astype(np.int64) | 1)


def host_table_insert(table: np.ndarray, fps: np.ndarray) -> None:
    """Inserts fingerprints into a host copy of the open-addressing table
    (vectorized double-hash probing, same slot/step functions as the
    device loop). Any table the host builds this way is a valid probe
    structure for the device: lookup walks the key's own probe sequence
    until the key or a SENTINEL gap. Used for seeding and for growth
    rehashes, where a scalar loop would stall the hot path for seconds
    per doubling."""
    if not len(fps):
        return
    capacity = len(table)
    mask = np.int64(capacity - 1)
    shift = np.uint64(64 - (capacity.bit_length() - 1))
    with np.errstate(over="ignore"):
        idx = ((fps.astype(np.uint64) * np.uint64(_TABLE_MIX))
               >> shift).astype(np.int64)
    step = _probe_step_host(fps, capacity)
    pending = np.ones(len(fps), bool)
    while pending.any():
        cur = table[idx]
        found = pending & (cur == fps)
        empty = pending & (cur == SENTINEL)
        # Claim: numpy fancy-store picks one winner per contended slot;
        # the re-gather tells the losers to advance (same as on device).
        table[idx[empty]] = fps[empty]
        won = empty & (table[idx] == fps)
        pending &= ~(found | won)
        idx = np.where(pending, (idx + step) & mask, idx)


def first_occurrence_candidates(dedup_fps):
    """Intra-wave dedup: True at the EARLIEST frontier-order occurrence
    of each non-sentinel fingerprint, preserving the host BFS enqueue
    order of bfs.rs:262. Shared by the XLA and Pallas table paths —
    their bit-identical-outputs contract starts here.

    Sort-free: a fingerprint's scratch slot is a function of the
    fingerprint alone, so same-fp candidates always collide — a
    scatter-min of the row index resolves one whole fp group per
    contended slot per round (the group containing the slot's smallest
    row; its smallest row is the first occurrence), and unresolved
    groups advance by their fp-derived odd step. The globally smallest
    pending row always wins its slot, so each round retires at least
    one group. Replaced a stable u64 argsort that was ~70% of the
    dedup stage on the XLA CPU backend (22k-row waves: 5.9 of 8.4 ms).
    """
    n = dedup_fps.shape[0]
    m = 1 << max(int(n - 1).bit_length() + 1, 4)  # >= 2n, power of two
    shift = jnp.uint64(64 - (m.bit_length() - 1))
    h0 = ((dedup_fps * jnp.uint64(_TABLE_MIX)) >> shift).astype(jnp.int32)
    step = (((dedup_fps * jnp.uint64(_STEP_MIX)) >> shift)
            .astype(jnp.int32) | 1)  # odd: tours the power-of-two scratch
    rows = jnp.arange(n, dtype=jnp.int32)
    pending0 = dedup_fps != jnp.uint64(SENTINEL)

    def cond(carry):
        _, pending, _ = carry
        return pending.any()

    def body(carry):
        h, pending, first = carry
        scratch = jnp.full((m,), n, jnp.int32).at[
            jnp.where(pending, h, m)].min(rows, mode="drop")
        winner_row = scratch[h]
        winner_fp = dedup_fps[jnp.minimum(winner_row, n - 1)]
        same = pending & (winner_fp == dedup_fps)
        first = first | (same & (winner_row == rows))
        pending = pending & ~same
        h = jnp.where(pending, (h + step) & (m - 1), h)
        return h, pending, first

    _, _, first = jax.lax.while_loop(
        cond, body, (h0, pending0, jnp.zeros((n,), bool)))
    return first


def dedup_and_insert(dedup_fps, visited, capacity: int):
    """First-occurrence + insert-or-test against the open-addressing
    table: the two-level composition of ``first_occurrence_candidates``
    (intra-wave local dedup) and ``global_insert`` (the table probe).
    Returns ``(new_mask, new_count, visited)``. Kept as the reference
    semantics every optimized path (the pallas kernel, the sharded
    sender-side dedup, the ladder regather) is differentially gated
    against; the table rehash programs also reuse it."""
    candidate = first_occurrence_candidates(dedup_fps)
    return global_insert(dedup_fps, candidate, visited, capacity)


def global_insert(dedup_fps, candidate, visited, capacity: int):
    """Insert-or-test of pre-deduplicated candidates against the
    open-addressing table.

    ``candidate`` marks the rows that probe (exactly one per distinct
    non-sentinel fingerprint — the first occurrence — so the
    while_loop's longest-chain cost and the claim contention are paid
    once per distinct candidate, never per duplicate). Each candidate
    gathers its slot; if the slot holds the key it is a revisit; if
    empty, claim it with a scatter and re-gather to see who won (two
    DISTINCT candidates can race for one slot — XLA picks one winner,
    the loser advances). The loop runs until every candidate resolves;
    with load factor <= 1/2 (guaranteed by ``_grow_table``) probe
    chains are O(1) expected, so the per-wave cost never depends on
    table occupancy."""
    sentinel = jnp.uint64(SENTINEL)

    shift = jnp.uint64(64 - (capacity.bit_length() - 1))
    slot_mask = jnp.int32(capacity - 1)
    idx0 = ((dedup_fps * jnp.uint64(_TABLE_MIX)) >> shift).astype(jnp.int32)
    step = (((dedup_fps * jnp.uint64(_STEP_MIX)) >> shift)
            .astype(jnp.int32) | 1)  # odd: tours the power-of-two table

    def cond(carry):
        _, _, pending, _ = carry
        return pending.any()

    def body(carry):
        table, idx, pending, is_new = carry
        cur = table[idx]
        found = pending & (cur == dedup_fps)
        empty = pending & (cur == sentinel)
        # Claim attempt: scatter into empty home slots (out-of-bounds
        # rows drop); the re-gather reveals which candidate won a
        # contended slot.
        table = table.at[jnp.where(empty, idx, capacity)].set(
            dedup_fps, mode="drop")
        won = empty & (table[idx] == dedup_fps)
        is_new = is_new | won
        pending = pending & ~(found | won)
        idx = jnp.where(pending, (idx + step) & slot_mask, idx)
        return table, idx, pending, is_new

    visited, _, _, new_mask = jax.lax.while_loop(
        cond, body,
        (visited, idx0, candidate, jnp.zeros(dedup_fps.shape, bool)))
    return new_mask, jnp.sum(new_mask, dtype=jnp.int32), visited

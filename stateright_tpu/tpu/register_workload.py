"""Reusable device compilation of register workloads under linearizability.

Every storage example in the reference follows one shape
(`actor/register.rs:119-217`): ``S`` servers behind the ``RegisterMsg``
Put/Get interface, ``C`` clients that each Put one value then Get
(round-robin destinations), and a ``LinearizabilityTester`` riding along
as ActorModel history. Round 1 hand-wrote this once, inside the paxos
device model; this module factors the workload-generic pieces so a new
register protocol gets a device form by implementing only its *server*:

- :class:`RegisterWorkloadDevice` — an ``ActorDeviceModel`` base that
  owns the envelope bit layout, the client state machine + history
  recording (`register.rs:174-217`, `register.rs:37-88`), the
  client/history/network host codec, and the two standard properties
  (``linearizable`` on device, ``value chosen``).
- :func:`serialization_tables` + the on-device linearizability predicate
  — the reference's per-state backtracking search
  (`linearizability.rs:178-240`) re-expressed as a static enumeration of
  all per-thread-ordered interleavings (a data-parallel reduction over
  multiset permutations, with all position reasoning precomputed into
  constant tables), valid for the "Put then Get per client" history
  universe.

Envelope bit layout (model-specific fields from bit 15 up):

====  ========  ========================================
bits  field     meaning
====  ========  ========================================
0:3   dst       destination actor index
3:6   src       source actor index
6:10  kind      PUT/GET/PUTOK/GETOK then internal kinds
10:13 req       request id as ``(op-1) << 2 | client``
13:15 value     0 = NO_VALUE else 1 + client index
====  ========  ========================================

Subclass contract: ``SERVER_LANES`` (lane names per server),
``server_deliver(lanes, f) -> (new_lanes, handled, outs)`` (the
delivery's effect on the ``f.dst`` server's pre-gathered lane vector —
the base class gathers it, scatters the result back, and assembles the
body), ``encode_server``/``decode_server`` (host codec), and — if the
protocol has internal messages — ``INTERNAL_KINDS`` +
``encode_internal`` / ``decode_internal``.
"""

from __future__ import annotations

from functools import lru_cache
from itertools import permutations

import numpy as np

import jax
import jax.numpy as jnp

from .actor_device import EMPTY_ENV, ActorDeviceModel

__all__ = ["RegisterWorkloadDevice", "perm_tables",
           "serialization_tables", "PUT", "GET", "PUTOK", "GETOK"]

PUT, GET, PUTOK, GETOK = range(4)

NO_VALUE = "\x00"


@lru_cache(maxsize=None)
def perm_tables(c: int):
    """Static serialization tables for the linearizability reduction: all
    multiset permutations of (thread 0 ×2, ..., thread c-1 ×2), each op's
    occurrence index, and the position of each (thread, op) slot."""
    seen = set()
    perms = []
    for p in permutations([t for t in range(c) for _ in range(2)]):
        if p not in seen:
            seen.add(p)
            perms.append(p)
    perms.sort()
    nc = len(perms)
    thread = np.array(perms, np.int32)                    # [NC, 2c]
    occ = np.zeros_like(thread)
    pos = np.zeros((nc, c, 2), np.int32)
    for i, p in enumerate(perms):
        counts = [0] * c
        for j, t in enumerate(p):
            occ[i, j] = counts[t]
            pos[i, t, counts[t]] = j
            counts[t] += 1
    return thread, occ, pos


@lru_cache(maxsize=None)
def observation_tables(c: int):
    """Constant tables for the gather-form serialization predicate.

    The combo axis is (inclusion mask x permutation), but a state only
    influences a combo through three *tiny* integers per thread —
    which writers are placed (a c-bit set), the thread's read return,
    and its happened-before edges (2 bits per peer) — so everything
    else collapses into lookup tables:

    - ``obs[perm, t, placed_set]``: the value thread t's read observes
      (0 = none): the placed writer with the greatest position before
      the read.
    - ``edge_ok[perm, t, hb]``: no op recorded as completed before t's
      read sits after it in this permutation
      (`linearizability.rs:198-227`).

    The runtime predicate is 2^c * c gathers of [n_perms] vectors from
    these tables — ~5x fewer (and far smaller) device ops than the
    flattened-combo reduction of :func:`serialization_tables`, which is
    kept for the differential test.
    """
    _, _, pos = perm_tables(c)
    nc = pos.shape[0]
    obs = np.zeros((nc, c, 1 << c), np.uint32)
    edge_ok = np.zeros((nc, c, 1 << (2 * c)), bool)
    for perm in range(nc):
        for t in range(c):
            p_read = pos[perm, t, 1]
            for placed in range(1 << c):
                best_pos, v = -1, 0
                for j in range(c):
                    pw = pos[perm, j, 0]
                    if (placed >> j) & 1 and pw < p_read and pw > best_pos:
                        best_pos, v = pw, j + 1
                obs[perm, t, placed] = v
            for hb in range(1 << (2 * c)):
                ok = True
                for j in range(c):
                    if j == t:
                        continue
                    edge = (hb >> (2 * j)) & 3
                    if ((edge >= 1 and pos[perm, j, 0] > p_read)
                            or (edge >= 2 and pos[perm, j, 1] > p_read)):
                        ok = False
                        break
                edge_ok[perm, t, hb] = ok
    return obs, edge_ok


@lru_cache(maxsize=None)
def packed_observation_tables(c: int):
    """Bit-packed (over the permutation axis) observation tables.

    At 4 clients the gather-form predicate moves 8 rows of 2,520 bools
    per (state, mask) — 283 us/state staged on the CPU backend, 144x
    the 3-client cost. Packing the permutation axis into uint64 words
    turns each constraint into one [n_words] gather + AND (n_words =
    ceil(n_perms/64): 40 at C=4, 2 at C=3), ~64x less data movement
    than the bool rows with identical semantics:

    - ``ok_v[t, placed * (c+1) + ret]``: bit p set iff thread t's read
      observes ``ret`` under permutation p with writer set ``placed``.
    - ``edge_pk[t, hb]``: bit p set iff no happened-before edge of
      thread t's read is violated by permutation p.

    Pad bits (beyond n_perms) are zero, so they never make ``any``
    true; rows for inactive constraints are all-ones and drop out of
    the AND.
    """
    obs, edge_ok = observation_tables(c)
    nc = obs.shape[0]
    # uint64 words (requires the engines' x64 mode, which the u64
    # fingerprints already force): half the gather traffic of u32 —
    # the row size is what the C=4 predicate cost scales with.
    nw = (nc + 63) // 64
    word = np.arange(nc) // 64
    bit = np.uint64(1) << (np.arange(nc) % 64).astype(np.uint64)

    def pack(bools):  # [NC] -> [nw]
        out = np.zeros(nw, np.uint64)
        np.bitwise_or.at(out, word[bools], bit[bools])
        return out

    ok_v = np.zeros((c, (1 << c) * (c + 1), nw), np.uint64)
    for t in range(c):
        for placed in range(1 << c):
            for ret in range(c + 1):
                ok_v[t, placed * (c + 1) + ret] = \
                    pack(obs[:, t, placed] == ret)
    edge_pk = np.zeros((c, 1 << (2 * c), nw), np.uint64)
    for t in range(c):
        for hb in range(1 << (2 * c)):
            edge_pk[t, hb] = pack(edge_ok[:, t, hb])
    return ok_v, edge_pk


@lru_cache(maxsize=None)
def serialization_tables(c: int):
    """Static tables for the *restructured* linearizability reduction.

    Instead of walking each permutation sequentially (simulating the
    register op by op), the predicate only needs, for every
    (inclusion-mask, permutation) combo and every reading thread ``t``:

    - which writer threads sit before ``t``'s read, in descending
      position order (the first *placed* one is the value the read
      observes) — ``wbefore[i, t, slot]`` with ``c`` meaning "none";
    - whether peer ``j``'s first/second op sits *after* ``t``'s read
      (``later0/later1[i, t, j]``) — a real-time-edge violation when the
      state's recorded happened-before edge says it completed earlier.

    Everything is independent of the state, so it collapses to constant
    gather tables over one flattened combo axis ``P = 2^c * NC``; the
    runtime predicate is ~10x fewer (and fully fusible) device ops than
    the sequential walk.
    """
    _, _, pos = perm_tables(c)
    nc = pos.shape[0]
    p_total = (1 << c) * nc
    include = np.zeros((p_total, c), bool)
    wbefore = np.zeros((p_total, c, c), np.int32)
    later0 = np.zeros((p_total, c, c), bool)
    later1 = np.zeros((p_total, c, c), bool)
    for mask in range(1 << c):
        for perm in range(nc):
            i = mask * nc + perm
            for t in range(c):
                include[i, t] = bool((mask >> t) & 1)
                p_read = pos[perm, t, 1]
                writers = sorted(
                    (j for j in range(c) if pos[perm, j, 0] < p_read),
                    key=lambda j: -pos[perm, j, 0])
                for slot in range(c):
                    wbefore[i, t, slot] = (writers[slot]
                                           if slot < len(writers) else c)
                for j in range(c):
                    later0[i, t, j] = pos[perm, j, 0] > p_read
                    later1[i, t, j] = pos[perm, j, 1] > p_read
    return include, wbefore, later0, later1


class _EnvFields:
    """Decoded common envelope fields (traced scalars). The value field
    is 2 bits for <= 3 clients (the historical layout) and 3 bits for 4,
    so ``dm`` supplies the layout."""

    __slots__ = ("env", "dst", "src", "kind", "req", "value", "extra")

    def __init__(self, env, dm):
        self.env = env
        self.dst = env & 7
        self.src = (env >> 3) & 7
        self.kind = (env >> 6) & 15
        self.req = (env >> 10) & 7
        self.value = (env >> 13) & dm.value_mask
        self.extra = env >> dm.extra_shift


class RegisterWorkloadDevice(ActorDeviceModel):
    """Base device model for S-servers / C-clients register workloads."""

    #: lane names for one server's state (subclass)
    SERVER_LANES: tuple = ()
    #: names of internal message kinds, assigned codes 4, 5, ... (subclass)
    INTERNAL_KINDS: tuple = ()

    max_out = 1

    def __init__(self, client_count: int, server_count: int, host_cfg,
                 net_slots: int = 0, duplicating: bool = False,
                 lossy: bool = False):
        from .device_model import DeviceFormUnavailable

        if not 1 <= client_count <= 4:
            # The real wall: the req field encodes the client in 2 bits
            # ((op-1)<<2 | client, register.rs:169-196 request-id
            # universe), and 5 clients would unroll 113,400 permutations
            # x 32 in-flight masks into the linearizability reduction.
            # spawn_tpu_bfs catches this and falls back to the host
            # engines, whose LinearizabilityTester + native C++ search
            # have no client bound.
            raise DeviceFormUnavailable(
                "the device envelope encoding and the statically "
                "enumerated linearizability interleavings are sized for "
                "<= 4 clients; larger workloads run on the host engines")
        if server_count > 7 or server_count + client_count > 8:
            raise DeviceFormUnavailable("actor index field is 3 bits")
        if len(self.INTERNAL_KINDS) > 12:
            raise NotImplementedError("kind field is 4 bits (12 internal)")
        self.S = server_count
        self.C = client_count
        # Envelope layout: the value field holds 0..C (0 = NO_VALUE), so
        # 4 clients widen it from the historical 2 bits to 3 and shift
        # the model-specific extra bits up by one.
        self.value_bits = 2 if client_count <= 3 else 3
        self.value_mask = (1 << self.value_bits) - 1
        self.extra_shift = 13 + self.value_bits
        self.host_cfg = host_cfg
        self.duplicating = duplicating
        self.lossy = lossy
        # Fan-out (and so per-wave work) scales with net_slots, so the
        # default tracks measured worst-case occupancy, not a guess: on a
        # non-duplicating network the register workloads peak at ~5
        # in-flight envelopes per client (paxos: 5 @ 1 client, 10 @ 2, 13
        # observed @ 3; ABD/single-copy: 2), so 5C+3 leaves real margin.
        # Broadcast-heavy servers can exceed a per-client bound (one
        # delivery adds up to max_out envelopes), hence the C*(max_out+2)
        # floor — and the engine's overflow lane turns any miss into a
        # hard error naming the fix, never silence. Duplicating networks
        # retain delivered envelopes and need the old generous bound.
        self.net_slots = net_slots or (
            16 * client_count if duplicating
            else max(5 * client_count + 3,
                     client_count * (self.max_out + 2)))
        nsl = len(self.SERVER_LANES)
        self._lane_idx = {n: j for j, n in enumerate(self.SERVER_LANES)}
        self.phase_off = nsl * server_count
        self.hist_off = self.phase_off + client_count
        self.net_offset = self.hist_off + 3 * client_count
        self.state_width = self.net_offset + self.net_slots + 1
        self.error_lane = self.net_offset + self.net_slots
        self._kind_code = {name: 4 + i
                          for i, name in enumerate(self.INTERNAL_KINDS)}

    # -- Packed-row layout (tpu/packing.py) -------------------------------

    def server_lane_bits(self) -> tuple:
        """Bits per server lane, in ``SERVER_LANES`` order (subclass
        hook). The conservative default keeps server lanes unpacked;
        protocols with bounded universes (paxos, ABD, single-copy)
        declare their real widths."""
        return (32,) * len(self.SERVER_LANES)

    def extra_bits(self) -> int:
        """Width of the envelope's model-specific ``extra`` field
        (subclass hook). Without internal kinds nothing writes extra,
        so the default is exact for public-only protocols; protocols
        with internal messages either declare their bound or fall back
        to the full remainder."""
        if not self.INTERNAL_KINDS:
            return 0
        return 32 - self.extra_shift

    def lane_bits(self):
        """The workload-generic packed layout: server lanes from the
        subclass hook, 2-bit client phases, (status, ret, hb) history
        triples, network slots at the real envelope width (+1 bit to
        reserve the all-ones field for ``EMPTY_ENV``), a 1-bit error
        lane. Every bound below mirrors a constant the encoding already
        enforces (the codecs mask by these exact widths)."""
        s_bits = list(self.server_lane_bits())
        env_bits = min(self.extra_shift + self.extra_bits(), 32)
        if env_bits >= 32:
            net_spec = 32
        else:
            net_spec = (env_bits + 1, int(EMPTY_ENV))
        hist = []
        for _ in range(self.C):
            hist += [3,                  # status 0..4
                     self.value_bits,    # get-return value index 0..C
                     2 * self.C]         # hb: 2 bits per peer
        return (s_bits * self.S
                + [2] * self.C           # phases 0..3
                + hist
                + [net_spec] * self.net_slots
                + [1])                   # error/overflow flag lane

    # -- Value universe: 0 = NO_VALUE, 1+k = client k's put value --------

    def value_idx(self, value) -> int:
        if value == NO_VALUE:
            return 0
        return ord(value) - ord("A") + 1

    def value_of(self, idx: int):
        return NO_VALUE if idx == 0 else chr(ord("A") + idx - 1)

    # -- Request ids: request_id = op * actor (`register.rs:169-196`) ----

    def _req_field(self, request_id: int, client_actor: int = None) -> int:
        """``client_actor`` (the Put/Get sender or PutOk/GetOk receiver)
        disambiguates colliding products — e.g. with one server,
        request id 2 is both client 1's op 2 and client 2's op 1."""
        if client_actor is not None:
            op = request_id // client_actor
            if op * client_actor != request_id or op not in (1, 2):
                raise ValueError(
                    f"request id {request_id} not from actor {client_actor}")
            return (op - 1) << 2 | (client_actor - self.S)
        matches = [
            (op, k) for k in range(self.C) for op in (1, 2)
            if op * (self.S + k) == request_id]
        if len(matches) != 1:
            raise ValueError(
                f"request id {request_id} is {'ambiguous' if matches else 'outside the universe'}; "
                "pass the client actor for context")
        op, k = matches[0]
        return (op - 1) << 2 | k

    def _req_id(self, field: int) -> int:
        return ((field >> 2) + 1) * (self.S + (field & 3))

    # -- Envelope codec ---------------------------------------------------

    def build_env(self, *, dst, src, kind, req=0, value=0, extra=0):
        """Device-side envelope construction (all args may be traced)."""
        u = jnp.uint32
        return (u(dst) | u(src) << 3 | u(kind) << 6 | u(req) << 10
                | u(value) << 13 | u(extra) << self.extra_shift)

    def encode_internal(self, inner) -> tuple:
        """Host codec for an ``Internal`` payload → (kind_name, req,
        value, extra). Subclass when INTERNAL_KINDS is nonempty."""
        raise NotImplementedError

    def decode_internal(self, kind_name: str, req: int, value: int,
                        extra: int):
        """Inverse of :meth:`encode_internal`: the inner host message."""
        raise NotImplementedError

    def env_encode(self, envelope) -> int:
        from ..actor.register import Get, GetOk, Internal, Put, PutOk

        msg = envelope.msg
        kind = req = value = extra = 0
        t = type(msg)
        if t is Put:
            kind, req = PUT, self._req_field(msg.request_id,
                                             int(envelope.src))
            value = self.value_idx(msg.value)
        elif t is Get:
            kind, req = GET, self._req_field(msg.request_id,
                                             int(envelope.src))
        elif t is PutOk:
            kind, req = PUTOK, self._req_field(msg.request_id,
                                               int(envelope.dst))
        elif t is GetOk:
            kind, req = GETOK, self._req_field(msg.request_id,
                                               int(envelope.dst))
            value = self.value_idx(msg.value)
        elif t is Internal:
            kind_name, req, value, extra = self.encode_internal(msg.msg)
            kind = self._kind_code[kind_name]
        else:
            raise ValueError(f"unsupported message {msg!r}")
        return (int(envelope.dst) | int(envelope.src) << 3 | kind << 6
                | req << 10 | value << 13 | extra << self.extra_shift)

    def env_decode(self, code: int):
        from ..actor import Id
        from ..actor.model_state import Envelope
        from ..actor.register import Get, GetOk, Internal, Put, PutOk

        dst, src = Id(code & 7), Id((code >> 3) & 7)
        kind = (code >> 6) & 15
        req = (code >> 10) & 7
        value = (code >> 13) & self.value_mask
        extra = code >> self.extra_shift
        if kind == PUT:
            msg = Put(self._req_id(req), self.value_of(value))
        elif kind == GET:
            msg = Get(self._req_id(req))
        elif kind == PUTOK:
            msg = PutOk(self._req_id(req))
        elif kind == GETOK:
            msg = GetOk(self._req_id(req), self.value_of(value))
        else:
            name = self.INTERNAL_KINDS[kind - 4]
            msg = Internal(self.decode_internal(name, req, value, extra))
        return Envelope(src, dst, msg)

    # -- Server lane helpers ----------------------------------------------

    def gather_server(self, vec, dst):
        """All lanes of the (traced) ``dst`` server: ``uint32[n_lanes]``.
        A client ``dst`` clips to server S-1; callers select the client
        branch away via ``is_server``."""
        import jax

        nsl = len(self.SERVER_LANES)
        start = jnp.clip(dst, 0, self.S - 1).astype(jnp.int32) * nsl
        return jax.lax.dynamic_slice(vec, (start,), (nsl,))

    def lane(self, lanes, name: str):
        return lanes[self._lane_idx[name]]

    def with_lane(self, lanes, name: str, value):
        return lanes.at[self._lane_idx[name]].set(jnp.uint32(value))

    def scatter_server(self, vec, dst, lanes):
        """Writes a server's lanes back at (traced) index ``dst`` (clipped
        like :meth:`gather_server`; the caller discards the client case)."""
        import jax

        nsl = len(self.SERVER_LANES)
        start = jnp.clip(dst, 0, self.S - 1).astype(jnp.int32) * nsl
        return jax.lax.dynamic_update_slice(vec, lanes, (start,))

    # -- Subclass surface -------------------------------------------------

    def server_deliver(self, lanes, f: _EnvFields):
        """Applies one delivery to the (traced) ``f.dst`` server, whose
        pre-gathered lane vector is ``lanes: uint32[n_lanes]``. Returns
        ``(new_lanes, handled, outs)`` — the updated lane vector (NOT
        scattered back; the base class installs it) and
        ``outs: uint32[max_out]``."""
        raise NotImplementedError

    def encode_server(self, server_state, vec: np.ndarray,
                      base: int) -> None:
        """Host → lanes for one server (``server_state`` is the *inner*
        state, unwrapped from ``RegisterServerState``)."""
        raise NotImplementedError

    def decode_server(self, vec: np.ndarray, base: int, server_index: int):
        """Lanes → inner host server state."""
        raise NotImplementedError

    # -- Deliver dispatch -------------------------------------------------

    def deliver(self, body, env):
        """Component-wise dispatch: the server branch updates only the
        ``f.dst`` server's lanes, the client branch only the phase and
        history components; the body is reassembled with one concatenate
        (full-width ``.at`` chains were the expand stage's dominant cost,
        see the actor_device module docstring)."""
        f = _EnvFields(env, self)
        is_server = f.dst < self.S
        lanes0 = self.gather_server(body, f.dst)
        srv_lanes, srv_handled, srv_outs = self.server_deliver(lanes0, f)
        (cli_phases, cli_hist, cli_handled,
         cli_outs) = self._client_deliver(body, f)
        servers = body[:self.phase_off]
        phases = body[self.phase_off:self.hist_off]
        hist = body[self.hist_off:self.net_offset]
        # Client deliveries scatter the *original* lanes back: a no-op.
        new_servers = self.scatter_server(
            servers, f.dst, jnp.where(is_server, srv_lanes, lanes0))
        new_body = jnp.concatenate([
            new_servers,
            jnp.where(is_server, phases, cli_phases),
            jnp.where(is_server, hist, cli_hist)])
        return (new_body,
                jnp.where(is_server, srv_handled, cli_handled),
                jnp.where(is_server, srv_outs, cli_outs))

    def _client_deliver(self, body, f: _EnvFields):
        """The round-robin Put-then-Get client (`register.rs:174-217`)
        plus history recording (`register.rs:37-88`): PutOk completes the
        Write and invokes the Read (recording happened-before edges over
        peers' completed ops); GetOk completes the Read with its value.
        Returns ``(new_phases [C], new_hist [3C], handled, outs)``."""
        s, c = self.S, self.C
        u = jnp.uint32
        k = f.dst - s  # client index (underflows for servers; masked off)
        phases = body[self.phase_off:self.hist_off]                  # [c]
        histm = body[self.hist_off:self.net_offset].reshape(c, 3)
        status, rets, hbs = histm[:, 0], histm[:, 1], histm[:, 2]
        phase = phases[jnp.clip(k, 0, c - 1)]
        req_op = (f.req >> 2) + 1
        req_k = f.req & 3
        req_matches = (req_k == k) & (req_op == phase)

        putok_case = (f.kind == PUTOK) & (phase == 1) & req_matches
        getok_case = (f.kind == GETOK) & (phase == 2) & req_matches
        handled = putok_case | getok_case

        is_k = jnp.arange(c, dtype=u) == k                      # [c] bool
        new_phase = jnp.where(putok_case, u(2),
                              jnp.where(getok_case, u(3), phase))
        new_phases = jnp.where(is_k, new_phase, phases)

        # Happened-before edges at Read invoke: the number of completed
        # ops per peer, (len-1)+1 encoded, 2 bits per peer.
        comp = jnp.where(status >= 4, u(2),
                         jnp.where(status >= 2, u(1), u(0)))         # [c]
        hb = jnp.sum(jnp.where(is_k, u(0), comp)
                     << (2 * jnp.arange(c, dtype=u)), dtype=u)
        new_status = jnp.where(
            is_k & putok_case, u(3),  # write done + read in flight
            jnp.where(is_k & getok_case, u(4), status))
        new_rets = jnp.where(is_k & getok_case, f.value, rets)
        new_hbs = jnp.where(is_k & putok_case, hb, hbs)
        new_hist = jnp.stack(
            [new_status, new_rets, new_hbs], axis=1).reshape(3 * c)

        # After PutOk the client Gets from server (actor + op_count) % S
        # (`register.rs:184-196` round-robin with op_count = 1).
        get_out = self.build_env(
            dst=(f.dst + 1) % s, src=f.dst, kind=GET,
            req=(u(1) << 2) | jnp.clip(k, 0, 3).astype(u))
        outs = jnp.full((self.max_out,), EMPTY_ENV, u)
        outs = outs.at[0].set(
            jnp.where(putok_case, get_out, u(EMPTY_ENV)))
        return new_phases, new_hist, handled, outs

    # -- Client-symmetry representative -----------------------------------
    #
    # The only sound client exchangeability for register workloads: the
    # scripted client's destinations are index-derived — Put to
    # ``index % server_count`` and op o to ``(index + o - 1) %
    # server_count`` (`register.rs:169-196`) — so exchanging clients
    # whose indices differ mod S would reroute their messages to
    # different servers and is NOT an automorphism. Clients in the same
    # residue class mod S run bit-identical scripts modulo id-derived
    # payloads (request ids ``op * index``, values ``'A' + k``, history
    # thread keys), so the symmetry group is the product of symmetric
    # groups over the residue classes; the representative takes the
    # lexicographically-minimal encoded vector over that group, with
    # every id-derived payload rewritten. At 3 servers the group is
    # trivial below 4 clients and exactly {id, swap(client 0, client 3)}
    # at 4 — the reduction driver config 5 ("paxos check 4 + symmetry")
    # exercises. No reference pin exists (the reference's paxos example
    # has no symmetry arm); the orbit counts are pinned in MEASUREMENTS.

    def client_permutations(self) -> list:
        """Non-identity client permutations (as ``sigma`` tuples mapping
        old client index -> new) preserving the destination pattern."""
        from itertools import permutations as iperms, product

        cached = getattr(self, "_sym_perms", None)
        if cached is not None:
            return cached
        classes: dict = {}
        for k in range(self.C):
            classes.setdefault(k % self.S, []).append(k)
        per_class = []
        for members in classes.values():
            per_class.append([dict(zip(members, p))
                              for p in iperms(members)])
        identity = tuple(range(self.C))
        sigmas = []
        for combo in product(*per_class):
            sigma = list(range(self.C))
            for mapping in combo:
                for old, new in mapping.items():
                    sigma[old] = new
            if tuple(sigma) != identity:
                sigmas.append(tuple(sigma))
        self._sym_perms = sigmas
        return sigmas

    def sym_extra_tables(self, sigma: tuple, t: dict) -> None:
        """Hook: add model-specific rewrite tables for ``sigma`` to ``t``
        (e.g. proposal/accepted-pair index maps). Default: none."""

    def sym_rewrite_servers(self, servers, t: dict, xp):
        """Hook: rewrite id-derived payloads inside the ``[S, n_lanes]``
        server lanes under the client permutation ``t``. Raises by
        default — an identity default would silently merge inequivalent
        states for any server that stores client-derived data."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement client-symmetry "
            "server rewriting (sym_rewrite_servers)")

    def sym_rewrite_extra(self, kind, extra, t: dict, xp):
        """Hook: rewrite the internal-message ``extra`` bits (vectorized
        over network slots) under ``t``. Default: identity when the
        protocol has no internal kinds; otherwise raises for the same
        reason as :meth:`sym_rewrite_servers`."""
        if not self.INTERNAL_KINDS:
            return extra
        raise NotImplementedError(
            f"{type(self).__name__} does not implement client-symmetry "
            "extra-bit rewriting (sym_rewrite_extra)")

    def sym_rewrite_internal_req(self, kind, req, t: dict, xp):
        """Hook: rewrite the ``req`` field of *internal* kinds under
        ``t`` (public Put/Get/PutOk/GetOk reqs are always client-derived
        and map generically). Identity when there are no internal kinds;
        otherwise the model must choose — e.g. paxos internals leave req
        unused (identity), ABD internals carry real request ids
        (``t["req"]`` map)."""
        if not self.INTERNAL_KINDS:
            return req
        raise NotImplementedError(
            f"{type(self).__name__} does not implement client-symmetry "
            "internal-req rewriting (sym_rewrite_internal_req)")

    def _sym_tables(self) -> list:
        """Per-permutation rewrite tables. Table sizes cover the full
        field ranges (not just the reachable universe) because the
        device path maps garbage rows of invalid successors too — jnp
        gathers clamp, but the tables stay total to keep the numpy host
        path identical."""
        cached = getattr(self, "_sym_tables_cache", None)
        if cached is not None:
            return cached
        c = self.C
        tables = []
        for sigma in self.client_permutations():
            val = np.arange(self.value_mask + 1, dtype=np.uint32)
            for k in range(c):
                val[1 + k] = 1 + sigma[k]
            req = np.arange(8, dtype=np.uint32)
            for r in range(8):
                op_bit, k = r >> 2, r & 3
                if k < c:
                    req[r] = (op_bit << 2) | sigma[k]
            actor = np.arange(8, dtype=np.uint32)
            for k in range(c):
                actor[self.S + k] = self.S + sigma[k]
            inv = np.argsort(np.asarray(sigma))
            t = {"sigma": sigma, "inv": inv, "val": val, "req": req,
                 "actor": actor}
            self.sym_extra_tables(sigma, t)
            tables.append(t)
        self._sym_tables_cache = tables
        return tables

    def _sym_rewrite(self, vec, t: dict, xp):
        """Applies one client permutation to an encoded state —
        ``xp``-generic (jnp on device, np on the host DFS path)."""
        s, c, e = self.S, self.C, self.net_slots
        nsl = len(self.SERVER_LANES)
        servers = vec[:self.phase_off].reshape(s, nsl)
        phases = vec[self.phase_off:self.hist_off]
        hist = vec[self.hist_off:self.net_offset].reshape(c, 3)
        net = vec[self.net_offset:self.net_offset + e]
        tail = vec[self.net_offset + e:]

        inv = t["inv"]  # static numpy: new row j takes old row inv[j]
        val_map = xp.asarray(t["val"])
        req_map = xp.asarray(t["req"])
        actor_map = xp.asarray(t["actor"])

        new_servers = self.sym_rewrite_servers(servers, t, xp)
        new_phases = phases[inv]
        status = hist[inv, 0]
        rets = val_map[xp.minimum(hist[inv, 1], self.value_mask)]
        hb_old = hist[inv, 2]
        hb_new = xp.zeros_like(hb_old)
        for j in range(c):  # new peer j == old peer inv[j]
            hb_new = hb_new | (((hb_old >> (2 * int(inv[j]))) & 3)
                               << (2 * j))
        new_hist = xp.stack([status, rets, hb_new], axis=1)

        dst = net & 7
        src = (net >> 3) & 7
        kind = (net >> 6) & 15
        req = (net >> 10) & 7
        value = (net >> 13) & self.value_mask
        extra = net >> self.extra_shift
        new_extra = self.sym_rewrite_extra(kind, extra, t, xp)
        new_req = xp.where(kind < 4, req_map[req],
                           self.sym_rewrite_internal_req(kind, req, t, xp))
        new_env = (actor_map[dst] | actor_map[src] << 3 | kind << 6
                   | new_req << 10 | val_map[value] << 13
                   | new_extra << self.extra_shift).astype(np.uint32)
        # EMPTY maps to itself by construction (all fields identity at
        # their masks' top values), but garbage extras could perturb it;
        # guard explicitly, then restore the sorted canonical slot form.
        new_net = xp.sort(xp.where(net == np.uint32(EMPTY_ENV),
                                   net, new_env))
        return xp.concatenate([
            new_servers.reshape(s * nsl), new_phases,
            new_hist.reshape(3 * c), new_net, tail])

    def representative(self, vec):
        """Device canonicalizer: lexicographically-minimal encoding over
        the client-symmetry group (identity when the group is trivial).
        Used for visited-set dedup only; paths keep original-state
        fingerprints (the `dfs.rs:258-267` rule). Returns ``None``
        (symmetry unsupported) when the model lacks the rewrite hooks."""
        best = vec
        try:
            for t in self._sym_tables():
                cand = self._sym_rewrite(vec, t, jnp)
                diff = best != cand
                first = jnp.argmax(diff)
                best_le = ~jnp.any(diff) | (best[first] < cand[first])
                best = jnp.where(best_le, best, cand)
        except NotImplementedError:
            return None
        return best

    def host_representative(self, state):
        """Host canonicalizer for ``CheckerBuilder.symmetry_fn``: the
        same partition as :meth:`representative`, via the shared
        encoding (encode -> lexmin rewrite -> decode)."""
        vec = np.asarray(self.encode(state), np.uint32)
        best = vec
        for t in self._sym_tables():
            cand = np.asarray(self._sym_rewrite(vec, t, np), np.uint32)
            for b, cv in zip(best.tolist(), cand.tolist()):
                if cv != b:
                    if cv < b:
                        best = cand
                    break
        return self.decode(best)

    # -- Host state codec -------------------------------------------------

    def encode(self, state) -> np.ndarray:
        s, c = self.S, self.C
        nsl = len(self.SERVER_LANES)
        vec = np.zeros(self.state_width, np.uint32)
        for i in range(s):
            self.encode_server(state.actor_states[i].state, vec, nsl * i)
        for k in range(c):
            cs = state.actor_states[s + k]
            vec[self.phase_off + k] = (3 if cs.awaiting is None
                                       else cs.op_count)
        self._encode_history(state.history, vec)
        vec[self.net_offset:] = self.encode_network(state.network)
        return vec

    def decode(self, vec: np.ndarray):
        from ..actor.model_state import ActorModelState, Network
        from ..actor.register import (RegisterClientState,
                                      RegisterServerState)

        s, c = self.S, self.C
        nsl = len(self.SERVER_LANES)
        actor_states = []
        for i in range(s):
            actor_states.append(RegisterServerState(
                self.decode_server(vec, nsl * i, i)))
        for k in range(c):
            phase = int(vec[self.phase_off + k])
            i = s + k
            if phase == 3:
                cs = RegisterClientState(awaiting=None, op_count=3)
            else:
                cs = RegisterClientState(awaiting=phase * i, op_count=phase)
            actor_states.append(cs)
        return ActorModelState(
            actor_states=actor_states,
            network=Network(self.decode_network(vec[self.net_offset:])),
            is_timer_set=[],
            history=self._decode_history(vec),
        )

    # -- History codec (status, get-ret, hb-edges per client) -------------

    def _encode_history(self, tester, vec: np.ndarray) -> None:
        from ..actor import Id

        s, c = self.S, self.C
        assert tester.is_valid_history, \
            "register workloads cannot produce invalid histories"
        for k in range(c):
            tid = Id(s + k)
            completed = tester.history_by_thread.get(tid, ())
            inflight = tester.in_flight_by_thread.get(tid)
            if len(completed) == 0:
                status = 1 if inflight is not None else 0
            elif len(completed) == 1:
                status = 3 if inflight is not None else 2
            else:
                status = 4
            ret = 0
            if len(completed) == 2:
                ret = self.value_idx(completed[1][2].value)  # ReadOk
            hb = 0
            read_cs = None
            if status == 3:
                read_cs = inflight[0]
            elif status == 4:
                read_cs = completed[1][0]
            if read_cs is not None:
                for peer_tid, last_idx in read_cs:
                    j = int(peer_tid) - s
                    hb |= (last_idx + 1) << (2 * j)
            base = self.hist_off + 3 * k
            vec[base] = status
            vec[base + 1] = ret
            vec[base + 2] = hb

    def _decode_history(self, vec: np.ndarray):
        from ..actor import Id
        from ..semantics import LinearizabilityTester, Register
        from ..semantics.register import Read, ReadOk, Write, WriteOk

        s, c = self.S, self.C
        tester = LinearizabilityTester(Register(NO_VALUE))
        for k in range(c):
            base = self.hist_off + 3 * k
            status = int(vec[base])
            if status == 0:
                continue
            tid = Id(s + k)
            hb = int(vec[base + 2])
            read_cs = tuple(sorted(
                (Id(s + j), ((hb >> (2 * j)) & 3) - 1)
                for j in range(c) if (hb >> (2 * j)) & 3))
            write_entry = ((), Write(self.value_of(k + 1)), WriteOk())
            tester.history_by_thread[tid] = ()
            if status == 1:
                tester.in_flight_by_thread[tid] = \
                    ((), Write(self.value_of(k + 1)))
            else:
                tester.history_by_thread[tid] = (write_entry,)
            if status == 3:
                tester.in_flight_by_thread[tid] = (read_cs, Read())
            elif status == 4:
                ret = ReadOk(self.value_of(int(vec[base + 1])))
                tester.history_by_thread[tid] = (
                    write_entry, (read_cs, Read(), ret))
        return tester

    # -- Properties -------------------------------------------------------

    def device_properties(self):
        c = self.C
        e = self.net_slots
        off = self.net_offset
        hist_off = self.hist_off
        ok_v_t, edge_pk_t = packed_observation_tables(c)
        ok_v = jnp.asarray(ok_v_t)          # [c, 2^c * (c+1), nw]
        edge_pk = jnp.asarray(edge_pk_t)    # [c, 4^c, nw]
        nw = ok_v.shape[-1]

        value_mask = self.value_mask

        def value_chosen(vec):
            net = vec[off:off + e]
            kind = (net >> 6) & 15
            value = (net >> 13) & value_mask
            return jnp.any((net != EMPTY_ENV) & (kind == GETOK)
                           & (value != 0))

        def serialization_search(vec, real_time_edges: bool):
            """The reference's backtracking searches
            (`linearizability.rs:178-240`,
            `sequential_consistency.rs:151-213`) as a static reduction
            over (inclusion-mask x permutation) combos, bit-packed over
            the permutation axis: a state touches a combo only through
            per-thread small integers (placed-writer set, read return,
            happened-before edges), so each constraint is one gather of
            an [n_words] uint64 row from ``packed_observation_tables``
            ANDed into the per-mask accumulator. The mask axis (2^c) is
            unrolled; dropping the edge constraint yields sequential
            consistency."""
            status = jnp.stack(
                [vec[hist_off + 3 * j] for j in range(c)])          # [c]
            rets = jnp.stack(
                [vec[hist_off + 3 * j + 1] for j in range(c)])
            hbs = jnp.stack(
                [vec[hist_off + 3 * j + 2] for j in range(c)])
            completed_w = jnp.uint32(0)
            inflight_w = jnp.uint32(0)
            for j in range(c):
                completed_w = completed_w | \
                    jnp.where(status[j] >= 2, jnp.uint32(1 << j),
                              jnp.uint32(0))
                inflight_w = inflight_w | \
                    jnp.where(status[j] == 1, jnp.uint32(1 << j),
                              jnp.uint32(0))
            ones = jnp.full((nw,), 0xFFFFFFFFFFFFFFFF, jnp.uint64)
            any_ok = jnp.zeros((), bool)
            for mask in range(1 << c):
                placed = (completed_w
                          | (inflight_w & jnp.uint32(mask))).astype(
                              jnp.int32)                # traced scalar
                acc = ones
                for t in range(c):
                    r_completed = status[t] == 4
                    read_placed = r_completed | \
                        ((status[t] == 3) & bool((mask >> t) & 1))
                    row_v = jax.lax.dynamic_index_in_dim(
                        ok_v[t], placed * (c + 1)
                        + rets[t].astype(jnp.int32),
                        axis=0, keepdims=False)
                    acc = acc & jnp.where(r_completed, row_v, ones)
                    if real_time_edges:
                        row_e = jax.lax.dynamic_index_in_dim(
                            edge_pk[t], hbs[t].astype(jnp.int32),
                            axis=0, keepdims=False)
                        acc = acc & jnp.where(read_placed, row_e, ones)
                any_ok = any_ok | jnp.any(acc != 0)
            return any_ok

        return {
            "linearizable":
                lambda vec: serialization_search(vec, True),
            "sequentially consistent":
                lambda vec: serialization_search(vec, False),
            "value chosen": value_chosen,
            # Same predicate under Eventually expectation (the engines
            # apply ebits semantics from the host property list): the
            # liveness config of BASELINE.json.
            "eventually chosen": value_chosen,
        }

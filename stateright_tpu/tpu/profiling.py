"""Wave-time attribution: per-stage device timings for the BFS wave.

The round-3 fused-engine design was motivated by a hand-made breakdown of
where a classic wave spends its time (expand vs probe-insert vs
transfers); this module makes that measurement reproducible and ships it
in the bench JSON (VERDICT r3 weak #6). It drives a real BFS frontier for
a few waves, dispatching each pipeline stage as its OWN jitted program
with ``block_until_ready`` around it:

- ``unpack``: packed storage rows -> uint32 register lanes (the packed
  arena's wave-start codec, ``tpu/packing.py`` — zero for models
  without a ``lane_bits`` layout)
- ``properties``: vmapped property predicates (bfs.rs:192-226)
- ``expand``: vmapped ``step`` + boundary + terminal detection
  (bfs.rs:231-244)
- ``matmul_expand``: the SAME expand contract in matmul form (round
  19, ``tpu/matmul_wave.py``): one-hot key encode, per-group dense
  transition product, uint32 decode. Timed on the same batches so its
  share sits next to ``expand`` (the stage it replaces under the
  ``wave_matmul`` knob) and next to pack/unpack (the other codec
  stages); zero when the transition compiler classifies the model
  irregular.
- ``fingerprint``: murmur3-pair over successors (lib.rs:302-344 analog)
- ``local_dedup``: intra-wave first-occurrence collapse of duplicate
  fingerprints (the pass that thins the candidate stream before the
  global table ever sees it — its own stage since round 7)
- ``dedup_insert``: the open-addressing visited-table probe loop over
  the pre-deduplicated candidates
- ``compact``: new-row compaction + gathers (full successor width; the
  production ladder's K-row win shows up in ``fused_wave_ladder_sec``)
- ``pack``: register lanes -> packed storage rows for the appended
  survivors (the append-side codec; zero without a layout)
- ``wave_kernel``: the single-kernel wave (round 15) — the whole
  unpack→expand→fingerprint→local-dedup→probe/claim→re-pack path as
  ONE ``pallas_call`` (``pallas_table.build_wave_megakernel``), timed
  against its own table copy. Read its share against the SUM of the
  stages it replaces (everything above but ``properties``/``host``),
  not against any single one; zero when the VMEM gate or pallas rules
  it out on this config. Comparing it with ``fused_wave_ladder_sec``
  is how the ladder's K choice is judged against the fused path.
- ``host``: everything between device dispatches (transfers, frontier
  bookkeeping)

Staged dispatches disable XLA's cross-stage fusion/overlap, so the sum
OVERSTATES a fused wave's wall time; the ``fused_wave`` figure times the
production single-program wave (``build_wave``) on the same batches for
the honest total. The per-stage shares are what guide optimization.

Since round 20 the measurement core rides the continuous wave profiler
(``obs/prof.py``): every staged callable AOT-compiles once per
(stage, bucket) — replacing, not doubling, the lazy-jit compile the
warm-up wave always paid — so its XLA cost model (flops, bytes, peak
memory) is captured, every timed dispatch emits a schema-v13
``profile_snapshot`` event with the roofline gauges, and the result
dict carries a per-stage ``roofline`` table next to the second-based
shares. The offline profiler is always armed at cadence 1 (every
dispatch is a sample): this is a measurement run, there is no
production pipeline to perturb.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import tracer_from_env
from ..obs.prof import WaveProfiler
from .engine import (batch_bucket_ladder, build_wave, compaction_order,
                     eval_properties, expand_frontier,
                     fingerprint_successors, first_occurrence_candidates,
                     global_insert, pick_bucket, succ_bucket_ladder)
from .hashing import SENTINEL, host_fp64_batch

__all__ = ["measure_wave_breakdown"]


class _DeadlineHit(Exception):
    """Raised between stage dispatches once ``deadline_s`` is exceeded,
    so even a warm-up (compile-bearing) wave stops at the next stage
    boundary instead of running all its remaining compiles. An XLA
    compile in flight cannot be preempted; a stage boundary is the
    tightest stop this measurement can honor."""


def measure_wave_breakdown(model, device_model=None, batch_size: int = 1024,
                           table_capacity: int = 1 << 20,
                           max_waves: int = 12,
                           deadline_s: Optional[float] = None,
                           max_batch_size: Optional[int] = None) -> Dict:
    """Runs up to ``max_waves`` BFS waves of ``model`` with staged timed
    dispatches; returns ``{stages: {name: sec}, fused_wave_sec, waves,
    states, per_state_us: {...}, bucket_ladder, bucket_waves}``.

    With ``max_batch_size`` set, each wave's dispatch width is picked
    from the live frontier over the same power-of-two bucket ladder the
    engines use (``batch_bucket_ladder``), and ``bucket_waves`` records
    how many timed waves ran at each width — the attribution BENCH_r06
    uses to tie the wave scheduler to the headline. A bucket's
    first-use wave carries its XLA compiles and is excluded from the
    stage accumulators (same principle as excluding wave 0).

    ``deadline_s`` bounds the WHOLE measurement including the warm-up
    waves: the budget is checked at every stage boundary, not just at
    loop top, so a slow first-bucket compile stops at its next stage
    instead of blowing the budget before a single warm wave lands.

    With ``STpu_TRACE`` set, every timed stage (including warm-up
    compiles) is emitted as a span in the shared trace, and the final
    shares land as gauges — the staged breakdown and the engines' wave
    events share one file."""
    dm = device_model
    if dm is None:
        dm = model.device_model()
    F, W = dm.max_fanout, dm.state_width
    ladder = batch_bucket_ladder(batch_size, max_batch_size)
    prop_fns = [fn for fn in dm.device_properties().values()]
    # Packed storage rows (tpu/packing.py): the production engines keep
    # the arena/frontier packed, so the breakdown stages the codec too
    # — pack/unpack must prove themselves amortized (<5% of wave time).
    from .packing import compile_layout

    layout = compile_layout(
        getattr(dm, "lane_bits", lambda: None)(), W)
    packs = layout.packs
    tracer = tracer_from_env("profiling", meta={
        "model": type(model).__name__, "batch_size": batch_size,
        "table_capacity": table_capacity, "max_waves": max_waves})
    # The round-20 sampler, always armed at cadence 1: an offline
    # measurement run has no pipeline to perturb, so every staged
    # dispatch is a sample and emits its profile_snapshot.
    prof = WaveProfiler("profiling", sample_every=1)
    #: per (stage, bucket) AOT-compiled executables — the compile
    #: happens on the excluded warm-up wave, where the lazy jit would
    #: have compiled anyway, and makes the XLA cost model readable.
    stage_progs: Dict[tuple, object] = {}

    # jax.jit specializes per input shape, so one jitted callable per
    # stage serves every bucket; the fused production wave bakes the
    # batch into its program and is cached per (bucket, out-rung)
    # instead.
    j_props = jax.jit(lambda vecs: eval_properties(prop_fns, vecs))
    j_expand = jax.jit(lambda vecs, valid: expand_frontier(dm, vecs, valid))
    # The matmul-form expand (round 19): timed when the transition
    # compiler classifies the model regular, 0.0 otherwise. Output
    # discarded — the staged pipeline downstream stays on the step
    # path, so the two expand implementations time the same inputs.
    from .matmul_wave import classify as matmul_classify
    from .matmul_wave import matmul_expand

    _mm_cls = matmul_classify(dm)
    j_matmul = (jax.jit(lambda vecs, valid: matmul_expand(
        dm, _mm_cls.plan, vecs, valid))
        if _mm_cls.regular else None)
    j_fp = jax.jit(lambda succ, sval: fingerprint_successors(
        dm, succ, sval, False))
    j_local = jax.jit(first_occurrence_candidates)
    j_dedup = jax.jit(
        lambda fps, cand, visited: global_insert(fps, cand, visited,
                                                 table_capacity),
        donate_argnums=(2,))

    def _compact(mask, succ, path_fps):
        comp = compaction_order(mask)
        return succ[comp], path_fps[comp], comp

    j_compact = jax.jit(_compact)
    j_unpack = jax.jit(layout.unpack) if packs else None
    j_pack = jax.jit(layout.pack) if packs else None
    fused_cache: Dict[tuple, object] = {}
    mega_cache: Dict[int, object] = {}

    def mega_for(bucket: int):
        # The single-kernel wave at this bucket (None when the VMEM
        # gate or pallas availability rules it out — the stage then
        # reads 0.0). Gated SILENTLY: nobody requested the megakernel
        # here, so the engines' once-per-shape degrade warning must
        # neither fire nor be consumed by this measurement. The
        # visited copy is donated like j_dedup's.
        if bucket not in mega_cache:
            from .pallas_table import (PALLAS_AVAILABLE,
                                       build_wave_megakernel,
                                       wave_kernel_ok)

            wr = layout.packed_width if packs else W
            mega_cache[bucket] = (
                jax.jit(build_wave_megakernel(
                    dm, bucket, table_capacity,
                    layout=layout if packs else None),
                    donate_argnums=(2,))
                if PALLAS_AVAILABLE and wave_kernel_ok(
                    table_capacity, bucket, F, W, wr)
                else None)
        return mega_cache[bucket]

    def fused_for(bucket: int, out_rows: Optional[int] = None):
        # The production wave in its production storage format: packed
        # inputs/outputs whenever the model declares a layout.
        fn = fused_cache.get((bucket, out_rows))
        if fn is None:
            fn = build_wave(dm, bucket, table_capacity, prop_fns=prop_fns,
                            out_rows=out_rows,
                            layout=layout if packs else None)
            fused_cache[(bucket, out_rows)] = fn
        return fn

    init = np.stack([np.asarray(dm.encode(s), np.uint32)
                     for s in model.init_states()
                     if model.within_boundary(s)])
    frontier = init
    seen = set(host_fp64_batch(init).tolist())
    visited = jnp.full((table_capacity,), jnp.uint64(SENTINEL))
    visited_f = jnp.full((table_capacity,), jnp.uint64(SENTINEL))
    visited_l = jnp.full((table_capacity,), jnp.uint64(SENTINEL))
    visited_k = jnp.full((table_capacity,), jnp.uint64(SENTINEL))

    stage_names = ("unpack", "properties", "expand", "matmul_expand",
                   "fingerprint", "local_dedup", "dedup_insert",
                   "compact", "pack", "wave_kernel", "host")
    stages = {k: 0.0 for k in stage_names}
    bucket_waves: Dict[int, int] = {}
    ladder_waves: Dict[int, int] = {}
    warm_buckets: set = set()
    warm_ladder: set = set()
    fused_sec = 0.0
    fused_ladder_sec = 0.0
    succ_total = 0
    cand_total = 0
    states = 0
    waves = 0
    t_start = time.perf_counter()
    t_host = t_start  # carried across waves: the post-fused tail
    # (output materialization, frontier bookkeeping) accrues into the
    # NEXT wave's "host" stage, as in the pre-adaptive accounting.

    def _over() -> bool:
        return (deadline_s is not None
                and time.perf_counter() - t_start > deadline_s)

    while frontier.shape[0] and waves < max_waves and not _over():
        B = pick_bucket(ladder, frontier.shape[0])
        warmed = B in warm_buckets  # first use carries the compiles
        batch = np.full((B, W), 0, np.uint32)
        n = min(B, frontier.shape[0])
        batch[:n] = frontier[:n]
        frontier = frontier[n:]
        valid = np.zeros((B,), bool)
        valid[:n] = True
        # The batch travels in the production storage format (packed
        # rows when the model has a layout); the staged pipeline pays
        # the unpack as its own timed stage, like the engines do.
        d_store = jnp.asarray(layout.pack_np(batch) if packs else batch)
        d_valid = jnp.asarray(valid)

        wave_stages = {k: 0.0 for k in stage_names}

        def timed(name, fn, *args):
            nonlocal t_host
            pkey = f"profiling|{name}|({B},)"
            prog = stage_progs.get((name, B))
            if prog is None:
                try:
                    prog = fn.lower(*args).compile()
                except Exception:
                    # Non-lowerable path (e.g. an interpret-mode pallas
                    # kernel): run the lazy jit, record null costs.
                    prog = fn
                stage_progs[(name, B)] = prog
                prof.capture(pkey, prog)
            t0 = time.perf_counter()
            wave_stages["host"] += t0 - t_host
            out = prog(*args)
            jax.block_until_ready(out)
            t_host = time.perf_counter()
            wave_stages[name] += t_host - t0
            prof.should_sample(pkey)
            prof.wave({"kernel_path": ("pallas-wave"
                                       if name == "wave_kernel"
                                       else None),
                       "expand_impl": {"expand": "step",
                                       "matmul_expand": "matmul"}.get(
                           name)},
                      pkey, t_host - t0, tracer, None)
            if tracer.enabled:
                tracer.span_event(name, t0, t_host - t0, depth=1,
                                  bucket=B)
            if _over():
                # Deadline at the stage boundary: a compile-bearing
                # warm-up wave must not run its remaining compiles
                # past the budget (the loop-top check alone let one
                # slow first-bucket compile eat the whole allowance).
                raise _DeadlineHit
            return out

        try:
            d_vecs = (timed("unpack", j_unpack, d_store) if packs
                      else d_store)
            timed("properties", j_props, d_vecs)
            succ, sval, succ_count, terminal = timed(
                "expand", j_expand, d_vecs, d_valid)
            if j_matmul is not None:
                # Same expand contract in matmul form, same batch
                # (output discarded; the staged pipeline continues on
                # the step path's outputs either way — bit-identical
                # by the differential suite).
                timed("matmul_expand", j_matmul, d_vecs, d_valid)
            dedup_fps, path_fps = timed("fingerprint", j_fp, succ, sval)
            candidate = timed("local_dedup", j_local, dedup_fps)
            new_mask, new_count, visited = timed(
                "dedup_insert", j_dedup, dedup_fps, candidate, visited)
            new_vecs, new_fps, comp = timed(
                "compact", j_compact, new_mask, succ, path_fps)
            if packs:
                # The append-side codec (timed; output discarded — the
                # host bookkeeping below wants the unpacked rows).
                timed("pack", j_pack, new_vecs)
            mega = mega_for(B)
            if mega is not None:
                # The single-kernel wave on the same batch against its
                # own table copy (same occupancy trajectory as the
                # staged table).
                out_k = timed("wave_kernel", mega, d_store, d_valid,
                              visited_k)
                visited_k = out_k[-1]
        except _DeadlineHit:
            break

        # The honest overlapped total: the production one-program wave
        # on the same batch (its own visited copy, same occupancy).
        t0 = time.perf_counter()
        out = fused_for(B)(d_store, d_valid, visited_f)
        jax.block_until_ready(out)
        t1 = time.perf_counter()
        wave_fused = t1 - t0
        visited_f = out[-1]
        if tracer.enabled:
            tracer.span_event("fused_wave", t0, wave_fused, depth=1,
                              bucket=B)
        if _over():
            break

        k = int(new_count)
        # The production wave under the successor ladder, at the rung
        # covering this wave's novel set (the scheduler's best case) —
        # its delta vs fused_wave_sec is the ladder's attributed win.
        K = pick_bucket(succ_bucket_ladder(B * F), max(k, 1))
        ladder_warm = (B, K) in warm_ladder
        t0 = time.perf_counter()
        out_l = fused_for(B, K)(d_store, d_valid, visited_l)
        jax.block_until_ready(out_l)
        t_host = time.perf_counter()
        wave_ladder = t_host - t0
        visited_l = out_l[-1]
        if tracer.enabled:
            tracer.span_event("fused_wave_ladder", t0, wave_ladder,
                              depth=1, bucket=B, out_rows=K)

        new_vecs = np.asarray(new_vecs[:k])
        new_fps = np.asarray(new_fps[:k])
        fresh = [v for v, f in zip(new_vecs, new_fps.tolist())
                 if f not in seen and not seen.add(f)]
        if fresh:
            frontier = (np.concatenate([frontier, np.stack(fresh)])
                        if frontier.shape[0] else np.stack(fresh))
        if warmed and ladder_warm:
            for name in stage_names:
                stages[name] += wave_stages[name]
            fused_sec += wave_fused
            fused_ladder_sec += wave_ladder
            bucket_waves[B] = bucket_waves.get(B, 0) + 1
            ladder_waves[K] = ladder_waves.get(K, 0) + 1
            succ_total += int(succ_count)
            cand_total += int(np.asarray(candidate).sum())
            states += int(succ_count)
            waves += 1
        else:
            warm_buckets.add(B)
            warm_ladder.add((B, K))

    # Per-stage roofline attribution (round 20, obs/prof.py): the last
    # sampled snapshot per stage — flops/bytes are the XLA cost model
    # of the stage's own compiled program, None where it never AOT'd.
    roofline_by_stage: Dict[str, dict] = {}
    for key, snap in prof.stats()["programs"].items():
        roofline_by_stage[key.split("|")[1]] = {
            f: snap.get(f) for f in ("flops", "bytes", "peak_bytes",
                                     "flops_per_s", "bytes_per_s",
                                     "intensity", "measured_s")}

    staged_total = sum(stages.values())
    per_state = {k: round(1e6 * v / max(states, 1), 2)
                 for k, v in stages.items()}
    if tracer.enabled:
        for name, sec in stages.items():
            tracer.gauge(f"profiling_stage_sec.{name}", round(sec, 6))
        tracer.gauge("profiling_fused_wave_sec", round(fused_sec, 6))
        tracer.gauge("profiling_waves", waves)
        tracer.gauge("profiling_states", states)
    tracer.close()
    return {
        "stages_sec": {k: round(v, 4) for k, v in stages.items()},
        "stages_share": {k: round(v / max(staged_total, 1e-9), 3)
                         for k, v in stages.items()},
        "per_state_us": per_state,
        "fused_wave_sec": round(fused_sec, 4),
        "fused_wave_ladder_sec": round(fused_ladder_sec, 4),
        "staged_total_sec": round(staged_total, 4),
        "waves": waves,
        "states": states,
        "batch_size": batch_size,
        "bucket_ladder": list(ladder),
        "bucket_waves": {str(b): c for b, c in sorted(bucket_waves.items())},
        "ladder_rows_waves": {str(k): c
                              for k, c in sorted(ladder_waves.items())},
        "local_dedup_collapse_ratio": round(
            1.0 - cand_total / max(succ_total, 1), 4) if succ_total
        else 0.0,
        "roofline": roofline_by_stage,
    }

"""Wave-time attribution: per-stage device timings for the BFS wave.

The round-3 fused-engine design was motivated by a hand-made breakdown of
where a classic wave spends its time (expand vs probe-insert vs
transfers); this module makes that measurement reproducible and ships it
in the bench JSON (VERDICT r3 weak #6). It drives a real BFS frontier for
a few waves, dispatching each pipeline stage as its OWN jitted program
with ``block_until_ready`` around it:

- ``properties``: vmapped property predicates (bfs.rs:192-226)
- ``expand``: vmapped ``step`` + boundary + terminal detection
  (bfs.rs:231-244)
- ``fingerprint``: murmur3-pair over successors (lib.rs:302-344 analog)
- ``dedup_insert``: the open-addressing visited-table probe loop
- ``compact``: new-row compaction + gathers
- ``host``: everything between device dispatches (transfers, frontier
  bookkeeping)

Staged dispatches disable XLA's cross-stage fusion/overlap, so the sum
OVERSTATES a fused wave's wall time; the ``fused_wave`` figure times the
production single-program wave (``build_wave``) on the same batches for
the honest total. The per-stage shares are what guide optimization.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .engine import (build_wave, compaction_order, dedup_and_insert,
                     eval_properties, expand_frontier,
                     fingerprint_successors)
from .hashing import SENTINEL, host_fp64_batch

__all__ = ["measure_wave_breakdown"]


def measure_wave_breakdown(model, device_model=None, batch_size: int = 1024,
                           table_capacity: int = 1 << 20,
                           max_waves: int = 12,
                           deadline_s: Optional[float] = None) -> Dict:
    """Runs up to ``max_waves`` BFS waves of ``model`` with staged timed
    dispatches; returns ``{stages: {name: sec}, fused_wave_sec, waves,
    states, per_state_us: {...}}``."""
    dm = device_model
    if dm is None:
        dm = model.device_model()
    B, F, W = batch_size, dm.max_fanout, dm.state_width
    prop_fns = [fn for fn in dm.device_properties().values()]

    j_props = jax.jit(lambda vecs: eval_properties(prop_fns, vecs))
    j_expand = jax.jit(lambda vecs, valid: expand_frontier(dm, vecs, valid))
    j_fp = jax.jit(lambda succ, sval: fingerprint_successors(
        dm, succ, sval, False))
    j_dedup = jax.jit(
        lambda fps, visited: dedup_and_insert(fps, visited, table_capacity),
        donate_argnums=(1,))

    def _compact(mask, succ, path_fps):
        comp = compaction_order(mask)
        return succ[comp], path_fps[comp], comp

    j_compact = jax.jit(_compact)
    fused = build_wave(dm, B, table_capacity, prop_fns=prop_fns)

    init = np.stack([np.asarray(dm.encode(s), np.uint32)
                     for s in model.init_states()
                     if model.within_boundary(s)])
    frontier = init
    seen = set(host_fp64_batch(init).tolist())
    visited = jnp.full((table_capacity,), jnp.uint64(SENTINEL))
    visited_f = jnp.full((table_capacity,), jnp.uint64(SENTINEL))

    stages = {k: 0.0 for k in ("properties", "expand", "fingerprint",
                               "dedup_insert", "compact", "host")}
    fused_sec = 0.0
    states = 0
    waves = 0
    warmed = False
    t_host = time.perf_counter()
    t_start = t_host
    while frontier.shape[0] and waves < max_waves:
        if deadline_s is not None and time.perf_counter() - t_start > deadline_s:
            break
        batch = np.full((B, W), 0, np.uint32)
        n = min(B, frontier.shape[0])
        batch[:n] = frontier[:n]
        frontier = frontier[n:]
        valid = np.zeros((B,), bool)
        valid[:n] = True
        d_vecs = jnp.asarray(batch)
        d_valid = jnp.asarray(valid)

        def timed(name, fn, *args):
            nonlocal t_host
            t0 = time.perf_counter()
            stages["host"] += t0 - t_host
            out = fn(*args)
            jax.block_until_ready(out)
            t_host = time.perf_counter()
            stages[name] += t_host - t0
            return out

        timed("properties", j_props, d_vecs)
        succ, sval, succ_count, terminal = timed(
            "expand", j_expand, d_vecs, d_valid)
        dedup_fps, path_fps = timed("fingerprint", j_fp, succ, sval)
        new_mask, new_count, visited = timed(
            "dedup_insert", j_dedup, dedup_fps, visited)
        new_vecs, new_fps, comp = timed(
            "compact", j_compact, new_mask, succ, path_fps)

        # The honest overlapped total: the production one-program wave
        # on the same batch (its own visited copy, same occupancy).
        t0 = time.perf_counter()
        out = fused(d_vecs, d_valid, visited_f)
        jax.block_until_ready(out)
        fused_sec += time.perf_counter() - t0
        visited_f = out[-1]
        t_host = time.perf_counter()

        k = int(new_count)
        new_vecs = np.asarray(new_vecs[:k])
        new_fps = np.asarray(new_fps[:k])
        fresh = [v for v, f in zip(new_vecs, new_fps.tolist())
                 if f not in seen and not seen.add(f)]
        if fresh:
            frontier = (np.concatenate([frontier, np.stack(fresh)])
                        if frontier.shape[0] else np.stack(fresh))
        states += int(succ_count)
        waves += 1
        if not warmed:
            # Wave 0 carries every stage's XLA compile; steady-state
            # attribution starts after it (like bench.py's _steady_rate).
            warmed = True
            stages = {k: 0.0 for k in stages}
            fused_sec = 0.0
            states = 0
            waves = 0
            t_host = time.perf_counter()

    staged_total = sum(stages.values())
    per_state = {k: round(1e6 * v / max(states, 1), 2)
                 for k, v in stages.items()}
    return {
        "stages_sec": {k: round(v, 4) for k, v in stages.items()},
        "stages_share": {k: round(v / max(staged_total, 1e-9), 3)
                         for k, v in stages.items()},
        "per_state_us": per_state,
        "fused_wave_sec": round(fused_sec, 4),
        "staged_total_sec": round(staged_total, 4),
        "waves": waves,
        "states": states,
        "batch_size": B,
    }

"""The engine-agnostic checkpoint format, shared by every BFS engine.

One (visited fingerprints, pending frontier blocks, discoveries,
fingerprint->parent map) snapshot — written by the device
classic/fused/sharded engines (`tpu/engine.py`) or the native C++ engine
(`checker/native_bfs.py`) — resumes on any of them. This module owns the
version constant, the header validation, and the atomic write, so the
format cannot drift between the writers/readers.

npz payload keys: ``header`` (json as uint8), ``visited`` (uint64 fps),
``pending_vecs``/``pending_fps``/``pending_ebits``, ``parent_child``/
``parent_parent``/``parent_rooted``.
"""

from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["CKPT_VERSION", "make_header", "validate_header",
           "write_atomic"]

CKPT_VERSION = 1


def make_header(*, model_name: str, state_width: int, state_count: int,
                unique_count: int, use_symmetry: bool,
                discoveries: dict) -> np.ndarray:
    """The header payload: json encoded as a uint8 array (npz-friendly).
    ``discoveries`` maps property name -> fingerprint (stringified, since
    json has no uint64)."""
    header = {
        "version": CKPT_VERSION,
        "model": model_name,
        "state_width": state_width,
        "state_count": state_count,
        "unique_count": unique_count,
        "use_symmetry": use_symmetry,
        "discoveries": {k: str(v) for k, v in discoveries.items()},
    }
    return np.frombuffer(json.dumps(header).encode(), np.uint8)


def validate_header(data, *, model_name: str, state_width: int,
                    use_symmetry: bool) -> dict:
    """Parses and validates a loaded checkpoint's header against the
    resuming checker's configuration; returns the header dict."""
    header = json.loads(bytes(data["header"].tobytes()).decode())
    if header["version"] != CKPT_VERSION:
        raise ValueError(
            f"checkpoint version {header['version']} != {CKPT_VERSION}")
    if header["model"] != model_name:
        raise ValueError(
            f"checkpoint is from model {header['model']!r}, not "
            f"{model_name!r}")
    if header["state_width"] != state_width:
        raise ValueError(
            f"checkpoint state_width {header['state_width']} does not "
            f"match this model's {state_width} — wrong model or encoding "
            "changed")
    if header["use_symmetry"] != use_symmetry:
        raise ValueError(
            "checkpoint symmetry setting does not match builder")
    return header


def write_atomic(path: str, payload: dict) -> None:
    """Writes the npz atomically: never a torn checkpoint, and never an
    orphaned temp file when the write itself fails (e.g. disk full)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

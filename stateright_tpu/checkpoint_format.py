"""The engine-agnostic checkpoint format, shared by every BFS engine.

One (visited fingerprints, pending frontier blocks, discoveries,
fingerprint->parent map) snapshot — written by the device
classic/fused/sharded engines (`tpu/engine.py`) or the native C++ engine
(`checker/native_bfs.py`) — resumes on any of them. This module owns the
version constant, the header validation, and the atomic write, so the
format cannot drift between the writers/readers.

npz payload keys: ``header`` (json as uint8), ``visited`` (uint64 fps),
``pending_vecs``/``pending_fps``/``pending_ebits``, ``parent_child``/
``parent_parent``/``parent_rooted``.

Version history:

- **v1**: ``pending_vecs`` is always unpacked ``uint32[n, state_width]``.
- **v2** (round 9): ``pending_vecs`` may be *bit-packed* rows
  (``row_format: "packed"``) when the writing engine stored its arena
  packed (``tpu/packing.py``); the header then self-describes the
  layout (``lane_bits``, ``packed_width``), so any reader — packed or
  not, Python or native — reconstructs the exact unpacked rows via
  :func:`pending_rows`. v1 snapshots still load (no ``row_format`` key
  means ``"u32"``); snapshots newer than this build are refused with a
  clear message instead of a shape mismatch downstream.
"""

from __future__ import annotations

import json
import os

import numpy as np

__all__ = ["CKPT_VERSION", "make_header", "validate_header",
           "pending_rows", "write_atomic"]

CKPT_VERSION = 2


def make_header(*, model_name: str, state_width: int, state_count: int,
                unique_count: int, use_symmetry: bool,
                discoveries: dict, row_format: str = "u32",
                lane_bits=None, packed_width=None) -> np.ndarray:
    """The header payload: json encoded as a uint8 array (npz-friendly).
    ``discoveries`` maps property name -> fingerprint (stringified, since
    json has no uint64). ``state_width`` is always the UNPACKED width
    (the model contract); ``row_format``/``lane_bits``/``packed_width``
    describe how ``pending_vecs`` is stored."""
    if row_format not in ("u32", "packed"):
        raise ValueError(f"unknown row_format {row_format!r}")
    if row_format == "packed" and lane_bits is None:
        raise ValueError(
            "row_format='packed' requires the lane_bits layout so the "
            "checkpoint stays self-describing")
    header = {
        "version": CKPT_VERSION,
        "model": model_name,
        "state_width": state_width,
        "state_count": state_count,
        "unique_count": unique_count,
        "use_symmetry": use_symmetry,
        "discoveries": {k: str(v) for k, v in discoveries.items()},
        "row_format": row_format,
    }
    if row_format == "packed":
        header["lane_bits"] = [list(b) if isinstance(b, (tuple, list))
                               else int(b) for b in lane_bits]
        header["packed_width"] = int(packed_width)
    return np.frombuffer(json.dumps(header).encode(), np.uint8)


def validate_header(data, *, model_name: str, state_width: int,
                    use_symmetry: bool) -> dict:
    """Parses and validates a loaded checkpoint's header against the
    resuming checker's configuration; returns the header dict. Accepts
    every version up to ``CKPT_VERSION`` (v1 headers predate
    ``row_format`` and mean unpacked rows)."""
    header = json.loads(bytes(data["header"].tobytes()).decode())
    if header["version"] > CKPT_VERSION:
        raise ValueError(
            f"checkpoint version {header['version']} is newer than this "
            f"build supports ({CKPT_VERSION}); upgrade before resuming")
    if header["version"] < 1:
        raise ValueError(
            f"checkpoint version {header['version']} is not valid")
    if header["model"] != model_name:
        raise ValueError(
            f"checkpoint is from model {header['model']!r}, not "
            f"{model_name!r}")
    if header["state_width"] != state_width:
        raise ValueError(
            f"checkpoint state_width {header['state_width']} does not "
            f"match this model's {state_width} — wrong model or encoding "
            "changed")
    if header["use_symmetry"] != use_symmetry:
        raise ValueError(
            "checkpoint symmetry setting does not match builder")
    return header


def pending_rows(data, header: dict, state_width: int) -> np.ndarray:
    """The pending frontier rows, UNPACKED (``uint32[n, state_width]``)
    whatever row format the writer stored — the one conversion point
    every resuming engine goes through, so a packed snapshot resumes on
    an unpacked engine (and the native C++ reader) and vice versa."""
    vecs = np.asarray(data["pending_vecs"], np.uint32)
    if header.get("row_format", "u32") == "packed":
        from .tpu.packing import compile_layout

        layout = compile_layout(header["lane_bits"], state_width)
        if vecs.shape[-1] != layout.packed_width:
            raise ValueError(
                f"packed checkpoint rows are {vecs.shape[-1]} words but "
                f"the declared layout packs to {layout.packed_width}")
        vecs = layout.unpack_np(vecs)
    elif vecs.size and vecs.shape[-1] != state_width:
        raise ValueError(
            f"checkpoint pending rows are {vecs.shape[-1]} wide, "
            f"expected state_width {state_width}")
    return np.ascontiguousarray(vecs, np.uint32)


def write_atomic(path: str, payload: dict) -> None:
    """Writes the npz atomically: never a torn checkpoint, and never an
    orphaned temp file when the write itself fails (e.g. disk full)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise

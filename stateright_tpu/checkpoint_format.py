"""The engine-agnostic checkpoint format, shared by every BFS engine.

One (visited fingerprints, pending frontier blocks, discoveries,
fingerprint->parent map) snapshot — written by the device
classic/fused/sharded engines (`tpu/engine.py`) or the native C++ engine
(`checker/native_bfs.py`) — resumes on any of them. This module owns the
version constant, the header validation, the integrity check, and the
atomic write, so the format cannot drift between the writers/readers.

npz payload keys: ``header`` (json as uint8), ``visited`` (uint64 fps),
``pending_vecs``/``pending_fps``/``pending_ebits``, ``parent_child``/
``parent_parent``/``parent_rooted``, and (v3) ``crcs`` (json as uint8:
section name -> CRC32 of the section's raw bytes).

Version history:

- **v1**: ``pending_vecs`` is always unpacked ``uint32[n, state_width]``.
- **v2** (round 9): ``pending_vecs`` may be *bit-packed* rows
  (``row_format: "packed"``) when the writing engine stored its arena
  packed (``tpu/packing.py``); the header then self-describes the
  layout (``lane_bits``, ``packed_width``), so any reader — packed or
  not, Python or native — reconstructs the exact unpacked rows via
  :func:`pending_rows`.
- **v3** (round 10): integrity + rotation. Every section's CRC32 is
  stored in the ``crcs`` payload key and verified on load — a
  corrupted section is rejected with a clear message instead of a
  numpy decode error. :func:`write_atomic` keeps the LAST TWO
  generations (the previous snapshot rotates to ``path + ".prev"``
  before the new one lands), so a torn or corrupted current snapshot
  falls back one generation
  (``resilience.supervisor.newest_valid_checkpoint``).
- **v4** (round 11): per-shard generations for elastic runs. A v4
  header may carry a ``shard`` section (``{"index", "of", "round",
  "epoch"}``) marking the file as ONE partition's snapshot — written
  at :func:`shard_path` with the same sections/CRCs/rotation as a
  whole-run snapshot, so a partition is recoverable *independently*
  (shard migration rebuilds only the lost partition from its newest
  valid generation). A coordinator manifest instead carries an
  ``elastic`` header section (``{"round", "epoch", "partitions",
  "workers"}``) plus the run-global counters; manifest + the shard
  files whose ``round`` matches form one consistent generation.
  Single-file snapshots are UNCHANGED beyond the version stamp — a
  v3-era reader's sections all still exist, and v3 (and older)
  single-shard files still load everywhere, including as adopted
  partitions.

- **v5** (round 13): the tiered state store. A header may carry a
  ``store`` section referencing COLD visited segments **by content
  hash** (``{"segment_dir", "cold": [{"partition", "file", "sha",
  "rows"}]}``): checkpointing a spilled run moves only hot+warm bytes
  — the cold segments already on disk are not rewritten, and resume
  re-attaches them after verifying both the per-section CRCs and the
  referenced hash (a torn current segment falls back to its
  ``.prev`` rotation predecessor when THAT matches). A cold segment
  itself is written through :func:`write_atomic` with
  ``compress=False`` (so its ``visited`` section memory-maps in
  place) and a ``store_segment`` header marker — a segment IS a
  valid checkpoint shard and :func:`verify_file` validates it.
  ``write_atomic`` gained the ``compress`` knob; everything else is
  unchanged beyond the version stamp.

v1-v4 snapshots still load (pre-v3 has no ``crcs`` key and skips
the CRC check); snapshots newer than this build are refused with a
clear message instead of a shape mismatch downstream.
"""

from __future__ import annotations

import hashlib
import json
import os
import zlib

import numpy as np

__all__ = ["CKPT_VERSION", "PREV_SUFFIX", "content_hash", "make_header",
           "shard_path", "validate_header", "verify_sections",
           "verify_file", "load_checkpoint", "pending_rows",
           "write_atomic"]

CKPT_VERSION = 5


def content_hash(arr) -> str:
    """The content hash v5 ``store`` sections reference cold segments
    by: blake2b over the raw section bytes, truncated to 16 hex chars
    (collision space far beyond any run's segment count)."""
    return hashlib.blake2b(
        np.ascontiguousarray(arr).tobytes(), digest_size=8).hexdigest()

#: Where :func:`write_atomic` rotates the previous generation
#: (keep-last-2: a torn current write falls back here).
PREV_SUFFIX = ".prev"


def shard_path(path: str, index: int) -> str:
    """Where partition ``index``'s per-shard generations live, derived
    from the run's checkpoint path (the coordinator manifest). Each
    shard file rotates independently through :func:`write_atomic`, so
    keep-last-2 holds PER SHARD."""
    return f"{path}.shard{int(index):03d}"


def make_header(*, model_name: str, state_width: int, state_count: int,
                unique_count: int, use_symmetry: bool,
                discoveries: dict, row_format: str = "u32",
                lane_bits=None, packed_width=None, shard=None,
                elastic=None, store=None,
                store_segment=None) -> np.ndarray:
    """The header payload: json encoded as a uint8 array (npz-friendly).
    ``discoveries`` maps property name -> fingerprint (stringified, since
    json has no uint64). ``state_width`` is always the UNPACKED width
    (the model contract); ``row_format``/``lane_bits``/``packed_width``
    describe how ``pending_vecs`` is stored.

    v4 extras (both optional): ``shard`` marks a per-partition snapshot
    (``{"index", "of", "round", "epoch"}``); ``elastic`` marks a
    coordinator manifest (``{"round", "epoch", "partitions",
    "workers"}``). ``state_count``/``unique_count`` in a shard header
    are PARTITION-local; the manifest owns the run-global counters.

    v5 extras (both optional): ``store`` references the tiered store's
    cold segments by content hash (see the module docstring);
    ``store_segment`` marks the file as ONE cold segment
    (``{"partition", "rows", "sha"}``) — what makes a segment a valid
    checkpoint shard instead of a bag of fingerprints."""
    if row_format not in ("u32", "packed"):
        raise ValueError(f"unknown row_format {row_format!r}")
    if row_format == "packed" and lane_bits is None:
        raise ValueError(
            "row_format='packed' requires the lane_bits layout so the "
            "checkpoint stays self-describing")
    header = {
        "version": CKPT_VERSION,
        "model": model_name,
        "state_width": state_width,
        "state_count": state_count,
        "unique_count": unique_count,
        "use_symmetry": use_symmetry,
        # Sorted so the header bytes don't depend on discovery ORDER —
        # wave granularity can find two properties in either order, and
        # the round-16 mux-vs-solo byte-identity check needs the same
        # run state to serialize to the same bytes.
        "discoveries": {k: str(discoveries[k])
                        for k in sorted(discoveries)},
        "row_format": row_format,
    }
    if row_format == "packed":
        header["lane_bits"] = [list(b) if isinstance(b, (tuple, list))
                               else int(b) for b in lane_bits]
        header["packed_width"] = int(packed_width)
    if shard is not None:
        header["shard"] = {k: int(v) for k, v in dict(shard).items()}
    if elastic is not None:
        header["elastic"] = {
            k: (list(v) if isinstance(v, (list, tuple)) else int(v)
                if not isinstance(v, str) else v)
            for k, v in dict(elastic).items()}
    if store is not None:
        header["store"] = store
    if store_segment is not None:
        header["store_segment"] = dict(store_segment)
    return np.frombuffer(json.dumps(header).encode(), np.uint8)


def _crc32(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _section_names(data) -> list:
    files = getattr(data, "files", None)
    return list(files) if files is not None else list(data)


def verify_sections(data, where: str = "checkpoint") -> None:
    """Verifies every section listed in the ``crcs`` payload against
    its stored CRC32 (v3+; older snapshots have no ``crcs`` and skip).
    A section that cannot even be decoded (torn write) or whose bytes
    changed (lying disk, partial copy) is rejected with a clear
    message instead of a numpy decode error downstream."""
    if "crcs" not in _section_names(data):
        return
    try:
        crcs = json.loads(bytes(
            np.asarray(data["crcs"]).tobytes()).decode())
    except Exception as e:  # noqa: BLE001 — the crc table itself is torn
        raise ValueError(
            f"{where}: integrity table is unreadable (torn write or "
            f"corruption): {e}") from e
    for key, want in crcs.items():
        try:
            arr = np.asarray(data[key])
        except Exception as e:  # noqa: BLE001 — torn/undecodable section
            raise ValueError(
                f"{where}: section {key!r} is unreadable (torn write "
                f"or corruption): {e}") from e
        got = _crc32(arr)
        if got != int(want):
            raise ValueError(
                f"{where}: section {key!r} failed its CRC32 check "
                f"(stored {int(want):#010x}, computed {got:#010x}) — "
                f"corrupted snapshot; the previous generation "
                f"('{PREV_SUFFIX}' rotation) may still be valid")


def validate_header(data, *, model_name: str, state_width: int,
                    use_symmetry: bool, expect_shard=None) -> dict:
    """Parses and validates a loaded checkpoint's header against the
    resuming checker's configuration; returns the header dict. The
    version gate runs BEFORE the per-section integrity check: a
    genuinely newer snapshot must be refused as "newer than this
    build", not misdiagnosed as corrupt because a future format
    changed what the ``crcs`` table covers. Accepts every version up
    to ``CKPT_VERSION`` (v1 headers predate ``row_format`` and mean
    unpacked rows; v1/v2 predate the CRC table and skip the check)."""
    header = _parse_header(data)
    if header["version"] > CKPT_VERSION:
        raise ValueError(
            f"checkpoint version {header['version']} is newer than this "
            f"build supports ({CKPT_VERSION}); upgrade before resuming")
    if header["version"] < 1:
        raise ValueError(
            f"checkpoint version {header['version']} is not valid")
    verify_sections(data)
    if header["model"] != model_name:
        raise ValueError(
            f"checkpoint is from model {header['model']!r}, not "
            f"{model_name!r}")
    if header["state_width"] != state_width:
        raise ValueError(
            f"checkpoint state_width {header['state_width']} does not "
            f"match this model's {state_width} — wrong model or encoding "
            "changed")
    if header["use_symmetry"] != use_symmetry:
        raise ValueError(
            "checkpoint symmetry setting does not match builder")
    if expect_shard is not None and "shard" in header:
        # A pre-v4 single-shard file has no shard section and is
        # accepted as-is (an adopted partition); a v4 shard header must
        # name the expected partition — loading shard 3's file into
        # partition 5 would silently scramble ownership.
        want_index, want_of = expect_shard
        got = header["shard"]
        if (int(got.get("index", -1)) != int(want_index)
                or int(got.get("of", -1)) != int(want_of)):
            raise ValueError(
                f"checkpoint is partition {got.get('index')}/"
                f"{got.get('of')}, expected {want_index}/{want_of} — "
                "wrong shard file for this partition")
    return header


def _parse_header(data) -> dict:
    """Decodes the json header, wrapping low-level decode failures (a
    torn header section) in the same clear ``ValueError`` family."""
    try:
        return json.loads(bytes(
            np.asarray(data["header"]).tobytes()).decode())
    except Exception as e:  # noqa: BLE001 — torn/undecodable header
        raise ValueError(
            f"checkpoint header is unreadable (torn write or "
            f"corruption): {e}") from e


def verify_file(path: str) -> dict:
    """Integrity-only validation of a checkpoint file (no model-identity
    checks): readable npz, parseable header, acceptable version, every
    section passing its CRC. Returns the header dict; raises
    ``ValueError`` on any corruption. This is what
    ``newest_valid_checkpoint`` probes each generation with."""
    with load_checkpoint(path) as data:
        header = _parse_header(data)
        if header.get("version", 0) > CKPT_VERSION:
            raise ValueError(
                f"checkpoint {path!r} version {header['version']} "
                f"is newer than this build supports ({CKPT_VERSION})")
        verify_sections(data, where=f"checkpoint {path!r}")
    return header


def load_checkpoint(path: str):
    """Opens a checkpoint npz for reading, turning low-level decode
    failures (a torn write is a truncated zip) into the same clear
    ``ValueError`` family the header/CRC checks raise."""
    try:
        return np.load(path)
    except Exception as e:  # noqa: BLE001 — BadZipFile/OSError/...
        raise ValueError(
            f"checkpoint {path!r} is unreadable (torn write or not a "
            f"checkpoint): {e}") from e


def pending_rows(data, header: dict, state_width: int) -> np.ndarray:
    """The pending frontier rows, UNPACKED (``uint32[n, state_width]``)
    whatever row format the writer stored — the one conversion point
    every resuming engine goes through, so a packed snapshot resumes on
    an unpacked engine (and the native C++ reader) and vice versa."""
    vecs = np.asarray(data["pending_vecs"], np.uint32)
    if header.get("row_format", "u32") == "packed":
        from .tpu.packing import compile_layout

        layout = compile_layout(header["lane_bits"], state_width)
        if vecs.shape[-1] != layout.packed_width:
            raise ValueError(
                f"packed checkpoint rows are {vecs.shape[-1]} words but "
                f"the declared layout packs to {layout.packed_width}")
        vecs = layout.unpack_np(vecs)
    elif vecs.size and vecs.shape[-1] != state_width:
        raise ValueError(
            f"checkpoint pending rows are {vecs.shape[-1]} wide, "
            f"expected state_width {state_width}")
    return np.ascontiguousarray(vecs, np.uint32)


def write_atomic(path: str, payload: dict, compress: bool = True) -> None:
    """Writes the npz atomically with keep-last-2 rotation: the previous
    snapshot moves to ``path + PREV_SUFFIX`` just before the new one
    lands, so at every instant at least one complete generation exists
    on disk — a torn current write (crash mid-sequence, injected
    ``torn_ckpt``) falls back one generation. Never leaves an orphaned
    temp file when the write itself fails (e.g. disk full). Every
    section's CRC32 is recorded in the ``crcs`` payload key (format
    v3). ``compress=False`` stores sections raw (ZIP_STORED) — the
    tiered store's cold segments need it so their ``visited`` section
    can be memory-mapped in place (format v5)."""
    from .resilience.faults import InjectedFault, fault_plan_from_env

    payload = dict(payload)
    payload["crcs"] = _crcs_of(payload)
    plan = fault_plan_from_env()
    if (plan.active and np.asarray(payload.get("visited", ())).size
            and plan.fires("ckpt_crc", key="visited")):
        # A lying disk: one section's bytes silently change after the
        # CRC table was computed — the honest CRCs of the original
        # bytes are kept, so only the v3 CRC check at load catches it.
        corrupt = np.array(payload["visited"], copy=True)
        corrupt.reshape(-1)[0] ^= np.asarray(1, corrupt.dtype)
        payload["visited"] = corrupt
    tmp = f"{path}.tmp-{os.getpid()}"
    writer = np.savez_compressed if compress else np.savez
    try:
        with open(tmp, "wb") as f:
            writer(f, **payload)
        if plan.active and plan.fires("torn_ckpt", path=path):
            # The writer "dies" mid-sequence: the previous generation
            # has already rotated and only a truncated prefix of the
            # new snapshot reaches the final path.
            if _rotatable(path):
                os.replace(path, path + PREV_SUFFIX)
            with open(tmp, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(blob[:max(8, len(blob) // 3)])
            os.unlink(tmp)
            raise InjectedFault(
                "checkpoint writer died mid-write (injected torn_ckpt): "
                f"{path!r} holds a truncated snapshot; the previous "
                f"generation is at {path + PREV_SUFFIX!r}")
        if _rotatable(path):
            os.replace(path, path + PREV_SUFFIX)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _rotatable(path: str) -> bool:
    """Whether the current snapshot deserves the ``.prev`` slot. A
    KNOWN-TORN current file (e.g. left behind by a crashed writer the
    supervisor already fell back from) must NOT rotate over the good
    previous generation — that would destroy the only valid fallback,
    and a crash between the rotation and the final rename would leave
    ZERO complete generations on disk. The check is the cheap
    structural one (intact zip container with a header member), not
    the full CRC pass: it runs on every periodic write."""
    if not os.path.exists(path):
        return False
    import zipfile

    try:
        with zipfile.ZipFile(path) as z:
            z.getinfo("header.npy")
        return True
    except Exception:  # noqa: BLE001 — BadZipFile/KeyError/OSError
        return False


def _crcs_of(payload: dict) -> np.ndarray:
    crcs = {key: _crc32(np.asarray(value))
            for key, value in payload.items() if key != "crcs"}
    return np.frombuffer(json.dumps(crcs).encode(), np.uint8)

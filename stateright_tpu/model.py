"""The core ``Model`` abstraction and ``Property`` predicates.

Counterpart of the reference's `src/lib.rs:155-300`. A ``Model`` describes a
nondeterministic transition system: initial states, enabled actions per
state, and a (partial) transition function. Properties are named predicates
with an expectation — ``ALWAYS`` (safety; the checker hunts a
counterexample), ``SOMETIMES`` (reachability; the checker hunts an example),
or ``EVENTUALLY`` (liveness; a counterexample is a terminal path that never
satisfies the predicate — only sound on acyclic state graphs, see
`lib.rs:263-267`).

Models whose transition functions are additionally expressible as JAX
functions over an encoded fixed-width state vector can opt into the TPU
engine; see ``stateright_tpu.tpu``.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from pprint import pformat
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

State = TypeVar("State")
Action = TypeVar("Action")

__all__ = ["Model", "Property", "Expectation"]


class Expectation(Enum):
    """Whether a property is always, eventually, or sometimes true (lib.rs:290-300)."""

    ALWAYS = "always"
    EVENTUALLY = "eventually"
    SOMETIMES = "sometimes"


@dataclass(frozen=True)
class Property:
    """A named predicate over (model, state) with an expectation (lib.rs:244-279)."""

    expectation: Expectation
    name: str
    condition: Callable[[Any, Any], bool]

    @staticmethod
    def always(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        """A safety invariant; the checker will try to find a counterexample."""
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        """A liveness property; a counterexample is a terminal path never
        satisfying the condition. Only sound on acyclic paths (lib.rs:263-267)."""
        return Property(Expectation.EVENTUALLY, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        """A reachability property; the checker will try to find an example."""
        return Property(Expectation.SOMETIMES, name, condition)


class Model(Generic[State, Action]):
    """The primary abstraction: a nondeterministic transition system
    (lib.rs:155-237). Subclass and implement ``init_states``, ``actions``,
    and ``next_state``; optionally ``properties``, ``within_boundary``, and
    the explorer formatting hooks.

    A minimal sliding-tile puzzle, in the spirit of the reference's API
    doc example (`lib.rs:40-116`):

    >>> from stateright_tpu import Model, Property
    >>> class Puzzle(Model):
    ...     '''Slide the blank (0) until the board reads (0, 1, 2).'''
    ...     def init_states(self):
    ...         return [(1, 2, 0)]
    ...     def actions(self, state, actions):
    ...         actions += ["slide left", "slide right"]
    ...     def next_state(self, s, a):
    ...         b = s.index(0)
    ...         j = b - 1 if a == "slide left" else b + 1
    ...         if not 0 <= j < len(s):
    ...             return None  # the action is ignored at the edge
    ...         t = list(s)
    ...         t[b], t[j] = t[j], t[b]
    ...         return tuple(t)
    ...     def properties(self):
    ...         return [Property.sometimes(
    ...             "solved", lambda model, s: s == (0, 1, 2))]
    >>> checker = Puzzle().checker().spawn_bfs().join()
    >>> checker.assert_properties()
    >>> checker.discovery("solved").into_actions()  # shortest (BFS)
    ['slide left', 'slide left']
    >>> checker.unique_state_count()
    3
    """

    def init_states(self) -> List[State]:
        """Returns the initial possible states."""
        raise NotImplementedError

    def actions(self, state: State, actions: List[Action]) -> None:
        """Appends the enabled actions for ``state`` to ``actions``."""
        raise NotImplementedError

    def next_state(self, last_state: State, action: Action) -> Optional[State]:
        """Applies ``action``; ``None`` indicates the action is ignored."""
        raise NotImplementedError

    def properties(self) -> List[Property]:
        """The expected properties of this model."""
        return []

    def within_boundary(self, state: State) -> bool:
        """Whether ``state`` is inside the state space to be checked (pruning)."""
        return True

    # -- Explorer / formatting hooks -------------------------------------

    def format_action(self, action: Action) -> str:
        return _fmt(action)

    def format_step(self, last_state: State, action: Action) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else pformat(next_state)

    def as_svg(self, path) -> Optional[str]:
        """Returns an SVG rendering of a path, if the model supports one."""
        return None

    # -- Derived helpers (lib.rs:191-225) --------------------------------

    def next_steps(self, last_state: State) -> List[Tuple[Action, State]]:
        """The (action, state) pairs that follow a particular state."""
        actions: List[Action] = []
        self.actions(last_state, actions)
        steps = []
        for action in actions:
            next_state = self.next_state(last_state, action)
            if next_state is not None:
                steps.append((action, next_state))
        return steps

    def next_states(self, last_state: State) -> List[State]:
        """The states that follow a particular state."""
        actions: List[Action] = []
        self.actions(last_state, actions)
        states = []
        for action in actions:
            next_state = self.next_state(last_state, action)
            if next_state is not None:
                states.append(next_state)
        return states

    def property(self, name: str) -> Property:
        """Looks up a property by name; raises if absent (lib.rs:218-225)."""
        for p in self.properties():
            if p.name == name:
                return p
        available = [p.name for p in self.properties()]
        raise KeyError(f"Unknown property. requested={name}, available={available}")

    def checker(self) -> "CheckerBuilder":
        """Instantiates a ``CheckerBuilder`` for this model."""
        from .checker.builder import CheckerBuilder

        return CheckerBuilder(self)


def _fmt(value: Any) -> str:
    """Debug-style formatting: Enum members print as their bare name."""
    if isinstance(value, Enum):
        return value.name
    return repr(value)

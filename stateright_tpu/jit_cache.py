"""Persistent XLA executable cache, shared by tests, bench, and the
driver entry points.

The wave programs of the big actor models take tens of seconds to
compile; the cache (default: ``.jax_cache/`` at the repo root,
gitignored) lets warm runs skip them entirely. Enabling the cache is an
optimization and must never be a failure.

Cache entries are keyed by a *host-profile fingerprint* subdirectory:
XLA:CPU AOT artifacts embed the build machine's CPU features, and a
cache populated under one profile served to another triggers the
loader's "could lead to execution errors such as SIGILL" warnings (seen
in BENCH_r03.json when the bench machine differed from the machine that
warmed the cache). Scoping the directory by (machine, CPU flags, jax
version) makes a profile change a cold cache instead of a latent crash.
"""

from __future__ import annotations

import hashlib
import os
import platform

__all__ = ["enable_persistent_jit_cache", "host_profile_fingerprint"]

#: compiles cheaper than this aren't worth the disk round-trip
_MIN_COMPILE_SECS = 0.5


def host_profile_fingerprint() -> str:
    """A short stable hash of the machine profile that affects compiled
    artifact compatibility: architecture, CPU feature flags, jax/jaxlib
    versions."""
    parts = [platform.machine(), platform.system()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(line.split(":", 1)[1].strip())
                    break
    except OSError:
        pass
    try:
        import jax
        import jaxlib

        parts.append(jax.__version__)
        parts.append(jaxlib.__version__)
    except Exception:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def enable_persistent_jit_cache(cache_dir: str | None = None) -> None:
    try:
        import jax

        if cache_dir is None:
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache")
        cache_dir = os.path.join(cache_dir, host_profile_fingerprint())
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          _MIN_COMPILE_SECS)
    except Exception:
        pass

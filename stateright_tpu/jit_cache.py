"""Persistent XLA executable cache, shared by tests, bench, and the
driver entry points.

The wave programs of the big actor models take tens of seconds to
compile; the cache (default: ``.jax_cache/`` at the repo root,
gitignored) lets warm runs skip them entirely. Enabling the cache is an
optimization and must never be a failure — in particular it must never
*initialize* a JAX backend (on a wedged TPU tunnel that is an unbounded
hang, which is exactly what ``bench.py``'s subprocess probe exists to
avoid), so the platform is sniffed from config/env or passed by the
caller.

Two hazards shape the policy:

- Cache entries are scoped by a *host-profile fingerprint* subdirectory
  (machine, CPU flags, jax version): artifacts from a genuinely
  different machine profile become a cold cache instead of a latent
  crash.
- On the **CPU backend the cache is disabled unconditionally**: beyond
  the loader's "could lead to execution errors such as SIGILL" warning
  (XLA:CPU AOT artifacts embed compile-time pseudo-features like
  ``+prefer-no-scatter`` that never appear in the host-feature list),
  cache-deserialized CPU executables were observed to **mishandle
  donated buffers**: the engines' donated visited-table/arena chain
  read back with stale slots, zeros, and heap-pointer garbage while
  counts stayed right — silent checkpoint corruption (reproduced on the
  round-5 engine as well, 2026-08-03). Every device engine donates by
  design, so the old ``STATERIGHT_TPU_FORCE_JIT_CACHE=1`` escape hatch
  now refuses on CPU with a warning instead of corrupting.
"""

from __future__ import annotations

import hashlib
import os
import platform as _platform_mod
import threading

__all__ = ["enable_persistent_jit_cache", "host_profile_fingerprint",
           "WaveProgramCache", "shared_program_cache"]

#: compiles cheaper than this aren't worth the disk round-trip
_MIN_COMPILE_SECS = 0.5


class WaveProgramCache:
    """In-process cache of compiled wave programs, shared across engine
    INSTANCES — the job service's amortization layer (ROADMAP item 5:
    the Nth submission of a hot model skips compilation entirely).

    The persistent cache above amortizes compiles across *processes*
    via serialized XLA artifacts (and is refused on CPU — see the
    module doc); this one shares the live compiled callables within a
    process, which is safe on every backend: nothing is serialized, the
    second engine simply calls the same executable the first one built.
    Donation is per-call state, not per-program state, so two engines
    sharing a program each donate their own buffers.

    Keys must capture everything that affects the traced computation:
    the caller prefixes the engine's shape/knob key with a *model key*
    (the corpus registry name + canonical params) — two engines may
    share a program only when their device models are semantically
    identical, which is exactly what a registry key certifies. Ad-hoc
    models (no registry key) never reach this cache. Path-selection
    knobs ride in the key too (``table_impl``, ``pack_arena``,
    ``wave_kernel``): a megakernel program and an XLA-ladder program
    are different executables even at identical shapes.

    ``get_or_build`` holds a per-key lock across the build, so N
    concurrent same-model jobs pay ONE compile and N-1 hits instead of
    racing N compiles into the same slot (the acceptance gate observes
    the second job's hit deterministically).

    The cache is bounded (``max_programs``, FIFO eviction): keys embed
    tenant-settable knobs (batch/table shapes; every capacity doubling
    adds an entry), so an unbounded dict would grow process memory for
    the service's lifetime. Eviction only drops the CACHE's reference
    — engines keep the executables they already fetched in their
    instance caches, so a running job never loses its programs.
    """

    def __init__(self, max_programs: int = 256):
        self._programs: dict = {}
        self._locks: dict = {}
        self._mu = threading.Lock()
        self._max = max(1, int(max_programs))
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_build(self, key, build):
        """Returns ``(program, hit)``; ``build()`` runs at most once per
        key across every thread."""
        with self._mu:
            prog = self._programs.get(key)
            if prog is not None:
                self.hits += 1
                return prog, True
            lock = self._locks.setdefault(key, threading.Lock())
        with lock:
            with self._mu:
                prog = self._programs.get(key)
                if prog is not None:
                    self.hits += 1
                    return prog, True
            prog = build()
            with self._mu:
                self._programs[key] = prog
                self.misses += 1
                while len(self._programs) > self._max:
                    oldest = next(iter(self._programs))
                    del self._programs[oldest]
                    self._locks.pop(oldest, None)
                    self.evictions += 1
        return prog, False

    def stats(self) -> dict:
        with self._mu:
            return {"programs": len(self._programs),
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "hit_ratio": round(
                        self.hits / max(1, self.hits + self.misses), 4)}


_SHARED_CACHE: WaveProgramCache | None = None
_SHARED_CACHE_MU = threading.Lock()


def shared_program_cache() -> WaveProgramCache:
    """The process-wide wave-program cache (lazily created); the job
    service hands this to every engine it spawns."""
    global _SHARED_CACHE
    with _SHARED_CACHE_MU:
        if _SHARED_CACHE is None:
            _SHARED_CACHE = WaveProgramCache()
        return _SHARED_CACHE


def host_profile_fingerprint() -> str:
    """A short stable hash of the machine profile that affects compiled
    artifact compatibility: architecture, CPU feature flags, jax/jaxlib
    versions."""
    parts = [_platform_mod.machine(), _platform_mod.system()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(line.split(":", 1)[1].strip())
                    break
    except OSError:
        pass
    try:
        import jax
        import jaxlib

        parts.append(jax.__version__)
        parts.append(jaxlib.__version__)
    except Exception:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _sniff_platform():
    """The configured platform WITHOUT initializing a backend (a wedged
    TPU tunnel makes backend init an unbounded hang). None = unknown."""
    try:
        import jax

        configured = jax.config.jax_platforms
        if configured:
            return configured.split(",")[0]
    except Exception:
        pass
    env = os.environ.get("JAX_PLATFORMS", "")
    return env.split(",")[0] if env else None


def enable_persistent_jit_cache(cache_dir: str | None = None,
                                platform: str | None = None,
                                force: bool = False) -> None:
    """Enables the cache unless the backend is (or may be) XLA:CPU —
    see the module doc. On CPU the cache is refused even with
    ``force=True`` / ``STATERIGHT_TPU_FORCE_JIT_CACHE=1``: deserialized
    CPU executables corrupt donated buffers (module doc), and every
    device engine donates. An unknown platform counts as CPU, the safe
    default."""
    try:
        import jax

        forced = force or \
            os.environ.get("STATERIGHT_TPU_FORCE_JIT_CACHE", "") not in \
            ("", "0")
        if platform is None:
            platform = _sniff_platform()
        if platform in (None, "cpu"):
            if forced:
                import warnings

                warnings.warn(
                    "persistent jit cache refused on the CPU backend: "
                    "cache-deserialized XLA:CPU executables corrupt "
                    "donated buffers (see jit_cache.py); running with "
                    "cold compiles instead", RuntimeWarning,
                    stacklevel=2)
            return
        if cache_dir is None:
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache")
        cache_dir = os.path.join(cache_dir, host_profile_fingerprint())
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          _MIN_COMPILE_SECS)
    except Exception:
        pass

"""Persistent XLA executable cache, shared by tests, bench, and the
driver entry points.

The wave programs of the big actor models take tens of seconds to
compile; the cache (default: ``.jax_cache/`` at the repo root,
gitignored) lets warm runs skip them entirely. Enabling the cache is an
optimization and must never be a failure.
"""

from __future__ import annotations

import os

__all__ = ["enable_persistent_jit_cache"]

#: compiles cheaper than this aren't worth the disk round-trip
_MIN_COMPILE_SECS = 0.5


def enable_persistent_jit_cache(cache_dir: str | None = None) -> None:
    try:
        import jax

        if cache_dir is None:
            cache_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          _MIN_COMPILE_SECS)
    except Exception:
        pass

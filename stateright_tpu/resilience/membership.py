"""Membership and ownership for elastic sharded runs.

Two small, dependency-light pieces (no jax — the coordinator, the
worker processes, and the single-process sharded engines all import
this):

- :class:`OwnerMap` — an epoch-versioned assignment of **fixed logical
  partitions** to **owners**. The partition function (``fp %
  n_partitions``) never changes over a run, so BFS results are
  independent of which owner currently hosts a partition; only the
  assignment moves, and every move bumps the ``epoch`` so exchange
  routing can tell pre- and post-migration maps apart. Assignment is
  *rendezvous hashing* (highest-random-weight): each partition goes to
  the owner with the largest keyed hash, so losing an owner moves ONLY
  that owner's partitions (to survivors it already "loses" to) and a
  joining owner steals only the partitions it now wins — the minimal
  migration set, with no central ring state to persist.
- :class:`Membership` — the coordinator's heartbeat-lease table. A
  worker is *live* while its lease (last heartbeat + ``lease_s``)
  holds; an expired lease is the ``worker_lost`` signal that triggers
  migration rather than aborting the run (a dead socket reports
  through the same path, just sooner).

The single-process sharded engines use the **identity** owner map
(partition ``p`` lives on shard ``p`` of the mesh) so their device
routing stays the raw ``fp % n`` modulo; the elastic runtime uses
rendezvous maps over worker names. Both share the same epoch
discipline: an ownership change is only applied at an exchange-drained
rest point, and the epoch bump is what invalidates any cached routing
derived from the old map.
"""

from __future__ import annotations

import hashlib
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["OwnerMap", "Membership", "EpochOwnership",
           "rendezvous_weight"]


def rendezvous_weight(partition: int, owner: str) -> int:
    """The keyed highest-random-weight score of ``(partition, owner)``:
    deterministic across processes and Python runs (no PYTHONHASHSEED
    dependence — migration decisions made by the coordinator must be
    reproducible by a test and by a resumed coordinator)."""
    digest = hashlib.blake2b(f"{partition}:{owner}".encode(),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big")


class OwnerMap:
    """An immutable epoch-versioned partition->owner assignment.

    ``owners`` are opaque identifiers (worker names for the elastic
    runtime, shard indices for the single-process engines). Derive new
    maps with :meth:`with_owners` — the epoch always advances, and
    :meth:`moves_from` reports exactly which partitions changed hands
    (the migration set).
    """

    __slots__ = ("n_partitions", "owners", "epoch", "_assign")

    def __init__(self, n_partitions: int, owners: Iterable,
                 epoch: int = 0, assignment: Optional[list] = None):
        owners = tuple(owners)
        if not owners:
            raise ValueError("an OwnerMap needs at least one owner")
        if n_partitions < 1:
            raise ValueError("n_partitions must be >= 1")
        self.n_partitions = int(n_partitions)
        self.owners = owners
        self.epoch = int(epoch)
        if assignment is not None:
            assignment = list(assignment)
            if len(assignment) != self.n_partitions:
                raise ValueError(
                    f"assignment covers {len(assignment)} partitions, "
                    f"expected {self.n_partitions}")
            unknown = set(assignment) - set(owners)
            if unknown:
                raise ValueError(
                    f"assignment names unknown owners {sorted(map(str, unknown))}")
            self._assign = assignment
        else:
            self._assign = [
                max(owners,
                    key=lambda w, p=p: rendezvous_weight(p, str(w)))
                for p in range(self.n_partitions)]

    # -- Construction ------------------------------------------------------

    @classmethod
    def identity(cls, n: int) -> "OwnerMap":
        """Partition ``p`` owned by shard ``p`` — the single-process
        sharded engines' map (device routing stays raw ``fp % n``)."""
        return cls(n, range(n), epoch=0, assignment=list(range(n)))

    def with_owners(self, owners: Iterable) -> "OwnerMap":
        """A NEW map over ``owners`` (rendezvous assignment), one epoch
        later. Use for both loss (drop the dead owner) and join (add
        the new one)."""
        return OwnerMap(self.n_partitions, owners, epoch=self.epoch + 1)

    def with_assignment(self, assignment: list) -> "OwnerMap":
        """A NEW map with an explicit assignment (e.g. a permutation on
        the single-process engines), one epoch later."""
        return OwnerMap(self.n_partitions, self.owners,
                        epoch=self.epoch + 1, assignment=assignment)

    # -- Lookup ------------------------------------------------------------

    @property
    def is_identity(self) -> bool:
        """Whether owner-of-partition is the identity on 0..n-1 (the
        device fast path: routing is the raw modulo, no gather)."""
        return self._assign == list(range(self.n_partitions))

    def partition_of(self, fp: int) -> int:
        return int(fp) % self.n_partitions

    def owner_of(self, partition: int):
        return self._assign[partition]

    def owner(self, fp: int):
        return self._assign[int(fp) % self.n_partitions]

    def partitions_of(self, owner) -> Tuple[int, ...]:
        return tuple(p for p, w in enumerate(self._assign) if w == owner)

    def assignment(self) -> List:
        return list(self._assign)

    def moves_from(self, old: "OwnerMap") -> Dict[int, tuple]:
        """``{partition: (old_owner, new_owner)}`` for every partition
        that changes hands going ``old`` -> ``self`` — the migration
        set an epoch bump must transfer before routing resumes."""
        if old.n_partitions != self.n_partitions:
            raise ValueError("owner maps over different partition counts")
        return {p: (old._assign[p], self._assign[p])
                for p in range(self.n_partitions)
                if old._assign[p] != self._assign[p]}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"OwnerMap(n={self.n_partitions}, epoch={self.epoch}, "
                f"owners={self.owners!r})")


class EpochOwnership:
    """Mixin for the single-process sharded engines: the epoch-aware
    ``_owner()`` surface over a ``self._owner_map`` the engine's
    ``__init__`` sets to :meth:`OwnerMap.identity`. One implementation
    for both sharded engines (the round-6..10 lesson: no fourth copy).

    The engines bake the assignment into their compiled wave programs
    and key their wave caches by ``owner_epoch``, so a remap can never
    dispatch stale routing; :meth:`set_owner_assignment` is only legal
    at a stopped rest point, which is the single-process engines'
    exchange-drained barrier (between dispatches every all-to-all has
    completed and every received row is queued — there is no
    in-flight exchange to mis-route)."""

    def _owner(self, fp: int) -> int:
        """The shard owning fingerprint ``fp`` under the CURRENT
        epoch's assignment (identity unless remapped)."""
        return int(self._owner_map.owner(int(fp)))

    @property
    def owner_epoch(self) -> int:
        return self._owner_map.epoch

    def set_owner_assignment(self, assignment) -> None:
        """Remaps partition->shard ownership at a rest point, bumping
        the epoch. Only valid once the worker has stopped (the same
        rest contract as ``restart_from``): the next run re-buckets
        queues and rebuilds the table under the new map, and the
        epoch-keyed wave cache guarantees no compiled program with
        stale routing is ever dispatched. This is the single-process
        sibling of the elastic runtime's migration remap
        (``resilience/elastic.py``)."""
        if not self._done.is_set():
            raise RuntimeError(
                "set_owner_assignment() while the checker is running; "
                "join() (or wait for the failure) first — ownership "
                "remaps only at an exchange-drained rest point")
        self._owner_map = self._owner_map.with_assignment(
            list(assignment))


class Membership:
    """The coordinator's heartbeat-lease table.

    Every message from a worker (heartbeats included) renews its lease
    via :meth:`beat`; :meth:`expired` names the workers whose lease has
    lapsed — the membership signal that turns into a ``worker_lost``
    event and a migration. ``now`` is injectable so tests can expire
    leases without sleeping."""

    def __init__(self, lease_s: float,
                 clock=time.monotonic):
        self.lease_s = float(lease_s)
        self._clock = clock
        self._last: Dict[str, float] = {}

    def add(self, worker: str) -> None:
        self._last[worker] = self._clock()

    def beat(self, worker: str) -> None:
        if worker in self._last:
            self._last[worker] = self._clock()

    def drop(self, worker: str) -> None:
        self._last.pop(worker, None)

    def workers(self) -> List[str]:
        return sorted(self._last)

    def __contains__(self, worker: str) -> bool:
        return worker in self._last

    def __len__(self) -> int:
        return len(self._last)

    def remaining(self, worker: str) -> float:
        """Seconds of lease left (negative = expired)."""
        return self._last[worker] + self.lease_s - self._clock()

    def ages(self) -> Dict[str, float]:
        """Seconds since each worker's last heartbeat — the liveness
        gauge the elastic ``GET /.metrics`` view exports per worker
        (an age approaching ``lease_s`` is a loss about to be
        declared). Unlike every other accessor this is called from
        OUTSIDE the coordinator thread (the explorer's metrics poll),
        so it snapshots the table atomically (C-level dict copy, str
        keys) before iterating — a concurrent add/drop must not raise
        mid-scrape."""
        now = self._clock()
        snapshot = self._last.copy()
        return {w: round(now - t, 3) for w, t in sorted(snapshot.items())}

    def expired(self) -> List[str]:
        now = self._clock()
        return sorted(w for w, t in self._last.items()
                      if now - t > self.lease_s)

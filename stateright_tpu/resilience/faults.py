"""Deterministic fault injection: the ``STpu_FAULTS`` registry.

Multi-hour runs on preemptible accelerators (the ROADMAP's production
north star) die in ways no happy-path suite exercises: a grow-time OOM,
a checkpoint torn mid-write, a dead measurement child, a corrupt
collective. This module makes every one of those failures *injectable
on demand, deterministically*, so the recovery paths (supervisor retry,
CRC'd checkpoint rotation, in-engine OOM degradation) are tested code,
not luck.

Spec grammar (the ``STpu_FAULTS`` environment variable)::

    STpu_FAULTS="grow_oom@n=1,torn_ckpt@n=2,wave_crash@n=12@times=2"

Comma-separated entries, each ``point[@key=value]...``:

- ``n=N``      fire starting at the Nth *hit* of the fault point
               (hits are counted per point, process-wide — replays of
               the same spec in the same process order fire at the
               same sites). Default 1. ``wave=N`` is an alias, reading
               naturally at wave-indexed sites.
- ``times=K``  fire on K consecutive eligible hits (default 1; ``0``
               means every eligible hit — e.g. a permanently-failing
               allocation).
- ``p=X``      Bernoulli(X) per hit instead of the deterministic
               window, drawn from a generator seeded by
               ``seed=S`` xor the point name — two runs with the same
               spec fire identically (replayable).

Fault *points* (see ``FAULT_POINTS``) are threaded through the four
device engines, the host BFS, the checkpoint writer, the sharded
all-to-all, and the bench device child. A point that is not armed costs
one attribute check (``plan.active``) — with ``STpu_FAULTS`` unset the
shared ``NULL_PLAN`` is returned and the hot loops pay nothing else
(same contract as the obs tracer; MEASUREMENTS round-10 pins the <1%
overhead).

Every firing emits a versioned ``fault`` obs event, and every recovery
path emits ``recover`` (or terminal ``abort``) — ``tools/trace_lint.py``
asserts the pairing over a captured stream.

Dependency-free beyond ``stateright_tpu.obs`` (no jax, no numpy): the
lint tool, the checkpoint writer, and the bench child all import this
without touching a backend.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, Optional

from ..obs.tracer import tracer_from_env

__all__ = [
    "FAULTS_ENV", "FAULT_POINTS", "InjectedFault", "InjectedOom",
    "ExchangeIntegrityError", "FaultPlan", "NULL_PLAN",
    "fault_plan_from_env", "reset_fault_plans", "strip_point", "is_oom",
]

#: Environment knob: a comma-separated fault spec (see module docstring).
#: Unset means the shared ``NULL_PLAN`` — hot loops pay one attribute
#: check.
FAULTS_ENV = "STpu_FAULTS"

#: The registry: every injectable site, with where its hook lives. A
#: spec naming an unknown point is rejected at parse time — a typo must
#: not silently disarm a chaos run.
FAULT_POINTS: Dict[str, str] = {
    "wave_crash": "engine wave loops (all four device engines): raise "
                  "while processing the Nth dispatch",
    "grow_oom": "visited-table/arena growth (all four device engines): "
                "simulated RESOURCE_EXHAUSTED at the Nth grow attempt",
    "torn_ckpt": "checkpoint writer: the Nth write dies mid-write, "
                 "leaving truncated bytes at the final path",
    "ckpt_crc": "checkpoint writer: the Nth write silently lands one "
                "corrupted section (lying disk; caught by the v3 CRCs)",
    "a2a_short": "sharded all-to-all: the Nth exchange delivers a short "
                 "shard block (tail rows missing)",
    "a2a_corrupt": "sharded all-to-all: the Nth exchange delivers a "
                   "corrupted fingerprint payload",
    "host_crash": "host BFS worker: raise in the Nth check block",
    "child_death": "bench device child: os._exit mid-run at the Nth "
                   "supervision tick (models SIGKILL/preemption)",
    "worker_crash": "elastic worker: die (hard-exit / abrupt socket "
                    "close) at the Nth coordinated round — the "
                    "coordinator's lease machinery must turn it into "
                    "worker_lost + migration, not an abort",
    "spill_fail": "tiered store: the Nth device->host visited spill "
                  "dies before any tier mutation — recovered by a "
                  "supervised checkpoint resume",
    "disk_full": "tiered store: the Nth cold write (visited segment "
                 "or frontier stash) raises at allocation (models "
                 "ENOSPC) — recovered by a supervised checkpoint "
                 "resume",
    "page_in_torn": "tiered store: the Nth cold-segment write lands "
                    "torn (truncated final path) — the store's "
                    "immediate CRC re-verify falls back to the "
                    "rotation predecessor and keeps the rows warm; "
                    "at a frontier page-in site it raises instead",
    "admit_fault": "overload controller: the Nth admission decision "
                   "raises mid-policy, BEFORE any job state mutates — "
                   "submission handling must fail that one request and "
                   "leak nothing (no half-admitted job, queue "
                   "unwedged, later submissions unaffected)",
    "preempt_wedge": "overload controller: the Nth controller-driven "
                     "park dies mid-actuation (models a wedged "
                     "checkpoint write at the drain rest point) — the "
                     "controller must survive its own crash, the "
                     "victim keeps running under its Supervisor, and "
                     "any park that does land still pairs with a "
                     "resume or terminal abort",
}


class InjectedFault(RuntimeError):
    """An injected failure (``STpu_FAULTS``). Deliberately a plain
    ``RuntimeError`` subclass: recovery code must treat it exactly like
    the organic failure it models."""


class InjectedOom(InjectedFault, MemoryError):
    """An injected allocation failure — caught by the same handlers
    that field a real ``RESOURCE_EXHAUSTED``/``MemoryError``."""


class ExchangeIntegrityError(RuntimeError):
    """A sharded all-to-all delivered a block that fails the owner-side
    integrity check (short rows or sentinel fingerprints in the
    payload). The wave's table insertions are already applied, so the
    in-memory frontier is torn — resume from the last checkpoint."""


def is_oom(err: BaseException) -> bool:
    """Whether ``err`` is an allocation failure worth degrading for:
    a ``MemoryError`` (incl. :class:`InjectedOom`) or a jax/XLA
    RESOURCE_EXHAUSTED, matched textually so this module never imports
    a backend."""
    if isinstance(err, MemoryError):
        return True
    text = f"{type(err).__name__}: {err}"
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


class _PointState:
    __slots__ = ("n", "times", "p", "rng", "hits", "fired")

    def __init__(self, n: int, times: int, p: Optional[float],
                 seed: int, point: str):
        self.n = n
        self.times = times
        self.p = p
        # Per-point stream: the same spec replays identically whatever
        # other points interleave.
        self.rng = random.Random(f"{seed}:{point}") if p is not None \
            else None
        self.hits = 0
        self.fired = 0


class FaultPlan:
    """A parsed ``STpu_FAULTS`` spec with per-point hit counters.

    Counters are process-wide per plan and plans are cached per spec
    string (:func:`fault_plan_from_env`), so a supervisor's respawned
    engine continues the SAME countdown — a ``times=1`` fault fires
    once per process, not once per engine instance (otherwise every
    recovery would re-fault identically and never converge).
    """

    active = True

    def __init__(self, spec: str):
        self.spec = spec
        self._lock = threading.Lock()
        self._points: Dict[str, _PointState] = {}
        self._tracer = None
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split("@")
            point, kvs = parts[0].strip(), parts[1:]
            if point not in FAULT_POINTS:
                raise ValueError(
                    f"unknown fault point {point!r} in {FAULTS_ENV} "
                    f"(known: {sorted(FAULT_POINTS)})")
            n, times, p, seed = 1, 1, None, 0
            for kv in kvs:
                key, _, value = kv.partition("=")
                key, value = key.strip(), value.strip()
                if key in ("n", "wave"):
                    n = int(value)
                elif key == "times":
                    times = int(value)
                elif key == "p":
                    p = float(value)
                elif key == "seed":
                    seed = int(value)
                else:
                    raise ValueError(
                        f"unknown fault key {key!r} in {FAULTS_ENV} "
                        f"entry {entry!r} (known: n/wave, times, p, "
                        "seed)")
            if n < 1:
                raise ValueError(
                    f"fault point {point!r}: n must be >= 1")
            self._points[point] = _PointState(n, times, p, seed, point)

    def _decide(self, point: str) -> Optional[int]:
        """Counts one hit of ``point``; returns the hit index when the
        plan says fire, else None."""
        st = self._points.get(point)
        if st is None:
            return None
        with self._lock:
            st.hits += 1
            if st.hits < st.n:
                return None
            if st.times and st.fired >= st.times:
                return None
            if st.p is not None and st.rng.random() >= st.p:
                return None
            if st.p is None and st.times \
                    and st.hits >= st.n + st.times:
                return None
            st.fired += 1
            return st.hits

    def _emit(self, point: str, hit: int, mode: str, tracer,
              **ctx) -> None:
        if tracer is None or not tracer.enabled:
            # Sites without an engine tracer (the checkpoint writer,
            # the bench child) still record their firing. Created
            # under the plan lock: concurrent first firings from two
            # threads must not each open the stream (the loser's
            # run_start would orphan and its flusher thread leak).
            with self._lock:
                if self._tracer is None:
                    self._tracer = tracer_from_env(
                        "faults", meta={"spec": self.spec})
            tracer = self._tracer
        if tracer.enabled:
            # Always flushed: fault events are rare, several producers
            # append to one stream with independent buffers, and the
            # lint's fault->recover pairing reads FILE order — a
            # buffered fault draining after its recovery would read as
            # an unrecovered failure.
            tracer.event("fault", point=point, hit=hit, mode=mode,
                         _flush=True, **ctx)

    def crash(self, point: str, tracer=None, **ctx) -> None:
        """Raises :class:`InjectedFault` (or :class:`InjectedOom` for
        ``grow_oom``) when the plan fires at this hit; a no-op
        otherwise."""
        hit = self._decide(point)
        if hit is None:
            return
        if point == "grow_oom":
            self._emit(point, hit, "oom", tracer, **ctx)
            raise InjectedOom(
                f"injected RESOURCE_EXHAUSTED at fault point "
                f"{point!r} (hit {hit})")
        self._emit(point, hit, "raise", tracer, **ctx)
        raise InjectedFault(
            f"injected crash at fault point {point!r} (hit {hit})")

    def fires(self, point: str, tracer=None, mode: str = "corrupt",
              **ctx) -> bool:
        """Counts a hit and reports whether the caller should apply the
        point's corruption/exit behavior (used by sites whose fault is
        data damage rather than an exception)."""
        hit = self._decide(point)
        if hit is None:
            return False
        self._emit(point, hit, mode, tracer, **ctx)
        return True

    def close(self) -> None:
        with self._lock:
            tracer, self._tracer = self._tracer, None
        if tracer is not None:
            tracer.close()


class _NullPlan:
    """The disarmed plan: ``active`` is False and every probe is a
    no-op. Hot loops guard with ``if plan.active:`` — one attribute
    check per wave, exactly the null-tracer contract."""

    __slots__ = ()
    active = False
    spec = ""

    def crash(self, point, tracer=None, **ctx) -> None:
        pass

    def fires(self, point, tracer=None, mode="corrupt", **ctx) -> bool:
        return False

    def close(self) -> None:
        pass


#: The shared disarmed plan (identity-testable, like ``NULL_TRACER``).
NULL_PLAN = _NullPlan()

#: spec string -> live plan. Cached so hit counters survive engine
#: re-creation (supervisor respawns) within one process.
_PLANS: Dict[str, FaultPlan] = {}
_PLANS_LOCK = threading.Lock()


def fault_plan_from_env(spec: Optional[str] = None):
    """The plan factory every site uses: ``STpu_FAULTS`` set means the
    (process-cached) live plan for that spec; unset means
    ``NULL_PLAN``."""
    spec = os.environ.get(FAULTS_ENV, "") if spec is None else spec
    if not spec:
        return NULL_PLAN
    with _PLANS_LOCK:
        plan = _PLANS.get(spec)
        if plan is None:
            plan = _PLANS[spec] = FaultPlan(spec)
        return plan


def reset_fault_plans() -> None:
    """Drops every cached plan (fresh hit counters). Test isolation
    only: two tests arming the same spec string must not share a
    consumed countdown."""
    with _PLANS_LOCK:
        for plan in _PLANS.values():
            plan.close()
        _PLANS.clear()


def strip_point(spec: str, point: str) -> str:
    """Returns ``spec`` without any entries for ``point``. The bench
    uses this when respawning a dead device child: an inherited
    ``child_death`` spec would kill the respawn at the same
    deterministic tick, by construction forever."""
    return ",".join(
        e for e in spec.split(",")
        if e.strip() and e.strip().split("@")[0].strip() != point)

"""Resilient execution: deterministic fault injection + supervised
crash-recovery.

Two halves, built for multi-hour runs on preemptible accelerators:

- **Fault injection** (``faults.py``): the ``STpu_FAULTS`` registry —
  seeded, replayable fault points threaded through all four device
  engines, the host BFS, the checkpoint writer, the sharded
  all-to-all, and the bench device child. Unset, the whole subsystem
  is one attribute check per wave (``NULL_PLAN``).
- **Supervised recovery** (``supervisor.py``): bounded retry +
  jittered exponential backoff over any engine factory, resuming from
  the newest CRC-valid checkpoint generation (format v3+ keeps the
  last two, so a torn write falls back one generation). Every retry
  is an obs ``retry`` event.
- **Elasticity** (``elastic.py`` + ``membership.py``): the
  coordinator/worker runtime — heartbeat-lease membership, per-shard
  checkpoint generations (format v4), shard migration onto survivors
  under an epoch-versioned rendezvous :class:`OwnerMap`, and mid-run
  join/rebalance. A lost worker is a ``worker_lost`` -> migration,
  not an abort.

Every fault and recovery emits versioned obs events (``fault`` /
``recover`` / ``retry`` / ``degrade`` / ``abort`` / ``worker_lost`` /
``migrate_done`` / ``rebalance``); ``tools/trace_lint.py`` asserts
the pairings, and ``tests/test_resilience.py`` +
``tests/test_elastic.py`` assert every recovered/migrated run's
counts and discoveries are bit-identical to an unfaulted run. See the
Resilience and Elasticity sections of ARCHITECTURE.md.
"""

from .elastic import ElasticChecker, elastic_check
from .faults import (FAULT_POINTS, FAULTS_ENV, ExchangeIntegrityError,
                     FaultPlan, InjectedFault, InjectedOom, NULL_PLAN,
                     fault_plan_from_env, is_oom, reset_fault_plans,
                     strip_point)
from .membership import Membership, OwnerMap
from .supervisor import Supervisor, newest_valid_checkpoint, supervise

__all__ = [
    "FAULT_POINTS", "FAULTS_ENV", "ExchangeIntegrityError", "FaultPlan",
    "InjectedFault", "InjectedOom", "NULL_PLAN", "fault_plan_from_env",
    "is_oom", "reset_fault_plans", "strip_point",
    "Supervisor", "newest_valid_checkpoint", "supervise",
    "ElasticChecker", "elastic_check", "Membership", "OwnerMap",
]

"""Resilient execution: deterministic fault injection + supervised
crash-recovery.

Two halves, built for multi-hour runs on preemptible accelerators:

- **Fault injection** (``faults.py``): the ``STpu_FAULTS`` registry —
  seeded, replayable fault points threaded through all four device
  engines, the host BFS, the checkpoint writer, the sharded
  all-to-all, and the bench device child. Unset, the whole subsystem
  is one attribute check per wave (``NULL_PLAN``).
- **Supervised recovery** (``supervisor.py``): bounded retry +
  exponential backoff over any engine factory, resuming from the
  newest CRC-valid checkpoint generation (format v3 keeps the last
  two, so a torn write falls back one generation).

Every fault and recovery emits versioned obs events (``fault`` /
``recover`` / ``degrade`` / ``abort``); ``tools/trace_lint.py``
asserts the pairing, and ``tests/test_resilience.py`` asserts every
recovered run's counts and discoveries are bit-identical to an
unfaulted run. See the Resilience section of ARCHITECTURE.md.
"""

from .faults import (FAULT_POINTS, FAULTS_ENV, ExchangeIntegrityError,
                     FaultPlan, InjectedFault, InjectedOom, NULL_PLAN,
                     fault_plan_from_env, is_oom, reset_fault_plans,
                     strip_point)
from .supervisor import Supervisor, newest_valid_checkpoint, supervise

__all__ = [
    "FAULT_POINTS", "FAULTS_ENV", "ExchangeIntegrityError", "FaultPlan",
    "InjectedFault", "InjectedOom", "NULL_PLAN", "fault_plan_from_env",
    "is_oom", "reset_fault_plans", "strip_point",
    "Supervisor", "newest_valid_checkpoint", "supervise",
]

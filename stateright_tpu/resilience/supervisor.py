"""Supervised crash-recovery: the bounded-retry run loop.

The reference stateright restarts a killed run from scratch; the device
engines already write periodic CRC'd checkpoints (format v3), and this
module closes the loop: a :class:`Supervisor` wraps *any* engine
factory with bounded retry + exponential backoff, resuming each attempt
from the newest checkpoint generation that passes its CRC check — a
torn or corrupted current snapshot falls back one generation
(``checkpoint_format`` keeps the last two).

Recovery strategy, in preference order:

1. **In-place restart** (``checker.restart_from``): the failed device
   engine reloads the checkpoint into its existing instance — the
   compiled wave-program cache survives, so a recovery costs zero
   recompiles. Also clears the engine's failed-run flag, so a post-run
   ``checkpoint()`` works again.
2. **Re-spawn**: engines without in-place restart (the host BFS, or a
   checker that died during construction) are re-created through the
   factory, with ``resume_from`` pointing at the newest valid
   generation (``None`` restarts from scratch — the host engines'
   only option, and still bit-identical for full enumerations).

Every retry emits a versioned ``retry`` obs event (schema v4 — the
``self.recoveries`` record, serialized) and exhaustion emits a
terminal ``abort`` — ``tools/trace_lint.py`` asserts every
injected/observed ``fault`` is eventually followed by one of the two
(``recover``, the in-engine degradation acknowledgment, retires a
fault the same way).

Backoff is *jittered*: each delay is the exponential base plus a
seeded random fraction of it (``jitter_frac``), so several supervised
workers resuming from the same cluster-wide event (a preemption sweep,
a storage blip) fan out instead of thundering back in lockstep against
the same checkpoint store. The jitter source is injectable and the
drawn ``jitter_s`` is recorded per retry, so chaos runs stay
replayable from their records.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, List, Optional

from ..obs.tracer import tracer_from_env

__all__ = ["Supervisor", "supervise", "newest_valid_checkpoint"]


def newest_valid_checkpoint(path: Optional[str]) -> Optional[str]:
    """The newest checkpoint generation at ``path`` that passes the
    integrity check (readable npz + header + per-section CRC32):
    ``path`` itself, else ``path + PREV_SUFFIX`` (the keep-last-2
    rotation's previous generation), else None — resume from scratch.
    """
    from ..checkpoint_format import PREV_SUFFIX, verify_file

    if not path:
        return None
    for candidate in (path, path + PREV_SUFFIX):
        if not os.path.exists(candidate):
            continue
        try:
            verify_file(candidate)
            return candidate
        except ValueError:
            continue
    return None


class Supervisor:
    """Runs ``factory(resume_from=...)`` to completion, retrying
    failures from the newest valid checkpoint.

    ``factory`` must return a checker whose ``join()`` raises on
    failure (every engine in this repo). ``checkpoint_path`` is the
    engine's periodic snapshot path (the same value the factory passes
    as ``checkpoint_path=``); without it, retries restart from scratch.

    ``sleep`` is injectable for tests. ``self.recoveries`` records one
    dict per retry (attempt index, backoff, jitter, resume source,
    error) — the same payload each ``retry`` obs event carries.

    ``jitter_frac`` spreads concurrent restarts: each delay is the
    exponential base plus ``U(0, jitter_frac) * base`` drawn from
    ``rng`` (default: seeded per process, so a preempted fleet's
    workers — same spec, same attempt index — still draw different
    delays instead of thundering back together). Pass ``rng`` for
    deterministic tests, or ``jitter_frac=0`` for the exact pre-v4
    schedule.

    A checker that stopped *preempted* (the job service's cooperative
    ``preempt()``) returns from ``join()`` normally — preemption is an
    outcome, not a failure, so it is never retried; the caller reads
    ``checker.preempted``. Round 21's overload controller leans on
    exactly this contract for deadline-driven *parking*: a
    controller-issued preempt drains the victim to its own checkpoint
    generation through this supervised path, and the later auto-resume
    is an ordinary ``{"resume": id}`` submission — so a parked run's
    recovery semantics (newest-valid-generation fallback, bounded
    retries, bit-identical counters) are the same ones every other
    supervised run already has. ``trace_path`` overrides where the
    supervisor's own retry/abort events land (the job service points
    it at the job's per-job trace stream; default: the process-global
    ``STpu_TRACE``).
    """

    def __init__(self, factory: Callable, *,
                 checkpoint_path: Optional[str] = None,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 backoff_factor: float = 2.0, max_backoff_s: float = 5.0,
                 jitter_frac: float = 0.25,
                 rng: Optional[random.Random] = None,
                 trace_path: Optional[str] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._factory = factory
        self._ckpt = checkpoint_path
        self._max_retries = max(0, int(max_retries))
        self._backoff = float(backoff_s)
        self._factor = float(backoff_factor)
        self._max_backoff = float(max_backoff_s)
        self._jitter_frac = max(0.0, float(jitter_frac))
        # Entropy-seeded by default: a containerized fleet is routinely
        # ALL pid 1, so a pid seed would hand the whole herd identical
        # jitter streams — the exact lockstep this knob exists to
        # break. The drawn jitter is recorded per retry, so runs stay
        # diagnosable from their records; inject ``rng`` for
        # deterministic tests.
        self._rng = rng if rng is not None else random.Random(
            os.urandom(16))
        self._trace_path = trace_path
        self._sleep = sleep
        self.recoveries: List[dict] = []

    def run(self):
        """Runs to completion; returns the (joined) checker of the
        successful attempt. Re-raises the final error after
        ``max_retries`` recoveries, with a terminal ``abort`` event.

        The FIRST attempt also resumes from the newest valid generation
        when one already exists at ``checkpoint_path`` — that is the
        preemption story: a SIGKILLed process leaves no in-process
        state, only its checkpoints, and a fresh supervisor must
        continue from them (not restart from scratch and rotate the
        survivors away). Start from a fresh path to begin anew."""
        tracer = tracer_from_env("supervisor", path=self._trace_path,
                                 meta={
                                     "checkpoint_path": self._ckpt,
                                     "max_retries": self._max_retries})
        checker = None
        resume: Optional[str] = newest_valid_checkpoint(self._ckpt)
        attempt = 0
        try:
            while True:
                try:
                    if (checker is not None and resume is not None
                            and hasattr(checker, "restart_from")):
                        # In-place: reuses the compiled wave cache and
                        # clears the engine's failed-run flag.
                        checker.restart_from(resume)
                    else:
                        checker = None  # a half-built checker is dead
                        checker = self._factory(resume_from=resume)
                    checker.join()
                    return checker
                except Exception as e:  # noqa: BLE001 — supervision IS
                    # the handler of last resort for engine failures
                    # The failed engine's flight recorder already
                    # dumped its ring (always-on, even untraced);
                    # naming the postmortem in the retry/abort record
                    # is what makes a dark run's death diagnosable.
                    dump = getattr(checker, "flight_dump", None)
                    if attempt >= self._max_retries:
                        if tracer.enabled:
                            # Flushed immediately, like every
                            # resilience event: the lint pairs
                            # fault->recover/abort by FILE order. The
                            # abort record carries the tiered store's
                            # high-water marks (when the engine has
                            # one) so a memory-pressure death explains
                            # WHY memory ran out, alongside the
                            # flight-recorder dump path.
                            tracer.event(
                                "abort", attempts=attempt, _flush=True,
                                dump=dump,
                                tiers=self._store_high_water(checker),
                                reason=f"{type(e).__name__}: {e}"[:300])
                        raise
                    attempt += 1
                    base = min(
                        self._backoff * self._factor ** (attempt - 1),
                        self._max_backoff)
                    jitter = base * self._jitter_frac * self._rng.random()
                    self._sleep(base + jitter)
                    resume = newest_valid_checkpoint(self._ckpt)
                    record = {
                        "attempt": attempt,
                        "backoff_s": round(base, 4),
                        "jitter_s": round(jitter, 4),
                        "resumed_from": resume,
                        "dump": dump,
                        "error": f"{type(e).__name__}: {e}"[:300]}
                    self.recoveries.append(record)
                    if tracer.enabled:
                        # The retry record IS the obs event (schema v4;
                        # the lint retires an open fault on it, exactly
                        # like a recover — pairing now works when the
                        # fault was emitted by a DIFFERENT, since-dead
                        # process into the same stream).
                        tracer.event("retry", _flush=True, **record)
        finally:
            tracer.close()


    @staticmethod
    def _store_high_water(checker):
        """The failed engine's per-tier high-water marks (None when it
        has no tiered store, or the stats call itself fails — a dying
        engine must not be able to mask its own abort record)."""
        fn = getattr(checker, "store_stats", None)
        if not callable(fn):
            return None
        try:
            stats = fn()
        except Exception:  # noqa: BLE001 — diagnostics must not raise
            return None
        if not stats.get("enabled"):
            return None
        return {
            "device_table_bytes": stats.get("device", {}).get(
                "table_bytes"),
            "device_budget": stats.get("device_budget"),
            "host_high_water_bytes": stats.get("host", {}).get(
                "high_water_bytes"),
            "host_budget": stats.get("host_budget"),
            "disk_high_water_bytes": stats.get("disk", {}).get(
                "high_water_bytes"),
            "spill_bytes": stats.get("spill_bytes"),
            "resident_ratio": stats.get("resident_ratio"),
        }


def supervise(factory: Callable, **kwargs):
    """One-shot convenience: ``Supervisor(factory, **kwargs).run()``."""
    return Supervisor(factory, **kwargs).run()

"""Supervised crash-recovery: the bounded-retry run loop.

The reference stateright restarts a killed run from scratch; the device
engines already write periodic CRC'd checkpoints (format v3), and this
module closes the loop: a :class:`Supervisor` wraps *any* engine
factory with bounded retry + exponential backoff, resuming each attempt
from the newest checkpoint generation that passes its CRC check — a
torn or corrupted current snapshot falls back one generation
(``checkpoint_format`` keeps the last two).

Recovery strategy, in preference order:

1. **In-place restart** (``checker.restart_from``): the failed device
   engine reloads the checkpoint into its existing instance — the
   compiled wave-program cache survives, so a recovery costs zero
   recompiles. Also clears the engine's failed-run flag, so a post-run
   ``checkpoint()`` works again.
2. **Re-spawn**: engines without in-place restart (the host BFS, or a
   checker that died during construction) are re-created through the
   factory, with ``resume_from`` pointing at the newest valid
   generation (``None`` restarts from scratch — the host engines'
   only option, and still bit-identical for full enumerations).

Every recovery emits a versioned ``recover`` obs event and exhaustion
emits a terminal ``abort`` — ``tools/trace_lint.py`` asserts every
injected/observed ``fault`` is eventually followed by one of the two.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from ..obs.tracer import tracer_from_env

__all__ = ["Supervisor", "supervise", "newest_valid_checkpoint"]


def newest_valid_checkpoint(path: Optional[str]) -> Optional[str]:
    """The newest checkpoint generation at ``path`` that passes the
    integrity check (readable npz + header + per-section CRC32):
    ``path`` itself, else ``path + PREV_SUFFIX`` (the keep-last-2
    rotation's previous generation), else None — resume from scratch.
    """
    from ..checkpoint_format import PREV_SUFFIX, verify_file

    if not path:
        return None
    for candidate in (path, path + PREV_SUFFIX):
        if not os.path.exists(candidate):
            continue
        try:
            verify_file(candidate)
            return candidate
        except ValueError:
            continue
    return None


class Supervisor:
    """Runs ``factory(resume_from=...)`` to completion, retrying
    failures from the newest valid checkpoint.

    ``factory`` must return a checker whose ``join()`` raises on
    failure (every engine in this repo). ``checkpoint_path`` is the
    engine's periodic snapshot path (the same value the factory passes
    as ``checkpoint_path=``); without it, retries restart from scratch.

    ``sleep`` is injectable for tests. ``self.recoveries`` records one
    dict per retry (attempt index, backoff, resume source, error) —
    the same payload each ``recover`` obs event carries.
    """

    def __init__(self, factory: Callable, *,
                 checkpoint_path: Optional[str] = None,
                 max_retries: int = 3, backoff_s: float = 0.05,
                 backoff_factor: float = 2.0, max_backoff_s: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep):
        self._factory = factory
        self._ckpt = checkpoint_path
        self._max_retries = max(0, int(max_retries))
        self._backoff = float(backoff_s)
        self._factor = float(backoff_factor)
        self._max_backoff = float(max_backoff_s)
        self._sleep = sleep
        self.recoveries: List[dict] = []

    def run(self):
        """Runs to completion; returns the (joined) checker of the
        successful attempt. Re-raises the final error after
        ``max_retries`` recoveries, with a terminal ``abort`` event.

        The FIRST attempt also resumes from the newest valid generation
        when one already exists at ``checkpoint_path`` — that is the
        preemption story: a SIGKILLed process leaves no in-process
        state, only its checkpoints, and a fresh supervisor must
        continue from them (not restart from scratch and rotate the
        survivors away). Start from a fresh path to begin anew."""
        tracer = tracer_from_env("supervisor", meta={
            "checkpoint_path": self._ckpt,
            "max_retries": self._max_retries})
        checker = None
        resume: Optional[str] = newest_valid_checkpoint(self._ckpt)
        attempt = 0
        try:
            while True:
                try:
                    if (checker is not None and resume is not None
                            and hasattr(checker, "restart_from")):
                        # In-place: reuses the compiled wave cache and
                        # clears the engine's failed-run flag.
                        checker.restart_from(resume)
                    else:
                        checker = None  # a half-built checker is dead
                        checker = self._factory(resume_from=resume)
                    checker.join()
                    return checker
                except Exception as e:  # noqa: BLE001 — supervision IS
                    # the handler of last resort for engine failures
                    if attempt >= self._max_retries:
                        if tracer.enabled:
                            # Flushed immediately, like every
                            # resilience event: the lint pairs
                            # fault->recover/abort by FILE order.
                            tracer.event(
                                "abort", attempts=attempt, _flush=True,
                                reason=f"{type(e).__name__}: {e}"[:300])
                        raise
                    attempt += 1
                    delay = min(
                        self._backoff * self._factor ** (attempt - 1),
                        self._max_backoff)
                    self._sleep(delay)
                    resume = newest_valid_checkpoint(self._ckpt)
                    record = {
                        "attempt": attempt,
                        "backoff_s": round(delay, 4),
                        "resumed_from": resume,
                        "error": f"{type(e).__name__}: {e}"[:300]}
                    self.recoveries.append(record)
                    if tracer.enabled:
                        tracer.event("recover", _flush=True, **record)
        finally:
            tracer.close()


def supervise(factory: Callable, **kwargs):
    """One-shot convenience: ``Supervisor(factory, **kwargs).run()``."""
    return Supervisor(factory, **kwargs).run()

"""Elastic multi-worker sharding: coordinator/worker BFS with shard
migration and mid-run rebalance.

Round 10 made single-*process* failures a tested code path (seeded
faults + supervised checkpoint resume); the sharded engines, though,
still ran only on a single-process virtual mesh — lose the process and
the whole run restarts from one monolithic snapshot. This module is
ROADMAP item 4's production story for preemptible fleets: the
owner-partitioned wave (the shared-hash-table design of
arXiv:1004.2772, scaled the way GPUexplore's multi-GPU study
arXiv:1801.05857 scales it) across **N workers** — OS processes over
local sockets, or in-process threads over the same sockets for the
fast test tier — where

- **membership** is heartbeat leases (:class:`~.membership.Membership`):
  a missed lease emits a ``worker_lost`` obs event and triggers shard
  *migration*, not an abort;
- **ownership** is a fixed logical partition function (``fp %
  n_partitions``) under an epoch-versioned rendezvous
  :class:`~.membership.OwnerMap` — results never depend on which
  worker hosts a partition, and every remap bumps the epoch at an
  exchange-drained barrier so in-flight rows always route by exactly
  one map;
- **durability** is per-shard checkpoint generations (format v4): each
  partition snapshots to its own :func:`~..checkpoint_format.shard_path`
  file (CRC'd, keep-last-2 PER SHARD) at a coordinator round barrier,
  plus a manifest carrying the run-global counters — so a dead
  worker's partitions are rebuilt *independently* on survivors from
  their newest valid generations;
- **elasticity** is mid-run join: a new worker registers, wins its
  rendezvous share of partitions, receives them via fresh per-shard
  snapshots at a drained barrier (no rollback, no lost work), logged
  as a ``rebalance`` event.

The wave itself reuses the engines' building blocks
(``expand_frontier`` / ``fingerprint_successors`` /
``first_occurrence_candidates``, jitted per worker) and the
checkpoint-format machinery (``make_header`` / ``write_atomic`` /
``pending_rows``) — the same packed-row path ``restart_from`` resumes
through — so a completed elastic run is **bit-identical in totals**
(state count, unique count, discovery set, final checkpoint payload)
to a single-process sharded run of the same model:
``tests/test_elastic.py`` pins kill-one-worker and join-one-worker
runs against the unfaulted single-process reference.

Transport is a deliberately simple coordinator-star over localhost TCP
with length-prefixed pickle frames (trusted same-host peers only — the
multi-host deployment swaps this layer for jax.distributed /
collectives while keeping the membership, epoch, and per-shard
generation machinery, which is the part that is actually new). The
coordinator drives synchronous rounds:

1. ``wave``: every worker expands up to ``batch_rows`` rows from its
   partitions' queues, evaluates properties, fingerprints successors,
   and returns locally-deduped outbound rows grouped by destination
   partition (sender-side dedup — the novelty-routed exchange);
2. ``deliver``: the coordinator routes each partition's rows to its
   CURRENT owner (this is the epoch-aware hop), which dedups them
   against that partition's visited set and enqueues the novel rows;
3. counters/discoveries merge; at the checkpoint cadence every worker
   snapshots every owned partition and the coordinator writes the
   manifest — one consistent generation, because the barrier has
   drained all exchange.

A loss rolls every survivor back to the newest complete generation
(counters included, so recovered totals cannot double-count), adopts
the dead worker's partitions onto the rendezvous winners, and bumps
the epoch (``migrate_done``). A join hands off at a live barrier with
no rollback (``rebalance``).

Observability (round 12, schema v5): every worker owns a
``RelayTracer`` emitting its wave/lifecycle events WHERE the work
happens, shipped in bounded batches piggybacked on round replies and
merged by the coordinator's ``TraceCollector`` into one causally
ordered trace — plus per-round straggler attribution (compute /
exchange / barrier-wait per worker, from self-reported durations) and
an always-on flight-recorder ring in every worker and the coordinator
that dumps a postmortem on crashes and ``worker_lost``. See
``obs/collect.py`` / ``obs/flight.py`` and the Observability section
of ARCHITECTURE.md.
"""

from __future__ import annotations

import os
import pickle
import queue
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs.collect import RelayTracer, TraceCollector
from ..obs.flight import recorder_from_env
from ..obs.hist import wave_obs_from_env
from ..obs.prof import prof_from_env
from ..obs.tracer import tracer_from_env
from .faults import fault_plan_from_env
from .membership import Membership, OwnerMap

__all__ = ["ElasticChecker", "elastic_check"]


# -- Framing ---------------------------------------------------------------
#
# Length-prefixed pickle over a localhost socket. Pickle because the
# payloads are numpy blocks between trusted same-host peers the
# coordinator itself spawned; a multi-host deployment replaces this
# transport wholesale (see module docstring), not incrementally.

_LEN = struct.Struct(">Q")


def _send_msg(sock: socket.socket, obj, lock: Optional[threading.Lock]
              = None) -> None:
    data = pickle.dumps(obj, protocol=4)
    frame = _LEN.pack(len(data)) + data
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the socket")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class _WorkerLost(Exception):
    """A worker's socket died or its lease lapsed mid-operation."""

    def __init__(self, names):
        super().__init__(f"worker(s) lost: {sorted(names)}")
        self.names = sorted(names)


class _Abort(Exception):
    """The run cannot continue (no survivors / no recoverable
    generation); surfaces as the terminal ``abort`` obs event."""


# -- Worker side -----------------------------------------------------------

class _Partition:
    """One logical shard's state on its current owner: the visited set
    (dedup fingerprints) and the pending frontier as (vecs, path-fps,
    ebits) blocks — the same block shape the engines queue."""

    __slots__ = ("visited", "queue")

    def __init__(self, visited=None, blocks=None):
        self.visited = set() if visited is None else visited
        self.queue: deque = deque(blocks or [])

    def queued_rows(self) -> int:
        return sum(len(b[1]) for b in self.queue)


class _WorkerRuntime:
    """The worker half: owns a set of partitions, expands their
    frontiers with the jitted engine building blocks, and serves the
    coordinator's command protocol over one socket."""

    def __init__(self, name: str, model_factory: Callable, cfg: dict):
        self.name = name
        #: attached by the entry functions AFTER construction: the
        #: heavy build (model, device model, jit wrapper, a process's
        #: jax import) happens before the coordinator ever sees the
        #: register, so the lease clock starts on a ready worker.
        self.sock: Optional[socket.socket] = None
        self.send_lock = threading.Lock()
        self.cfg = cfg
        self.n_parts = int(cfg["n_partitions"])
        self.B = int(cfg["batch_rows"])
        self.use_sym = bool(cfg.get("symmetry", False))
        self.parts: Dict[int, _Partition] = {}
        self._stop_hb = threading.Event()
        self._faults = fault_plan_from_env()
        #: the ownership epoch / coordinated round the LAST command ran
        #: under — stamped onto every relayed event for the collector's
        #: (epoch, round, worker, seq) merge order.
        self._epoch = 0
        self._round = 0
        #: cumulative per-worker wave totals: successors this worker
        #: generated, novel rows it accepted since its last wave event.
        self._states_total = 0
        self._novel_accum = 0
        self._compiled_once = False
        #: always-on flight recorder: the worker's last events survive
        #: its death as a postmortem dump (named for the worker, so a
        #: drill can find the casualty's file deterministically).
        self._flight = recorder_from_env(name)
        #: per-worker trace stream (obs schema v5): wave/fault events
        #: are emitted HERE, where the work happens, stamped with
        #: (worker, seq, epoch, round), and shipped to the coordinator
        #: in bounded batches piggybacked on round replies. With the
        #: coordinator untraced (``relay_trace`` off) nothing is
        #: buffered or shipped, but the stamped events still tee into
        #: the flight ring — postmortems work for dark runs too.
        self._relay = RelayTracer(
            name, buffering=bool(cfg.get("relay_trace")),
            mirror=(self._flight.record if self._flight.armed else None),
            meta={"transport": cfg.get("transport"),
                  "n_partitions": self.n_parts})
        #: the store's event sink (read lazily via owner._tracer): the
        #: worker's spill/pressure events relay with its wave stream.
        self._tracer = self._relay
        #: service observability (obs/hist.py): per-worker wave
        #: latency histograms; snapshots ride the relay (stamped
        #: worker/seq) so they merge causally at the coordinator.
        self._wave_obs = wave_obs_from_env(name)
        if self._wave_obs.enabled and self._flight.armed:
            self._flight.set_hist_source(
                self._wave_obs.final_snapshot_event)
        #: continuous wave profiler (obs/prof.py): the worker's expand
        #: is a lazy ``jax.jit`` (no AOT cost analysis), so its record
        #: carries null flops/bytes — but the sampled stage timings and
        #: ``cost_ratio`` still ride the relay as ``profile_snapshot``
        #: events (stamped worker/seq) and merge causally at the
        #: coordinator, like the r18 histogram snapshots.
        self._prof = prof_from_env(name)

        from ..model import Expectation

        model = model_factory()
        self.model = model
        self.dm = model.device_model()
        self.W = self.dm.state_width
        self.F = self.dm.max_fanout
        self.properties = model.properties()
        device_props = self.dm.device_properties()
        self.prop_fns = [device_props.get(p.name) for p in self.properties]
        self.eventually_idx = [
            i for i, p in enumerate(self.properties)
            if p.expectation is Expectation.EVENTUALLY]
        for i in self.eventually_idx:
            if self.prop_fns[i] is None:
                raise NotImplementedError(
                    "the elastic runtime requires a device predicate "
                    f"for eventually property "
                    f"{self.properties[i].name!r} (per-row bits are "
                    "cleared before the exchange, like the sharded "
                    "engines)")
        self._expand = self._build_expand()
        #: the worker's single program key; the capture records null
        #: flops/bytes (lazy jit — no AOT cost analysis) but still
        #: attributes the key so its sampled snapshots join the table.
        self._prof_pkey = f"{name}|expand|({self.B},)"
        if self._prof.enabled:
            self._prof.capture(self._prof_pkey, self._expand)
        # Tiered state store (stateright_tpu.store): partition-keyed,
        # so a partition's spilled visited rows checkpoint/migrate/drop
        # with the partition. Armed by the STpu_TIER_* env knobs (the
        # coordinator's environment reaches process workers through
        # spawn); disarmed = NULL_STORE, one attribute check per
        # deliver.
        from ..store.tiered import store_from_config

        self._store = store_from_config(
            owner=self, prefix=f"{name}-",
            n_partitions=self.n_parts,
            meta={"model_name": type(model).__name__,
                  "state_width": self.W,
                  "use_symmetry": self.use_sym})
        # Round 17: background writer for shard checkpoints and cold
        # spills. Env-knob only (STpu_ASYNC_IO) — process workers
        # inherit the coordinator's environment through spawn, so the
        # knob reaches every worker without protocol changes. The
        # checkpoint command JOINS before replying ok: the coordinator
        # writes the manifest only after every worker acked, so the
        # manifest-last crash-consistency invariant is preserved.
        from ..io.async_io import writer_from_config

        self._aio = writer_from_config(None, name=f"stpu-aio-{name}")
        self._store.attach_async(self._aio)

    # -- The jitted sender side (one compile per worker) ------------------

    def _build_expand(self):
        import jax
        import jax.numpy as jnp

        from ..tpu.engine import (eval_properties, expand_frontier,
                                  fingerprint_successors,
                                  first_occurrence_candidates)

        dm = self.dm
        prop_fns = list(self.prop_fns)
        use_sym = self.use_sym
        eventually_device = list(self.eventually_idx)

        def expand(vecs, valid, ebits):
            conds = eval_properties(prop_fns, vecs)
            succ_flat, sflat, succ_count, terminal = expand_frontier(
                dm, vecs, valid)
            dedup_fps, path_fps = fingerprint_successors(
                dm, succ_flat, sflat, use_sym)
            cleared = ebits
            for i in eventually_device:
                cleared = cleared & ~jnp.where(
                    conds[i], jnp.uint32(1 << i), jnp.uint32(0))
            child_ebits = jnp.repeat(cleared, dm.max_fanout)
            # Sender-side local dedup (exchange_novel_only): only the
            # first occurrence of each distinct fingerprint rides to
            # its owner — same rule and bit-identity argument as the
            # sharded engines' novelty-routed exchange.
            send_mask = first_occurrence_candidates(dedup_fps)
            conds_out = [c for c in conds if c is not None]
            return (conds_out, succ_count, terminal, cleared, succ_flat,
                    dedup_fps, path_fps, child_ebits, send_mask)

        return jax.jit(expand)

    # -- Partition state --------------------------------------------------

    def _install_seed(self, p: int, seed) -> None:
        vecs, fps, ebits, visited = seed
        blocks = [(np.asarray(vecs, np.uint32), np.asarray(fps, np.uint64),
                   np.asarray(ebits, np.uint32))] if len(fps) else []
        if self._store.active:
            # Fresh ownership: any spilled tiers from a previous
            # assignment of this partition are stale.
            self._store.drop_partition(p)
        self.parts[p] = _Partition(
            visited=set(int(f) for f in np.asarray(visited, np.uint64)),
            blocks=blocks)

    def _visited_rows_in_ram(self) -> int:
        return sum(len(part.visited) for part in self.parts.values())

    def _maybe_spill_visited(self) -> None:
        """Host-tier budget for the in-RAM visited sets: move the
        largest partitions' sets into the store (warm, then cold under
        pressure) until the worker fits. Membership stays exact — the
        deliver path probes the store before the set."""
        budget = self._store.host_budget
        if budget is None:
            return
        while 8 * self._visited_rows_in_ram() > budget:
            p, part = max(self.parts.items(),
                          key=lambda kv: len(kv[1].visited))
            if not part.visited:
                break
            fps = np.fromiter(part.visited, np.uint64,
                              len(part.visited))
            self._store.spill_partition_rows(p, fps)
            part.visited.clear()

    def _load_partition(self, p: int, path: str,
                        want_round: Optional[int]) -> None:
        """Rebuilds partition ``p`` from its newest per-shard
        generation whose recorded round matches the target generation
        — migration and rollback both land here, through the same
        checkpoint-format machinery ``restart_from`` resumes with."""
        from ..checkpoint_format import (PREV_SUFFIX, load_checkpoint,
                                         pending_rows, shard_path,
                                         validate_header)

        base = shard_path(path, p)
        last_err: Optional[str] = None
        for candidate in (base, base + PREV_SUFFIX):
            if not os.path.exists(candidate):
                continue
            try:
                with load_checkpoint(candidate) as data:
                    header = validate_header(
                        data, model_name=type(self.model).__name__,
                        state_width=self.W, use_symmetry=self.use_sym,
                        expect_shard=(p, self.n_parts))
                    shard_hdr = header.get("shard") or {}
                    if (want_round is not None and "shard" in header
                            and int(shard_hdr.get("round", -1))
                            != int(want_round)):
                        last_err = (
                            f"{candidate}: generation round "
                            f"{shard_hdr.get('round')} != manifest "
                            f"round {want_round}")
                        continue
                    vecs = pending_rows(data, header, self.W)
                    fps = np.asarray(data["pending_fps"], np.uint64)
                    ebits = np.asarray(data["pending_ebits"], np.uint32)
                    visited = set(
                        int(f) for f in np.asarray(data["visited"],
                                                   np.uint64))
            except ValueError as e:
                last_err = str(e)
                continue
            blocks = [(vecs, fps, ebits)] if len(fps) else []
            if self._store.active:
                # The shard file is self-contained (spilled rows were
                # materialized at write); stale tiers must not shadow
                # the rebuilt set.
                self._store.drop_partition(p)
            self.parts[p] = _Partition(visited=visited, blocks=blocks)
            return
        raise ValueError(
            f"partition {p}: no valid generation at {base!r}"
            + (f" ({last_err})" if last_err else ""))

    def _write_partition(self, p: int, path: str, round_: int,
                         epoch: int) -> None:
        from ..checkpoint_format import (make_header, shard_path,
                                         write_atomic)

        part = self.parts[p]
        visited = np.fromiter(sorted(part.visited), np.uint64,
                              len(part.visited))
        if self._store.active:
            # Spilled rows materialize into the shard file: a per-shard
            # generation must stay self-contained so migration can
            # rebuild the partition anywhere (honesty note: elastic
            # shard snapshots do NOT use v5 cold refs — the segment
            # files live on the casualty's disk).
            spilled = self._store.partition_fps(p)
            if len(spilled):
                visited = np.union1d(visited, spilled)
        blocks = list(part.queue)
        if blocks:
            vecs = np.concatenate([b[0] for b in blocks])
            fps = np.concatenate([b[1] for b in blocks])
            ebits = np.concatenate([b[2] for b in blocks])
        else:
            vecs = np.zeros((0, self.W), np.uint32)
            fps = np.zeros(0, np.uint64)
            ebits = np.zeros(0, np.uint32)
        header = make_header(
            model_name=type(self.model).__name__, state_width=self.W,
            state_count=len(visited),
            unique_count=len(visited),
            use_symmetry=self.use_sym, discoveries={},
            shard={"index": p, "of": self.n_parts, "round": round_,
                   "epoch": epoch})
        payload = dict(
            header=header, visited=visited, pending_vecs=vecs,
            pending_fps=fps, pending_ebits=ebits)
        # Payload assembly stays on the command thread (the snapshot is
        # captured at the rest point); only the CRC/serialize/rename
        # rides the writer. Under async the next partition's payload
        # builds while this one writes; the handler joins before the
        # ok reply so the coordinator's manifest stays last.
        self._aio.submit(
            lambda: write_atomic(shard_path(path, p), payload),
            kind="shard")

    # -- Command handlers -------------------------------------------------

    def _take_batch(self, rows: int):
        """Up to ``rows`` frontier rows across owned partitions, in
        partition order (the engines' block-splitting discipline)."""
        parts_vecs, parts_fps, parts_ebits = [], [], []
        taken = 0
        for p in sorted(self.parts):
            q = self.parts[p].queue
            while q and taken < rows:
                vecs, fps, ebits = q[0]
                k = len(fps)
                take = min(k, rows - taken)
                if take == k:
                    q.popleft()
                    parts_vecs.append(vecs)
                    parts_fps.append(fps)
                    parts_ebits.append(ebits)
                else:
                    parts_vecs.append(vecs[:take])
                    parts_fps.append(fps[:take])
                    parts_ebits.append(ebits[:take])
                    q[0] = (vecs[take:], fps[take:], ebits[take:])
                taken += take
            if taken >= rows:
                break
        return parts_vecs, parts_fps, parts_ebits, taken

    def _queued(self) -> Dict[int, int]:
        return {p: part.queued_rows() for p, part in self.parts.items()}

    def _host_conds(self, conds_out, batch_vecs, n):
        """Reattaches device conds to property slots; host-fallback
        slots decode each valid batch row once (the engines'
        ``_eval_host_conds`` discipline)."""
        conds: List[np.ndarray] = []
        it = iter(conds_out)
        decoded = None
        for i, fn in enumerate(self.prop_fns):
            if fn is not None:
                conds.append(np.asarray(next(it)))
                continue
            if decoded is None:
                decode = self.dm.decode
                decoded = [(r, decode(batch_vecs[r])) for r in range(n)]
            cond = np.zeros(len(batch_vecs), bool)
            prop_cond = self.properties[i].condition
            for r, state in decoded:
                cond[r] = bool(prop_cond(self.model, state))
            conds.append(cond)
        return conds

    def _handle_wave(self, cmd: dict) -> dict:
        from ..model import Expectation

        t_start = time.monotonic()
        self._round = int(cmd.get("round", self._round))
        self._epoch = int(cmd.get("epoch", self._epoch))
        self._faults.crash("worker_crash", wave=self._round,
                           worker=self.name)
        B = self.B
        parts_vecs, parts_fps, parts_ebits, n = self._take_batch(B)
        if n == 0:
            # Still a barrier participant: compute_s rides back so the
            # straggler attribution sees an (idle) segment, but an
            # empty wave emits no event — nothing happened here.
            return {"ok": True, "successors": 0, "candidates": 0,
                    "hits": {}, "out": {}, "queued": self._queued(),
                    "compute_s": round(time.monotonic() - t_start, 6)}
        batch_vecs = np.zeros((B, self.W), np.uint32)
        batch_fps = np.zeros(B, np.uint64)
        batch_ebits = np.zeros(B, np.uint32)
        row = 0
        for vecs, fps, ebits in zip(parts_vecs, parts_fps, parts_ebits):
            k = len(fps)
            batch_vecs[row:row + k] = vecs
            batch_fps[row:row + k] = fps
            batch_ebits[row:row + k] = ebits
            row += k
        valid = np.arange(B) < n

        prof_s = t0 = None
        if self._prof.enabled and self._prof.should_sample(
                self._prof_pkey):
            t0 = time.monotonic()
        (conds_out, succ_count, terminal, cleared, succ_flat, dedup_fps,
         path_fps, child_ebits, send_mask) = self._expand(
            batch_vecs, valid, batch_ebits)
        terminal = np.asarray(terminal)
        cleared = np.asarray(cleared)
        succ_flat = np.asarray(succ_flat)
        dedup_fps = np.asarray(dedup_fps)
        path_fps = np.asarray(path_fps)
        child_ebits = np.asarray(child_ebits)
        send_mask = np.asarray(send_mask)
        if t0 is not None:
            # The np.asarray conversions above already materialized
            # every output — the worker's expand is synchronous, so
            # this rest point costs nothing extra (obs/prof.py).
            prof_s = time.monotonic() - t0

        conds = self._host_conds(conds_out, batch_vecs, n)

        # Discoveries on the expanded batch (first hit per property, in
        # batch order — the engines' rule).
        hits: Dict[str, int] = {}
        for i, prop in enumerate(self.properties):
            if prop.expectation is Expectation.ALWAYS:
                hit = valid & ~conds[i]
            elif prop.expectation is Expectation.SOMETIMES:
                hit = valid & conds[i]
            else:
                continue
            rows = np.flatnonzero(hit)
            if rows.size:
                hits.setdefault(prop.name, int(batch_fps[rows[0]]))
        if self.eventually_idx:
            for r in np.flatnonzero(terminal[:n] & (cleared[:n] != 0)):
                for i in self.eventually_idx:
                    prop = self.properties[i]
                    if (int(cleared[r]) >> i) & 1 \
                            and prop.name not in hits:
                        hits[prop.name] = int(batch_fps[r])

        # Outbound rows grouped by destination partition.
        idx = np.flatnonzero(send_mask)
        out: Dict[int, tuple] = {}
        if idx.size:
            dest = (dedup_fps[idx] % np.uint64(self.n_parts)).astype(
                np.int64)
            for p in np.unique(dest):
                rows = idx[dest == p]
                out[int(p)] = (succ_flat[rows], dedup_fps[rows],
                               path_fps[rows], child_ebits[rows])
        successors = int(np.asarray(succ_count))
        self._states_total += successors
        compiled, self._compiled_once = (not self._compiled_once,
                                         True)
        # The per-worker wave event (schema v5), emitted where the
        # work happened: cumulative counts are THIS worker's (they
        # rewind only across a relay rotation, which starts a new run),
        # novel is what this worker's partitions accepted since its
        # last wave event (owner-side dedup happens in deliver).
        novel, self._novel_accum = self._novel_accum, 0
        from ..checker.base import host_store_capacity

        in_ram = self._visited_rows_in_ram()
        capacity = host_store_capacity(in_ram)
        evt = {
            "t": round(time.monotonic(), 6),
            "states": self._states_total,
            "unique": in_ram + (self._store.spilled_rows
                                if self._store.active else 0),
            "bucket": B, "waves": 1, "inflight": 0,
            "compiled": compiled, "successors": successors,
            "candidates": int(idx.size), "novel": novel,
            # Real host-store occupancy gauges (schema v6; these
            # shipped as permanent nulls through v5).
            "out_rows": novel, "capacity": capacity,
            "load_factor": round(in_ram / capacity, 4),
            "overflow": False, "bytes_per_state": 4 * self.W,
            "arena_bytes": None, "table_bytes": 8 * in_ram,
            "epoch": self._epoch, "round": self._round,
            "tier_host_rows": in_ram, "tier_host_bytes": 8 * in_ram}
        if self._store.active:
            g = self._store.gauges()
            evt["tier_host_rows"] += g["tier_host_rows"]
            evt["tier_host_bytes"] += g["tier_host_bytes"]
            evt["tier_disk_rows"] = g["tier_disk_rows"]
            evt["tier_disk_bytes"] = g["tier_disk_bytes"]
        if self._prof.enabled:
            # v13 cost stamping + (on sampled expands) the
            # profile_snapshot roofline event — it rides the relay
            # with the wave stream, stamped worker/seq.
            self._prof.wave(evt, self._prof_pkey, prof_s, self._relay,
                            self._flight)
        self._relay.wave(evt)
        if self._wave_obs.enabled:
            self._wave_obs.wave(evt, self._relay, self._flight)
        return {"ok": True, "successors": successors,
                "candidates": int(idx.size), "hits": hits, "out": out,
                "queued": self._queued(),
                "compute_s": round(time.monotonic() - t_start, 6),
                # Compact per-worker tier summary (None when the store
                # is disarmed) — the coordinator's store aggregate.
                "store": ({"spilled_rows": int(self._store.spilled_rows),
                           "disk_rows": int(self._store.cold_rows),
                           "host_rows": int(self._store.warm_rows)}
                          if self._store.active else None)}

    def _handle_deliver(self, cmd: dict) -> dict:
        t_start = time.monotonic()
        novel_total = 0
        err_lane = self.dm.error_lane
        for p in sorted(cmd["blocks"]):
            part = self.parts.get(p)
            if part is None:
                return {"ok": False,
                        "error": f"delivery for partition {p} this "
                                 f"worker does not own (epoch skew)"}
            blocks = cmd["blocks"][p]
            vecs = np.concatenate([b[0] for b in blocks])
            dfps = np.concatenate([b[1] for b in blocks])
            pfps = np.concatenate([b[2] for b in blocks])
            ebits = np.concatenate([b[3] for b in blocks])
            # First occurrence within the concatenated receive order,
            # then membership against the partition's visited set — the
            # owner-side dedup of the sharded exchange.
            _, first_idx = np.unique(dfps, return_index=True)
            first = np.zeros(len(dfps), bool)
            first[first_idx] = True
            visited = part.visited
            rows = np.flatnonzero(first)
            if self._store.active and self._store.spilled_rows \
                    and rows.size:
                # Spilled-tier membership first: a fingerprint whose
                # set was moved warm/cold must not be re-counted (the
                # engines' per-wave host probe, partition-scoped).
                rows = rows[~self._store.probe_partition(p, dfps[rows])]
            keep = []
            for r in rows:
                fp = int(dfps[r])
                if fp not in visited:
                    visited.add(fp)
                    keep.append(r)
            if not keep:
                continue
            keep = np.asarray(keep)
            new_vecs = vecs[keep]
            if err_lane is not None and new_vecs[:, err_lane].any():
                return {"ok": False,
                        "error": f"device model error lane {err_lane} "
                                 "is set in a generated state: an "
                                 "encoding capacity was exceeded"}
            part.queue.append((new_vecs, pfps[keep], ebits[keep]))
            novel_total += len(keep)
        self._novel_accum += novel_total
        if self._store.active:
            self._maybe_spill_visited()
        return {"ok": True, "novel": novel_total,
                "queued": self._queued(),
                "exchange_s": round(time.monotonic() - t_start, 6)}

    def _handle(self, cmd: dict) -> Optional[dict]:
        op = cmd["cmd"]
        if op == "wave":
            return self._handle_wave(cmd)
        if op == "deliver":
            return self._handle_deliver(cmd)
        if op == "assign":
            if "epoch" in cmd:
                self._epoch = int(cmd["epoch"])
            if cmd.get("reset"):
                self.parts.clear()
                if self._store.active:
                    self._store.reset()
                # A reassignment rewinds/re-bases this worker's
                # cumulative counters (rollback migration, join
                # handoff), so the relayed stream starts a NEW run —
                # the lint's per-run monotonicity survives, and seq
                # keeps counting across the rotation.
                self._states_total = 0
                self._novel_accum = 0
                self._relay.rotate({"reassigned_at_epoch": self._epoch})
            for p, seed in (cmd.get("seed") or {}).items():
                self._install_seed(int(p), seed)
            for p, (path, want_round) in (cmd.get("load") or {}).items():
                self._load_partition(int(p), path, want_round)
            return {"ok": True, "queued": self._queued(),
                    "unique": {p: len(part.visited)
                               for p, part in self.parts.items()}}
        if op == "drop":
            for p in cmd["partitions"]:
                self.parts.pop(int(p), None)
                if self._store.active:
                    self._store.drop_partition(int(p))
            # Dropping partitions shrinks this worker's visited union;
            # rotate so the next wave's smaller cumulative ``unique``
            # starts a fresh run instead of going backwards in the old
            # one.
            self._relay.rotate({"dropped": len(cmd["partitions"])})
            return {"ok": True, "queued": self._queued()}
        if op == "checkpoint":
            parts = cmd.get("partitions")
            parts = sorted(self.parts) if parts is None else parts
            for p in parts:
                self._write_partition(int(p), cmd["path"],
                                      int(cmd["round"]),
                                      int(cmd["epoch"]))
            # Safe point: all shard writes must have landed before the
            # ok reply — the coordinator writes the manifest only once
            # every worker acked, so a crash mid-write leaves the old
            # generation authoritative. A writer-thread fault (torn
            # shard, disk full) surfaces here and rides the error reply.
            self._aio.join()
            return {"ok": True,
                    "unique": {p: len(self.parts[p].visited)
                               for p in parts}}
        if op == "stop":
            # Clean exit: drain the background writer (pending spills
            # land or are dropped; either is safe — warm rows stay warm
            # until a landing, and unmanifested shards are inert).
            self._aio.close()
            return None  # signals a clean exit
        return {"ok": False, "error": f"unknown command {op!r}"}

    # -- Main loop ---------------------------------------------------------

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._stop_hb.wait(interval):
            try:
                _send_msg(self.sock, {"msg": "heartbeat",
                                      "worker": self.name},
                          self.send_lock)
            except OSError:
                return

    def serve(self, kill_event: Optional[threading.Event] = None) -> None:
        """Serves coordinator commands until ``stop``, death, or an
        injected crash. ``kill_event`` (thread transport) simulates a
        SIGKILL: die abruptly — no reply, no goodbye — at the next
        command, which is exactly what the coordinator's lease/EOF
        machinery must absorb."""
        from .faults import InjectedFault

        try:
            # Register FIRST, then start heartbeating: the acceptor
            # treats the first frame on a fresh socket as the hello,
            # and a heartbeat winning the send_lock race would get the
            # whole worker silently dropped.
            _send_msg(self.sock, {"msg": "register", "worker": self.name},
                      self.send_lock)
            hb = threading.Thread(
                target=self._heartbeat_loop,
                args=(float(self.cfg.get("heartbeat_s", 0.25)),),
                daemon=True)
            hb.start()
            while True:
                cmd = _recv_msg(self.sock)
                if kill_event is not None and kill_event.is_set():
                    return  # vanish without a reply (simulated SIGKILL)
                try:
                    reply = self._handle(cmd)
                except InjectedFault as e:
                    # worker_crash fired: die the hard way. The fault
                    # event is already flushed by the plan's emitter;
                    # the flight ring additionally records it and dumps
                    # — the postmortem's LAST event is the fault point,
                    # which is the whole point of a flight recorder.
                    if self._flight.armed:
                        self._flight.record_event(
                            "fault", point="worker_crash", hit=0,
                            mode="crash", worker=self.name,
                            error=str(e)[:300])
                        self._flight.dump(
                            f"injected worker_crash: {e}")
                    if self.cfg.get("transport") == "process":
                        os._exit(17)
                    return
                except Exception as e:  # noqa: BLE001 — surface upward
                    reply = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"[:500]}
                stop = reply is None
                reply = {"ok": True} if stop else reply
                # Echo the command's sequence number: the coordinator
                # drops stale replies (a round torn by a loss leaves
                # unread replies in buffers) by matching on it.
                reply["seq"] = cmd.get("seq")
                # Piggyback the relayed trace batch (bounded) on the
                # reply that was going to the coordinator anyway.
                batch, dropped = self._relay.drain()
                if batch:
                    reply["trace"] = batch
                if dropped:
                    reply["trace_dropped"] = dropped
                _send_msg(self.sock, reply, self.send_lock)
                if stop:
                    return
        except (ConnectionError, OSError):
            return  # the coordinator went away; nothing to report to
        finally:
            self._stop_hb.set()
            try:
                self.sock.close()
            except OSError:
                pass


def _worker_thread_main(addr, name, model_factory, cfg, kill_event):
    runtime = None
    try:
        runtime = _WorkerRuntime(name, model_factory, cfg)
        runtime.sock = socket.create_connection(addr)
        runtime.serve(kill_event)
    except Exception as e:  # noqa: BLE001 — a dead worker is a lease lapse
        if runtime is not None and runtime._flight.armed:
            # The unhandled-exception postmortem: the coordinator only
            # sees a lease lapse; the ring's dump says what the worker
            # was doing when it died.
            runtime._flight.dump(f"{type(e).__name__}: {e}")
        if runtime is not None and runtime.sock is not None:
            try:
                runtime.sock.close()
            except OSError:
                pass


def _worker_process_entry(addr, name, model_factory, cfg):
    """Module-level so multiprocessing's spawn context can import it.
    The spawned interpreter inherits JAX_PLATFORMS from the parent
    environment (the tests pin cpu), builds its own backend, and is
    exactly the per-host process a jax.distributed deployment runs.
    Heavy construction (the jax import) runs BEFORE connecting, so
    the coordinator's lease clock starts on a ready worker."""
    runtime = _WorkerRuntime(name, model_factory, cfg)
    runtime.sock = socket.create_connection(addr)
    try:
        runtime.serve(None)
    except Exception as e:  # noqa: BLE001 — dump, then die as before
        if runtime._flight.armed:
            runtime._flight.dump(f"{type(e).__name__}: {e}")
        raise


# -- Coordinator -----------------------------------------------------------

class _Handle:
    """The coordinator's view of one worker."""

    __slots__ = ("name", "sock", "thread", "proc", "kill_event")

    def __init__(self, name, sock, thread=None, proc=None,
                 kill_event=None):
        self.name = name
        self.sock = sock
        self.thread = thread
        self.proc = proc
        self.kill_event = kill_event


class ElasticChecker:
    """Runs an owner-partitioned BFS over ``workers`` elastic workers.

    ``model_factory`` must be picklable for ``transport="process"``
    (e.g. ``functools.partial(TwoPhaseSys, 3)``); any callable works
    for ``transport="thread"``. The checker facade mirrors the engine
    API (``join`` / ``state_count`` / ``unique_state_count`` /
    ``discoveries`` / ``wave_log`` / ``dispatch_log``) so bench and
    tests drive it like any other engine — ``discoveries()`` returns
    ``{property name: fingerprint}`` (no Path reconstruction: the
    parent map is distributed; replay it on a single-process engine
    from the same checkpoint when a trace is needed).

    Deterministic chaos for tests/bench: ``kill_at={round: worker}``
    kills a worker just before that coordinated round;
    ``join_at={round: name}`` spawns and admits a new worker at that
    round's barrier. Both are also drivable live via
    :meth:`kill_worker` / :meth:`add_worker`.
    """

    def __init__(self, model_factory: Callable, *, workers: int = 2,
                 n_partitions: int = 8, batch_rows: int = 256,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every_rounds: int = 4,
                 transport: str = "thread",
                 lease_s: float = 15.0, heartbeat_s: float = 0.25,
                 symmetry: bool = False,
                 target_state_count: Optional[int] = None,
                 resume_from: Optional[str] = None,
                 kill_at: Optional[Dict[int, str]] = None,
                 join_at: Optional[Dict[int, str]] = None,
                 spawn_timeout_s: float = 120.0,
                 command_timeout_s: float = 300.0):
        if transport not in ("thread", "process"):
            raise ValueError(
                f"transport must be 'thread' or 'process', got "
                f"{transport!r}")
        if workers < 1:
            raise ValueError("need at least one worker")
        self._factory = model_factory
        self._n_parts = int(n_partitions)
        self._B = int(batch_rows)
        self._ckpt = checkpoint_path
        self._ckpt_every = max(1, int(checkpoint_every_rounds))
        self._transport = transport
        self._lease_s = float(lease_s)
        self._hb_s = float(heartbeat_s)
        self._symmetry = bool(symmetry)
        self._target = target_state_count
        self._resume_from = resume_from
        self._kill_at = dict(kill_at or {})
        self._join_at = dict(join_at or {})
        self._spawn_timeout = float(spawn_timeout_s)
        self._cmd_timeout = float(command_timeout_s)

        self._model = model_factory()
        self._dm = self._model.device_model()
        self._W = self._dm.state_width
        from ..model import Expectation

        self._ebits_all = 0
        self._n_properties = len(self._model.properties())
        for i, p in enumerate(self._model.properties()):
            if p.expectation is Expectation.EVENTUALLY:
                self._ebits_all |= 1 << i

        self._lock = threading.Lock()
        self._done = threading.Event()
        self._stop_req = threading.Event()
        self._error: Optional[BaseException] = None
        self._state_count = 0
        self._unique_count = 0
        self._discoveries: Dict[str, int] = {}
        self._round = 0
        self._queued: Dict[int, int] = {}
        self._migrations = 0
        self._rebalances = 0
        #: last per-worker tier summary off the wave replies (None
        #: entries never land) — the coordinator's store aggregate.
        self._worker_store: Dict[str, dict] = {}
        #: lifecycle records (worker_lost / migrate_done / rebalance /
        #: worker_join), mirroring the obs events, for tests and bench.
        self.events: List[dict] = []
        self.wave_log: List[tuple] = []
        self.dispatch_log: List[dict] = []

        self._members: Dict[str, _Handle] = {}
        #: command sequence counter: replies echo it, so a round torn
        #: by a loss cannot desync the protocol (stale replies parked
        #: in a survivor's socket buffer are matched and dropped).
        self._seq = 0
        self._membership = Membership(self._lease_s)
        self._map = OwnerMap(self._n_parts,
                             [f"w{i}" for i in range(int(workers))])
        self._next_worker = int(workers)
        self._incoming: "queue.Queue" = queue.Queue()
        self._pending_joins: List[str] = []

        self._listener = socket.create_server(("127.0.0.1", 0))
        self._addr = self._listener.getsockname()
        self._accept_stop = threading.Event()
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._acceptor.start()

        self._tracer = tracer_from_env("elastic", meta={
            "model": type(self._model).__name__,
            "workers": list(self._map.owners),
            "n_partitions": self._n_parts,
            "batch_rows": self._B,
            "transport": transport})
        #: always-on coordinator flight ring: sees the coordinator's
        #: own round entries, lifecycle events, AND every merged
        #: worker event — so a worker_lost dump contains the
        #: casualty's last relayed waves even when the worker itself
        #: could not dump (SIGKILL leaves no exception handler).
        self._flight = recorder_from_env(
            f"elastic-coordinator-{os.getpid()}")
        #: service observability (obs/hist.py): round-summary latency
        #: histograms, SLO tracking, and slow-wave anomaly attribution
        #: over the coordinator's dispatch entries; the collector also
        #: feeds per-worker compute-vs-wait segments into it.
        self._wave_obs = wave_obs_from_env("elastic")
        if self._wave_obs.enabled and self._flight.armed:
            self._flight.set_hist_source(
                self._wave_obs.final_snapshot_event)
        #: postmortem dump paths this run produced (worker losses,
        #: terminal aborts) — surfaced via ``elastic_obs`` and bench.
        self.postmortems: List[str] = []
        #: merges the workers' relayed streams into the trace file in
        #: (epoch, round, worker, seq) order and owns the straggler
        #: attribution (obs/collect.py).
        self._collector = TraceCollector(self._tracer,
                                         flight=self._flight,
                                         obs=self._wave_obs)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- Transport plumbing ------------------------------------------------

    def _accept_loop(self) -> None:
        self._listener.settimeout(0.25)
        while not self._accept_stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                sock.settimeout(10.0)
                hello = _recv_msg(sock)
                sock.settimeout(None)
            except (ConnectionError, OSError, struct.error):
                sock.close()
                continue
            if hello.get("msg") == "register":
                self._incoming.put((hello["worker"], sock))
            else:
                sock.close()

    def _spawn_worker(self, name: str) -> None:
        if name in self._members:
            raise ValueError(
                f"worker name {name!r} is already a live member — a "
                "duplicate would clobber its handle and strand its "
                "partitions")
        cfg = {"n_partitions": self._n_parts, "batch_rows": self._B,
               "symmetry": self._symmetry, "heartbeat_s": self._hb_s,
               "transport": self._transport,
               # Workers buffer/ship their relayed streams only when
               # the coordinator is actually writing a trace; their
               # flight recorders stay on regardless.
               "relay_trace": self._tracer.enabled}
        if self._transport == "thread":
            kill_event = threading.Event()
            t = threading.Thread(
                target=_worker_thread_main,
                args=(self._addr, name, self._factory, cfg, kill_event),
                daemon=True)
            t.start()
            self._members[name] = _Handle(name, None, thread=t,
                                          kill_event=kill_event)
        else:
            import multiprocessing

            ctx = multiprocessing.get_context("spawn")
            proc = ctx.Process(
                target=_worker_process_entry,
                args=(self._addr, name, self._factory, cfg), daemon=True)
            proc.start()
            self._members[name] = _Handle(name, None, proc=proc)

    def _await_register(self, names, deadline: float) -> None:
        waiting = set(names)
        while waiting:
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                raise _Abort(
                    f"worker(s) {sorted(waiting)} never registered "
                    f"within {self._spawn_timeout:.0f}s")
            try:
                name, sock = self._incoming.get(timeout=min(timeout, 1.0))
            except queue.Empty:
                continue
            handle = self._members.get(name)
            if handle is None:
                sock.close()
                continue
            handle.sock = sock
            self._membership.add(name)
            waiting.discard(name)

    def _reap(self, name: str) -> None:
        handle = self._members.pop(name, None)
        self._membership.drop(name)
        if handle is None:
            return
        if handle.sock is not None:
            try:
                handle.sock.close()
            except OSError:
                pass
        if handle.proc is not None and handle.proc.is_alive():
            handle.proc.kill()
            handle.proc.join(timeout=5.0)
        if handle.kill_event is not None:
            handle.kill_event.set()

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _send(self, name: str, msg: dict) -> None:
        handle = self._members[name]
        try:
            _send_msg(handle.sock, msg)
        except OSError as e:
            raise _WorkerLost([name]) from e

    def _await_reply(self, name: str, seq: Optional[int] = None) -> dict:
        """One reply from ``name``, absorbing heartbeats and dropping
        stale replies (``seq`` mismatch — leftovers of a round a loss
        tore down). Two liveness bounds, because they catch different
        deaths: the LEASE (no traffic at all — dead process, dead
        socket) and the COMMAND TIMEOUT (a worker wedged inside a
        command whose heartbeat thread is still dutifully beating —
        the preemptible-accelerator wedge mode; heartbeats prove the
        process lives, not that it progresses)."""
        handle = self._members[name]
        cmd_deadline = time.monotonic() + self._cmd_timeout
        handle.sock.settimeout(min(1.0, self._lease_s / 4))
        try:
            while True:
                try:
                    obj = _recv_msg(handle.sock)
                except socket.timeout:
                    if (self._membership.remaining(name) < 0
                            or time.monotonic() > cmd_deadline):
                        raise _WorkerLost([name]) from None
                    continue
                self._membership.beat(name)
                if obj.get("msg") == "heartbeat":
                    continue
                # Harvest the piggybacked trace batch off EVERY reply
                # — stale ones included: those events were already
                # drained from the worker's relay and exist nowhere
                # else.
                batch = obj.pop("trace", None)
                dropped = obj.pop("trace_dropped", 0)
                if batch or dropped:
                    self._collector.add_batch(name, batch or [],
                                              int(dropped))
                if seq is not None and obj.get("seq") != seq:
                    continue  # stale reply from a torn round
                return obj
        except (ConnectionError, OSError) as e:
            raise _WorkerLost([name]) from e
        finally:
            try:
                handle.sock.settimeout(None)
            except OSError:
                pass

    def _broadcast(self, msg: dict, names=None) -> Dict[str, dict]:
        """Send to every (or the named) live workers, then collect all
        replies; socket failures and lease lapses surface as
        :class:`_WorkerLost` carrying every casualty of the round."""
        names = self._membership.workers() if names is None else names
        seq = self._next_seq()
        msg = dict(msg, seq=seq)
        lost: List[str] = []
        for name in names:
            try:
                self._send(name, msg)
            except _WorkerLost as e:
                lost.extend(e.names)
        replies: Dict[str, dict] = {}
        for name in names:
            if name in lost:
                continue
            try:
                replies[name] = self._await_reply(name, seq)
            except _WorkerLost as e:
                lost.extend(e.names)
        if lost:
            raise _WorkerLost(lost)
        for name, reply in replies.items():
            if not reply.get("ok"):
                raise _Abort(
                    f"worker {name}: {reply.get('error', 'failed')}")
        return replies

    # -- Seeding / generations ---------------------------------------------

    def _seed_blocks(self):
        """Initial states, encoded/fingerprinted/deduplicated exactly
        like the engines' ``__init__`` seeding, bucketed by partition."""
        import jax.numpy as jnp

        from ..tpu.hashing import host_fp64

        model, dm = self._model, self._dm
        init_states = [s for s in model.init_states()
                       if model.within_boundary(s)]
        self._state_count = len(init_states)
        seen_reps = set()
        rows = []  # (partition, vec, raw fp, rep fp)
        for s in init_states:
            vec = np.asarray(dm.encode(s), np.uint32)
            fp = host_fp64(vec)
            if self._symmetry:
                rep = np.asarray(dm.representative(jnp.asarray(vec)),
                                 np.uint32)
                rep_fp = host_fp64(rep)
            else:
                rep_fp = fp
            if rep_fp in seen_reps:
                continue
            seen_reps.add(rep_fp)
            rows.append((int(rep_fp) % self._n_parts, vec, fp, rep_fp))
        self._unique_count = len(rows)
        seeds = {}
        for p in range(self._n_parts):
            mine = [r for r in rows if r[0] == p]
            vecs = (np.stack([r[1] for r in mine]).astype(np.uint32)
                    if mine else np.zeros((0, self._W), np.uint32))
            fps = np.array([r[2] for r in mine], np.uint64)
            ebits = np.full(len(mine), self._ebits_all, np.uint32)
            visited = np.array([r[3] for r in mine], np.uint64)
            seeds[p] = (vecs, fps, ebits, visited)
        return seeds

    def _assign_all(self, seeds=None, load_round=None,
                    load_path=None) -> None:
        """(Re)assigns every partition per the current map — seeding a
        fresh run, resuming a coordinator (``load_path`` = the resumed
        manifest's path), or rolling everyone back to a generation
        (``load_round`` at the run's own checkpoint path)."""
        load_path = self._ckpt if load_path is None else load_path
        seq = self._next_seq()
        for name in self._membership.workers():
            parts = self._map.partitions_of(name)
            msg = {"cmd": "assign", "partitions": list(parts),
                   "epoch": self._map.epoch, "reset": True, "seq": seq}
            if seeds is not None:
                msg["seed"] = {p: seeds[p] for p in parts}
            else:
                msg["load"] = {p: (load_path, load_round)
                               for p in parts}
            self._send(name, msg)
        queued: Dict[int, int] = {}
        for name in self._membership.workers():
            reply = self._await_reply(name, seq)
            if not reply.get("ok"):
                raise _Abort(f"worker {name}: assign failed: "
                             f"{reply.get('error')}")
            queued.update({int(p): r
                           for p, r in reply["queued"].items()})
        self._queued = queued

    def _write_generation(self, round_: int) -> None:
        """The full-barrier checkpoint: every worker snapshots every
        owned partition at ``round_``, then the manifest lands LAST —
        so the newest valid manifest always names a round whose shard
        files (current or ``.prev``) all exist. Exchange is drained by
        construction (we only checkpoint between rounds)."""
        if self._ckpt is None:
            return
        from ..checkpoint_format import make_header, write_atomic

        replies = self._broadcast({
            "cmd": "checkpoint", "partitions": None, "path": self._ckpt,
            "round": round_, "epoch": self._map.epoch})
        part_unique = np.zeros(self._n_parts, np.uint64)
        for reply in replies.values():
            for p, u in reply.get("unique", {}).items():
                part_unique[int(p)] = u
        header = make_header(
            model_name=type(self._model).__name__, state_width=self._W,
            state_count=self._state_count,
            unique_count=self._unique_count,
            use_symmetry=self._symmetry, discoveries=self._discoveries,
            elastic={"round": round_, "epoch": self._map.epoch,
                     "partitions": self._n_parts,
                     "workers": list(self._membership.workers())})
        write_atomic(self._ckpt, dict(header=header,
                                      partition_unique=part_unique))

    def _read_generation(self, source: Optional[str] = None) -> dict:
        """The newest valid manifest's round + run-global counters —
        what a rollback (or a resumed coordinator, via ``source`` =
        the ``resume_from`` manifest) restores."""
        from ..checkpoint_format import load_checkpoint, validate_header
        from .supervisor import newest_valid_checkpoint

        source = self._ckpt if source is None else source
        path = newest_valid_checkpoint(source)
        if path is None:
            raise _Abort(
                f"no valid checkpoint generation at {source!r} to "
                "recover from")
        with load_checkpoint(path) as data:
            header = validate_header(
                data, model_name=type(self._model).__name__,
                state_width=self._W, use_symmetry=self._symmetry)
            elastic = header.get("elastic")
            if not elastic:
                raise _Abort(
                    f"checkpoint {path!r} is not an elastic manifest "
                    "(no per-shard generation to recover)")
            return {
                "round": int(elastic["round"]),
                "state_count": int(header["state_count"]),
                "unique_count": int(header["unique_count"]),
                "discoveries": {k: int(v) for k, v
                                in header["discoveries"].items()},
            }

    # -- Membership transitions --------------------------------------------

    def _emit_lifecycle(self, etype: str, **fields) -> None:
        record = dict(fields, type=etype, t=time.monotonic())
        with self._lock:
            self.events.append(record)
        if self._flight.armed:
            self._flight.record_event(etype, **fields)
        if self._tracer.enabled:
            self._tracer.event(etype, _flush=True, **fields)

    def _recover(self, lost: List[str]) -> None:
        """Migration: roll every survivor back to the newest complete
        generation, adopt the dead workers' partitions onto the
        rendezvous winners, bump the epoch. Survivors dying mid-
        recovery just widen the casualty list and retry."""
        pending = list(lost)
        #: every casualty of this recovery cycle with the partition
        #: count it owned when it died — exactly one migrate_done is
        #: emitted per entry on success (the lint's 1:1 pairing).
        casualties: Dict[str, int] = {}
        while True:
            # Merge whatever the casualties' last replies already
            # relayed BEFORE dumping: the coordinator's ring (and the
            # trace) must show the dead worker's final waves.
            self._collector.flush()
            for name in pending:
                casualties[name] = len(self._map.partitions_of(name))
                dump = None
                if self._flight.armed:
                    # A SIGKILLed worker cannot dump its own ring; the
                    # coordinator dumps ITS ring — which contains the
                    # merged recent history, the casualty's relayed
                    # events included — named for the casualty.
                    dump = self._flight.dump(
                        f"worker_lost: {name} (epoch "
                        f"{self._map.epoch})",
                        name=f"{name}-coordinator")
                    if dump:
                        self.postmortems.append(dump)
                self._emit_lifecycle("worker_lost", worker=name,
                                     epoch=self._map.epoch, dump=dump)
                # The casualty's tier summary must not keep feeding
                # the coordinator's store aggregate (its spilled rows
                # are rebuilt into survivors' in-RAM sets by the
                # migration). NOT in _reap: the normal end-of-run
                # shutdown reaps every worker and the final stats must
                # keep their summaries.
                self._worker_store.pop(name, None)
                self._reap(name)
            survivors = self._membership.workers()
            if not survivors:
                raise _Abort("all workers lost; nothing to migrate to")
            if self._ckpt is None:
                raise _Abort(
                    "worker lost with no checkpoint_path: partitions "
                    "are unrecoverable (run with a checkpoint path for "
                    "elasticity)")
            old_map = self._map
            self._map = old_map.with_owners(survivors)
            gen = self._read_generation()
            try:
                self._assign_all(load_round=gen["round"])
            except _WorkerLost as e:
                pending = e.names
                continue
            # Counters rewind WITH the data — recovered totals cannot
            # double-count work redone since the generation.
            with self._lock:
                self._state_count = gen["state_count"]
                self._unique_count = gen["unique_count"]
                self._discoveries = dict(gen["discoveries"])
                # Tier summaries rewind with the data: every worker's
                # store was reset by the reassign, so stale spill
                # counts must not survive into the new epoch's
                # aggregate (the next round's replies repopulate).
                self._worker_store.clear()
            self._round = gen["round"]
            self._migrations += 1
            # Rotate the tracer run: cumulative wave counters rewind
            # with the rollback, and the lint's monotonicity invariant
            # is per run — a migration starts a new one, exactly as a
            # supervisor restart does (each attempt is its own run).
            # The collector flushes through the OLD tracer first (the
            # survivors' reassign replies carried their own rotation
            # markers), then follows the coordinator onto the new one
            # — cross-stream fault/recover pairing is file-order
            # global, so it survives the rotation by construction.
            self._collector.flush()
            if self._wave_obs.enabled:
                # Final snapshot into the closing run (cumulative
                # counts stay monotone within the new run too).
                self._wave_obs.close(self._tracer)
            self._tracer.close()
            self._tracer = tracer_from_env("elastic", meta={
                "model": type(self._model).__name__,
                "migrated_after": sorted(pending),
                "epoch": self._map.epoch})
            self._collector.tracer = self._tracer
            # Exactly ONE migrate_done per lost worker (the lint's 1:1
            # membership pairing): even a worker that owned nothing is
            # acknowledged, and two losses in one round get two. ``to``
            # names the survivor that adopted the plurality of the dead
            # worker's partitions (first survivor when it owned none).
            adopters: Dict[str, Dict[str, int]] = {}
            for p, (old, new) in self._map.moves_from(old_map).items():
                if old in casualties:
                    by = adopters.setdefault(old, {})
                    by[new] = by.get(new, 0) + 1
            for name in sorted(casualties):
                by = adopters.get(name, {})
                to = (max(sorted(by), key=by.get) if by
                      else survivors[0])
                self._emit_lifecycle("migrate_done",
                                     partitions=casualties[name],
                                     to=to, epoch=self._map.epoch)
            if self._tracer.enabled:
                # The migration IS the recovery: an injected
                # worker_crash fault pairs with this, exactly like a
                # supervised retry pairs with a wave_crash.
                self._tracer.event(
                    "recover", attempt=self._migrations, backoff_s=0.0,
                    resumed_from=self._ckpt, kind="migration",
                    _flush=True)
            return

    def _admit_join(self, name: str, sock) -> None:
        """Admits a registered joiner at a drained barrier: donors
        snapshot the partitions the joiner wins, the joiner loads them,
        donors drop them, the epoch bumps, and a fresh full generation
        lands so later rollbacks stay consistent. No rollback here —
        a join loses no work."""
        handle = self._members.get(name)
        if handle is None:
            handle = self._members[name] = _Handle(name, sock)
        else:
            handle.sock = sock
        self._membership.add(name)
        self._emit_lifecycle("worker_join", worker=name,
                             epoch=self._map.epoch)
        old_map = self._map
        new_map = old_map.with_owners(
            list(old_map.owners) + [name]
            if name not in old_map.owners else old_map.owners)
        moves = new_map.moves_from(old_map)
        if moves and self._ckpt is None:
            # No handoff medium: admit the worker but leave ownership
            # alone (it will win partitions at the next loss/epoch).
            self._map = old_map.with_assignment(old_map.assignment())
            return
        donors: Dict[str, List[int]] = {}
        for p, (old, _new) in sorted(moves.items()):
            donors.setdefault(old, []).append(p)
        for donor, ps in sorted(donors.items()):
            self._broadcast({"cmd": "checkpoint", "partitions": ps,
                             "path": self._ckpt, "round": self._round,
                             "epoch": old_map.epoch}, names=[donor])
        self._map = new_map
        moved = sorted(moves)
        replies = self._broadcast(
            {"cmd": "assign", "partitions": moved, "reset": True,
             "epoch": new_map.epoch,
             "load": {p: (self._ckpt, self._round) for p in moved}},
            names=[name])
        for donor, ps in sorted(donors.items()):
            self._broadcast({"cmd": "drop", "partitions": ps},
                            names=[donor])
        with self._lock:
            for p, r in replies[name]["queued"].items():
                self._queued[int(p)] = r
        self._rebalances += 1
        self._emit_lifecycle("rebalance", partitions=len(moved),
                             to=name, epoch=new_map.epoch)
        # A fresh generation at the new epoch: every later rollback
        # must see one consistent (manifest, shard files) cut that
        # already reflects the new ownership.
        self._write_generation(self._round)

    def _drain_joins(self) -> None:
        while True:
            try:
                name, sock = self._incoming.get_nowait()
            except queue.Empty:
                return
            try:
                self._admit_join(name, sock)
            except _WorkerLost as e:
                self._recover(e.names)
            except _Abort as e:
                # A failed admission (the joiner cannot load a donated
                # shard, a donor's handoff snapshot failed) must not
                # convert an ELECTIVE elasticity operation into total
                # run failure: the generations on disk are intact, so
                # treat the joiner as lost and recover — the rollback
                # re-derives ownership over the survivors, whichever
                # half-step the admission died at.
                if name not in self._members:
                    raise
                self.events.append({"type": "join_failed", "worker":
                                    name, "error": str(e)[:300],
                                    "t": time.monotonic()})
                self._recover([name])

    # -- The coordinated round loop ----------------------------------------

    def _run(self) -> None:
        try:
            self._run_rounds()
        except (_Abort, _WorkerLost) as e:
            # _WorkerLost escaping the recovery machinery (a loss
            # during startup seeding, before any generation exists to
            # migrate from) is terminal too: same public error type,
            # same acknowledged abort on the trace — never a silent
            # internal exception.
            dump = None
            if self._flight.armed:
                dump = self._flight.dump(f"abort: {e}")
                if dump:
                    self.postmortems.append(dump)
            if self._tracer.enabled:
                self._tracer.event("abort", reason=str(e)[:300],
                                   attempts=self._migrations,
                                   dump=dump, _flush=True)
            self._error = RuntimeError(str(e))
        except BaseException as e:  # noqa: BLE001 — surfaced at join()
            self._error = e
            if self._flight.armed:
                dump = self._flight.dump(f"{type(e).__name__}: {e}")
                if dump:
                    self.postmortems.append(dump)
        finally:
            # The stop replies carried each worker's final relay drain;
            # merge them before the stream closes.
            self._collector.flush()
            if self._wave_obs.enabled:
                self._wave_obs.close(self._tracer)
            self._tracer.close()
            self._done.set()

    def _run_rounds(self) -> None:
        initial = list(self._map.owners)
        for name in initial:
            self._spawn_worker(name)
        self._await_register(
            initial, time.monotonic() + self._spawn_timeout)
        if self._resume_from is not None:
            from ..checkpoint_format import PREV_SUFFIX

            gen = self._read_generation(self._resume_from)
            # Shard files always live beside the BASE manifest path:
            # a resume_from handed an explicit '...prev' manifest
            # (what newest_valid_checkpoint returns after a torn
            # write) must probe 'X.shardNNN(.prev)', not the
            # nonexistent 'X.prev.shardNNN'.
            base = self._resume_from
            if base.endswith(PREV_SUFFIX):
                base = base[:-len(PREV_SUFFIX)]
            self._assign_all(load_round=gen["round"], load_path=base)
            with self._lock:
                self._state_count = gen["state_count"]
                self._unique_count = gen["unique_count"]
                self._discoveries = dict(gen["discoveries"])
            self._round = gen["round"]
            # Re-establish a generation at THIS run's checkpoint path
            # (resume_from may be a different store): a worker lost
            # before the first post-resume cadence must migrate from
            # here, exactly like the seed path's generation 0.
            self._write_generation(self._round)
        else:
            self._assign_all(seeds=self._seed_blocks())
            # Generation 0 before any expansion: a worker lost before
            # the first cadence checkpoint still migrates (it rewinds
            # to the seed, not to nothing).
            self._write_generation(self._round)
        self.wave_log.append((time.monotonic(), self._state_count))

        while True:
            # Rest point: stop requests, scripted chaos, joins, lease
            # sweeps.
            if self._stop_req.is_set():
                break
            next_round = self._round + 1
            victim = self._kill_at.pop(next_round, None)
            if victim is not None:
                self.kill_worker(victim)
            joiner = self._join_at.pop(next_round, None)
            if joiner is not None:
                self._spawn_worker(joiner)
            self._drain_joins()
            expired = self._membership.expired()
            if expired:
                self._recover(expired)
                continue
            with self._lock:
                # The engine family's stop rule (bfs.rs:117 /
                # engine._run_waves): drained queues, every property
                # discovered, or the target cap — checked at the same
                # rest-point granularity the sharded host loop uses.
                done = (all(r == 0 for r in self._queued.values())
                        or len(self._discoveries) == self._n_properties
                        or (self._target is not None
                            and self._state_count >= self._target))
            if done:
                break
            try:
                self._one_round()
            except _WorkerLost as e:
                self._recover(e.names)
        self._final_workers = self._membership.workers()
        try:
            # The run is complete; a worker dying during the final
            # snapshot/goodbye loses nothing (totals are final and the
            # last cadence generation is on disk), so don't fail it.
            # A requested stop skips the final snapshot for promptness
            # (the last cadence generation already supports a resume).
            if not self._stop_req.is_set():
                self._write_generation(self._round)
            self._broadcast({"cmd": "stop"})
        except _WorkerLost:
            pass
        for name in list(self._members):
            self._reap(name)

    def _one_round(self) -> None:
        self._round += 1
        r = self._round
        replies = self._broadcast({"cmd": "wave", "round": r,
                                   "epoch": self._map.epoch})
        # Route every outbound block to its partition's CURRENT owner.
        # This is the epoch-aware hop: a block computed before a remap
        # never reaches a stale owner, because remaps only happen at
        # drained barriers (a loss discards the whole round instead).
        deliveries: Dict[str, Dict[int, list]] = {}
        successors = candidates = 0
        queued: Dict[int, int] = {}
        #: per-worker self-reported segment durations for this round —
        #: the straggler attribution's input (durations only: no
        #: cross-process clock ever gets compared).
        reports: Dict[str, dict] = {}
        for sender in sorted(replies):
            reply = replies[sender]
            successors += reply["successors"]
            candidates += reply["candidates"]
            queued.update({int(p): n
                           for p, n in reply["queued"].items()})
            if reply.get("store") is not None:
                self._worker_store[sender] = reply["store"]
            reports[sender] = {
                "compute_s": float(reply.get("compute_s") or 0.0),
                "successors": reply["successors"],
                "queued": sum(reply["queued"].values())}
            for p, block in reply["out"].items():
                owner = self._map.owner_of(int(p))
                deliveries.setdefault(owner, {}).setdefault(
                    int(p), []).append(block)
        novel = 0
        if deliveries:
            seq = self._next_seq()
            for name in sorted(deliveries):
                self._send(name, {"cmd": "deliver", "seq": seq,
                                  "blocks": deliveries[name]})
            for name in sorted(deliveries):
                reply = self._await_reply(name, seq)
                if not reply.get("ok"):
                    raise _Abort(f"worker {name}: "
                                 f"{reply.get('error', 'failed')}")
                novel += reply["novel"]
                queued.update({int(p): n
                               for p, n in reply["queued"].items()})
                if name in reports:
                    reports[name]["exchange_s"] = float(
                        reply.get("exchange_s") or 0.0)
                    reports[name]["queued"] = sum(
                        reply["queued"].values())
        # The round committed: apply counters and the wave event.
        hits: Dict[str, int] = {}
        for sender in sorted(replies):
            for prop, fp in replies[sender]["hits"].items():
                hits.setdefault(prop, fp)
        now = time.monotonic()
        with self._lock:
            self._state_count += successors
            self._unique_count += novel
            for prop, fp in hits.items():
                self._discoveries.setdefault(prop, fp)
            self._queued = queued
            self.wave_log.append((now, self._state_count))
            from ..checker.base import host_store_capacity

            capacity = host_store_capacity(self._unique_count)
            spilled = sum(s.get("spilled_rows", 0)
                          for s in self._worker_store.values())
            entry = {
                "t": now, "states": self._state_count,
                "unique": self._unique_count, "bucket": self._B,
                "waves": 1, "inflight": 0, "compiled": False,
                "successors": successors, "candidates": candidates,
                # Real store occupancy gauges (schema v6; permanent
                # nulls through v5): the run's visited store is the
                # union of the workers' host dicts, measured by the
                # same CPython growth policy the host engines report.
                "novel": novel, "out_rows": novel,
                "capacity": capacity,
                "load_factor": round(
                    max(0, self._unique_count - spilled) / capacity, 4),
                "overflow": False,
                "bytes_per_state": 4 * self._W, "arena_bytes": None,
                "table_bytes": 8 * self._unique_count,
                "tier_host_rows": max(0, self._unique_count - spilled),
                "tier_host_bytes": 8 * max(
                    0, self._unique_count - spilled),
                "tier_disk_rows": sum(
                    s.get("disk_rows", 0)
                    for s in self._worker_store.values()) or None,
                # v5 attribution: the coordinator's round summary is
                # positioned in the same (epoch, round) order its
                # workers' merged events use.
                "epoch": self._map.epoch, "round": r}
            self.dispatch_log.append(entry)
        if self._flight.armed:
            self._flight.record(entry)
        # Causal order in the merged file: the workers' round-r wave
        # events land BEFORE the coordinator's round-r summary that
        # folds them, then the straggler attribution for the round.
        self._collector.flush()
        if self._tracer.enabled:
            self._tracer.wave(entry)
        if self._wave_obs.enabled:
            # Straggler-wait hint for anomaly attribution: the round's
            # barrier waste is every worker's gap to the slowest one.
            computes = [float(rep.get("compute_s") or 0.0)
                        for rep in reports.values()]
            wait_hint = (len(computes) * max(computes) - sum(computes)
                         if computes else None)
            self._wave_obs.wave(entry, self._tracer, self._flight,
                                wait_s=wait_hint)
        self._collector.straggler(r, self._map.epoch, reports)
        if self._ckpt is not None and r % self._ckpt_every == 0:
            self._write_generation(r)

    # -- Live elasticity ---------------------------------------------------

    def kill_worker(self, name: str) -> None:
        """Kills a worker the hard way (SIGKILL for processes, vanish-
        at-next-command for threads); the coordinator discovers the
        death through its lease/EOF machinery and migrates — this is
        the preemption drill, not a graceful drain."""
        handle = self._members.get(name)
        if handle is None:
            raise ValueError(f"no such worker {name!r}")
        if handle.proc is not None:
            handle.proc.kill()
        elif handle.kill_event is not None:
            handle.kill_event.set()

    def stop(self) -> None:
        """Requests a prompt stop at the next round barrier (deadline
        cuts): workers are told to exit, no error is raised, counters
        reflect the committed rounds, and the last cadence generation
        stays on disk for a later ``resume_from``."""
        self._stop_req.set()

    def add_worker(self, name: Optional[str] = None) -> str:
        """Spawns a new worker that joins at the next round barrier
        (rendezvous rebalance, logged as a ``rebalance`` event)."""
        if name is None:
            name = f"w{self._next_worker}"
            self._next_worker += 1
        self._spawn_worker(name)
        return name

    # -- Checker facade ----------------------------------------------------

    def model(self):
        return self._model

    def state_count(self) -> int:
        with self._lock:
            return self._state_count

    def unique_state_count(self) -> int:
        with self._lock:
            return self._unique_count

    def discoveries(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._discoveries)

    @property
    def epoch(self) -> int:
        return self._map.epoch

    def workers(self) -> List[str]:
        """Live workers while running; the final membership once done
        (the coordinator reaps its sockets on completion)."""
        if self._done.is_set():
            return list(getattr(self, "_final_workers", []))
        return self._membership.workers()

    def scheduler_stats(self) -> dict:
        with self._lock:
            stats = {
                "elastic": {
                    "workers": self.workers(),
                    "n_partitions": self._n_parts,
                    "rounds": self._round,
                    "epoch": self._map.epoch,
                    "migrations": self._migrations,
                    "rebalances": self._rebalances,
                    "transport": self._transport,
                }
            }
            stats["store"] = {
                "enabled": bool(self._worker_store),
                "workers": dict(self._worker_store),
                "spilled_rows": sum(
                    s.get("spilled_rows", 0)
                    for s in self._worker_store.values()),
            }
        stats["elastic_obs"] = self.elastic_obs()
        stats["slo"] = self._wave_obs.slo_status()
        stats["anomalies"] = self._wave_obs.anomalies()
        return stats

    def elastic_obs(self) -> dict:
        """The distributed-observability aggregate: per-worker
        straggler gauges (compute/exchange/wait seconds, states/s,
        wait share), the slowest-worker histogram, trace-merge
        counters, heartbeat ages, and any postmortem dump paths.
        Cheap per call (reads the collector's running aggregates, not
        the event stream) — the explorer's ``GET /.metrics`` polls
        it."""
        obs = self._collector.summary()
        obs["postmortems"] = list(self.postmortems)
        obs["heartbeat_ages"] = (
            {} if self._done.is_set() else self._membership.ages())
        return obs

    def is_done(self) -> bool:
        return self._done.is_set()

    def join(self) -> "ElasticChecker":
        self._thread.join()
        self._accept_stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._error is not None:
            raise self._error
        return self


def elastic_check(model_factory: Callable, **kwargs) -> ElasticChecker:
    """One-shot convenience: spawn, run to completion, return the
    joined checker."""
    return ElasticChecker(model_factory, **kwargs).join()

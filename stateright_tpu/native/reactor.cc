// Native UDP actor executor: one epoll loop for every actor's IO.
//
// C++ counterpart of the reference's actor runtime (`src/actor/spawn.rs:
// 63-183`), restructured for a native event loop: where the reference
// dedicates an OS thread per actor (blocking recv + read-timeout timer
// emulation, `spawn.rs:73-139`), this reactor owns all actor sockets and
// one timerfd per actor in a single epoll set. Handler dispatch stays in
// the host language via a callback (the modeled handlers are user code);
// the executor — socket setup, datagram IO, timer arming/firing, wakeup
// and shutdown — is native.
//
// Contract (all functions single-loop-threaded except sr_reactor_stop,
// which is wakeup-safe via eventfd):
//  - sr_reactor_add_actor binds an AF_INET UDP socket (so only IPv4
//    traffic arrives, matching `spawn.rs:105-116`'s v4-only filter).
//  - sr_reactor_run dispatches events until stopped: a datagram invokes
//    cb(idx, src_ip, src_port, buf, len>=0); a timer expiry invokes
//    cb(idx, 0, 0, null, -1) after disarming (one-shot semantics, like
//    the reference resetting next_interrupt on fire, `spawn.rs:125-128`).
//  - sr_reactor_send / sr_reactor_set_timer / sr_reactor_cancel_timer
//    are called from inside the callback (same thread as the loop).
//    set_timer takes seconds; cancel disarms (the reference's
//    `practically_never()`, `spawn.rs:36-38`, is an arm-500-years —
//    disarming is the same observable behavior).
//
// Build: g++ -O3 -shared -fPIC (see native/reactor.py). Linux-only
// (epoll/timerfd/eventfd); the Python wrapper falls back to the
// thread-per-actor runtime elsewhere.

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <netinet/in.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/timerfd.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int kMaxDatagram = 65535;  // spawn.rs:82 receive buffer

struct ActorIo {
  int sock = -1;
  int timer = -1;
};

struct Reactor {
  int epoll_fd = -1;
  int wake_fd = -1;
  std::vector<ActorIo> actors;
  std::atomic<bool> stopping{false};  // written by sr_reactor_stop from
                                      // another thread
};

// epoll user data: actor index * 2 (+1 for its timer); wake marker = ~0.
constexpr uint64_t kWake = ~0ull;

}  // namespace

extern "C" {

typedef int (*sr_event_cb)(int actor_idx, uint32_t src_ip,
                           uint16_t src_port, const uint8_t* buf, int len);

void* sr_reactor_create() {
  Reactor* r = new Reactor();
  r->epoll_fd = epoll_create1(EPOLL_CLOEXEC);
  r->wake_fd = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (r->epoll_fd < 0 || r->wake_fd < 0) {
    delete r;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = kWake;
  epoll_ctl(r->epoll_fd, EPOLL_CTL_ADD, r->wake_fd, &ev);
  return r;
}

// Binds ip:port (host byte order) for a new actor; returns its index,
// or -(errno) on failure.
int sr_reactor_add_actor(void* h, uint32_t ip, uint16_t port) {
  Reactor* r = static_cast<Reactor*>(h);
  ActorIo io;
  io.sock = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (io.sock < 0) return -errno;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(ip);
  addr.sin_port = htons(port);
  if (bind(io.sock, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
    int e = errno;
    close(io.sock);
    return -e;
  }
  io.timer = timerfd_create(CLOCK_MONOTONIC, TFD_CLOEXEC | TFD_NONBLOCK);
  if (io.timer < 0) {
    int e = errno;
    close(io.sock);
    return -e;
  }
  int idx = static_cast<int>(r->actors.size());
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = static_cast<uint64_t>(idx) * 2;
  if (epoll_ctl(r->epoll_fd, EPOLL_CTL_ADD, io.sock, &ev) < 0) {
    // Registration failure (e.g. fd limits) would otherwise leave a
    // bound-but-deaf actor; surface it so start() can fail loudly.
    int e = errno;
    close(io.sock);
    close(io.timer);
    return -e;
  }
  ev.data.u64 = static_cast<uint64_t>(idx) * 2 + 1;
  if (epoll_ctl(r->epoll_fd, EPOLL_CTL_ADD, io.timer, &ev) < 0) {
    int e = errno;
    epoll_ctl(r->epoll_fd, EPOLL_CTL_DEL, io.sock, nullptr);
    close(io.sock);
    close(io.timer);
    return -e;
  }
  r->actors.push_back(io);
  return idx;
}

int sr_reactor_send(void* h, int idx, uint32_t dst_ip, uint16_t dst_port,
                    const uint8_t* buf, int len) {
  Reactor* r = static_cast<Reactor*>(h);
  if (idx < 0 || idx >= static_cast<int>(r->actors.size())) return -EINVAL;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(dst_ip);
  addr.sin_port = htons(dst_port);
  ssize_t n = sendto(r->actors[idx].sock, buf, len, 0,
                     reinterpret_cast<sockaddr*>(&addr), sizeof addr);
  return n < 0 ? -errno : 0;  // failed sends are ignored upstream
                              // (spawn.rs:150-158 logs and drops)
}

void sr_reactor_set_timer(void* h, int idx, double seconds) {
  Reactor* r = static_cast<Reactor*>(h);
  if (idx < 0 || idx >= static_cast<int>(r->actors.size())) return;
  if (seconds < 1e-9) seconds = 1e-9;  // 0 would disarm; fire "now"
  itimerspec spec{};
  spec.it_value.tv_sec = static_cast<time_t>(seconds);
  spec.it_value.tv_nsec =
      static_cast<long>((seconds - spec.it_value.tv_sec) * 1e9);
  timerfd_settime(r->actors[idx].timer, 0, &spec, nullptr);
}

void sr_reactor_cancel_timer(void* h, int idx) {
  Reactor* r = static_cast<Reactor*>(h);
  if (idx < 0 || idx >= static_cast<int>(r->actors.size())) return;
  itimerspec spec{};  // zero it_value disarms
  timerfd_settime(r->actors[idx].timer, 0, &spec, nullptr);
}

int sr_reactor_run(void* h, sr_event_cb cb) {
  Reactor* r = static_cast<Reactor*>(h);
  std::vector<uint8_t> buf(kMaxDatagram);
  epoll_event events[64];
  while (!r->stopping) {
    int n = epoll_wait(r->epoll_fd, events, 64, -1);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    for (int i = 0; i < n && !r->stopping; ++i) {
      uint64_t tag = events[i].data.u64;
      if (tag == kWake) {
        uint64_t drain;
        while (read(r->wake_fd, &drain, sizeof drain) > 0) {
        }
        continue;
      }
      int idx = static_cast<int>(tag >> 1);
      ActorIo& io = r->actors[idx];
      if (tag & 1) {  // timer expiry (one-shot: already disarmed)
        uint64_t expirations;
        if (read(io.timer, &expirations, sizeof expirations) > 0) {
          cb(idx, 0, 0, nullptr, -1);
        }
      } else {  // datagram(s); drain the level-triggered socket
        for (;;) {
          sockaddr_in src{};
          socklen_t src_len = sizeof src;
          ssize_t len = recvfrom(io.sock, buf.data(), buf.size(), 0,
                                 reinterpret_cast<sockaddr*>(&src),
                                 &src_len);
          if (len < 0) break;  // EAGAIN (or transient error: drop)
          cb(idx, ntohl(src.sin_addr.s_addr), ntohs(src.sin_port),
             buf.data(), static_cast<int>(len));
          if (r->stopping) break;
        }
      }
    }
  }
  return 0;
}

void sr_reactor_stop(void* h) {
  Reactor* r = static_cast<Reactor*>(h);
  r->stopping = true;
  uint64_t one = 1;
  ssize_t ignored = write(r->wake_fd, &one, sizeof one);
  (void)ignored;
}

void sr_reactor_destroy(void* h) {
  Reactor* r = static_cast<Reactor*>(h);
  for (ActorIo& io : r->actors) {
    if (io.sock >= 0) close(io.sock);
    if (io.timer >= 0) close(io.timer);
  }
  if (r->epoll_fd >= 0) close(r->epoll_fd);
  if (r->wake_fd >= 0) close(r->wake_fd);
  delete r;
}

}  // extern "C"

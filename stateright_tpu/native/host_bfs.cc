// Native multithreaded host BFS engine.
//
// The reference's host checker is compiled Rust (src/checker/bfs.rs:17-342):
// a work-sharing thread pool (JobMarket: Mutex + Condvar + job vector,
// bfs.rs:29-30,70-74), 1500-state check blocks (bfs.rs:113-120),
// share-splitting on surplus (bfs.rs:138-150), a concurrent visited map of
// fingerprint -> parent fingerprint (bfs.rs:26), and property evaluation at
// pop time (bfs.rs:192-226). The repo's Python spawn_bfs mirrors those
// semantics but runs 1-2 orders slower than compiled code, which made it a
// flattering bench denominator. This file is the honest one: the same
// engine design, compiled, multithreaded, operating on the SAME fixed-width
// uint32 state encoding and murmur3-pair fingerprints as the device engine
// (tpu/hashing.py), so unique counts and discovery fingerprints are
// directly comparable across Python, C++, and TPU engines.
//
// Models are compiled in (the reference compiles its models too): a model
// implements step() over the encoded vector exactly matching its
// DeviceModel form. First model: single-decree paxos under linearizability
// (tpu/models/paxos.py, tpu/register_workload.py; reference
// examples/paxos.rs:96-222, actor/register.rs:119-217).
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread (see native/__init__.py).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Fingerprints: identical to tpu/hashing.py (murmur3_32 pair -> uint64).
// ---------------------------------------------------------------------------

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

uint32_t mm3(const uint32_t* w, int n, uint32_t seed) {
  uint32_t h = seed;
  for (int i = 0; i < n; i++) {
    uint32_t k = w[i] * 0xCC9E2D51u;
    k = rotl32(k, 15);
    k *= 0x1B873593u;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5u + 0xE6546B64u;
  }
  h ^= static_cast<uint32_t>(4 * n);
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return h;
}

uint64_t fp64(const uint32_t* w, int n) {
  uint64_t fp = (static_cast<uint64_t>(mm3(w, n, 0x9747B28Cu)) << 32) |
                mm3(w, n, 0x2E1F36D9u);
  if (fp == 0xFFFFFFFFFFFFFFFFull) fp -= 1;  // sentinel (hashing.py:73-75)
  if (fp == 0) fp = 1;                       // nonzero (lib.rs:303)
  return fp;
}

// ---------------------------------------------------------------------------
// Model interface. Property kinds match model.py's Expectation.
// ---------------------------------------------------------------------------

enum PropKind { ALWAYS = 0, SOMETIMES = 1, EVENTUALLY = 2 };

struct Model {
  int W = 0;  // state width (uint32 lanes)
  int F = 0;  // max successors per state
  virtual ~Model() = default;
  // Writes up to F successors contiguously at out (count * W lanes);
  // returns the count, or -1 on an encoding-capacity error.
  virtual int step(const uint32_t* s, uint32_t* out) const = 0;
  virtual int n_props() const = 0;
  virtual PropKind prop_kind(int i) const = 0;
  virtual bool prop_eval(int i, const uint32_t* s) const = 0;
  // Canonical member of the state's symmetry class (representative.rs:65);
  // false = the model has no symmetry support.
  virtual bool representative(const uint32_t* s, uint32_t* out) const {
    (void)s;
    (void)out;
    return false;
  }
};

// ---------------------------------------------------------------------------
// Paxos register workload (model_id 0, cfg = [client_count]).
//
// Byte-identical encoding to tpu/models/paxos.py + tpu/register_workload.py:
// 3 servers x 8 lanes [ballot, proposal, prep0..2, accepts, accepted,
// decided], client phases [C], history [3C: status, ret, hb], sorted
// slot-list network [E = 5C+3] + overflow lane. Envelope:
// dst|src<<3|kind<<6|req<<10|value<<13|extra<<15 (register_workload.py:24-34).
// ---------------------------------------------------------------------------

constexpr uint32_t EMPTY_ENV = 0xFFFFFFFFu;
enum MsgKind {
  PUT = 0, GET = 1, PUTOK = 2, GETOK = 3,
  PREPARE = 4, PREPARED = 5, ACCEPT = 6, ACCEPTED = 7, DECIDED = 8,
};

// Decoded common envelope fields (register_workload.py:129-142).
struct EnvF {
  uint32_t dst, src, kind, req, value, extra;
};

// Shared base of all register workloads (register_workload.py:144-411):
// owns the lane layout, envelope codec, sorted slot-list network, the
// Put-then-Get client with history recording, the step loop, and the
// [ALWAYS linearizable, SOMETIMES value chosen, (EVENTUALLY eventually
// chosen)] property set. Subclasses implement only server_deliver.
struct RegisterModelBase : Model {
  //: upper bound on W across register models (stack scratch sizing)
  static constexpr int kMaxW = 256;
  int S, C, NSL, MAX_OUT;
  bool liveness = false;  // adds [EVENTUALLY "eventually chosen"]
  int phase_off, hist_off, net_off, E;
  // C-dependent bit layout: the envelope value field holds 0..C, so 4
  // clients widen it from 2 bits to 3 (register_workload.py layout).
  uint32_t value_mask, extra_shift;

  // Linearizability tables (register_workload.py:85-126): all multiset
  // permutations of (thread t x2 ops), each (thread, op)'s position.
  int n_perms = 0;
  std::vector<int> pos;  // [perm][t][op] -> position, flattened

  void init_layout(int s, int c, int nsl, int max_out, bool live) {
    S = s;
    C = c;
    NSL = nsl;
    // step()'s outs scratch is sized 8; a larger fan-out would write
    // past it silently, so fail construction loudly instead.
    if (max_out > 8) std::abort();
    MAX_OUT = max_out;
    liveness = live;
    phase_off = nsl * s;
    hist_off = phase_off + c;
    net_off = hist_off + 3 * c;
    // register_workload.py:176-188 (non-duplicating default)
    E = std::max(5 * c + 3, c * (max_out + 2));
    W = net_off + E + 1;
    F = E;  // one Deliver per slot; no lossy/timers
    int value_bits = c <= 3 ? 2 : 3;
    value_mask = (1u << value_bits) - 1;
    extra_shift = 13 + value_bits;
    if (W > kMaxW) std::abort();  // representative() stack scratch bound
    build_sym_tables();
    std::vector<int> base;
    for (int t = 0; t < c; t++) { base.push_back(t); base.push_back(t); }
    do {
      std::vector<int> cnt(c, 0);
      std::vector<int> p(c * 2, 0);
      for (int j = 0; j < 2 * c; j++) {
        int th = base[j];
        p[th * 2 + cnt[th]] = j;
        cnt[th]++;
      }
      pos.insert(pos.end(), p.begin(), p.end());
      n_perms++;
    } while (std::next_permutation(base.begin(), base.end()));
  }

  int pos_at(int perm, int t, int op) const {
    return pos[(perm * C + t) * 2 + op];
  }

  // -- Envelope helpers -----------------------------------------------------

  uint32_t env_of(uint32_t dst, uint32_t src, uint32_t kind,
                  uint32_t req = 0, uint32_t value = 0,
                  uint32_t extra = 0) const {
    return dst | src << 3 | kind << 6 | req << 10 | value << 13 |
           extra << extra_shift;
  }

  // Sorted-dedup insert (actor_device.py:46-60). Returns false on overflow.
  static bool net_insert(uint32_t* net, int e, uint32_t env) {
    if (env == EMPTY_ENV) return true;
    int pos = 0;
    while (pos < e && net[pos] < env) pos++;
    if (pos < e && net[pos] == env) return true;  // set semantics
    if (net[e - 1] != EMPTY_ENV) return false;    // full
    for (int i = e - 1; i > pos; i--) net[i] = net[i - 1];
    net[pos] = env;
    return true;
  }

  static void net_remove_at(uint32_t* net, int e, int slot) {
    for (int i = slot; i + 1 < e; i++) net[i] = net[i + 1];
    net[e - 1] = EMPTY_ENV;
  }

  // -- Server hook: apply one delivery to server f.dst. Mutates lanes in
  // s (network handled by step); outs has MAX_OUT slots, EMPTY-filled.
  virtual bool server_deliver(uint32_t* s, const EnvF& f,
                              uint32_t* outs) const = 0;

  // -- Client-exchangeability symmetry (register_workload.py sym section).
  //
  // The scripted client's destinations are index-derived (Put to
  // index % S, op o to (index + o - 1) % S, register.rs:169-196), so
  // only clients whose indices agree mod S are exchangeable; the group
  // is the product of symmetric groups over the residue classes
  // (nontrivial first at C=4, S=3: {id, swap(client 0, client 3)}).
  // The representative is the lexicographically-minimal encoding over
  // the group with every id-derived payload rewritten — identical
  // partition to the device representative (same encoding, same maps).

  struct SymTables {
    uint32_t sigma[4];  // old client index -> new
    uint32_t inv[4];    // new client index -> old
    uint32_t val[8];    // value-field map (0 = none, 1+k -> 1+sigma[k])
    uint32_t req[8];    // public req-field map ((op-1)<<2 | k)
    uint32_t actor[8];  // actor-index map (servers fixed)
  };
  std::vector<SymTables> sym_tables;  // built in init_layout

  void build_sym_tables() {
    std::vector<uint32_t> perm(C);
    for (int k = 0; k < C; k++) perm[k] = k;
    std::vector<std::vector<uint32_t>> sigmas;
    do {  // C <= 4: at most 24 candidates to filter
      bool same_class = true, identity = true;
      for (int k = 0; k < C; k++) {
        if (static_cast<int>(perm[k]) % S != k % S) same_class = false;
        if (static_cast<int>(perm[k]) != k) identity = false;
      }
      if (same_class && !identity) sigmas.push_back(perm);
    } while (std::next_permutation(perm.begin(), perm.end()));
    for (const auto& sg : sigmas) {
      SymTables t{};
      for (int k = 0; k < C; k++) {
        t.sigma[k] = sg[k];
        t.inv[sg[k]] = k;
      }
      for (uint32_t v = 0; v < 8; v++) t.val[v] = v;
      for (int k = 0; k < C; k++) t.val[1 + k] = 1 + sg[k];
      for (uint32_t r = 0; r < 8; r++) {
        uint32_t op_bit = r >> 2, k = r & 3;
        t.req[r] = static_cast<int>(k) < C ? (op_bit << 2 | sg[k]) : r;
      }
      for (uint32_t a = 0; a < 8; a++) t.actor[a] = a;
      for (int k = 0; k < C; k++) t.actor[S + k] = S + sg[k];
      sym_tables.push_back(t);
    }
  }

  // Model hooks for client-derived payloads outside the shared layout.
  // Returning false = no symmetry support (the engine then refuses
  // check-sym rather than producing wrong counts).
  virtual bool sym_server_lanes(const uint32_t* s, uint32_t* o,
                                const SymTables& t) const {
    (void)s; (void)o; (void)t;
    return false;
  }
  virtual bool sym_internal_env(uint32_t kind, uint32_t req, uint32_t extra,
                                uint32_t* req_out, uint32_t* extra_out,
                                const SymTables& t) const {
    (void)kind; (void)req; (void)extra; (void)req_out; (void)extra_out;
    (void)t;
    return false;
  }

  bool sym_rewrite(const uint32_t* s, uint32_t* o,
                   const SymTables& t) const {
    if (!sym_server_lanes(s, o, t)) return false;          // [0, phase_off)
    for (int j = 0; j < C; j++)
      o[phase_off + j] = s[phase_off + t.inv[j]];
    for (int j = 0; j < C; j++) {
      const uint32_t* h = s + hist_off + 3 * t.inv[j];
      o[hist_off + 3 * j] = h[0];
      o[hist_off + 3 * j + 1] = t.val[h[1]];
      uint32_t hb = 0;
      for (int jp = 0; jp < C; jp++)
        hb |= ((h[2] >> (2 * t.inv[jp])) & 3) << (2 * jp);
      o[hist_off + 3 * j + 2] = hb;
    }
    for (int slot = 0; slot < E; slot++) {
      uint32_t env = s[net_off + slot];
      if (env == EMPTY_ENV) {
        o[net_off + slot] = env;
        continue;
      }
      uint32_t dst = env & 7, src = (env >> 3) & 7, kind = (env >> 6) & 15;
      uint32_t req = (env >> 10) & 7, value = (env >> 13) & value_mask;
      uint32_t extra = env >> extra_shift;
      if (kind < 4) {
        req = t.req[req];
      } else if (!sym_internal_env(kind, req, extra, &req, &extra, t)) {
        return false;
      }
      o[net_off + slot] = env_of(t.actor[dst], t.actor[src], kind, req,
                                 t.val[value], extra);
    }
    std::sort(o + net_off, o + net_off + E);  // canonical slot form
    o[net_off + E] = s[net_off + E];          // overflow lane
    return true;
  }

  bool representative(const uint32_t* s, uint32_t* out) const override {
    std::copy(s, s + W, out);
    if (sym_tables.empty()) return true;  // trivial group: identity
    // Stack scratch: this runs once per generated successor in the
    // symmetric DFS hot loop — a per-call vector would malloc there.
    // W tops out at 147 (ABD at the S<=7, C<=4 construction bounds);
    // init_layout aborts above the bound.
    uint32_t cand[kMaxW];
    for (const auto& t : sym_tables) {
      if (!sym_rewrite(s, cand, t)) return false;
      if (std::lexicographical_compare(cand, cand + W, out, out + W))
        std::copy(cand, cand + W, out);
    }
    return true;
  }

  // -- One delivery (register_workload.py:332-411): dispatch to the
  // server hook or the shared Put-then-Get client.
  bool deliver(uint32_t* s, uint32_t env, uint32_t* outs) const {
    for (int j = 0; j < MAX_OUT; j++) outs[j] = EMPTY_ENV;
    EnvF f{env & 7,          (env >> 3) & 7,           (env >> 6) & 15,
           (env >> 10) & 7,  (env >> 13) & value_mask, env >> extra_shift};
    if (static_cast<int>(f.dst) < S) return server_deliver(s, f, outs);
    const uint32_t dst = f.dst, kind = f.kind, req = f.req;
    const uint32_t value = f.value;

    // ---- Client (register.rs:174-217 via register_workload.py:358-411) ----
    const int k = static_cast<int>(dst) - S;
    if (k < 0 || k >= C) return false;
    uint32_t& phase = s[phase_off + k];
    const uint32_t req_op = (req >> 2) + 1, req_k = req & 3;
    if (req_k != static_cast<uint32_t>(k) || req_op != phase) return false;
    uint32_t* hist = s + hist_off + 3 * k;
    if (kind == PUTOK && phase == 1) {
      // Record happened-before edges at Read invoke (register.rs:37-88):
      // completed-op counts per peer, 2 bits each.
      uint32_t hb = 0;
      for (int j = 0; j < C; j++) {
        if (j == k) continue;
        uint32_t st_j = s[hist_off + 3 * j];
        uint32_t comp = st_j >= 4 ? 2 : (st_j >= 2 ? 1 : 0);
        hb |= comp << (2 * j);
      }
      phase = 2;
      hist[0] = 3;  // write done + read in flight
      hist[2] = hb;
      // Round-robin Get: server (actor + op_count) % S (register.rs:184-196)
      outs[0] = env_of((S + k + 1) % S, dst, GET, (1u << 2) | k);
      return true;
    }
    if (kind == GETOK && phase == 2) {
      phase = 3;
      hist[0] = 4;
      hist[1] = value;
      return true;
    }
    return false;
  }

  int step(const uint32_t* s, uint32_t* out) const override {
    int n = 0;
    const uint32_t* net = s + net_off;
    uint32_t outs[8];  // MAX_OUT <= 6 across all register models
    for (int slot = 0; slot < E; slot++) {
      uint32_t env = net[slot];
      if (env == EMPTY_ENV) continue;
      uint32_t* succ = out + n * W;
      std::memcpy(succ, s, W * sizeof(uint32_t));
      if (!deliver(succ, env, outs)) continue;  // no-op elision
      uint32_t* snet = succ + net_off;
      net_remove_at(snet, E, slot);  // non-duplicating (actor/model.rs:290-297)
      for (int j = 0; j < MAX_OUT; j++)
        if (!net_insert(snet, E, outs[j])) {
          succ[net_off + E] = 1;  // overflow lane -> engine raises
          return -1;
        }
      n++;
    }
    return n;
  }

  // -- Properties: [ALWAYS linearizable, SOMETIMES value chosen] ----------
  // (examples/paxos.rs:251-258; device forms register_workload.py:525-607)

  int n_props() const override { return liveness ? 3 : 2; }
  PropKind prop_kind(int i) const override {
    return i == 0 ? ALWAYS : (i == 1 ? SOMETIMES : EVENTUALLY);
  }

  bool value_chosen(const uint32_t* s) const {
    const uint32_t* net = s + net_off;
    for (int i = 0; i < E; i++) {
      uint32_t env = net[i];
      if (env != EMPTY_ENV && ((env >> 6) & 15) == GETOK &&
          ((env >> 13) & value_mask) != 0)
        return true;
    }
    return false;
  }

  // The reference's per-state backtracking (linearizability.rs:178-240) as
  // an exhaustive scan over (in-flight inclusion mask x permutation)
  // combos — the same reduction the device predicate uses
  // (register_workload.py:544-599), evaluated with early exits.
  bool linearizable(const uint32_t* s) const {
    uint32_t status[4], rets[4], hbs[4];
    for (int t = 0; t < C; t++) {
      status[t] = s[hist_off + 3 * t];
      rets[t] = s[hist_off + 3 * t + 1];
      hbs[t] = s[hist_off + 3 * t + 2];
    }
    // Memoize on the packed history (the predicate depends on nothing
    // else); 14 bits per client (status 3 + ret 3 + hb 8: at C=4 ret
    // reaches 4 and hb spans 4 peers) + client count disambiguator.
    uint64_t key = static_cast<uint64_t>(C) << 57;
    for (int t = 0; t < C; t++)
      key |= static_cast<uint64_t>(status[t] | rets[t] << 3 | hbs[t] << 6)
             << (14 * t);
    thread_local std::unordered_map<uint64_t, bool> memo;
    auto it = memo.find(key);
    if (it != memo.end()) return it->second;

    bool any_ok = false;
    for (int mask = 0; mask < (1 << C) && !any_ok; mask++) {
      bool w_placed[4], r_placed[4];
      for (int j = 0; j < C; j++) {
        bool inc = (mask >> j) & 1;
        w_placed[j] = status[j] >= 2 || (status[j] == 1 && inc);
        r_placed[j] = status[j] == 4 || (status[j] == 3 && inc);
      }
      for (int perm = 0; perm < n_perms && !any_ok; perm++) {
        bool ok = true;
        for (int t = 0; t < C && ok; t++) {
          if (!r_placed[t]) continue;
          int p_read = pos_at(perm, t, 1);
          if (status[t] == 4) {  // completed read: value must match
            uint32_t v = 0;
            int best_pos = -1;
            for (int j = 0; j < C; j++) {
              int pw = pos_at(perm, j, 0);
              if (w_placed[j] && pw < p_read && pw > best_pos) {
                best_pos = pw;
                v = j + 1;
              }
            }
            if (v != rets[t]) { ok = false; break; }
          }
          // Real-time edges (linearizability.rs:198-227): ops recorded
          // as completed before the read must precede it.
          for (int j = 0; j < C; j++) {
            if (j == t) continue;
            uint32_t edge = (hbs[t] >> (2 * j)) & 3;
            if ((edge >= 1 && pos_at(perm, j, 0) > p_read) ||
                (edge >= 2 && pos_at(perm, j, 1) > p_read)) {
              ok = false;
              break;
            }
          }
        }
        if (ok) any_ok = true;
      }
    }
    memo.emplace(key, any_ok);
    return any_ok;
  }

  bool prop_eval(int i, const uint32_t* s) const override {
    return i == 0 ? linearizable(s) : value_chosen(s);  // props 1 and 2
  }
};


// ---------------------------------------------------------------------------
// Paxos register workload (model_id 0, cfg = [client_count, liveness]).
// Server logic per paxos.rs:96-222 via models/paxos.py:180-331; byte-
// identical encoding to the device form (3 servers x 8 lanes [ballot,
// proposal, prep0..2, accepts, accepted, decided]).
// ---------------------------------------------------------------------------

struct PaxosModel : RegisterModelBase {
  // Internal-message extra layout: ballot[0:4] | proposal | last-accepted
  // (widens with the client count like the envelope value field).
  uint32_t prop_mask, la_shift;

  explicit PaxosModel(int clients, bool live) {
    init_layout(3, clients, 8, 3, live);
    int prop_bits = clients <= 3 ? 2 : 3;
    prop_mask = (1u << prop_bits) - 1;
    la_shift = 4 + prop_bits;
  }

  // -- Client symmetry (models/paxos.py sym hooks): proposal indices are
  // client-derived (1+k); accepted-pair / last-accepted indices embed
  // the proposal; ballots are server-derived and untouched.

  uint32_t la_map(uint32_t la, const SymTables& t) const {
    if (la == 0) return 0;
    uint32_t b = (la - 1) / C + 1, p = (la - 1) % C + 1;
    return 1 + (b - 1) * C + (t.val[p] - 1);
  }

  bool sym_server_lanes(const uint32_t* s, uint32_t* o,
                        const SymTables& t) const override {
    for (int srv = 0; srv < S; srv++) {
      const uint32_t* ln = s + 8 * srv;
      uint32_t* lo = o + 8 * srv;
      lo[0] = ln[0];                          // ballot (server-derived)
      lo[1] = t.val[ln[1]];                   // proposal
      for (int a = 0; a < 3; a++)             // prepares: 0 or 1+la
        lo[2 + a] = ln[2 + a] == 0 ? 0 : 1 + la_map(ln[2 + a] - 1, t);
      lo[5] = ln[5];                          // accepts (server mask)
      lo[6] = la_map(ln[6], t);               // accepted
      lo[7] = ln[7];                          // decided
    }
    return true;
  }

  bool sym_internal_env(uint32_t kind, uint32_t req, uint32_t extra,
                        uint32_t* req_out, uint32_t* extra_out,
                        const SymTables& t) const override {
    *req_out = req;  // paxos internals leave the req field unused (0)
    uint32_t ballot = extra & 15;
    if (kind == PREPARED) {
      *extra_out = ballot | la_map(extra >> la_shift, t) << la_shift;
    } else if (kind == ACCEPT || kind == DECIDED) {
      *extra_out = ballot | t.val[(extra >> 4) & prop_mask] << 4;
    } else {
      *extra_out = extra;
    }
    return true;
  }

  bool server_deliver(uint32_t* s, const EnvF& f,
                      uint32_t* outs) const override {
    const uint32_t dst = f.dst, src = f.src, kind = f.kind, req = f.req;
    const uint32_t extra = f.extra;
    const int majority = S / 2 + 1;

    uint32_t* ln = s + 8 * dst;
    uint32_t &b = ln[0], &prop = ln[1];
    uint32_t* prep = ln + 2;
    uint32_t &accmask = ln[5], &acc = ln[6], &dec = ln[7];
    const uint32_t m_ballot = extra & 15;
    const uint32_t m_prop = (extra >> 4) & prop_mask;
    const uint32_t m_la = extra >> la_shift;

    if (dec == 1) {  // decided guard (paxos.rs:115-126)
      if (kind != GET) return false;
      uint32_t acc_prop = acc == 0 ? 0 : (acc - 1) % C + 1;
      outs[0] = env_of(src, dst, GETOK, req, acc_prop);
      return true;
    }
    switch (kind) {
      case PUT: {
        if (prop != 0) return false;  // paxos.rs:128-133
        uint32_t r_cur = b == 0 ? 0 : (b - 1) / S + 1;
        uint32_t ballot = r_cur * S + dst + 1;  // (r_cur+1, dst)
        b = ballot;
        prop = (req & 3) + 1;  // proposal idx = client k + 1
        for (int a = 0; a < S; a++) prep[a] = 0;
        prep[dst] = 1 + acc;
        accmask = 0;
        int o = 0;
        for (uint32_t p = 0; p < static_cast<uint32_t>(S); p++)
          if (p != dst) outs[o++] = env_of(p, dst, PREPARE, 0, 0, ballot);
        return true;
      }
      case PREPARE: {
        if (b >= m_ballot) return false;  // paxos.rs:138-143
        b = m_ballot;
        outs[0] =
            env_of(src, dst, PREPARED, 0, 0, m_ballot | acc << la_shift);
        return true;
      }
      case PREPARED: {
        if (m_ballot != b) return false;  // paxos.rs:145-165
        prep[src] = 1 + m_la;
        int cnt = 0;
        uint32_t best = 0;
        for (int a = 0; a < S; a++) {
          if (prep[a] != 0) cnt++;
          if (prep[a] > best) best = prep[a];
        }
        if (cnt == majority) {
          best -= 1;  // max last-accepted idx (la order == key order)
          uint32_t best_prop = best == 0 ? prop : (best - 1) % C + 1;
          prop = best_prop;
          accmask |= 1u << dst;
          acc = 1 + (b - 1) * C + (best_prop - 1);
          int o = 0;
          for (uint32_t p = 0; p < static_cast<uint32_t>(S); p++)
            if (p != dst)
              outs[o++] = env_of(p, dst, ACCEPT, 0, 0, b | best_prop << 4);
        }
        return true;
      }
      case ACCEPT: {
        if (b > m_ballot) return false;  // paxos.rs:167-170
        b = m_ballot;
        acc = 1 + (m_ballot - 1) * C + (m_prop - 1);
        outs[0] = env_of(src, dst, ACCEPTED, 0, 0, m_ballot);
        return true;
      }
      case ACCEPTED: {
        if (m_ballot != b) return false;  // paxos.rs:172-182
        accmask |= 1u << src;
        int cnt = 0;
        for (int a = 0; a < S; a++) cnt += (accmask >> a) & 1;
        if (cnt == majority) {
          dec = 1;
          uint32_t req_k = prop - 1;
          outs[0] = env_of(S + req_k, dst, PUTOK, req_k);
          int o = 1;
          for (uint32_t p = 0; p < static_cast<uint32_t>(S); p++)
            if (p != dst)
              outs[o++] = env_of(p, dst, DECIDED, 0, 0, b | prop << 4);
        }
        return true;
      }
      case DECIDED: {  // paxos.rs:184-187
        b = m_ballot;
        acc = 1 + (m_ballot - 1) * C + (m_prop - 1);
        dec = 1;
        return true;
      }
      default:
        return false;
    }
  }
};

// ---------------------------------------------------------------------------
// Single-copy register (model_id 3, cfg = [client_count, server_count]) —
// the device form tpu/models/single_copy.py (reference
// single-copy-register.rs:18-38): one value cell per server; Put
// overwrites and acks, Get replies with the cell. Intentionally NOT
// linearizable with more than one server.
// ---------------------------------------------------------------------------

struct SingleCopyModel : RegisterModelBase {
  SingleCopyModel(int clients, int servers) {
    init_layout(servers, clients, /*nsl=*/1, /*max_out=*/1, false);
  }

  // Client symmetry (models/single_copy.py sym hook): the server's only
  // client-derived datum is the stored value index; no internal kinds.
  bool sym_server_lanes(const uint32_t* s, uint32_t* o,
                        const SymTables& t) const override {
    for (int srv = 0; srv < S; srv++)
      o[srv] = t.val[s[srv] & value_mask];
    return true;
  }

  bool server_deliver(uint32_t* s, const EnvF& f,
                      uint32_t* outs) const override {
    uint32_t& value = s[f.dst];  // one lane per server
    if (f.kind == PUT) {
      value = f.value;
      outs[0] = env_of(f.src, f.dst, PUTOK, f.req);
      return true;
    }
    if (f.kind == GET) {
      outs[0] = env_of(f.src, f.dst, GETOK, f.req, value);
      return true;
    }
    return false;
  }
};

// ---------------------------------------------------------------------------
// ABD quorum register (model_id 4, cfg = [client_count, server_count]) —
// the device form tpu/models/abd.py (reference
// linearizable-register.rs:68-186): query phase (collect (seq, value)
// from a quorum) then record phase (install the chosen pair at a
// quorum); sequencers encoded as clock * S + id so integer order ==
// lexicographic tuple order. Lanes per server: [seq, val, ph_kind,
// ph_req, ph_write, ph_read, ph_acks, ph_resp0..S-1].
// ---------------------------------------------------------------------------

enum AbdKind { QUERY = 4, ACKQUERY = 5, RECORD = 6, ACKRECORD = 7 };

struct AbdModel : RegisterModelBase {
  AbdModel(int clients, int servers) {
    init_layout(servers, clients, /*nsl=*/7 + servers,
                /*max_out=*/servers > 1 ? servers - 1 : 1, false);
  }

  bool server_deliver(uint32_t* s, const EnvF& f,
                      uint32_t* outs) const override {
    uint32_t* ln = s + NSL * f.dst;
    uint32_t &seq = ln[0], &val = ln[1], &ph_kind = ln[2], &ph_req = ln[3];
    uint32_t &ph_write = ln[4], &ph_read = ln[5], &ph_acks = ln[6];
    uint32_t* resp = ln + 7;
    const int maj = S / 2 + 1;

    // Put/Get with no phase in flight: start the query phase.
    if ((f.kind == PUT || f.kind == GET) && ph_kind == 0) {
      ph_kind = 1;
      ph_req = f.req;
      ph_write = f.kind == PUT ? f.value : 0;
      ph_read = 0;
      ph_acks = 0;
      for (int j = 0; j < S; j++)
        resp[j] = static_cast<uint32_t>(j) == f.dst
                      ? 1 + seq * (C + 1) + val
                      : 0;
      int o = 0;
      for (uint32_t p = 0; p < static_cast<uint32_t>(S); p++)
        if (p != f.dst) outs[o++] = env_of(p, f.dst, QUERY, f.req);
      return true;
    }
    // Query: reply with our (seq, val); no state change.
    if (f.kind == QUERY) {
      outs[0] = env_of(f.src, f.dst, ACKQUERY, f.req, val, seq);
      return true;
    }
    // AckQuery during our query phase for this request.
    if (f.kind == ACKQUERY && ph_kind == 1 && ph_req == f.req) {
      resp[f.src] = 1 + f.extra * (C + 1) + f.value;
      int cnt = 0;
      uint32_t best = 0;
      for (int j = 0; j < S; j++) {
        if (resp[j] != 0) cnt++;
        if (resp[j] > best) best = resp[j];
      }
      if (cnt == maj) {
        best -= 1;  // distinct seqs: max encoding == max sequencer
        uint32_t best_seq = best / (C + 1), best_val = best % (C + 1);
        bool is_write = ph_write != 0;
        uint32_t new_seq =
            is_write ? (best_seq / S + 1) * S + f.dst : best_seq;
        uint32_t new_val = is_write ? ph_write : best_val;
        if (new_seq > seq) {  // self-Record effect
          seq = new_seq;
          val = new_val;
        }
        ph_kind = 2;
        ph_read = is_write ? 0 : 1 + best_val;
        ph_write = 0;
        ph_acks = 1u << f.dst;
        for (int j = 0; j < S; j++) resp[j] = 0;
        int o = 0;
        for (uint32_t p = 0; p < static_cast<uint32_t>(S); p++)
          if (p != f.dst)
            outs[o++] = env_of(p, f.dst, RECORD, ph_req, new_val, new_seq);
      }
      return true;
    }
    // Record: ack; adopt the pair if newer.
    if (f.kind == RECORD) {
      if (f.extra > seq) {
        seq = f.extra;
        val = f.value;
      }
      outs[0] = env_of(f.src, f.dst, ACKRECORD, f.req);
      return true;
    }
    // AckRecord during our record phase, new acker.
    if (f.kind == ACKRECORD && ph_kind == 2 && ph_req == f.req &&
        ((ph_acks >> f.src) & 1) == 0) {
      uint32_t acks2 = ph_acks | (1u << f.src);
      int cnt = 0;
      for (int j = 0; j < S; j++) cnt += (acks2 >> j) & 1;
      if (cnt == maj) {
        uint32_t requester = S + (ph_req & 3);
        outs[0] = ph_read != 0
                      ? env_of(requester, f.dst, GETOK, ph_req, ph_read - 1)
                      : env_of(requester, f.dst, PUTOK, ph_req);
        ph_kind = 0;
        ph_req = 0;
        ph_read = 0;
        ph_acks = 0;
      } else {
        ph_acks = acks2;
      }
      return true;
    }
    return false;
  }
};

// ---------------------------------------------------------------------------
// Counter DAG (model_id 1, cfg = [n, target]) — a test fixture in the
// spirit of the reference's dgraph models (test_util.rs:49-117): states
// 0..n-1, successors x+1 and x+2 (a DAG with joins, exercising dedup),
// properties [EVENTUALLY "hits target" (x == target), SOMETIMES "reaches
// end" (x == n-1)]. target >= n makes the eventually property fail at the
// terminal state — the ebits counterexample path paxos never reaches.
// ---------------------------------------------------------------------------

struct CounterDagModel : Model {
  uint32_t n, target;
  CounterDagModel(uint32_t n_, uint32_t target_) : n(n_), target(target_) {
    W = 1;
    F = 2;
  }
  int step(const uint32_t* s, uint32_t* out) const override {
    int cnt = 0;
    for (uint32_t d = 1; d <= 2; d++)
      if (s[0] + d < n) out[cnt++] = s[0] + d;
    return cnt;
  }
  int n_props() const override { return 2; }
  PropKind prop_kind(int i) const override {
    return i == 0 ? EVENTUALLY : SOMETIMES;
  }
  bool prop_eval(int i, const uint32_t* s) const override {
    return i == 0 ? s[0] == target : s[0] == n - 1;
  }
};

// ---------------------------------------------------------------------------
// Two-phase commit (model_id 2, cfg = [rm_count]) — examples/2pc.rs:43-121
// via the device encoding of tpu/models/twopc.py: lanes [rm_state x n,
// tm_state, tm_prepared bitmask, message-set bitmask]. Successors are
// emitted in the host model's action enumeration order (TmCommit, TmAbort,
// then per-RM TmRcvPrepared/RmPrepare/RmChooseToAbort/RmRcvCommitMsg/
// RmRcvAbortMsg) so DFS visit order — and therefore the order-dependent
// 665-state symmetry gate — matches the Python host engine.
// ---------------------------------------------------------------------------

struct TwoPcModel : Model {
  int n;
  explicit TwoPcModel(int n_) : n(n_) {
    W = n + 3;
    F = 2 + 5 * n;
  }

  int step(const uint32_t* s, uint32_t* out) const override {
    const uint32_t* rm = s;
    uint32_t tm = s[n], prep = s[n + 1], msgs = s[n + 2];
    uint32_t full = (1u << n) - 1;
    int cnt = 0;
    auto emit = [&](auto fn) {
      uint32_t* o = out + cnt * W;
      std::memcpy(o, s, W * sizeof(uint32_t));
      fn(o);
      cnt++;
    };
    if (tm == 0 && prep == full)  // TmCommit (2pc.rs:56-59)
      emit([&](uint32_t* o) { o[n] = 1; o[n + 2] = msgs | 1; });
    if (tm == 0)  // TmAbort (2pc.rs:60-63)
      emit([&](uint32_t* o) { o[n] = 2; o[n + 2] = msgs | 2; });
    for (int i = 0; i < n; i++) {
      if (tm == 0 && ((msgs >> (2 + i)) & 1))  // TmRcvPrepared
        emit([&](uint32_t* o) { o[n + 1] = prep | (1u << i); });
      if (rm[i] == 0) {  // RmPrepare / RmChooseToAbort
        emit([&](uint32_t* o) { o[i] = 1; o[n + 2] = msgs | (1u << (2 + i)); });
        emit([&](uint32_t* o) { o[i] = 3; });
      }
      if (msgs & 1)  // RmRcvCommitMsg
        emit([&](uint32_t* o) { o[i] = 2; });
      if (msgs & 2)  // RmRcvAbortMsg
        emit([&](uint32_t* o) { o[i] = 3; });
    }
    return cnt;
  }

  // [SOMETIMES abort agreement, SOMETIMES commit agreement,
  //  ALWAYS consistent] (2pc.rs:106-121, host order)
  int n_props() const override { return 3; }
  PropKind prop_kind(int i) const override {
    return i < 2 ? SOMETIMES : ALWAYS;
  }
  bool prop_eval(int i, const uint32_t* s) const override {
    bool all2 = true, all3 = true, any2 = false, any3 = false;
    for (int j = 0; j < n; j++) {
      all2 &= s[j] == 2;
      all3 &= s[j] == 3;
      any2 |= s[j] == 2;
      any3 |= s[j] == 3;
    }
    if (i == 0) return all3;
    if (i == 1) return all2;
    return !(any2 && any3);
  }

  bool representative(const uint32_t* s, uint32_t* out) const override {
    // The HOST heuristic (RewritePlan::from_values_to_sort on rm_state,
    // 2pc.rs:165-182 / rewrite_plan.rs:36-49): stable sort of RM
    // indices by state value, permuting rm lanes, tm_prepared bits, and
    // prepared-message bits. Deliberately NOT the device model's exact
    // composite-key canonicalization (314 true orbits) — the reference's
    // order-dependent 665 gate needs the reference's heuristic.
    int idx[28];
    for (int i = 0; i < n; i++) idx[i] = i;
    std::stable_sort(idx, idx + n, [&](int a, int b) { return s[a] < s[b]; });
    uint32_t prep = s[n + 1], msgs = s[n + 2];
    uint32_t nprep = 0, nmsg = msgs & 3;
    for (int dst = 0; dst < n; dst++) {
      int src = idx[dst];
      out[dst] = s[src];
      nprep |= ((prep >> src) & 1) << dst;
      nmsg |= ((msgs >> (2 + src)) & 1) << (2 + dst);
    }
    out[n] = s[n];
    out[n + 1] = nprep;
    out[n + 2] = nmsg;
    return true;
  }
};

// ---------------------------------------------------------------------------
// Racy shared counter (model_id 5, cfg = [thread_count]) and its
// lock-fixed variant (model_id 6) — examples/increment(.rs) via the
// device encodings of tpu/models/increment{,_lock}.py. Both carry exact
// thread-sort representatives, so the documented 13 -> 8 reduction
// (`increment.rs:36-105`) runs on the native DFS engine too.
// ---------------------------------------------------------------------------

struct IncrementModel : Model {
  int T;
  bool full;  // adds a never-true SOMETIMES property so the checker
              // cannot early-exit once "fin" is violated — makes the
              // documented 13 -> 8 counts assertable (full enumeration)
  IncrementModel(int threads, bool full_) : T(threads), full(full_) {
    W = 1 + 2 * threads;
    F = threads;
  }
  int step(const uint32_t* s, uint32_t* out) const override {
    int cnt = 0;
    for (int k = 0; k < T; k++) {
      uint32_t t = s[1 + 2 * k], pc = s[2 + 2 * k];
      if (pc != 1 && pc != 2) continue;
      uint32_t* o = out + cnt * W;
      std::memcpy(o, s, W * sizeof(uint32_t));
      if (pc == 1) {  // read the shared counter (increment.rs:163-171)
        o[1 + 2 * k] = s[0];
        o[2 + 2 * k] = 2;
      } else {  // non-atomic write-back: the lost-update race
        o[0] = t + 1;
        o[2 + 2 * k] = 3;
      }
      cnt++;
    }
    return cnt;
  }
  int n_props() const override { return full ? 2 : 1; }
  PropKind prop_kind(int i) const override {
    return i == 0 ? ALWAYS : SOMETIMES;
  }
  bool prop_eval(int i, const uint32_t* s) const override {
    if (i == 1) return false;  // "unreachable"
    uint32_t done = 0;  // "fin": every completed write is counted
    for (int k = 0; k < T; k++) done += s[2 + 2 * k] == 3;
    return done == s[0];
  }
  bool representative(const uint32_t* s, uint32_t* out) const override {
    // Exact form: threads are exchangeable (t, pc) pairs — sort them.
    std::vector<std::pair<uint32_t, uint32_t>> pairs(T);
    for (int k = 0; k < T; k++)
      pairs[k] = {s[1 + 2 * k], s[2 + 2 * k]};
    std::sort(pairs.begin(), pairs.end());
    out[0] = s[0];
    for (int k = 0; k < T; k++) {
      out[1 + 2 * k] = pairs[k].first;
      out[2 + 2 * k] = pairs[k].second;
    }
    return true;
  }
};

struct IncrementLockModel : Model {
  int T;
  explicit IncrementLockModel(int threads) : T(threads) {
    W = 2 + 2 * threads;
    F = threads;
  }
  int step(const uint32_t* s, uint32_t* out) const override {
    int cnt = 0;
    uint32_t lock = s[1];
    for (int k = 0; k < T; k++) {
      uint32_t t = s[2 + 2 * k], pc = s[3 + 2 * k];
      bool valid = (pc == 0 && lock == 0) || pc == 1 || pc == 2 ||
                   (pc == 3 && lock == 1);
      if (!valid) continue;
      uint32_t* o = out + cnt * W;
      std::memcpy(o, s, W * sizeof(uint32_t));
      switch (pc) {  // increment_lock.rs:60-96
        case 0:  // take the lock
          o[1] = 1;
          o[3 + 2 * k] = 1;
          break;
        case 1:  // read under the lock
          o[2 + 2 * k] = s[0];
          o[3 + 2 * k] = 2;
          break;
        case 2:  // write under the lock
          o[0] = t + 1;
          o[3 + 2 * k] = 3;
          break;
        default:  // release
          o[1] = 0;
          o[3 + 2 * k] = 4;
      }
      cnt++;
    }
    return cnt;
  }
  int n_props() const override { return 2; }
  PropKind prop_kind(int) const override { return ALWAYS; }
  bool prop_eval(int i, const uint32_t* s) const override {
    if (i == 0) {  // fin (increment_lock.rs:98-100)
      uint32_t done = 0;
      for (int k = 0; k < T; k++) done += s[3 + 2 * k] >= 3;
      return done == s[0];
    }
    uint32_t inside = 0;  // mutex (increment_lock.rs:102-104)
    for (int k = 0; k < T; k++) {
      uint32_t pc = s[3 + 2 * k];
      inside += pc >= 1 && pc < 4;
    }
    return inside <= 1;
  }
  bool representative(const uint32_t* s, uint32_t* out) const override {
    std::vector<std::pair<uint32_t, uint32_t>> pairs(T);
    for (int k = 0; k < T; k++)
      pairs[k] = {s[2 + 2 * k], s[3 + 2 * k]};
    std::sort(pairs.begin(), pairs.end());
    out[0] = s[0];
    out[1] = s[1];
    for (int k = 0; k < T; k++) {
      out[2 + 2 * k] = pairs[k].first;
      out[3 + 2 * k] = pairs[k].second;
    }
    return true;
  }
};

Model* make_model(int model_id, const long long* cfg, int ncfg) {
  if (model_id == 0 && ncfg >= 1 && cfg[0] >= 1 && cfg[0] <= 4)
    return new PaxosModel(static_cast<int>(cfg[0]),
                          ncfg >= 2 && cfg[1] != 0);
  if (model_id == 1 && ncfg >= 2 && cfg[0] >= 1)
    return new CounterDagModel(static_cast<uint32_t>(cfg[0]),
                               static_cast<uint32_t>(cfg[1]));
  if (model_id == 2 && ncfg >= 1 && cfg[0] >= 1 && cfg[0] <= 28)
    return new TwoPcModel(static_cast<int>(cfg[0]));
  if ((model_id == 3 || model_id == 4) && ncfg >= 2 && cfg[0] >= 1 &&
      cfg[0] <= 4 && cfg[1] >= 1 && cfg[1] <= 7 && cfg[0] + cfg[1] <= 8) {
    int c = static_cast<int>(cfg[0]), sv = static_cast<int>(cfg[1]);
    if (model_id == 3) return new SingleCopyModel(c, sv);
    return new AbdModel(c, sv);
  }
  if ((model_id == 5 || model_id == 6) && ncfg >= 1 && cfg[0] >= 1 &&
      cfg[0] <= 14) {
    int t = static_cast<int>(cfg[0]);
    if (model_id == 5)
      return new IncrementModel(t, ncfg >= 2 && cfg[1] != 0);
    return new IncrementLockModel(t);
  }
  return nullptr;
}

// ---------------------------------------------------------------------------
// The engine: JobMarket + check_block (bfs.rs:36-342, checker/_market.py).
// ---------------------------------------------------------------------------

constexpr int CHECK_BLOCK_SIZE = 1500;  // bfs.rs:120
constexpr int N_SHARDS = 64;

// One worker's loop (_market.py:_worker_loop / bfs.rs:83-152), shared by
// the BFS and DFS engines. E supplies the JobMarket fields (m, jobs,
// wait_count, dead_count, has_new_job, error, stop_requested,
// disc_count, target, state_count, model), a Job container, a Scratch
// per-worker workspace, check_block(Job&, Scratch&), and
// split_share(Job&, size) removing the `size` entries processed soonest.
template <typename E>
void market_worker(E* eng) {
  typename E::Job pending;
  typename E::Scratch scratch(eng);
  while (true) {
    if (pending.empty()) {
      std::unique_lock<std::mutex> lk(eng->m);
      while (true) {
        if (eng->error.load() != 0 || eng->stop_requested.load()) return;
        if (eng->disc_count.load() == eng->model->n_props()) return;
        if (eng->target > 0 && eng->state_count.load() >= eng->target) {
          // Do not hand parked jobs back out past the cap; move this
          // worker from waiting to dead (is_done stays false).
          eng->wait_count--;
          eng->dead_count++;
          eng->has_new_job.notify_all();
          return;
        }
        if (!eng->jobs.empty()) {
          pending = std::move(eng->jobs.back());
          eng->jobs.pop_back();
          eng->wait_count--;
          break;
        }
        if (eng->wait_count + eng->dead_count >= eng->threads) {
          eng->has_new_job.notify_all();
          return;
        }
        eng->has_new_job.wait(lk);
      }
    }
    eng->check_block(pending, scratch);
    if (eng->error.load() != 0 || eng->stop_requested.load()) {
      std::lock_guard<std::mutex> g(eng->m);
      // Park the unexpanded frontier so a later checkpoint sees it.
      if (!pending.empty()) eng->jobs.push_back(std::move(pending));
      eng->dead_count++;
      eng->has_new_job.notify_all();
      return;
    }
    if (eng->disc_count.load() == eng->model->n_props()) {
      std::lock_guard<std::mutex> g(eng->m);
      if (!pending.empty()) eng->jobs.push_back(std::move(pending));
      eng->wait_count++;
      eng->has_new_job.notify_all();
      return;
    }
    if (eng->target > 0 && eng->state_count.load() >= eng->target) {
      // Leaves is_done false: checking incomplete (bfs.rs:129-134).
      std::lock_guard<std::mutex> g(eng->m);
      if (!pending.empty()) eng->jobs.push_back(std::move(pending));
      eng->dead_count++;
      eng->has_new_job.notify_all();
      return;
    }
    // Share surplus (bfs.rs:138-150).
    if (pending.size() > 1 && eng->threads > 1) {
      std::lock_guard<std::mutex> g(eng->m);
      size_t pieces =
          1 + std::min<size_t>(eng->wait_count, pending.size());
      size_t size = pending.size() / pieces;
      if (size > 0) {  // avoid pushing empty shares (spurious wakeups)
        for (size_t p = 1; p < pieces; p++) {
          eng->jobs.push_back(eng->split_share(pending, size));
          eng->has_new_job.notify_one();
        }
      }
    } else if (pending.empty()) {
      std::lock_guard<std::mutex> g(eng->m);
      eng->wait_count++;
    }
  }
}

struct Entry {
  std::vector<uint32_t> s;
  uint64_t fp;
  uint32_t ebits;
};

struct Shard {
  std::mutex m;
  std::unordered_map<uint64_t, uint64_t> map;  // fp -> parent (0 = root)
};

struct Engine {
  using Job = std::deque<Entry>;
  struct Scratch {
    std::vector<uint32_t> succ;
    explicit Scratch(Engine* e)
        : succ(static_cast<size_t>(e->model->F) * e->model->W) {}
  };

  Model* model;
  int threads;
  long long target;  // 0 = none
  uint32_t init_ebits;

  std::vector<Shard> shards{N_SHARDS};
  std::atomic<long long> state_count{0};
  std::atomic<long long> unique_count{0};

  // JobMarket (bfs.rs:29-30; _market.py:42-60)
  std::mutex m;
  std::condition_variable has_new_job;
  int wait_count, dead_count = 0;
  std::vector<std::deque<Entry>> jobs;

  // Discoveries: first hit wins (bfs.rs:196-211). disc_set entries are
  // atomics because check_block reads them lock-free on the hot path.
  std::mutex disc_m;
  std::vector<uint64_t> disc_fp;
  std::unique_ptr<std::atomic<uint8_t>[]> disc_set;
  std::atomic<int> disc_count{0};

  std::atomic<bool> done{false};
  std::atomic<bool> stop_requested{false};
  std::atomic<int> error{0};  // -1: encoding capacity exceeded
  std::atomic<double> seconds{0.0};

  Engine(Model* mo, int th, long long tgt) : model(mo), threads(th),
                                             target(tgt), wait_count(th) {
    uint32_t eb = 0;
    for (int i = 0; i < mo->n_props(); i++)
      if (mo->prop_kind(i) == EVENTUALLY) eb |= 1u << i;
    init_ebits = eb;
    disc_fp.resize(mo->n_props(), 0);
    disc_set.reset(new std::atomic<uint8_t>[mo->n_props()]);
    for (int i = 0; i < mo->n_props(); i++) disc_set[i].store(0);
  }

  bool insert_if_absent(uint64_t fp, uint64_t parent) {
    Shard& sh = shards[fp & (N_SHARDS - 1)];
    std::lock_guard<std::mutex> g(sh.m);
    auto r = sh.map.emplace(fp, parent);
    if (r.second) unique_count.fetch_add(1, std::memory_order_relaxed);
    return r.second;
  }

  void record_discovery(int prop, uint64_t fp) {
    std::lock_guard<std::mutex> g(disc_m);
    if (!disc_set[prop].load(std::memory_order_relaxed)) {
      disc_fp[prop] = fp;
      disc_set[prop].store(1, std::memory_order_release);
      disc_count.fetch_add(1);
    }
  }

  // bfs.rs:165-274 / checker/bfs.py:_check_block
  void check_block(Job& pending, Scratch& sc) {
    std::vector<uint32_t>& succ = sc.succ;
    const int W = model->W, P = model->n_props();
    long long generated = 0;
    for (int left = CHECK_BLOCK_SIZE; left > 0; left--) {
      if (pending.empty()) break;
      Entry e = std::move(pending.back());
      pending.pop_back();

      bool awaiting = false;
      uint32_t ebits = e.ebits;
      for (int i = 0; i < P; i++) {
        if (disc_set[i].load(std::memory_order_acquire) &&
            model->prop_kind(i) != EVENTUALLY)
          continue;
        switch (model->prop_kind(i)) {
          case ALWAYS:
            if (!model->prop_eval(i, e.s.data())) record_discovery(i, e.fp);
            else awaiting = true;
            break;
          case SOMETIMES:
            if (model->prop_eval(i, e.s.data())) record_discovery(i, e.fp);
            else awaiting = true;
            break;
          case EVENTUALLY:
            awaiting = true;  // only discovered at terminal states
            if (model->prop_eval(i, e.s.data())) ebits &= ~(1u << i);
            break;
        }
      }
      if (!awaiting) {  // all discovered (bfs.rs:228)
        pending.push_back(std::move(e));  // keep the frontier complete
        break;
      }

      int n = model->step(e.s.data(), succ.data());
      if (n < 0) {
        error.store(-1);
        break;
      }
      bool terminal = n == 0;
      generated += n;
      for (int j = 0; j < n; j++) {
        const uint32_t* sv = succ.data() + j * W;
        uint64_t nfp = fp64(sv, W);
        if (!insert_if_absent(nfp, e.fp)) continue;  // revisit (bfs.rs:249)
        Entry ne;
        ne.s.assign(sv, sv + W);
        ne.fp = nfp;
        ne.ebits = ebits;
        pending.push_front(std::move(ne));
      }
      if (terminal && ebits) {  // bfs.rs:265-272
        for (int i = 0; i < P; i++)
          if (ebits & (1u << i)) record_discovery(i, e.fp);
      }
    }
    state_count.fetch_add(generated, std::memory_order_relaxed);
  }

  // VecDeque::split_off semantics: the back `size` entries (processed
  // soonest), preserving order.
  Job split_share(Job& pending, size_t size) {
    Job share;
    for (size_t i = 0; i < size; i++) {
      share.push_front(std::move(pending.back()));
      pending.pop_back();
    }
    return share;
  }
  bool seeded = false;  // resume: visited/pending installed externally

  int run(const uint32_t* init, int n_init) {
    const int W = model->W;
    if (!seeded) {
      std::deque<Entry> seed;
      for (int i = 0; i < n_init; i++) {
        Entry e;
        e.s.assign(init + i * W, init + (i + 1) * W);
        e.fp = fp64(e.s.data(), W);
        e.ebits = init_ebits;
        if (insert_if_absent(e.fp, 0)) seed.push_back(std::move(e));
      }
      state_count.store(n_init);
      jobs.push_back(std::move(seed));
    }
    auto t0 = std::chrono::steady_clock::now();
    std::vector<std::thread> ts;
    ts.reserve(threads);
    for (int i = 0; i < threads; i++)
      ts.emplace_back([this] { market_worker(this); });
    for (auto& t : ts) t.join();
    seconds.store(std::chrono::duration<double>(
        std::chrono::steady_clock::now() - t0).count());
    {
      // Under the market mutex so a concurrent stop() sees either
      // done (and no-ops) or not-yet-done (and its stop_requested is
      // what made workers exit). If stop() lands in the same instant
      // as natural completion, is_done() conservatively reports
      // incomplete -- never the unsafe direction.
      std::lock_guard<std::mutex> g(m);
      done.store(true);
    }
    return error.load();
  }

  void stop() {
    std::lock_guard<std::mutex> g(m);
    // No-op once the run has finished: stop() after completion must not
    // flip is_done() from true to false (a finished verification stays
    // complete).
    if (done.load()) return;
    stop_requested.store(true);
    has_new_job.notify_all();
  }
};

// ---------------------------------------------------------------------------
// DFS engine (dfs.rs:16-482 / checker/dfs.py): LIFO stacks, bare
// fingerprint visited set, each entry carries its full fingerprint trace
// so discoveries store whole paths, and symmetry reduction lives here —
// dedup by fingerprint(representative(next)), path continues with the
// original fingerprint (dfs.rs:258-267).
// ---------------------------------------------------------------------------

struct DfsEntry {
  std::vector<uint32_t> s;
  std::vector<uint64_t> trace;
  uint32_t ebits;
};

struct SetShard {
  std::mutex m;
  std::unordered_set<uint64_t> set;
};

struct DfsEngine {
  using Job = std::vector<DfsEntry>;
  struct Scratch {
    std::vector<uint32_t> succ;
    std::vector<uint32_t> rep;
    explicit Scratch(DfsEngine* e)
        : succ(static_cast<size_t>(e->model->F) * e->model->W),
          rep(e->model->W) {}
  };

  Model* model;
  int threads;
  long long target;
  bool use_symmetry;
  uint32_t init_ebits;

  std::vector<SetShard> shards{N_SHARDS};
  std::atomic<long long> state_count{0};
  std::atomic<long long> unique_count{0};

  std::mutex m;
  std::condition_variable has_new_job;
  int wait_count, dead_count = 0;
  std::vector<std::vector<DfsEntry>> jobs;

  std::mutex disc_m;
  std::vector<std::vector<uint64_t>> disc_trace;
  std::unique_ptr<std::atomic<uint8_t>[]> disc_set;
  std::atomic<int> disc_count{0};

  std::atomic<bool> done{false};
  std::atomic<bool> stop_requested{false};
  std::atomic<int> error{0};
  std::atomic<double> seconds{0.0};

  DfsEngine(Model* mo, int th, long long tgt, bool sym)
      : model(mo), threads(th), target(tgt), use_symmetry(sym),
        wait_count(th) {
    uint32_t eb = 0;
    for (int i = 0; i < mo->n_props(); i++)
      if (mo->prop_kind(i) == EVENTUALLY) eb |= 1u << i;
    init_ebits = eb;
    disc_trace.resize(mo->n_props());
    disc_set.reset(new std::atomic<uint8_t>[mo->n_props()]);
    for (int i = 0; i < mo->n_props(); i++) disc_set[i].store(0);
  }

  bool insert_if_absent(uint64_t fp) {
    SetShard& sh = shards[fp & (N_SHARDS - 1)];
    std::lock_guard<std::mutex> g(sh.m);
    bool fresh = sh.set.insert(fp).second;
    if (fresh) unique_count.fetch_add(1, std::memory_order_relaxed);
    return fresh;
  }

  void record_discovery(int prop, const std::vector<uint64_t>& trace) {
    std::lock_guard<std::mutex> g(disc_m);
    if (!disc_set[prop].load(std::memory_order_relaxed)) {
      disc_trace[prop] = trace;
      disc_set[prop].store(1, std::memory_order_release);
      disc_count.fetch_add(1);
    }
  }

  // dfs.rs:172-301 / checker/dfs.py:_check_block
  void check_block(Job& pending, Scratch& sc) {
    std::vector<uint32_t>& succ = sc.succ;
    std::vector<uint32_t>& rep = sc.rep;
    const int W = model->W, P = model->n_props();
    long long generated = 0;
    for (int left = CHECK_BLOCK_SIZE; left > 0; left--) {
      if (pending.empty()) break;
      DfsEntry e = std::move(pending.back());
      pending.pop_back();

      bool awaiting = false;
      uint32_t ebits = e.ebits;
      for (int i = 0; i < P; i++) {
        if (disc_set[i].load(std::memory_order_acquire) &&
            model->prop_kind(i) != EVENTUALLY)
          continue;
        switch (model->prop_kind(i)) {
          case ALWAYS:
            if (!model->prop_eval(i, e.s.data()))
              record_discovery(i, e.trace);
            else
              awaiting = true;
            break;
          case SOMETIMES:
            if (model->prop_eval(i, e.s.data()))
              record_discovery(i, e.trace);
            else
              awaiting = true;
            break;
          case EVENTUALLY:
            awaiting = true;
            if (model->prop_eval(i, e.s.data())) ebits &= ~(1u << i);
            break;
        }
      }
      if (!awaiting) {
        pending.push_back(std::move(e));  // keep the frontier complete
        break;
      }

      int nsucc = model->step(e.s.data(), succ.data());
      if (nsucc < 0) {
        error.store(-1);
        break;
      }
      bool terminal = nsucc == 0;
      generated += nsucc;
      for (int j = 0; j < nsucc; j++) {
        const uint32_t* sv = succ.data() + j * W;
        uint64_t path_fp = fp64(sv, W);
        uint64_t dedup_fp = path_fp;
        if (use_symmetry) {
          model->representative(sv, rep.data());
          dedup_fp = fp64(rep.data(), W);
        }
        if (!insert_if_absent(dedup_fp)) continue;
        DfsEntry ne;
        ne.s.assign(sv, sv + W);
        ne.trace = e.trace;
        ne.trace.push_back(path_fp);  // original-fp path rule
        ne.ebits = ebits;
        pending.push_back(std::move(ne));  // LIFO => DFS
      }
      if (terminal && ebits) {
        for (int i = 0; i < P; i++)
          if (ebits & (1u << i)) record_discovery(i, e.trace);
      }
    }
    state_count.fetch_add(generated, std::memory_order_relaxed);
  }

  // Stack split: the top `size` entries, preserving order (dfs.rs:144-157).
  Job split_share(Job& pending, size_t size) {
    Job share(std::make_move_iterator(pending.end() - size),
              std::make_move_iterator(pending.end()));
    pending.resize(pending.size() - size);
    return share;
  }

  int run(const uint32_t* init, int n_init) {
    const int W = model->W;
    std::vector<uint32_t> rep(W);
    std::vector<DfsEntry> seed;
    for (int i = 0; i < n_init; i++) {
      DfsEntry e;
      e.s.assign(init + i * W, init + (i + 1) * W);
      uint64_t dedup_fp;
      if (use_symmetry) {
        model->representative(e.s.data(), rep.data());
        dedup_fp = fp64(rep.data(), W);
      } else {
        dedup_fp = fp64(e.s.data(), W);
      }
      e.trace.push_back(fp64(e.s.data(), W));
      e.ebits = init_ebits;
      if (insert_if_absent(dedup_fp)) seed.push_back(std::move(e));
    }
    state_count.store(n_init);
    jobs.push_back(std::move(seed));
    auto t0 = std::chrono::steady_clock::now();
    std::vector<std::thread> ts;
    ts.reserve(threads);
    for (int i = 0; i < threads; i++)
      ts.emplace_back([this] { market_worker(this); });
    for (auto& t : ts) t.join();
    seconds.store(std::chrono::duration<double>(
        std::chrono::steady_clock::now() - t0).count());
    {
      // Under the market mutex so a concurrent stop() sees either
      // done (and no-ops) or not-yet-done (and its stop_requested is
      // what made workers exit). If stop() lands in the same instant
      // as natural completion, is_done() conservatively reports
      // incomplete -- never the unsafe direction.
      std::lock_guard<std::mutex> g(m);
      done.store(true);
    }
    return error.load();
  }

  void stop() {
    std::lock_guard<std::mutex> g(m);
    if (done.load()) return;  // see the BFS engine's stop()
    stop_requested.store(true);
    has_new_job.notify_all();
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI (ctypes; see native/host_bfs.py)
// ---------------------------------------------------------------------------

extern "C" {

struct Handle {
  Model* model;
  Engine* engine;
  std::vector<uint32_t> init;
  int n_init;
};

void* sr_hostbfs_create(int model_id, const long long* cfg, int ncfg,
                        const uint32_t* init, int n_init, int threads,
                        long long target) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return nullptr;
  Handle* h = new Handle;
  h->model = mo;
  h->engine = new Engine(mo, threads < 1 ? 1 : threads, target);
  h->init.assign(init, init + static_cast<size_t>(n_init) * mo->W);
  h->n_init = n_init;
  return h;
}

int sr_hostbfs_run(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  return h->engine->run(h->init.data(), h->n_init);
}

long long sr_hostbfs_state_count(void* hv) {
  return static_cast<Handle*>(hv)->engine->state_count.load();
}

long long sr_hostbfs_unique_count(void* hv) {
  return static_cast<Handle*>(hv)->engine->unique_count.load();
}

double sr_hostbfs_seconds(void* hv) {
  return static_cast<Handle*>(hv)->engine->seconds.load();
}

void sr_hostbfs_stop(void* hv) {
  static_cast<Handle*>(hv)->engine->stop();
}

int sr_hostbfs_is_done(void* hv) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  if (!e->done.load()) return 0;
  // Incomplete if a target cap parked workers (dead_count), stop() was
  // requested (workers may exit the pop loop without marking
  // themselves dead), or an error aborted the run.
  return (e->dead_count == 0 && e->error.load() == 0 &&
          !e->stop_requested.load()) ||
                 e->disc_count.load() == e->model->n_props()
             ? 1
             : 0;
}

int sr_hostbfs_n_discoveries(void* hv) {
  return static_cast<Handle*>(hv)->engine->disc_count.load();
}

int sr_hostbfs_discovery(void* hv, int i, int* prop_idx, uint64_t* fp) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  std::lock_guard<std::mutex> g(e->disc_m);
  int seen = 0;
  for (int p = 0; p < e->model->n_props(); p++) {
    if (!e->disc_set[p].load()) continue;
    if (seen == i) {
      *prop_idx = static_cast<int>(p);
      *fp = e->disc_fp[p];
      return 0;
    }
    seen++;
  }
  return -1;
}

int sr_hostbfs_parent(void* hv, uint64_t fp, uint64_t* parent) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  Shard& sh = e->shards[fp & (N_SHARDS - 1)];
  std::lock_guard<std::mutex> g(sh.m);
  auto it = sh.map.find(fp);
  if (it == sh.map.end()) return -1;
  *parent = it->second;
  return it->second == 0 ? 0 : 1;
}

void sr_hostbfs_destroy(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  delete h->engine;
  delete h->model;
  delete h;
}

// -- BFS checkpoint/resume surface (see checker/native_bfs.py) -------------
// The (visited fp -> parent fp map, pending frontier, discoveries) tuple
// IS the whole checker state — same npz payload as the device engines'
// checkpoints, so snapshots resume across the Python, device, and native
// engines interchangeably.

// Installs a checkpoint before run(): visited/parent pairs (parent 0 =
// root), pending frontier rows, restored counters, and already-recorded
// discoveries (prop index + fp; n_props entries, fp 0 = none).
int sr_hostbfs_seed(void* hv, const uint64_t* child, const uint64_t* parent,
                    long long n_visited, const uint32_t* vecs,
                    const uint64_t* fps, const uint32_t* ebits,
                    long long rows, long long state_count,
                    const uint64_t* disc_fps) {
  Handle* h = static_cast<Handle*>(hv);
  Engine* e = h->engine;
  if (e->done.load() || e->seeded) return -1;
  const int W = e->model->W;
  {
    // Validate BEFORE mutating: a mid-insert duplicate return would
    // leave the engine half-seeded (the caller would have to destroy
    // the handle to recover). A sorted copy (8 B/entry, freed before
    // insertion) beats a hash set (~32+ B/entry) on the multi-million-
    // state resumes where the spike would matter; the shard maps are
    // provably empty pre-seed (done/seeded guards), so in-batch
    // duplicates are the only case.
    std::vector<uint64_t> sorted_fps(child, child + n_visited);
    std::sort(sorted_fps.begin(), sorted_fps.end());
    if (std::adjacent_find(sorted_fps.begin(), sorted_fps.end()) !=
        sorted_fps.end())
      return -2;  // duplicate fps in checkpoint
  }
  for (long long i = 0; i < n_visited; i++) {
    Shard& sh = e->shards[child[i] & (N_SHARDS - 1)];
    sh.map.emplace(child[i], parent[i]);
  }
  e->unique_count.store(n_visited);
  std::deque<Entry> pend;
  for (long long r = 0; r < rows; r++) {
    Entry en;
    en.s.assign(vecs + r * W, vecs + (r + 1) * W);
    en.fp = fps[r];
    en.ebits = ebits[r];
    pend.push_back(std::move(en));
  }
  e->jobs.push_back(std::move(pend));
  e->state_count.store(state_count);
  for (int p = 0; p < e->model->n_props(); p++) {
    if (disc_fps[p] != 0) {
      e->disc_fp[p] = disc_fps[p];
      e->disc_set[p].store(1);
      e->disc_count.fetch_add(1);
    }
  }
  e->seeded = true;
  return 0;
}

// Post-run exports (engine stopped; workers have parked their frontier
// back into the job market).
long long sr_hostbfs_visited_dump(void* hv, uint64_t* child,
                                  uint64_t* parent, long long cap) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  if (!e->done.load()) return -1;
  long long n = 0;
  for (auto& sh : e->shards) {
    std::lock_guard<std::mutex> g(sh.m);
    for (auto& kv : sh.map) {
      if (n >= cap) return -2;
      child[n] = kv.first;
      parent[n] = kv.second;
      n++;
    }
  }
  return n;
}

long long sr_hostbfs_pending_rows(void* hv) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  if (!e->done.load()) return -1;
  long long rows = 0;
  for (auto& job : e->jobs) rows += static_cast<long long>(job.size());
  return rows;
}

int sr_hostbfs_pending_dump(void* hv, uint32_t* vecs, uint64_t* fps,
                            uint32_t* ebits, long long cap) {
  Engine* e = static_cast<Handle*>(hv)->engine;
  if (!e->done.load()) return -1;
  const int W = e->model->W;
  long long r = 0;
  for (auto& job : e->jobs)
    for (auto& en : job) {
      if (r >= cap) return -2;
      std::memcpy(vecs + r * W, en.s.data(), W * sizeof(uint32_t));
      fps[r] = en.fp;
      ebits[r] = en.ebits;
      r++;
    }
  return 0;
}

// -- DFS engine ------------------------------------------------------------

struct DfsHandle {
  Model* model;
  DfsEngine* engine;
  std::vector<uint32_t> init;
  int n_init;
};

void* sr_hostdfs_create(int model_id, const long long* cfg, int ncfg,
                        const uint32_t* init, int n_init, int threads,
                        long long target, int use_symmetry) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return nullptr;
  if (use_symmetry) {
    std::vector<uint32_t> probe(mo->W, 0), out(mo->W, 0);
    if (!mo->representative(probe.data(), out.data())) {
      delete mo;  // model has no compiled representative
      return nullptr;
    }
  }
  DfsHandle* h = new DfsHandle;
  h->model = mo;
  h->engine = new DfsEngine(mo, threads < 1 ? 1 : threads, target,
                            use_symmetry != 0);
  h->init.assign(init, init + static_cast<size_t>(n_init) * mo->W);
  h->n_init = n_init;
  return h;
}

int sr_hostdfs_run(void* hv) {
  DfsHandle* h = static_cast<DfsHandle*>(hv);
  return h->engine->run(h->init.data(), h->n_init);
}

long long sr_hostdfs_state_count(void* hv) {
  return static_cast<DfsHandle*>(hv)->engine->state_count.load();
}

long long sr_hostdfs_unique_count(void* hv) {
  return static_cast<DfsHandle*>(hv)->engine->unique_count.load();
}

double sr_hostdfs_seconds(void* hv) {
  return static_cast<DfsHandle*>(hv)->engine->seconds.load();
}

void sr_hostdfs_stop(void* hv) {
  static_cast<DfsHandle*>(hv)->engine->stop();
}

int sr_hostdfs_is_done(void* hv) {
  DfsEngine* e = static_cast<DfsHandle*>(hv)->engine;
  if (!e->done.load()) return 0;
  return (e->dead_count == 0 && e->error.load() == 0 &&
          !e->stop_requested.load()) ||
                 e->disc_count.load() == e->model->n_props()
             ? 1
             : 0;
}

int sr_hostdfs_n_discoveries(void* hv) {
  return static_cast<DfsHandle*>(hv)->engine->disc_count.load();
}

// Keyed by PROPERTY INDEX (not discovery ordinal) so a discovery landing
// between two calls cannot shift the mapping: returns the trace length
// of property p's discovery, or -1 when it has none.
int sr_hostdfs_discovery_len(void* hv, int p) {
  DfsEngine* e = static_cast<DfsHandle*>(hv)->engine;
  if (p < 0 || p >= e->model->n_props()) return -1;
  std::lock_guard<std::mutex> g(e->disc_m);
  if (!e->disc_set[p].load()) return -1;
  return static_cast<int>(e->disc_trace[p].size());
}

int sr_hostdfs_discovery_trace(void* hv, int p, uint64_t* buf, int maxlen) {
  DfsEngine* e = static_cast<DfsHandle*>(hv)->engine;
  if (p < 0 || p >= e->model->n_props()) return -1;
  std::lock_guard<std::mutex> g(e->disc_m);
  if (!e->disc_set[p].load()) return -1;
  int n = std::min<int>(maxlen, static_cast<int>(e->disc_trace[p].size()));
  std::memcpy(buf, e->disc_trace[p].data(), n * sizeof(uint64_t));
  return n;
}

void sr_hostdfs_destroy(void* hv) {
  DfsHandle* h = static_cast<DfsHandle*>(hv);
  delete h->engine;
  delete h->model;
  delete h;
}

// -- Model debug surface (differential tests vs the device model) ----------

int sr_model_representative(int model_id, const long long* cfg, int ncfg,
                            const uint32_t* s, uint32_t* out) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return -1;
  int r = mo->representative(s, out) ? 0 : -2;
  delete mo;
  return r;
}


int sr_model_info(int model_id, const long long* cfg, int ncfg, int* W,
                  int* F, int* nprops) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return -1;
  *W = mo->W;
  *F = mo->F;
  *nprops = mo->n_props();
  delete mo;
  return 0;
}

int sr_model_step(int model_id, const long long* cfg, int ncfg,
                  const uint32_t* s, uint32_t* succ_out, int* n_out) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return -1;
  int n = mo->step(s, succ_out);
  delete mo;
  if (n < 0) return -2;
  *n_out = n;
  return 0;
}

int sr_model_props(int model_id, const long long* cfg, int ncfg,
                   const uint32_t* s, uint8_t* out) {
  Model* mo = make_model(model_id, cfg, ncfg);
  if (!mo) return -1;
  for (int i = 0; i < mo->n_props(); i++)
    out[i] = mo->prop_eval(i, s) ? 1 : 0;
  delete mo;
  return 0;
}

}  // extern "C"

// Native backtracking search for the consistency testers.
//
// C++ counterpart of the hot inner search of the reference's
// `src/semantics/linearizability.rs:165-240` and
// `src/semantics/sequential_consistency.rs:151-213`, specialized to
// register semantics (`src/semantics/register.rs`): the reference object
// is a single value cell, ops are Write(v) / Read, and values arrive
// pre-interned as int64 ids (equality is all that matters). The Python
// testers (`stateright_tpu/semantics/*.py`) flatten their per-thread
// histories into the arrays below and dispatch here when the reference
// object is a `Register`; any other spec falls back to the Python search.
//
// The search mirrors the Python/Rust one exactly:
//  - per-thread program order is preserved (only each thread's next
//    unserialized op is a candidate);
//  - an in-flight op (invoked, not returned) may only serialize after all
//    of its thread's completed ops, and is OPTIONAL — the search succeeds
//    once every completed op is serialized;
//  - under `realtime` (linearizability), a candidate is rejected while
//    some peer still has an unserialized completed op at or before the
//    happened-before index recorded at invoke time
//    (`linearizability.rs:198-227`).
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py). No deps.

#include <cstdint>

namespace {

constexpr int8_t kWrite = 0;  // Write(val): always valid, sets the cell
constexpr int8_t kRead = 1;   // Read -> ReadOk(val): valid iff cell == val

struct Ctx {
  int n_threads;
  const int32_t* t_off;    // [n_threads+1] completed-op offsets
  const int8_t* kind;      // [n_ops] op kind
  const int64_t* val;      // [n_ops] written value / expected read value
  const int32_t* cs_off;   // [n_ops+1] happened-before edge offsets
  const int32_t* cs_peer;  // edge: peer thread index
  const int32_t* cs_time;  // edge: peer's last completed index at invoke
  const int8_t* has_if;    // [n_threads] thread has an in-flight op
  const int8_t* if_kind;   // [n_threads]
  const int64_t* if_val;   // [n_threads]
  const int32_t* if_cs_off;   // [n_threads+1]
  const int32_t* if_cs_peer;  // edges for in-flight ops
  const int32_t* if_cs_time;
  bool realtime;
  // Mutable search state.
  int32_t* pos;      // [n_threads] absolute index of next completed op
  int8_t* if_done;   // [n_threads] in-flight op already serialized
};

// `_violates_realtime` (linearizability.py): peer p still has an
// unserialized completed op whose per-thread index <= the recorded edge.
bool Violates(const Ctx& c, int32_t begin, int32_t end,
              const int32_t* peers, const int32_t* times) {
  for (int32_t e = begin; e < end; ++e) {
    const int p = peers[e];
    if (c.pos[p] < c.t_off[p + 1] &&
        c.pos[p] - c.t_off[p] <= times[e]) {
      return true;
    }
  }
  return false;
}

// Returns true iff the remaining completed ops admit a valid total order.
// `reg` is the interned register cell; `remaining` counts completed ops.
bool Search(Ctx& c, int64_t reg, int remaining) {
  if (remaining == 0) return true;
  for (int t = 0; t < c.n_threads; ++t) {
    const int32_t next = c.pos[t];
    if (next >= c.t_off[t + 1]) {
      // Case 1: only a possible in-flight op for this thread. Its return
      // was never recorded, so any outcome is acceptable; a Write still
      // takes effect on the cell.
      if (!c.has_if[t] || c.if_done[t]) continue;
      if (c.realtime && Violates(c, c.if_cs_off[t], c.if_cs_off[t + 1],
                                 c.if_cs_peer, c.if_cs_time)) {
        continue;
      }
      const int64_t nreg = c.if_kind[t] == kWrite ? c.if_val[t] : reg;
      c.if_done[t] = 1;
      if (Search(c, nreg, remaining)) return true;
      c.if_done[t] = 0;
    } else {
      // Case 2: the thread's next completed op.
      if (c.realtime && Violates(c, c.cs_off[next], c.cs_off[next + 1],
                                 c.cs_peer, c.cs_time)) {
        continue;
      }
      int64_t nreg = reg;
      if (c.kind[next] == kWrite) {
        nreg = c.val[next];
      } else if (c.val[next] != reg) {
        continue;  // read must observe the current cell value
      }
      c.pos[t] = next + 1;
      if (Search(c, nreg, remaining - 1)) return true;
      c.pos[t] = next;
    }
  }
  return false;
}

}  // namespace

extern "C" {

// Returns 1 if the history serializes (consistent), 0 if not.
// `realtime` = 1 checks linearizability, 0 sequential consistency.
// Scratch arrays `pos` (int32[n_threads]) and `if_done`
// (int8[n_threads]) are caller-allocated.
int sr_register_check(
    int n_threads, int64_t init_val, int realtime,
    const int32_t* t_off, const int8_t* kind, const int64_t* val,
    const int32_t* cs_off, const int32_t* cs_peer, const int32_t* cs_time,
    const int8_t* has_if, const int8_t* if_kind, const int64_t* if_val,
    const int32_t* if_cs_off, const int32_t* if_cs_peer,
    const int32_t* if_cs_time,
    int32_t* pos, int8_t* if_done) {
  Ctx c{n_threads, t_off,   kind,       val,        cs_off,
        cs_peer,   cs_time, has_if,     if_kind,    if_val,
        if_cs_off, if_cs_peer, if_cs_time, realtime != 0, pos, if_done};
  int remaining = t_off[n_threads];
  for (int t = 0; t < n_threads; ++t) {
    pos[t] = t_off[t];
    if_done[t] = 0;
  }
  return Search(c, init_val, remaining) ? 1 : 0;
}

}  // extern "C"

"""ctypes loader for the native UDP reactor (``reactor.cc``).

Same build pattern as the consistency extension: one dependency-free C++
file compiled on first use with ``g++ -O3 -shared -fPIC`` and loaded via
ctypes. Linux-only (epoll/timerfd); on build/load failure
``REACTOR_AVAILABLE`` is False and the actor runtime falls back to its
thread-per-actor loop.
"""

from __future__ import annotations

import ctypes
import os

from . import build_and_load

__all__ = ["load_reactor", "REACTOR_AVAILABLE", "EVENT_CB"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "reactor.cc")
_SO = os.path.join(_DIR, "_reactor.so")

#: cb(actor_idx, src_ip, src_port, buf, len) — len < 0 marks a timeout.
EVENT_CB = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint16,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int)


def load_reactor():
    lib = build_and_load(_SRC, _SO)
    if lib is None:
        return None
    lib.sr_reactor_create.restype = ctypes.c_void_p
    lib.sr_reactor_create.argtypes = []
    lib.sr_reactor_add_actor.restype = ctypes.c_int
    lib.sr_reactor_add_actor.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint16]
    lib.sr_reactor_send.restype = ctypes.c_int
    lib.sr_reactor_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint16,
        ctypes.c_char_p, ctypes.c_int]
    lib.sr_reactor_set_timer.restype = None
    lib.sr_reactor_set_timer.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_double]
    lib.sr_reactor_cancel_timer.restype = None
    lib.sr_reactor_cancel_timer.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sr_reactor_run.restype = ctypes.c_int
    lib.sr_reactor_run.argtypes = [ctypes.c_void_p, EVENT_CB]
    lib.sr_reactor_stop.restype = None
    lib.sr_reactor_stop.argtypes = [ctypes.c_void_p]
    lib.sr_reactor_destroy.restype = None
    lib.sr_reactor_destroy.argtypes = [ctypes.c_void_p]
    return lib


_lib = load_reactor()
REACTOR_AVAILABLE = _lib is not None


def reactor_lib():
    return _lib

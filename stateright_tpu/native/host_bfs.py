"""ctypes loader for the native multithreaded host BFS (``host_bfs.cc``).

Same build pattern as the other extensions: one dependency-free C++ file
compiled on first use (here with ``-std=c++17 -pthread`` for
``std::thread``) and loaded via ctypes. On build/load failure
``HOSTBFS_AVAILABLE`` is False and callers fall back to the Python engine.
"""

from __future__ import annotations

import ctypes
import os

from . import build_and_load

__all__ = ["hostbfs_lib", "HOSTBFS_AVAILABLE", "model_info", "model_step",
           "model_props", "model_representative"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "host_bfs.cc")
_SO = os.path.join(_DIR, "_host_bfs.so")

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i64p = ctypes.POINTER(ctypes.c_longlong)
_i32p = ctypes.POINTER(ctypes.c_int)


def _load():
    lib = build_and_load(_SRC, _SO, extra_flags=("-std=c++17", "-pthread"))
    if lib is None:
        return None
    lib.sr_hostbfs_create.restype = ctypes.c_void_p
    lib.sr_hostbfs_create.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _u32p, ctypes.c_int,
        ctypes.c_int, ctypes.c_longlong]
    lib.sr_hostbfs_run.restype = ctypes.c_int
    lib.sr_hostbfs_run.argtypes = [ctypes.c_void_p]
    for name in ("state_count", "unique_count"):
        fn = getattr(lib, f"sr_hostbfs_{name}")
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_seconds.restype = ctypes.c_double
    lib.sr_hostbfs_seconds.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_stop.restype = None
    lib.sr_hostbfs_stop.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_is_done.restype = ctypes.c_int
    lib.sr_hostbfs_is_done.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_n_discoveries.restype = ctypes.c_int
    lib.sr_hostbfs_n_discoveries.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_discovery.restype = ctypes.c_int
    lib.sr_hostbfs_discovery.argtypes = [
        ctypes.c_void_p, ctypes.c_int, _i32p, _u64p]
    lib.sr_hostbfs_parent.restype = ctypes.c_int
    lib.sr_hostbfs_parent.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                      _u64p]
    lib.sr_hostbfs_destroy.restype = None
    lib.sr_hostbfs_destroy.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_seed.restype = ctypes.c_int
    lib.sr_hostbfs_seed.argtypes = [
        ctypes.c_void_p, _u64p, _u64p, ctypes.c_longlong, _u32p, _u64p,
        _u32p, ctypes.c_longlong, ctypes.c_longlong, _u64p]
    lib.sr_hostbfs_visited_dump.restype = ctypes.c_longlong
    lib.sr_hostbfs_visited_dump.argtypes = [
        ctypes.c_void_p, _u64p, _u64p, ctypes.c_longlong]
    lib.sr_hostbfs_pending_rows.restype = ctypes.c_longlong
    lib.sr_hostbfs_pending_rows.argtypes = [ctypes.c_void_p]
    lib.sr_hostbfs_pending_dump.restype = ctypes.c_int
    lib.sr_hostbfs_pending_dump.argtypes = [
        ctypes.c_void_p, _u32p, _u64p, _u32p, ctypes.c_longlong]
    lib.sr_hostdfs_create.restype = ctypes.c_void_p
    lib.sr_hostdfs_create.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _u32p, ctypes.c_int,
        ctypes.c_int, ctypes.c_longlong, ctypes.c_int]
    lib.sr_hostdfs_run.restype = ctypes.c_int
    lib.sr_hostdfs_run.argtypes = [ctypes.c_void_p]
    for name in ("state_count", "unique_count"):
        fn = getattr(lib, f"sr_hostdfs_{name}")
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_void_p]
    lib.sr_hostdfs_seconds.restype = ctypes.c_double
    lib.sr_hostdfs_seconds.argtypes = [ctypes.c_void_p]
    lib.sr_hostdfs_stop.restype = None
    lib.sr_hostdfs_stop.argtypes = [ctypes.c_void_p]
    lib.sr_hostdfs_is_done.restype = ctypes.c_int
    lib.sr_hostdfs_is_done.argtypes = [ctypes.c_void_p]
    lib.sr_hostdfs_n_discoveries.restype = ctypes.c_int
    lib.sr_hostdfs_n_discoveries.argtypes = [ctypes.c_void_p]
    lib.sr_hostdfs_discovery_len.restype = ctypes.c_int
    lib.sr_hostdfs_discovery_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sr_hostdfs_discovery_trace.restype = ctypes.c_int
    lib.sr_hostdfs_discovery_trace.argtypes = [
        ctypes.c_void_p, ctypes.c_int, _u64p, ctypes.c_int]
    lib.sr_hostdfs_destroy.restype = None
    lib.sr_hostdfs_destroy.argtypes = [ctypes.c_void_p]
    lib.sr_model_info.restype = ctypes.c_int
    lib.sr_model_info.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _i32p, _i32p, _i32p]
    lib.sr_model_representative.restype = ctypes.c_int
    lib.sr_model_representative.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _u32p, _u32p]
    lib.sr_model_step.restype = ctypes.c_int
    lib.sr_model_step.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _u32p, _u32p, _i32p]
    lib.sr_model_props.restype = ctypes.c_int
    lib.sr_model_props.argtypes = [
        ctypes.c_int, _i64p, ctypes.c_int, _u32p, _u8p]
    return lib


_lib = _load()
HOSTBFS_AVAILABLE = _lib is not None


def hostbfs_lib():
    return _lib


def _cfg_arr(cfg):
    return (ctypes.c_longlong * len(cfg))(*cfg)


def model_info(model_id: int, cfg) -> tuple:
    """(state_width, max_fanout, n_props) of a registered native model."""
    w = ctypes.c_int()
    f = ctypes.c_int()
    p = ctypes.c_int()
    rc = _lib.sr_model_info(model_id, _cfg_arr(cfg), len(cfg),
                            ctypes.byref(w), ctypes.byref(f),
                            ctypes.byref(p))
    if rc != 0:
        raise ValueError(f"unknown native model {model_id} cfg={cfg}")
    return w.value, f.value, p.value


def model_step(model_id: int, cfg, state):
    """Debug surface: the native model's successors of one encoded state
    (``uint32[W] -> uint32[n, W]``), for differential tests vs the
    device model."""
    import numpy as np

    w, f, _ = model_info(model_id, cfg)
    state = np.ascontiguousarray(state, np.uint32)
    out = np.zeros((f, w), np.uint32)
    n = ctypes.c_int()
    rc = _lib.sr_model_step(
        model_id, _cfg_arr(cfg), len(cfg),
        state.ctypes.data_as(_u32p), out.ctypes.data_as(_u32p),
        ctypes.byref(n))
    if rc == -2:
        raise RuntimeError("native model: encoding capacity exceeded")
    if rc != 0:
        raise ValueError(f"unknown native model {model_id}")
    return out[:n.value]


def model_props(model_id: int, cfg, state):
    """Debug surface: property verdicts on one encoded state."""
    import numpy as np

    _, _, p = model_info(model_id, cfg)
    state = np.ascontiguousarray(state, np.uint32)
    out = np.zeros(p, np.uint8)
    rc = _lib.sr_model_props(model_id, _cfg_arr(cfg), len(cfg),
                             state.ctypes.data_as(_u32p),
                             out.ctypes.data_as(_u8p))
    if rc != 0:
        raise ValueError(f"unknown native model {model_id}")
    return out.astype(bool)


def model_representative(model_id: int, cfg, state):
    """Debug surface: the native model's canonical symmetry member."""
    import numpy as np

    w, _, _ = model_info(model_id, cfg)
    state = np.ascontiguousarray(state, np.uint32)
    out = np.zeros(w, np.uint32)
    rc = _lib.sr_model_representative(
        model_id, _cfg_arr(cfg), len(cfg),
        state.ctypes.data_as(_u32p), out.ctypes.data_as(_u32p))
    if rc == -2:
        raise NotImplementedError(
            f"native model {model_id} has no representative")
    if rc != 0:
        raise ValueError(f"unknown native model {model_id}")
    return out

"""``stateright_tpu.native`` — C++ fast paths for host-side hot loops.

The reference is native (Rust) throughout; this package supplies the
promised native equivalents for the performance-critical *host* pieces of
the framework (the device pieces are JAX/XLA — see ``stateright_tpu.tpu``).
Today that is the consistency testers' backtracking search
(`src/semantics/linearizability.rs:165-240`,
`src/semantics/sequential_consistency.rs:151-213`), which the reference
runs once per evaluated state for storage workloads — the second hot loop
after successor expansion (SURVEY §3.1).

The extension is a single dependency-free C++ file compiled on first use
with ``g++ -O3 -shared -fPIC`` into ``_consistency.so`` next to the source
(rebuilt when the source is newer) and loaded via ``ctypes`` — no
pybind11/pyo3 in this image. If no toolchain is available the package
degrades gracefully: ``register_check`` is ``None`` and the Python search
runs instead.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

__all__ = ["register_check", "NATIVE_AVAILABLE", "build_and_load"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "consistency.cc")
_SO = os.path.join(_DIR, "_consistency.so")

_i8 = ctypes.POINTER(ctypes.c_int8)
_i32 = ctypes.POINTER(ctypes.c_int32)
_i64 = ctypes.POINTER(ctypes.c_int64)


def build_and_load(src: str, so: str, extra_flags: tuple = ()):
    """Compiles ``src`` into ``so`` if missing or stale and CDLL-loads
    it; returns the library or ``None`` (graceful degradation). Shared by
    every extension in this package."""
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            # Build into a temp file then rename: concurrent test workers
            # may race here, and a half-written .so must never be
            # dlopen'd.
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
            os.close(fd)
            proc = subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", *extra_flags,
                 "-o", tmp, src],
                capture_output=True, timeout=120)
            if proc.returncode != 0:
                os.unlink(tmp)
                return None
            os.replace(tmp, so)
    except (OSError, subprocess.SubprocessError):
        return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _load():
    lib = build_and_load(_SRC, _SO)
    if lib is None:
        return None
    fn = lib.sr_register_check
    fn.restype = ctypes.c_int
    fn.argtypes = [
        ctypes.c_int, ctypes.c_int64, ctypes.c_int,
        _i32, _i8, _i64,            # t_off, kind, val
        _i32, _i32, _i32,           # cs_off, cs_peer, cs_time
        _i8, _i8, _i64,             # has_if, if_kind, if_val
        _i32, _i32, _i32,           # if_cs_off, if_cs_peer, if_cs_time
        _i32, _i8,                  # pos, if_done scratch
    ]
    return fn


_raw = _load()
NATIVE_AVAILABLE = _raw is not None


def _arr(ctype, values):
    return (ctype * max(len(values), 1))(*values)


def register_check(n_threads: int, init_val: int, realtime: bool,
                   t_off, kind, val, cs_off, cs_peer, cs_time,
                   has_if, if_kind, if_val,
                   if_cs_off, if_cs_peer, if_cs_time) -> bool:
    """Runs the native search on a flattened register history. All list
    arguments are plain Python int lists (see consistency.cc for the
    layout); the testers in ``stateright_tpu.semantics`` do the
    flattening + value interning."""
    pos = (ctypes.c_int32 * max(n_threads, 1))()
    if_done = (ctypes.c_int8 * max(n_threads, 1))()
    rc = _raw(
        n_threads, init_val, 1 if realtime else 0,
        _arr(ctypes.c_int32, t_off), _arr(ctypes.c_int8, kind),
        _arr(ctypes.c_int64, val),
        _arr(ctypes.c_int32, cs_off), _arr(ctypes.c_int32, cs_peer),
        _arr(ctypes.c_int32, cs_time),
        _arr(ctypes.c_int8, has_if), _arr(ctypes.c_int8, if_kind),
        _arr(ctypes.c_int64, if_val),
        _arr(ctypes.c_int32, if_cs_off), _arr(ctypes.c_int32, if_cs_peer),
        _arr(ctypes.c_int32, if_cs_time),
        pos, if_done)
    return bool(rc)


if not NATIVE_AVAILABLE:
    register_check = None  # noqa: F811 — documented degraded mode

"""Deterministic toy models used as fixtures by the test battery.

Counterpart of the reference's `src/test_util.rs` (public here, since
Python has no test-only compilation): a 2-state binary clock, an arbitrary
digraph specified via paths (used to pin eventually-property semantics,
including the documented false negatives), a function-as-model adapter, and
the linear Diophantine equation solver whose BFS/DFS visit orders and exact
state counts are asserted.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional, Set

from .model import Model, Property

__all__ = ["BinaryClock", "BinaryClockAction", "DGraph", "FnModel",
           "LinearEquation", "Guess"]


class BinaryClockAction(Enum):
    GO_LOW = 0
    GO_HIGH = 1


class BinaryClock(Model):
    """A machine that cycles between two states (`test_util.rs:4-46`)."""

    def init_states(self):
        return [0, 1]

    def actions(self, state, actions):
        if state == 0:
            actions.append(BinaryClockAction.GO_HIGH)
        else:
            actions.append(BinaryClockAction.GO_LOW)

    def next_state(self, state, action):
        return 1 if action is BinaryClockAction.GO_HIGH else 0

    def properties(self):
        return [Property.always("in [0, 1]", lambda _, state: 0 <= state <= 1)]


class DGraph(Model):
    """A directed graph specified via paths from initial states
    (`test_util.rs:49-117`). With a device predicate attached (see
    :meth:`with_device_predicate`) it also runs on the TPU engines,
    where it pins the *device* eventually-bits semantics."""

    def __init__(self, property: Property,
                 inits: Optional[Set[int]] = None,
                 edges: Optional[Dict[int, Set[int]]] = None,
                 device_preds: Optional[Dict[str, object]] = None):
        self._property = property
        self._inits: Set[int] = inits or set()
        self._edges: Dict[int, Set[int]] = edges or {}
        self._device_preds = device_preds or {}

    @staticmethod
    def with_property(property: Property) -> "DGraph":
        return DGraph(property)

    def with_path(self, path: List[int]) -> "DGraph":
        inits = set(self._inits)
        inits.add(path[0])
        edges = {k: set(v) for k, v in self._edges.items()}
        src = path[0]
        for dst in path[1:]:
            edges.setdefault(src, set()).add(dst)
            src = dst
        return DGraph(self._property, inits, edges, self._device_preds)

    def with_device_predicate(self, name: str, fn) -> "DGraph":
        """Attaches a jittable ``uint32[1] -> bool`` predicate so the
        graph can run on the device engines."""
        preds = dict(self._device_preds)
        preds[name] = fn
        return DGraph(self._property, self._inits, self._edges, preds)

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self):
        return sorted(self._inits)

    def actions(self, state, actions):
        actions.extend(sorted(self._edges.get(state, ())))

    def next_state(self, state, action):
        return action

    def properties(self):
        return [self._property]

    def device_model(self):
        return _DGraphDevice(self)


class _DGraphDevice:
    """Device form of :class:`DGraph`: a dense successor table indexed by
    node id, looked up per frontier row. Fanout rows follow the host's
    sorted-successor action order so device BFS visits levels in the same
    order as the host engine."""

    error_lane = None

    def __init__(self, graph: DGraph):
        import numpy as np

        from .tpu.device_model import DeviceModel  # noqa: F401 (contract)

        self._graph = graph
        nodes = set(graph._inits)
        for src, dsts in graph._edges.items():
            nodes.add(src)
            nodes.update(dsts)
        self._n = max(nodes) + 1 if nodes else 1
        self.state_width = 1
        self.max_fanout = max(
            [len(d) for d in graph._edges.values()] or [1])
        succ = np.zeros((self._n, self.max_fanout), np.uint32)
        valid = np.zeros((self._n, self.max_fanout), bool)
        for src, dsts in graph._edges.items():
            for j, dst in enumerate(sorted(dsts)):
                succ[src, j] = dst
                valid[src, j] = True
        self._succ = succ
        self._valid = valid

    def encode(self, state):
        import numpy as np

        return np.array([state], np.uint32)

    def decode(self, vec):
        return int(vec[0])

    def step(self, vec):
        import jax.numpy as jnp

        succ = jnp.asarray(self._succ)[vec[0]]
        valid = jnp.asarray(self._valid)[vec[0]]
        return succ[:, None], valid

    def device_properties(self):
        return dict(self._graph._device_preds)

    def boundary(self, vec):
        return None

    def representative(self, vec):
        return None


class FnModel(Model):
    """A model defined by a function ``fn(prev_state_or_None, actions)``
    (`test_util.rs:120-138`): with ``None`` it appends init states; with a
    state it appends successor states (actions are the states themselves)."""

    def __init__(self, fn):
        self._fn = fn

    def init_states(self):
        actions: List = []
        self._fn(None, actions)
        return actions

    def actions(self, state, actions):
        self._fn(state, actions)

    def next_state(self, state, action):
        return action


class _LinearEquationDevice:
    """Device form of :class:`LinearEquation`: two u8 lanes, wraparound
    increments, the solvable predicate as a device reduction. Exercises
    full-space enumeration (65,536 states at full coverage,
    `bfs.rs:371`) on the device engines."""

    error_lane = None
    state_width = 2
    max_fanout = 2

    def __init__(self, model: "LinearEquation"):
        self._m = model

    def encode(self, state):
        import numpy as np

        return np.array(state, np.uint32)

    def decode(self, vec):
        return (int(vec[0]), int(vec[1]))

    def step(self, vec):
        import jax.numpy as jnp

        x, y = vec[0], vec[1]
        succ = jnp.stack([
            jnp.stack([(x + 1) % 256, y]),
            jnp.stack([x, (y + 1) % 256]),
        ])
        return succ, jnp.ones(2, bool)

    def device_properties(self):
        import jax.numpy as jnp

        a, b, c = self._m.a, self._m.b, self._m.c

        def solvable(vec):
            return (a * vec[0] + b * vec[1]) % 256 == c

        return {"solvable": solvable}

    def boundary(self, vec):
        return None

    def representative(self, vec):
        return None


class Guess(Enum):
    INCREASE_X = 0
    INCREASE_Y = 1

    def __repr__(self):  # Debug-style, for discovery summaries
        return self.name


class LinearEquation(Model):
    """Finds `x`, `y` in u8 such that `a*x + b*y = c (mod 256)`
    (`test_util.rs:141-188`). State: ``(x, y)``."""

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def device_model(self):
        return _LinearEquationDevice(self)

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append(Guess.INCREASE_X)
        actions.append(Guess.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action is Guess.INCREASE_X:
            return ((x + 1) % 256, y)
        return (x, (y + 1) % 256)

    def properties(self):
        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) % 256 == model.c

        return [Property.sometimes("solvable", solvable)]

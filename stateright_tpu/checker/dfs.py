"""Host depth-first checker engine.

Counterpart of the reference's `src/checker/dfs.rs`. Differences from BFS:
the visited set stores bare fingerprints (no parent pointers), each pending
entry carries its *entire* fingerprint trace so discoveries store full
paths, and pending is a LIFO stack. Symmetry reduction lives here
(`dfs.rs:258-267`): dedup inserts the fingerprint of the *representative*
of each successor, while the path continues with the original state's
fingerprint — jumping to the canonical member could leave the collected
path without a valid extension (regression documented at `dfs.rs:399-425`).
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..fingerprint import fingerprint
from ..model import Expectation, Model
from ..obs import tracer_from_env, wave_obs_from_env
from .base import Checker
from .path import Path
from ._market import JobMarket, SharedCount, run_worker_loop
from .visitor import as_visitor

__all__ = ["DfsChecker"]


class DfsChecker(Checker):
    #: wave-event ``engine`` id (obs schema): a host "wave" is one
    #: worker check_block.
    _ENGINE_ID = "host_dfs"

    def __init__(self, builder):
        model = builder._model
        self._model = model
        self._thread_count = builder._thread_count
        target_state_count = builder._target_state_count
        visitor = as_visitor(builder._visitor) if builder._visitor else None
        properties = model.properties()
        property_count = len(properties)
        symmetry = builder._symmetry

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = SharedCount(len(init_states))
        generated: Set[int] = set()
        for s in init_states:
            if symmetry is not None:
                generated.add(fingerprint(symmetry(s)))
            else:
                generated.add(fingerprint(s))
        self._generated = generated
        ebits = frozenset(
            i for i, p in enumerate(properties)
            if p.expectation is Expectation.EVENTUALLY)
        pending = [(s, [fingerprint(s)], ebits) for s in init_states]
        self._discoveries: Dict[str, List[int]] = {}
        self._properties = properties
        self._visitor = visitor
        self._symmetry = symmetry

        import threading

        self._tracer = tracer_from_env(self._ENGINE_ID, meta={
            "model": type(model).__name__,
            "threads": self._thread_count})
        #: service observability (obs/hist.py) — see BfsChecker.
        self._wave_obs = wave_obs_from_env(self._ENGINE_ID)
        self._emit_lock = threading.Lock()  # see Checker._emit_wave
        self._market = JobMarket(self._thread_count, pending)
        self._handles = []
        for _ in range(self._thread_count):
            t = threading.Thread(
                target=run_worker_loop,
                args=(self._market, self._thread_count, self._check_block,
                      self._discoveries, property_count, target_state_count,
                      self._state_count),
                kwargs=dict(
                    empty_job=list,
                    job_len=len,
                    split_off=_split_off_list,
                ),
                daemon=True)
            t.start()
            self._handles.append(t)

    # -- Hot loop (dfs.rs:172-301) ---------------------------------------

    def _check_block(self, pending: list, max_count: int) -> None:
        model = self._model
        properties = self._properties
        generated = self._generated
        discoveries = self._discoveries
        visitor = self._visitor
        symmetry = self._symmetry

        actions: List = []
        generated_count = 0  # flushed to the shared counter once per block
        popped = 0           # states expanded this block (wave "bucket")
        novel_count = 0      # first-seen fingerprints this block
        try:
            while max_count > 0:
                max_count -= 1
                if not pending:
                    return
                state, fingerprints, ebits = pending.pop()
                popped += 1
                if visitor is not None:
                    visitor.visit(
                        model, Path.from_fingerprints(model, fingerprints))

                # Done if discoveries found for all properties.
                is_awaiting_discoveries = False
                for i, prop in enumerate(properties):
                    if prop.name in discoveries:
                        continue
                    if prop.expectation is Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            discoveries[prop.name] = list(fingerprints)
                        else:
                            is_awaiting_discoveries = True
                    elif prop.expectation is Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            discoveries[prop.name] = list(fingerprints)
                        else:
                            is_awaiting_discoveries = True
                    else:  # EVENTUALLY (see bfs.py note)
                        is_awaiting_discoveries = True
                        if prop.condition(model, state):
                            ebits = ebits - {i}
                if not is_awaiting_discoveries:
                    return

                # Enqueue newly generated states.
                is_terminal = True
                actions.clear()
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    generated_count += 1
                    if symmetry is not None:
                        # Dedup canonically; continue the path with the
                        # pre-canonicalized fingerprint (dfs.rs:258-267).
                        rep_fp = fingerprint(symmetry(next_state))
                        if rep_fp in generated:
                            is_terminal = False
                            continue
                        generated.add(rep_fp)
                        novel_count += 1
                        next_fp = fingerprint(next_state)
                    else:
                        next_fp = fingerprint(next_state)
                        if next_fp in generated:
                            is_terminal = False
                            continue
                        generated.add(next_fp)
                        novel_count += 1
                    is_terminal = False
                    pending.append(
                        (next_state, fingerprints + [next_fp], ebits))
                if is_terminal:
                    for i, prop in enumerate(properties):
                        if i in ebits:
                            discoveries[prop.name] = list(fingerprints)
        finally:
            self._state_count.add(generated_count)
            if popped and (self._tracer.enabled
                           or self._wave_obs.enabled):
                self._emit_wave(popped, generated_count, novel_count)

    def _host_store_bytes(self) -> int:
        # The visited dict's measured footprint (obs schema v6 host
        # occupancy gauges — see Checker._emit_wave).
        import sys

        return sys.getsizeof(self._generated)

    # -- Checker API -----------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count.value

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> Dict[str, Path]:
        return {name: Path.from_fingerprints(self._model, fps)
                for name, fps in list(self._discoveries.items())}

    def join(self) -> "DfsChecker":
        for h in self._handles:
            h.join()
        self._handles = []
        if self._wave_obs.enabled:
            self._wave_obs.close(self._tracer)
        self._tracer.close()
        if self._market.errors:
            raise self._market.errors[0]
        return self

    def is_done(self) -> bool:
        with self._market.lock:
            idle = (not self._market.jobs
                    and self._market.wait_count == self._thread_count)
        return idle or len(self._discoveries) == len(self._properties)


def _split_off_list(pending: list, size: int) -> list:
    """Removes and returns the top ``size`` stack elements, preserving order."""
    share = pending[-size:]
    del pending[-size:]
    return share

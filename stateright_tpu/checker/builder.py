"""``CheckerBuilder``: configures and spawns checker engines.

Counterpart of the reference's `src/checker.rs:35-178`, plus the TPU-native
``spawn_tpu_bfs`` strategy (the BASELINE.json north star): whole-frontier
waves of vmapped successor generation with a device-resident visited set.
"""

from __future__ import annotations

from typing import Callable, Optional

from .base import Checker

__all__ = ["CheckerBuilder"]


def _pop_fused_kwargs(kwargs) -> None:
    """Strips the fused-engine-only knobs before a classic-engine
    fallback (one place: adding a fused knob must not require editing
    every fallback branch)."""
    for key in ("waves_per_dispatch", "arena_capacity",
                "inflight_dispatches"):
        kwargs.pop(key, None)



class CheckerBuilder:
    """Builds a checker for a model. Instantiate via ``model.checker()``."""

    def __init__(self, model):
        self._model = model
        self._symmetry: Optional[Callable] = None
        self._target_state_count: Optional[int] = None
        self._thread_count = 1
        self._visitor = None

    def spawn_bfs(self) -> Checker:
        """Spawns a breadth-first checker: more memory than DFS but finds
        the shortest path to each discovery when single-threaded (the
        default). Does not block; call ``join()``."""
        from .bfs import BfsChecker

        return BfsChecker(self)

    def spawn_dfs(self) -> Checker:
        """Spawns a depth-first checker: dramatically less memory than BFS
        at the cost of not finding shortest paths. Does not block; call
        ``join()``."""
        from .dfs import DfsChecker

        return DfsChecker(self)

    def spawn_fastest(self, device_model=None, python: bool = False
                      ) -> Checker:
        """The default ``check`` path: the compiled C++ engine when the
        model has a native form, else the Python DFS.

        In the reference, ``check`` IS the fast path (compiled, all
        cores, `examples/paxos.rs:325-331`); routing it to the
        interpreted engine would hand a user a ~300x slower default for
        no reason. ``python=True`` (the examples' ``--python`` flag)
        forces the pure-Python reference-semantics engine. With
        symmetry enabled the native DFS is used (the native BFS has no
        symmetry support); a custom ``symmetry_fn`` or a missing
        compiled representative falls back to Python, which honors
        both. Counts and property verdicts are engine-independent (the
        cross-engine parity gates in tests/); pick an explicit spawn
        when you need a specific traversal order or path shape."""
        if not python:
            try:
                if device_model is None:
                    factory = getattr(self._model, "device_model", None)
                    if factory is not None:
                        device_model = factory()
                if (device_model is not None
                        and device_model.native_form() is not None):
                    if self._symmetry is not None:
                        return self.spawn_native_dfs(device_model)
                    return self.spawn_native_bfs(device_model)
            except (NotImplementedError, ImportError, ValueError):
                # No device form for this configuration, a jax-free
                # install (resolving the device model imports
                # stateright_tpu.tpu), no native extension, no compiled
                # representative, or a native cfg rejection: the Python
                # DFS handles all of those (and honors custom
                # symmetry_fn canonicalizers).
                pass
        return self.spawn_dfs()

    def spawn_tpu_bfs(self, mesh=None, sharded=None, fused=None,
                      **kwargs) -> Checker:
        """Spawns the TPU engine: breadth-first frontier waves executed on
        device (vmapped successor generation + device hash-table dedup).
        Requires the model to provide a ``DeviceModel`` encoding; see
        ``stateright_tpu.tpu``.

        By default the *fused* engine runs: the frontier queue, visited
        table, and parent log stay device-resident and several waves run
        per dispatch (``stateright_tpu.tpu.fused``). Models that need a
        per-wave host hook (a visitor, or a property without a device
        predicate) automatically fall back to the classic per-wave
        engine; ``fused=True`` makes that fallback an error,
        ``fused=False`` forces the classic engine.

        With ``mesh=`` (or ``sharded=True``, meshing all visible devices)
        the fingerprint space is hash-partitioned across devices and each
        wave's successors are routed to their owner shard by an ICI
        all-to-all; see ``stateright_tpu.tpu.sharded``.

        Successor-path knobs (both default on; results are bit-identical
        either way — they are performance schedules, not semantics):
        ``succ_ladder=False`` disables the classic engines' K-bounded
        output compaction (waves then always gather/emit the full B*F
        successor window); ``exchange_novel_only=False`` (sharded
        engines) disables sender-side local dedup before the all-to-all
        (every valid successor then rides the interconnect, duplicates
        included).

        ``pack_arena`` (round 9, also bit-identical either way) stores
        arena/frontier rows — and the sharded engines' all-to-all
        payloads — in the model-derived bit-packed row format
        (``tpu/packing.py``). Default: packed on accelerators, unpacked
        on the CPU backend (where the codec is pure compute overhead);
        ``True``/``False`` force either arm."""
        try:
            # Enables x64 before engine import.
            import stateright_tpu.tpu as tpu
        except ImportError as e:
            import importlib.util

            if importlib.util.find_spec("jax") is not None:
                # jax exists, so this is a real error from the engine
                # package (e.g. the deliberate JAX_ENABLE_X64 opt-out
                # guard) — don't mask it.
                raise
            raise NotImplementedError(
                "the TPU engine module is not available in this build "
                "(jax is required)") from e

        if fused and kwargs.get("pipeline"):
            # pipeline= is a classic-engine knob; silently dropping an
            # explicit fused=True would violate the "fused=True makes
            # fallback an error" contract.
            raise ValueError(
                "fused=True and pipeline=True are mutually exclusive: "
                "pipelining is a classic-engine knob")
        if kwargs.get("device_model") is None:
            # Resolve the model's device form eagerly: configurations the
            # encoding cannot express (e.g. a register workload beyond
            # the device client bound) degrade to the host engine with a
            # warning instead of dying (`check-tpu` stays usable at any
            # CLI count).
            import warnings

            from ..tpu.device_model import DeviceFormUnavailable

            factory = getattr(self._model, "device_model", None)
            if factory is not None:
                try:
                    kwargs["device_model"] = factory()
                except DeviceFormUnavailable as e:
                    # The host BFS has no engine knobs: silently dropping
                    # resume_from/checkpoint_path would restart a long
                    # run from scratch AND stop writing snapshots, and an
                    # explicit fused=True promises fallback-is-an-error.
                    critical = [k for k in ("resume_from",
                                            "checkpoint_path")
                                if kwargs.get(k) is not None]
                    if fused:
                        critical.append("fused=True")
                    if critical:
                        raise DeviceFormUnavailable(
                            f"{e}; refusing the host-BFS fallback "
                            f"because it cannot honor {critical} — "
                            "drop those knobs or use a device-formable "
                            "configuration") from e
                    dropped = sorted(
                        k for k, v in kwargs.items()
                        if v is not None and k != "device_model")
                    if mesh is not None or sharded:
                        dropped.append("mesh/sharded")
                    warnings.warn(
                        f"no device form for this configuration ({e}); "
                        "falling back to the host BFS engine"
                        + (f" (dropping engine knobs {dropped})"
                           if dropped else ""),
                        RuntimeWarning)
                    return self.spawn_bfs()
        if mesh is not None or sharded:
            from ..tpu.sharded import ShardedTpuBfsChecker

            if fused is False or kwargs.get("pipeline"):
                _pop_fused_kwargs(kwargs)
                return ShardedTpuBfsChecker(self, mesh=mesh, **kwargs)
            from ..tpu.fused import FusedUnsupported
            from ..tpu.sharded_fused import ShardedFusedTpuBfsChecker

            try:
                return ShardedFusedTpuBfsChecker(self, mesh=mesh, **kwargs)
            except FusedUnsupported:
                if fused:
                    raise
                _pop_fused_kwargs(kwargs)
                return ShardedTpuBfsChecker(self, mesh=mesh, **kwargs)
        if fused is False or kwargs.get("pipeline"):
            # An explicit pipeline=True is a classic-engine opt-in.
            _pop_fused_kwargs(kwargs)
            return tpu.TpuBfsChecker(self, **kwargs)
        from ..tpu.fused import FusedTpuBfsChecker, FusedUnsupported

        try:
            return FusedTpuBfsChecker(self, **kwargs)
        except FusedUnsupported:
            if fused:
                raise
            _pop_fused_kwargs(kwargs)
            return tpu.TpuBfsChecker(self, **kwargs)

    def spawn_native_bfs(self, device_model, threads=None,
                         resume_from=None, async_io=None) -> Checker:
        """Spawns the compiled multithreaded host BFS (C++,
        ``native/host_bfs.cc``) — the reference's `bfs.rs:17-342` engine
        design operating on the model's device encoding. Requires the
        device model to declare a ``native_form()``; raises
        ``NotImplementedError`` otherwise (fall back to ``spawn_bfs``).
        ``threads`` defaults to the builder's ``threads()`` knob;
        ``resume_from`` accepts a checkpoint from any BFS engine."""
        from .native_bfs import NativeBfsChecker

        return NativeBfsChecker(self, device_model, threads=threads,
                                resume_from=resume_from,
                                async_io=async_io)

    def spawn_native_dfs(self, device_model, threads=None) -> Checker:
        """Spawns the compiled depth-first engine (C++,
        ``native/host_bfs.cc`` — the `dfs.rs:16-482` design): LIFO
        stacks, full-trace discoveries, symmetry via the model's
        compiled ``representative``. Same ``native_form()`` opt-in as
        ``spawn_native_bfs``."""
        from .native_bfs import NativeDfsChecker

        return NativeDfsChecker(self, device_model, threads=threads)

    def serve(self, addresses) -> Checker:
        """Starts the interactive web explorer (blocks). See
        ``stateright_tpu.explorer``."""
        try:
            from ..explorer import serve
        except ImportError as e:
            raise NotImplementedError(
                "the explorer module is not available in this build") from e

        return serve(self, addresses)

    def symmetry(self) -> "CheckerBuilder":
        """Enables symmetry reduction; model states must implement
        ``representative()`` (`checker.rs:149-153`)."""
        self.symmetry_fn(lambda state: state.representative())
        # The native DFS engine can honor the model's own representative
        # (it has a compiled copy) but not an arbitrary canonicalizer.
        self._symmetry_is_default = True
        return self

    def symmetry_fn(self, representative: Callable) -> "CheckerBuilder":
        """Enables symmetry reduction with an explicit canonicalizer."""
        self._symmetry = representative
        self._symmetry_is_default = False
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        """Approximate number of states to generate; the checker may exceed
        it, but never generates fewer if more exist."""
        self._target_state_count = count if count > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        """Worker count for the host engines (ignored by the TPU engine,
        which parallelizes over the frontier instead)."""
        self._thread_count = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        """A function or ``CheckerVisitor`` run on each evaluated state."""
        self._visitor = visitor
        return self

"""Paths through a model's state graph, reconstructible from fingerprints.

Counterpart of the reference's `src/checker/path.rs`. A path is a sequence
``state --action--> state ... --action--> state``. Checkers store only
fingerprints (and parent pointers); a ``Path`` is rebuilt by *re-executing
the model* along the fingerprint trail — the technique from "Model Checking
TLA+ Specifications" (Yu, Manolios, Lamport). Reconstruction failure means
the model is nondeterministic, so the detailed error doubles as a
determinism sanitizer (`path.rs:35-49,62-79`).
"""

from __future__ import annotations

from typing import Generic, Iterable, List, Optional, Sequence, Tuple, TypeVar

from ..fingerprint import fingerprint
from ..model import _fmt

State = TypeVar("State")
Action = TypeVar("Action")

__all__ = ["Path", "NondeterminismError"]


class NondeterminismError(RuntimeError):
    """Raised when a fingerprint path cannot be replayed against the model,
    which indicates the model's transitions are not deterministic functions
    of their inputs (`path.rs:35-49`)."""


_INIT_MSG = """\
Unable to reconstruct a `Path` from fingerprints of states visited earlier. No
init state has the expected fingerprint ({fp}). This usually happens when the
return value of `Model.init_states` varies between calls.

The most obvious cause is a model that reads untracked external state such as
the file system, a global mutable, or a source of randomness (including
iteration order of an unordered container with unstable ordering).

Available init fingerprints (none of which match): {available}"""

_NEXT_MSG = """\
Unable to reconstruct a `Path` from fingerprints of states visited earlier.
{n} previous state(s) of the path were reconstructed, but no subsequent state
has the next fingerprint ({fp}). This usually happens when `Model.actions` or
`Model.next_state` vary even when given the same input arguments.

The most obvious cause is a model that reads untracked external state such as
the file system, a global mutable, or a source of randomness (including
iteration order of an unordered container with unstable ordering).

Available next fingerprints (none of which match): {available}"""


class Path(Generic[State, Action]):
    """A list of ``(state, action-or-None)`` pairs; the final pair's action
    is ``None`` (`path.rs:16`)."""

    __slots__ = ("_pairs",)

    def __init__(self, pairs: Sequence[Tuple[State, Optional[Action]]]):
        self._pairs = list(pairs)

    # -- Construction ----------------------------------------------------

    @staticmethod
    def from_fingerprints(model, fingerprints: Iterable[int],
                          fingerprint_fn=fingerprint) -> "Path":
        """Replays the model along a fingerprint sequence (`path.rs:20-86`).

        ``fingerprint_fn`` lets engines with a different state-identity
        function (the TPU engine hashes *encoded* state vectors) replay
        their own fingerprints; it defaults to the host fingerprint.
        """
        fps = list(fingerprints)
        if not fps:
            raise NondeterminismError("empty path is invalid")
        init_fp, rest = fps[0], fps[1:]
        last_state = None
        for s in model.init_states():
            if fingerprint_fn(s) == init_fp:
                last_state = s
                break
        else:
            raise NondeterminismError(_INIT_MSG.format(
                fp=init_fp,
                available=[fingerprint_fn(s) for s in model.init_states()]))
        pairs: List[Tuple[State, Optional[Action]]] = []
        for next_fp in rest:
            for action, next_state in model.next_steps(last_state):
                if fingerprint_fn(next_state) == next_fp:
                    pairs.append((last_state, action))
                    last_state = next_state
                    break
            else:
                raise NondeterminismError(_NEXT_MSG.format(
                    n=1 + len(pairs),
                    fp=next_fp,
                    available=[fingerprint_fn(s) for s in model.next_states(last_state)]))
        pairs.append((last_state, None))
        return Path(pairs)

    @staticmethod
    def from_actions(model, init_state: State,
                     actions: Iterable[Action]) -> Optional["Path"]:
        """Replays a model from ``init_state`` along ``actions``; ``None`` if
        the actions are not enabled along the way (`path.rs:90-112`)."""
        if not any(s == init_state for s in model.init_states()):
            return None
        pairs: List[Tuple[State, Optional[Action]]] = []
        prev_state = init_state
        for action in actions:
            for candidate, next_state in model.next_steps(prev_state):
                if candidate == action:
                    pairs.append((prev_state, candidate))
                    prev_state = next_state
                    break
            else:
                return None
        pairs.append((prev_state, None))
        return Path(pairs)

    @staticmethod
    def final_state(model, fingerprints: Iterable[int]) -> Optional[State]:
        """The final state of a fingerprint path, or ``None`` (`path.rs:115-136`)."""
        fps = list(fingerprints)
        if not fps:
            return None
        matching = None
        for s in model.init_states():
            if fingerprint(s) == fps[0]:
                matching = s
                break
        if matching is None:
            return None
        for next_fp in fps[1:]:
            for s in model.next_states(matching):
                if fingerprint(s) == next_fp:
                    matching = s
                    break
            else:
                return None
        return matching

    # -- Accessors -------------------------------------------------------

    def last_state(self) -> State:
        return self._pairs[-1][0]

    def into_states(self) -> List[State]:
        return [s for s, _ in self._pairs]

    def into_actions(self) -> List[Action]:
        return [a for _, a in self._pairs if a is not None]

    def into_vec(self) -> List[Tuple[State, Optional[Action]]]:
        return list(self._pairs)

    def encode(self) -> str:
        """Path as `/`-joined fingerprints — explorer URL format (`path.rs:160-165`)."""
        return "/".join(str(fingerprint(s)) for s, _ in self._pairs)

    def __len__(self) -> int:
        return len(self._pairs)

    def __iter__(self):
        return iter(self._pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._pairs == other._pairs

    def __hash__(self) -> int:
        return hash(tuple((fingerprint(s), fingerprint(a) if a is not None else 0)
                          for s, a in self._pairs))

    def __repr__(self) -> str:
        return f"Path({self._pairs!r})"

    def __str__(self) -> str:
        lines = [f"Path[{len(self._pairs) - 1}]:"]
        for _, action in self._pairs:
            if action is not None:
                lines.append(f"- {_fmt(action)}")
        return "\n".join(lines) + "\n"

"""The common ``Checker`` API shared by every engine.

Counterpart of the reference's `src/checker.rs:184-338`: state counts,
discovery lookup, joining, the periodic status report, and the assertion
helpers used throughout the test batteries (including the subtle
``assert_discovery`` replay validation for eventually properties,
`checker.rs:292-337`).
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

from ..model import Expectation, Model
from ..obs.hist import NULL_OBS
from .path import Path

__all__ = ["Checker", "host_store_capacity"]


def host_store_capacity(rows: int) -> int:
    """The host visited store's slot capacity at ``rows`` entries,
    derived from CPython's dict growth policy (power-of-two slots,
    resize at 2/3 load, 8 minimum) — the real occupancy figure behind
    the host engines' ``capacity``/``load_factor`` wave gauges (obs
    schema v6; these used to ship as permanent nulls)."""
    cap = 8
    while 3 * max(0, int(rows)) >= 2 * cap:
        cap *= 2
    return cap


class Checker:
    """Performs model checking. Instantiate via ``model.checker()`` then
    ``spawn_bfs()`` / ``spawn_dfs()`` / ``spawn_tpu_bfs()``."""

    #: class-level disarmed default: every engine __init__ replaces it
    #: with ``wave_obs_from_env(...)`` so ``_emit_wave`` can always
    #: check ``.enabled`` without a per-subclass guard.
    _wave_obs = NULL_OBS

    def model(self) -> Model:
        raise NotImplementedError

    def state_count(self) -> int:
        """States generated *including* repeats; >= ``unique_state_count``."""
        raise NotImplementedError

    def unique_state_count(self) -> int:
        """Unique states generated; <= ``state_count``."""
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        """Map from property name to its discovery path."""
        raise NotImplementedError

    def join(self) -> "Checker":
        """Blocks until checking is done (or each worker hits its cap)."""
        raise NotImplementedError

    def is_done(self) -> bool:
        """All properties have discoveries or all reachable states visited."""
        raise NotImplementedError

    # -- Derived helpers (checker.rs:210-337) ----------------------------

    def discovery(self, name: str) -> Optional[Path]:
        """Looks up a discovery by property name."""
        return self.discoveries().get(name)

    def _emit_wave(self, bucket: int, successors: int, novel: int) -> None:
        """Serializes one unified wave event (obs schema) for engines
        without a device dispatch log — the host checkers call this per
        worker block. Only call when ``self._tracer.enabled`` or
        ``self._wave_obs.enabled``: the caller's guard is what keeps
        the disabled path allocation-free.
        The host visited store is a CPython dict, so the occupancy
        gauges are REAL (schema v6): ``capacity`` is its slot capacity
        under the documented growth policy, ``load_factor`` the
        entries/slots ratio, ``out_rows`` the block's emitted novel
        rows, and ``table_bytes`` the dict's measured footprint
        (``_host_store_bytes``).

        The counter reads and the tracer write are serialized under one
        lock: with several worker threads, a thread that read
        ``state_count()=N`` must not be overtaken by a peer writing
        ``N+k`` first — the stream's cumulative counts would go
        backwards and ``trace_lint`` would reject a legitimate capture.
        Counters only grow, so read-then-write under the same lock
        makes the written sequence non-decreasing."""
        with self._emit_lock:
            unique = self.unique_state_count()
            capacity = host_store_capacity(unique)
            table_bytes = self._host_store_bytes()
            entry = {
                "t": time.monotonic(), "states": self.state_count(),
                "unique": unique, "bucket": bucket,
                "waves": 1, "inflight": 0, "compiled": False,
                "successors": successors, "candidates": successors,
                "novel": novel, "out_rows": novel,
                "capacity": capacity,
                "load_factor": round(unique / capacity, 4),
                "overflow": False,
                # v2 bandwidth gauges: no device arena and states are
                # Python objects, so bytes_per_state/arena stay null —
                # but the visited dict's footprint is measurable.
                "bytes_per_state": None, "arena_bytes": None,
                "table_bytes": table_bytes,
                # v6 tier gauges: the host store IS the host tier.
                "tier_host_rows": unique,
                "tier_host_bytes": table_bytes}
            if self._tracer.enabled:
                self._tracer.wave(entry)
            if self._wave_obs.enabled:
                # Latency histograms / SLO / anomaly detection
                # (obs/hist.py) — works untraced, same entry dict.
                self._wave_obs.wave(entry, self._tracer)

    def _host_store_bytes(self):
        """The host visited store's measured byte footprint (engines
        with a dict/set visited structure override; None means the
        gauge ships null)."""
        return None

    def report(self, w=None, period_s: float = 1.0) -> "Checker":
        """Periodically emits a status line, then a discovery summary
        (`checker.rs:216-241`). This is also the benchmark surface: the
        final line carries ``states=``/``unique=``/``sec=`` plus a
        ``states/s=`` rate. Each line is flushed as written, so piped
        and benchmark runs see progress live instead of one buffered
        blob at exit; ``period_s`` sets the cadence."""
        if w is None:
            w = sys.stdout
        flush = getattr(w, "flush", None)
        method_start = time.monotonic()
        while not self.is_done():
            w.write(f"Checking. states={self.state_count()}, "
                    f"unique={self.unique_state_count()}\n")
            if flush is not None:
                flush()
            time.sleep(period_s)
        elapsed_f = time.monotonic() - method_start
        states = self.state_count()
        w.write(f"Done. states={states}, "
                f"unique={self.unique_state_count()}, "
                f"sec={int(elapsed_f)}, "
                f"states/s={states / max(elapsed_f, 1e-9):.0f}\n")
        for name, path in self.discoveries().items():
            w.write(f'Discovered "{name}" '
                    f"{self.discovery_classification(name)} {path}")
        if flush is not None:
            flush()
        return self

    def discovery_classification(self, name: str) -> str:
        """Whether a discovery is an ``example`` or ``counterexample``."""
        prop = self.model().property(name)
        if prop.expectation is Expectation.SOMETIMES:
            return "example"
        return "counterexample"

    def assert_properties(self) -> None:
        """Examples exist for all sometimes properties; no counterexamples
        exist for always/eventually properties."""
        for p in self.model().properties():
            if p.expectation is Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        assert self.is_done(), \
            f'Discovery for "{name}" not found, but model checking is incomplete.'
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n")
        assert self.is_done(), \
            f'Discovery for "{name}" not found, but model checking is incomplete.'

    def assert_discovery(self, name: str, actions: List) -> None:
        """Panics unless ``actions`` demonstrates a valid discovery for the
        property (replays the actions and validates per-expectation,
        `checker.rs:292-337`)."""
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self.model()
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.property(name)
            if prop.expectation is Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation is Expectation.EVENTUALLY:
                states = path.into_states()
                is_liveness_satisfied = any(
                    prop.condition(model, s) for s in states)
                last_actions: List = []
                model.actions(states[-1], last_actions)
                is_path_terminal = not last_actions
                if not is_liveness_satisfied and is_path_terminal:
                    return
                if is_liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property")
                if not is_path_terminal:
                    additional_info.append(
                        "incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was '
            f"found. found={found.into_actions()!r}")

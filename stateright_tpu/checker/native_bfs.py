"""Compiled host engines: ``NativeBfsChecker`` / ``NativeDfsChecker``.

The reference's host checkers are compiled Rust (`src/checker/bfs.rs:17-342`,
`dfs.rs:16-482`); these wrappers drive their C++ counterparts
(``native/host_bfs.cc``): the same JobMarket work-sharing pool, 1500-state
check blocks, and concurrent visited structures, operating on the model's
*device encoding* (fixed-width ``uint32`` vectors, murmur3-pair
fingerprints identical to ``tpu/hashing.py``). Because the encoding and
hashing are shared with the TPU engines, counts and discovery fingerprints
are directly comparable across the Python, native, and device engines —
and the BFS engine is the honest performance baseline for ``bench.py``
(the Python engine runs 1-2 orders slower than any compiled checker).

Models opt in by returning ``(model_id, cfg)`` from
``DeviceModel.native_form()`` — the id of a C++ model compiled into the
extension whose ``step``/properties are differentially tested against the
device form (``tests/test_native_bfs.py``). Models without a native form,
or builders with features the engines cannot honor (visitors; custom
symmetry canonicalizers), raise ``NotImplementedError`` so callers can
fall back to the Python engines.
"""

from __future__ import annotations

import ctypes
import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..model import Model
from .base import Checker
from .path import Path

__all__ = ["NativeBfsChecker", "NativeDfsChecker"]


class _NativeChecker(Checker):
    """Shared lifecycle for the compiled engines; subclasses set
    ``_prefix`` (the C-function family) and implement ``discoveries``."""

    _prefix: str

    def _c(self, name: str):
        return getattr(self._lib, f"{self._prefix}_{name}")

    def _prepare(self, builder, device_model):
        """Validates the configuration and returns everything needed for
        the create call — run BEFORE allocating the native handle so a
        validation error cannot leak it."""
        from ..native.host_bfs import (HOSTBFS_AVAILABLE, hostbfs_lib,
                                       model_info)

        if not HOSTBFS_AVAILABLE:
            raise NotImplementedError(
                "the native host engine extension failed to build; use "
                "the Python engines (spawn_bfs/spawn_dfs) instead")
        native_form = device_model.native_form()
        if native_form is None:
            raise NotImplementedError(
                f"{type(device_model).__name__} has no native (C++) model "
                "form; use the Python or device engines")
        if builder._visitor is not None:
            raise NotImplementedError(
                "visitors need the Python host loop; use "
                "spawn_bfs()/spawn_dfs()")
        self._model = builder._model
        self._dm = device_model
        self._lib = hostbfs_lib()
        model_id, cfg = native_form

        init_states = [s for s in self._model.init_states()
                       if self._model.within_boundary(s)]
        if init_states:
            init = np.stack([np.asarray(device_model.encode(s),
                                        np.uint32)
                             for s in init_states])
        else:
            # Zero within-boundary init states: complete trivially with
            # 0 states, exactly like the Python engines (np.stack([])
            # would instead die with an opaque shape error).
            init = np.zeros((0, device_model.state_width), np.uint32)
        w = init.shape[1]
        if w != device_model.state_width:
            raise ValueError("encode() width != device_model.state_width")
        native_w, _, native_props = model_info(model_id, cfg)
        if native_w != w:
            # e.g. a net_slots override changed the device layout while
            # the compiled model kept its default; running anyway would
            # silently check garbage states.
            raise ValueError(
                f"device encoding width {w} != native model width "
                f"{native_w}; the native form does not support this "
                "configuration (e.g. a net_slots override)")
        # Host property order == native property order (asserted by the
        # differential tests); map indices to names for discoveries().
        self._prop_names = [p.name for p in self._model.properties()]
        if len(self._prop_names) != native_props:
            raise ValueError(
                f"model has {len(self._prop_names)} properties but the "
                f"native form evaluates {native_props}")
        return model_id, cfg, init

    def _start(self) -> None:
        self._rc: Optional[int] = None
        # ctypes releases the GIL for the blocking run() call.
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        self._rc = self._c("run")(self._handle)

    def stop(self) -> "_NativeChecker":
        """Requests early exit: workers park at the next block boundary
        and ``is_done()`` stays false (like a target-count stop)."""
        self._c("stop")(self._handle)
        return self

    def __del__(self):
        handle = getattr(self, "_handle", None)
        thread = getattr(self, "_thread", None)
        if not handle or thread is None:
            return
        if thread.is_alive():
            # Abandoned mid-run: ask the engine to park its workers so
            # the visited structures are not grown forever, then free.
            self._c("stop")(handle)
            thread.join(timeout=30.0)
        if not thread.is_alive():
            self._c("destroy")(handle)
            self._handle = None

    def _fingerprint_state(self, state) -> int:
        from ..tpu.hashing import host_fp64

        return host_fp64(np.asarray(self._dm.encode(state), np.uint32))

    # -- Checker API ------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._c("state_count")(self._handle)

    def unique_state_count(self) -> int:
        return self._c("unique_count")(self._handle)

    def seconds(self) -> float:
        """Engine-measured wall time of the run (0.0 until joined)."""
        return self._c("seconds")(self._handle)

    def join(self) -> "_NativeChecker":
        self._thread.join()
        if self._rc is not None and self._rc < 0:
            raise RuntimeError(
                "native model error: an encoding capacity was exceeded "
                "(for actor models: raise net_slots)")
        return self

    def is_done(self) -> bool:
        return bool(self._c("is_done")(self._handle))


class NativeBfsChecker(_NativeChecker):
    """The compiled breadth-first engine (bfs.rs:17-342 design).

    Supports the framework's engine-agnostic checkpoints: ``resume_from``
    accepts a snapshot written by ANY of the BFS engines (Python device
    classic/fused/sharded or this one), and :meth:`checkpoint` writes one
    they can all resume — the (visited->parent map, pending frontier,
    discoveries) tuple is the whole checker state."""

    _prefix = "sr_hostbfs"

    def __init__(self, builder, device_model, threads: Optional[int] = None,
                 resume_from: Optional[str] = None,
                 async_io: Optional[bool] = None):
        # Asynchronous host I/O (round 17): the host BFS has no wave
        # loop to overlap (checkpoint() is post-run), but it shares the
        # knob so the serialize/CRC/write path — and any fault injected
        # there — runs and surfaces through the same writer machinery
        # as the device engines.
        from ..io.async_io import writer_from_config

        self._aio = writer_from_config(async_io, name="stpu-aio-hostbfs")
        if builder._symmetry is not None:
            raise NotImplementedError(
                "symmetry reduction lives in the DFS engines "
                "(dfs.rs:258-267); use spawn_native_dfs()/spawn_dfs()")
        model_id, cfg, init = self._prepare(builder, device_model)
        cfg_arr = (ctypes.c_longlong * len(cfg))(*cfg)
        self._handle = self._lib.sr_hostbfs_create(
            model_id, cfg_arr, len(cfg),
            init.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(init), threads or builder._thread_count,
            builder._target_state_count or 0)
        if not self._handle:
            raise ValueError(
                f"native model {model_id} rejected cfg={list(cfg)}")
        if resume_from is not None:
            try:
                self._seed_from_checkpoint(resume_from)
            except Exception:
                self._lib.sr_hostbfs_destroy(self._handle)
                self._handle = None
                raise
        self._start()

    # -- Checkpoint / resume (format of tpu/engine.py:_snapshot) --------

    def _seed_from_checkpoint(self, path: str) -> None:
        from ..checkpoint_format import (load_checkpoint, pending_rows,
                                         validate_header)

        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        with load_checkpoint(path) as data:
            header = validate_header(
                data, model_name=type(self._model).__name__,
                state_width=self._dm.state_width, use_symmetry=False)
            child = np.ascontiguousarray(data["parent_child"], np.uint64)
            # The native engine rebuilds its visited MAP from the parent
            # pairs; the format's separate visited array must describe
            # the same set, or resumed counts would silently diverge.
            if len(data["visited"]) != len(child):
                raise ValueError(
                    f"checkpoint visited set ({len(data['visited'])}) != "
                    f"parent map ({len(child)}); cannot rebuild the "
                    "native visited map faithfully")
            parent = np.ascontiguousarray(data["parent_parent"], np.uint64)
            rooted = np.asarray(data["parent_rooted"], bool)
            parent = np.where(rooted, np.uint64(0), parent)
            parent = np.ascontiguousarray(parent, np.uint64)
            # pending_rows unpacks a v2 packed-row snapshot (the header
            # self-describes the layout); the native engine always works
            # on full-width rows.
            vecs = pending_rows(data, header, self._dm.state_width)
            fps = np.ascontiguousarray(data["pending_fps"], np.uint64)
            ebits = np.ascontiguousarray(data["pending_ebits"], np.uint32)
            disc = np.zeros(len(self._prop_names), np.uint64)
            for name, fp in header["discoveries"].items():
                if name not in self._prop_names:
                    raise ValueError(
                        f"checkpoint records a discovery for property "
                        f"{name!r}, which this model configuration does "
                        f"not define (properties: {self._prop_names}) — "
                        "wrong configuration for this snapshot")
                disc[self._prop_names.index(name)] = np.uint64(int(fp))
            rc = self._lib.sr_hostbfs_seed(
                self._handle,
                child.ctypes.data_as(u64p), parent.ctypes.data_as(u64p),
                len(child),
                vecs.ctypes.data_as(u32p), fps.ctypes.data_as(u64p),
                ebits.ctypes.data_as(u32p), len(fps),
                int(header["state_count"]),
                np.ascontiguousarray(disc).ctypes.data_as(u64p))
            if rc != 0:
                raise RuntimeError(f"native seed failed (rc={rc})")

    def checkpoint(self, path: str) -> None:
        """Writes a snapshot resumable by any BFS engine. Call after the
        run has stopped (joined; done, all-discovered, target reached, or
        stop()ped)."""
        from ..checkpoint_format import make_header, write_atomic

        if self._thread.is_alive():
            raise RuntimeError(
                "checkpoint() while the checker is running would race "
                "the workers; stop() and join() first")
        if self._rc is not None and self._rc < 0:
            raise RuntimeError(
                "checkpoint() after a failed run would snapshot a torn "
                "frontier")
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        n = self._lib.sr_hostbfs_unique_count(self._handle)
        child = np.zeros(n, np.uint64)
        parent = np.zeros(n, np.uint64)
        got = self._lib.sr_hostbfs_visited_dump(
            self._handle, child.ctypes.data_as(u64p),
            parent.ctypes.data_as(u64p), n)
        if got != n:
            raise RuntimeError(f"visited dump failed ({got} != {n})")
        rows = self._lib.sr_hostbfs_pending_rows(self._handle)
        w = self._dm.state_width
        vecs = np.zeros((rows, w), np.uint32)
        fps = np.zeros(rows, np.uint64)
        ebits = np.zeros(rows, np.uint32)
        if rows and self._lib.sr_hostbfs_pending_dump(
                self._handle, vecs.ctypes.data_as(u32p),
                fps.ctypes.data_as(u64p), ebits.ctypes.data_as(u32p),
                rows) != 0:
            raise RuntimeError("pending dump failed")
        discs = self._raw_discoveries()
        header = make_header(
            model_name=type(self._model).__name__, state_width=w,
            state_count=int(
                self._lib.sr_hostbfs_state_count(self._handle)),
            unique_count=int(n), use_symmetry=False, discoveries=discs)
        payload = dict(
            header=header,
            visited=child, pending_vecs=vecs, pending_fps=fps,
            pending_ebits=ebits, parent_child=child,
            parent_parent=parent, parent_rooted=parent == 0)
        # Snapshot captured synchronously above; the write itself rides
        # the round-17 writer (inline with the knob off). The immediate
        # join keeps checkpoint()'s durability contract: the file
        # exists — or the failure raised here — on return.
        self._aio.submit(lambda: write_atomic(path, payload),
                         kind="checkpoint")
        self._aio.join()

    # -- Path reconstruction (bfs.rs:314-342) ----------------------------

    def _raw_discoveries(self) -> Dict[str, int]:
        """Property name -> discovery fingerprint, straight from the
        engine (shared by discoveries() and checkpoint())."""
        out = {}
        prop_idx = ctypes.c_int()
        fp = ctypes.c_uint64()
        for i in range(self._lib.sr_hostbfs_n_discoveries(self._handle)):
            if self._lib.sr_hostbfs_discovery(
                    self._handle, i, ctypes.byref(prop_idx),
                    ctypes.byref(fp)) == 0:
                out[self._prop_names[prop_idx.value]] = fp.value
        return out

    def _reconstruct_path(self, fp: int) -> Path:
        fingerprints: deque = deque()
        parent = ctypes.c_uint64()
        next_fp = fp
        while True:
            rc = self._lib.sr_hostbfs_parent(
                self._handle, ctypes.c_uint64(next_fp),
                ctypes.byref(parent))
            if rc < 0:
                break
            fingerprints.appendleft(next_fp)
            if rc == 0:  # root
                break
            next_fp = parent.value
        return Path.from_fingerprints(
            self._model, fingerprints, fingerprint_fn=self._fingerprint_state)

    def discoveries(self) -> Dict[str, Path]:
        return {name: self._reconstruct_path(fp)
                for name, fp in self._raw_discoveries().items()}


class NativeDfsChecker(_NativeChecker):
    """The compiled depth-first engine (`dfs.rs:16-482` design): LIFO
    work stacks, full-trace discoveries, and symmetry reduction with the
    original-fingerprint path rule (`dfs.rs:258-267`).

    Symmetry uses the *model's compiled* ``representative``
    (differentially tested against the host one); only the default
    ``builder.symmetry()`` is accepted — a custom ``symmetry_fn``
    canonicalizer cannot be honored by compiled code and raises."""

    _prefix = "sr_hostdfs"

    def __init__(self, builder, device_model, threads: Optional[int] = None):
        use_symmetry = builder._symmetry is not None
        if use_symmetry and not getattr(builder, "_symmetry_is_default",
                                        False):
            raise NotImplementedError(
                "the native DFS engine canonicalizes with the model's "
                "compiled representative and cannot honor a custom "
                "symmetry_fn; use .symmetry() (the default "
                "representative) or the Python spawn_dfs()")
        model_id, cfg, init = self._prepare(builder, device_model)
        cfg_arr = (ctypes.c_longlong * len(cfg))(*cfg)
        self._handle = self._lib.sr_hostdfs_create(
            model_id, cfg_arr, len(cfg),
            init.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(init), threads or builder._thread_count,
            builder._target_state_count or 0, 1 if use_symmetry else 0)
        if not self._handle:
            raise NotImplementedError(
                f"native model {model_id} rejected cfg={list(cfg)}"
                + (" (no compiled representative for symmetry)"
                   if use_symmetry else ""))
        self._start()

    def discoveries(self) -> Dict[str, Path]:
        out = {}
        # Keyed by property index (not discovery ordinal): a discovery
        # recorded between two C calls cannot shift the mapping.
        for p, name in enumerate(self._prop_names):
            n = self._lib.sr_hostdfs_discovery_len(self._handle, p)
            if n < 0:
                continue
            buf = (ctypes.c_uint64 * n)()
            if self._lib.sr_hostdfs_discovery_trace(
                    self._handle, p, buf, n) != n:
                continue
            out[name] = Path.from_fingerprints(
                self._model, list(buf),
                fingerprint_fn=self._fingerprint_state)
        return out

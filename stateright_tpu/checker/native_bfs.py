"""``NativeBfsChecker``: the compiled multithreaded host BFS engine.

The reference's host checker is compiled Rust (`src/checker/bfs.rs:17-342`);
this wrapper drives its C++ counterpart (``native/host_bfs.cc``): the same
JobMarket work-sharing pool, 1500-state check blocks, and concurrent
fingerprint->parent visited map, operating on the model's *device encoding*
(fixed-width ``uint32`` vectors, murmur3-pair fingerprints identical to
``tpu/hashing.py``). Because the encoding and hashing are shared with the
TPU engines, counts and discovery fingerprints are directly comparable
across the Python, native, and device engines — and this engine is the
honest performance baseline for ``bench.py`` (the Python engine runs 1-2
orders slower than any compiled checker).

Models opt in by returning ``(model_id, cfg)`` from
``DeviceModel.native_form()`` — the id of a C++ model compiled into the
extension whose ``step``/properties are differentially tested against the
device form (``tests/test_native_bfs.py``). Models without a native form,
or builders with a visitor/symmetry, raise ``NotImplementedError`` so
callers can fall back to the Python engines.
"""

from __future__ import annotations

import ctypes
import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

from ..model import Model
from .base import Checker
from .path import Path

__all__ = ["NativeBfsChecker"]


class NativeBfsChecker(Checker):
    def __init__(self, builder, device_model, threads: Optional[int] = None):
        from ..native.host_bfs import HOSTBFS_AVAILABLE, hostbfs_lib

        if not HOSTBFS_AVAILABLE:
            raise NotImplementedError(
                "the native host BFS extension failed to build; use "
                "spawn_bfs() (Python) instead")
        native_form = device_model.native_form()
        if native_form is None:
            raise NotImplementedError(
                f"{type(device_model).__name__} has no native (C++) model "
                "form; use spawn_bfs() or spawn_tpu_bfs()")
        if builder._visitor is not None:
            raise NotImplementedError(
                "visitors need the Python host loop; use spawn_bfs()")
        if builder._symmetry is not None:
            raise NotImplementedError(
                "symmetry reduction is not implemented in the native host "
                "engine; use spawn_bfs()/spawn_dfs()")
        self._model: Model = builder._model
        self._dm = device_model
        self._lib = hostbfs_lib()
        model_id, cfg = native_form

        init_states = [s for s in self._model.init_states()
                       if self._model.within_boundary(s)]
        init = np.stack([np.asarray(device_model.encode(s), np.uint32)
                         for s in init_states])
        w = init.shape[1]
        if w != device_model.state_width:
            raise ValueError("encode() width != device_model.state_width")
        from ..native.host_bfs import model_info

        native_w, _, native_props = model_info(model_id, cfg)
        if native_w != w:
            # e.g. a net_slots override changed the device layout while
            # the compiled model kept its default; running anyway would
            # silently check garbage states.
            raise ValueError(
                f"device encoding width {w} != native model width "
                f"{native_w}; the native form does not support this "
                "configuration (e.g. a net_slots override)")
        cfg_arr = (ctypes.c_longlong * len(cfg))(*cfg)
        self._handle = self._lib.sr_hostbfs_create(
            model_id, cfg_arr, len(cfg),
            init.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(init), threads or builder._thread_count,
            builder._target_state_count or 0)
        if not self._handle:
            raise ValueError(
                f"native model {model_id} rejected cfg={list(cfg)}")
        # Host property order == native property order (asserted by the
        # differential tests); map indices to names for discoveries().
        self._prop_names = [p.name for p in self._model.properties()]
        if len(self._prop_names) != native_props:
            raise ValueError(
                f"model has {len(self._prop_names)} properties but the "
                f"native form evaluates {native_props}")
        self._rc: Optional[int] = None
        # ctypes releases the GIL for the blocking run() call.
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        self._rc = self._lib.sr_hostbfs_run(self._handle)

    def stop(self) -> "NativeBfsChecker":
        """Requests early exit: workers park at the next block boundary
        and ``is_done()`` stays false (like a target-count stop)."""
        self._lib.sr_hostbfs_stop(self._handle)
        return self

    def __del__(self):
        handle = getattr(self, "_handle", None)
        thread = getattr(self, "_thread", None)
        if not handle or thread is None:
            return
        if thread.is_alive():
            # Abandoned mid-run: ask the engine to park its workers so
            # the visited map is not grown forever, then free it.
            self._lib.sr_hostbfs_stop(handle)
            thread.join(timeout=30.0)
        if not thread.is_alive():
            self._lib.sr_hostbfs_destroy(handle)
            self._handle = None

    # -- Path reconstruction (bfs.rs:314-342) ----------------------------

    def _fingerprint_state(self, state) -> int:
        from ..tpu.hashing import host_fp64

        return host_fp64(np.asarray(self._dm.encode(state), np.uint32))

    def _reconstruct_path(self, fp: int) -> Path:
        fingerprints: deque = deque()
        parent = ctypes.c_uint64()
        next_fp = fp
        while True:
            rc = self._lib.sr_hostbfs_parent(
                self._handle, ctypes.c_uint64(next_fp),
                ctypes.byref(parent))
            if rc < 0:
                break
            fingerprints.appendleft(next_fp)
            if rc == 0:  # root
                break
            next_fp = parent.value
        return Path.from_fingerprints(
            self._model, fingerprints, fingerprint_fn=self._fingerprint_state)

    # -- Checker API ------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._lib.sr_hostbfs_state_count(self._handle)

    def unique_state_count(self) -> int:
        return self._lib.sr_hostbfs_unique_count(self._handle)

    def discoveries(self) -> Dict[str, Path]:
        n = self._lib.sr_hostbfs_n_discoveries(self._handle)
        out = {}
        prop_idx = ctypes.c_int()
        fp = ctypes.c_uint64()
        for i in range(n):
            if self._lib.sr_hostbfs_discovery(
                    self._handle, i, ctypes.byref(prop_idx),
                    ctypes.byref(fp)) == 0:
                out[self._prop_names[prop_idx.value]] = \
                    self._reconstruct_path(fp.value)
        return out

    def seconds(self) -> float:
        """Engine-measured wall time of the run (0.0 until joined)."""
        return self._lib.sr_hostbfs_seconds(self._handle)

    def join(self) -> "NativeBfsChecker":
        self._thread.join()
        if self._rc is not None and self._rc < 0:
            raise RuntimeError(
                "native model error: an encoding capacity was exceeded "
                "(for actor models: raise net_slots)")
        return self

    def is_done(self) -> bool:
        return bool(self._lib.sr_hostbfs_is_done(self._handle))

"""Host breadth-first checker engine.

Counterpart of the reference's `src/checker/bfs.rs`. The visited map
``generated`` maps each state fingerprint to its *parent* fingerprint,
enabling path reconstruction by replay. Pending states are processed FIFO
(push-front/pop-back), giving BFS order; with a single worker (the default)
discovered paths are shortest. Properties are evaluated at pop time;
``Always``/``Sometimes`` discoveries record immediately, ``Eventually``
properties clear their per-path bit when satisfied, and remaining bits at a
terminal state become counterexamples (with the reference's documented
revisit/DAG-join caveats, `bfs.rs:239-259`, preserved deliberately for
parity).

This engine is the semantic reference for the TPU engine
(``stateright_tpu.tpu``), which replaces the worker/job-market loop with
whole-frontier waves on device.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from ..fingerprint import fingerprint
from ..model import Expectation, Model
from ..obs import tracer_from_env, wave_obs_from_env
from ..resilience.faults import fault_plan_from_env
from .base import Checker
from .path import Path
from ._market import JobMarket, SharedCount, run_worker_loop
from .visitor import as_visitor

__all__ = ["BfsChecker"]


class BfsChecker(Checker):
    #: wave-event ``engine`` id (obs schema): a host "wave" is one
    #: worker check_block.
    _ENGINE_ID = "host_bfs"

    def __init__(self, builder):
        model = builder._model
        self._model = model
        self._thread_count = builder._thread_count
        target_state_count = builder._target_state_count
        visitor = as_visitor(builder._visitor) if builder._visitor else None
        properties = model.properties()
        property_count = len(properties)

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = SharedCount(len(init_states))
        generated: Dict[int, Optional[int]] = {}
        for s in init_states:
            generated.setdefault(fingerprint(s), None)
        self._generated = generated
        ebits = frozenset(
            i for i, p in enumerate(properties)
            if p.expectation is Expectation.EVENTUALLY)
        pending = deque(
            (s, fingerprint(s), ebits) for s in init_states)
        self._discoveries: Dict[str, int] = {}
        self._properties = properties
        self._visitor = visitor

        self._tracer = tracer_from_env(self._ENGINE_ID, meta={
            "model": type(model).__name__,
            "threads": self._thread_count})
        self._faults = fault_plan_from_env()
        #: service observability (obs/hist.py): wave-latency
        #: histograms etc. over the same per-block wave entries the
        #: tracer serializes. Disarmed = the shared NULL_OBS.
        self._wave_obs = wave_obs_from_env(self._ENGINE_ID)
        self._emit_lock = threading.Lock()  # see Checker._emit_wave
        self._market = JobMarket(self._thread_count, pending)
        self._handles = []
        for _ in range(self._thread_count):
            t = threading.Thread(
                target=run_worker_loop,
                args=(self._market, self._thread_count, self._check_block,
                      self._discoveries, property_count, target_state_count,
                      self._state_count),
                kwargs=dict(
                    empty_job=deque,
                    job_len=len,
                    split_off=_split_off_deque,
                ),
                daemon=True)
            t.start()
            self._handles.append(t)

    # -- Hot loop (bfs.rs:165-274) ---------------------------------------

    def _check_block(self, pending: deque, max_count: int) -> None:
        if self._faults.active:
            # The host engine has no checkpoints (reference semantics:
            # a killed run restarts from scratch), so a crash here is
            # recovered by a supervised full re-run.
            self._faults.crash("host_crash", self._tracer)
        model = self._model
        properties = self._properties
        generated = self._generated
        discoveries = self._discoveries
        visitor = self._visitor

        actions: List = []
        generated_count = 0  # flushed to the shared counter once per block
        popped = 0           # states expanded this block (wave "bucket")
        novel_count = 0      # first-seen fingerprints this block
        try:
            while max_count > 0:
                max_count -= 1
                if not pending:
                    return
                state, state_fp, ebits = pending.pop()
                popped += 1
                if visitor is not None:
                    visitor.visit(model, self._reconstruct_path(state_fp))

                # Done if discoveries found for all properties.
                is_awaiting_discoveries = False
                for i, prop in enumerate(properties):
                    if prop.name in discoveries:
                        continue
                    if prop.expectation is Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            discoveries[prop.name] = state_fp
                        else:
                            is_awaiting_discoveries = True
                    elif prop.expectation is Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            discoveries[prop.name] = state_fp
                        else:
                            is_awaiting_discoveries = True
                    else:  # EVENTUALLY: discoveries only identified at
                        # terminal states, so still awaiting (bfs.rs:212-222).
                        is_awaiting_discoveries = True
                        if prop.condition(model, state):
                            ebits = ebits - {i}
                if not is_awaiting_discoveries:
                    return

                # Enqueue newly generated states.
                is_terminal = True
                actions.clear()
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    generated_count += 1
                    # Dedup by fingerprint. NOTE (parity with bfs.rs:239-259):
                    # ebits should arguably be part of the fingerprint, and a
                    # revisit may be a cycle, but the reference treats
                    # revisits as non-terminal; we preserve that.
                    next_fp = fingerprint(next_state)
                    if next_fp in generated:
                        is_terminal = False
                        continue
                    generated[next_fp] = state_fp
                    novel_count += 1
                    is_terminal = False
                    pending.appendleft((next_state, next_fp, ebits))
                if is_terminal:
                    for i, prop in enumerate(properties):
                        if i in ebits:
                            discoveries[prop.name] = state_fp
        finally:
            self._state_count.add(generated_count)
            if popped and (self._tracer.enabled
                           or self._wave_obs.enabled):
                self._emit_wave(popped, generated_count, novel_count)

    def _host_store_bytes(self) -> int:
        # The visited dict's measured footprint (obs schema v6 host
        # occupancy gauges — see Checker._emit_wave).
        import sys

        return sys.getsizeof(self._generated)

    def _reconstruct_path(self, fp: int) -> Path:
        """Walks parent pointers back to an init state, then replays the
        model along the fingerprints (`bfs.rs:314-342`)."""
        fingerprints: deque = deque()
        next_fp = fp
        while next_fp in self._generated:
            source = self._generated[next_fp]
            fingerprints.appendleft(next_fp)
            if source is None:
                break
            next_fp = source
        return Path.from_fingerprints(self._model, fingerprints)

    # -- Checker API -----------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count.value

    def unique_state_count(self) -> int:
        return len(self._generated)

    def discoveries(self) -> Dict[str, Path]:
        return {name: self._reconstruct_path(fp)
                for name, fp in list(self._discoveries.items())}

    def join(self) -> "BfsChecker":
        for h in self._handles:
            h.join()
        self._handles = []
        if self._wave_obs.enabled:
            self._wave_obs.close(self._tracer)
        self._tracer.close()
        if self._market.errors:
            raise self._market.errors[0]
        return self

    def is_done(self) -> bool:
        with self._market.lock:
            idle = (not self._market.jobs
                    and self._market.wait_count == self._thread_count)
        return idle or len(self._discoveries) == len(self._properties)


def _split_off_deque(pending: deque, size: int) -> deque:
    """Removes and returns the back ``size`` elements (processed soonest),
    preserving order — VecDeque::split_off semantics."""
    share = deque()
    for _ in range(size):
        share.appendleft(pending.pop())
    return share

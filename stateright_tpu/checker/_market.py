"""Work-sharing job market for the host checker engines.

Counterpart of the reference's ``JobMarket`` (Mutex + Condvar + job vector,
`bfs.rs:29-30,70-152`; `dfs.rs:28-29,76-158`): workers pull a job (a batch
of pending states), run a bounded ``check_block``, then split surplus
pending work into shares for waiting workers. BFS and DFS share this loop;
only the job container discipline (FIFO deque vs LIFO stack) and the
``check_block`` body differ.

On the TPU engine none of this exists — data parallelism over the frontier
replaces work stealing — but the host engines keep the reference's
semantics (including termination and early-exit behavior) bit-for-bit.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

__all__ = ["JobMarket", "SharedCount", "run_worker_loop"]

CHECK_BLOCK_SIZE = 1500  # states per check_block call (bfs.rs:120)


class SharedCount:
    """Thread-safe counter (the reference's ``AtomicUsize``). Engines
    accumulate locally inside ``check_block`` and flush once per block, so
    the lock is uncontended in practice."""

    __slots__ = ("value", "_lock")

    def __init__(self, value: int = 0):
        self.value = value
        self._lock = threading.Lock()

    def add(self, n: int) -> None:
        if n:
            with self._lock:
                self.value += n


class JobMarket:
    """Shared queue of jobs guarded by a lock + condition.

    ``dead_count`` tracks workers that exited on ``target_state_count``
    without marking themselves waiting (the reference leaves ``is_done``
    false in that case, `bfs.rs:129-134` — but unlike the reference, a
    still-parked waiter here is released once everyone else is waiting or
    dead, so ``join()`` cannot hang)."""

    def __init__(self, thread_count: int, initial_job):
        self.lock = threading.Lock()
        self.has_new_job = threading.Condition(self.lock)
        self.wait_count = thread_count
        self.dead_count = 0
        self.jobs: List = [initial_job]
        #: worker exceptions, re-raised by ``Checker.join()`` — a worker
        #: that dies must not let the run report partial results as if
        #: checking completed.
        self.errors: List[BaseException] = []


def run_worker_loop(
    market: JobMarket,
    thread_count: int,
    check_block: Callable,
    discoveries: dict,
    property_count: int,
    target_state_count: Optional[int],
    state_count: "SharedCount",
    empty_job: Callable,
    job_len: Callable,
    split_off: Callable,
) -> None:
    """One worker's loop (`bfs.rs:83-152`). ``check_block(pending)`` mutates
    the job in place; ``split_off(pending, size)`` removes and returns the
    ``size`` elements that would be processed soonest."""
    try:
        _worker_loop(market, thread_count, check_block, discoveries,
                     property_count, target_state_count, state_count,
                     empty_job, job_len, split_off)
    except BaseException as e:  # noqa: BLE001 — surfaced at join()
        with market.lock:
            market.errors.append(e)
            market.dead_count += 1
            market.has_new_job.notify_all()


def _worker_loop(
    market: JobMarket,
    thread_count: int,
    check_block: Callable,
    discoveries: dict,
    property_count: int,
    target_state_count: Optional[int],
    state_count: "SharedCount",
    empty_job: Callable,
    job_len: Callable,
    split_off: Callable,
) -> None:
    pending = empty_job()
    while True:
        # Step 1: Do work.
        if job_len(pending) == 0:
            with market.lock:
                while True:
                    if market.jobs:
                        pending = market.jobs.pop()
                        market.wait_count -= 1
                        break
                    # Done if all peers are waiting or dead.
                    if market.wait_count + market.dead_count >= thread_count:
                        market.has_new_job.notify_all()
                        return
                    market.has_new_job.wait()
        check_block(pending, CHECK_BLOCK_SIZE)
        if len(discoveries) == property_count:
            with market.lock:
                market.wait_count += 1
                market.has_new_job.notify_all()
            return
        if target_state_count is not None and target_state_count <= state_count.value:
            # Deliberately does NOT increment wait_count, matching the
            # reference (`bfs.rs:129-134`): is_done() stays false because
            # checking is incomplete. dead_count releases parked waiters.
            with market.lock:
                market.dead_count += 1
                market.has_new_job.notify_all()
            return

        # Step 2: Share work.
        if job_len(pending) > 1 and thread_count > 1:
            with market.lock:
                pieces = 1 + min(market.wait_count, job_len(pending))
                size = job_len(pending) // pieces
                for _ in range(1, pieces):
                    market.jobs.append(split_off(pending, size))
                    market.has_new_job.notify()
        elif job_len(pending) == 0:
            with market.lock:
                market.wait_count += 1

"""Checker engines and supporting types (paths, visitors, symmetry)."""

from .base import Checker
from .builder import CheckerBuilder
from .path import NondeterminismError, Path
from .visitor import CheckerVisitor, PathRecorder, StateRecorder

__all__ = [
    "Checker",
    "CheckerBuilder",
    "NondeterminismError",
    "Path",
    "CheckerVisitor",
    "PathRecorder",
    "StateRecorder",
]

"""Checker visitors: a hook run on every evaluated state.

Counterpart of the reference's `src/checker/visitor.rs`. A visitor receives
the model and the ``Path`` by which the checker reached the state being
evaluated (BFS reconstructs the path from parent pointers; DFS passes its
trace). Plain callables ``f(model, path)`` are accepted wherever a visitor
is expected (mirroring the closure blanket impl, `visitor.rs:23-30`).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Set

from .path import Path

__all__ = ["CheckerVisitor", "PathRecorder", "StateRecorder"]


class CheckerVisitor:
    """Visits every state evaluated by the checker (`visitor.rs:19-21`)."""

    def visit(self, model, path: Path) -> None:
        raise NotImplementedError


class _FnVisitor(CheckerVisitor):
    def __init__(self, fn: Callable):
        self._fn = fn

    def visit(self, model, path: Path) -> None:
        self._fn(model, path)


def as_visitor(v) -> CheckerVisitor:
    """Coerces a callable into a visitor (closure blanket impl)."""
    if isinstance(v, CheckerVisitor):
        return v
    if callable(v):
        return _FnVisitor(v)
    raise TypeError(f"not a visitor: {v!r}")


class PathRecorder(CheckerVisitor):
    """Records every visited path (`visitor.rs:45-66`). Paths passed to
    ``visit`` were already validated by reconstruction, so recording them
    doubles as a path-validity check (used by the symmetry regression test,
    `dfs.rs:476-480`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._paths: Set[Path] = set()

    @classmethod
    def new_with_accessor(cls):
        recorder = cls()

        def accessor() -> Set[Path]:
            with recorder._lock:
                return set(recorder._paths)

        return recorder, accessor

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._paths.add(path)


class StateRecorder(CheckerVisitor):
    """Records the final state of every visited path, in visit order
    (`visitor.rs:80-99`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._states: List = []

    @classmethod
    def new_with_accessor(cls):
        recorder = cls()

        def accessor() -> List:
            with recorder._lock:
                return list(recorder._states)

        return recorder, accessor

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._states.append(path.last_state())

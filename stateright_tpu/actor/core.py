"""The ``Actor`` abstraction: event-driven state machines.

Counterpart of the reference's `src/actor.rs:102-444`. The same actor code
runs under the model checker (``ActorModel`` explores every interleaving,
loss, and duplication) and on a real UDP network (``spawn``) — the headline
dual-execution capability.

API style: where the reference threads a ``Cow`` through handlers and
detects no-ops by whether ``to_mut()`` was called, the Python handlers are
*functional*: ``on_msg``/``on_timeout`` receive the current (immutable)
state and return the next state, or ``None`` to signal "state unchanged".
A delivery that returns ``None`` and emits no commands is a no-op and
produces no checker action (`actor.rs:232-234`, `actor/model.rs:278`).

Heterogeneous actor lists need no special machinery here (Python lists mix
actor types natively); the reference's ``Choice`` sum types
(`actor.rs:285-399`) survive as the ``choice`` module's variant-tagged
wrapper, whose load-bearing part is keeping equal inner states of
different variants distinct.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, List, Tuple

__all__ = [
    "Id",
    "Actor",
    "Out",
    "Command",
    "SendCmd",
    "SetTimerCmd",
    "CancelTimerCmd",
    "ScriptActor",
    "majority",
    "peer_ids",
    "model_timeout",
    "model_peers",
]


class Id(int):
    """Uniquely identifies an actor: an index under the checker, an encoded
    IPv4 socket address under the runtime (`actor.rs:102-148`,
    `spawn.rs:9-33`)."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def vec_from(ids: Iterable[int]) -> List["Id"]:
        return [Id(i) for i in ids]

    # -- Socket-address codec (spawn.rs:9-33): bytes 2-5 = IPv4, 6-7 = port

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        octets = [int(o) for o in ip.split(".")]
        value = 0
        for o in octets:
            value = (value << 8) | o
        return Id((value << 16) | port)

    def to_addr(self) -> Tuple[str, int]:
        port = int(self) & 0xFFFF
        ip_bits = (int(self) >> 16) & 0xFFFFFFFF
        ip = ".".join(str((ip_bits >> s) & 0xFF) for s in (24, 16, 8, 0))
        return ip, port


@dataclass(frozen=True)
class SendCmd:
    """Send a message to a destination."""
    dst: Id
    msg: Any


@dataclass(frozen=True)
class SetTimerCmd:
    """Set/reset the timer; ``(lo, hi)`` duration range in seconds."""
    range: Tuple[float, float]


@dataclass(frozen=True)
class CancelTimerCmd:
    """Cancel the timer if one is set."""


Command = (SendCmd, SetTimerCmd, CancelTimerCmd)


class Out:
    """Collects the commands emitted by a handler (`actor.rs:163-228`)."""

    __slots__ = ("commands",)

    def __init__(self):
        self.commands: List = []

    def send(self, recipient: Id, msg: Any) -> None:
        self.commands.append(SendCmd(recipient, msg))

    def broadcast(self, recipients: Iterable[Id], msg: Any) -> None:
        for recipient in recipients:
            self.commands.append(SendCmd(recipient, msg))

    def set_timer(self, duration_range: Tuple[float, float]) -> None:
        self.commands.append(SetTimerCmd(duration_range))

    def cancel_timer(self) -> None:
        self.commands.append(CancelTimerCmd())

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands.clear()

    def __len__(self) -> int:
        return len(self.commands)

    def __iter__(self):
        return iter(self.commands)

    def __repr__(self) -> str:
        return repr(self.commands)


class Actor:
    """An actor initializes internal state, then responds to incoming
    events by returning updated state and emitting commands
    (`actor.rs:240-283`).

    State values must be treated as immutable (use frozen dataclasses or
    tuples): return a *new* state rather than mutating, or ``None`` for
    "unchanged". Mutating a received state corrupts the checker's
    structural sharing."""

    def on_start(self, id: Id, o: Out):
        """Returns the initial state; may emit commands."""
        raise NotImplementedError

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        """Handles a message; returns the next state or ``None`` if
        unchanged. Default: no-op."""
        return None

    def on_timeout(self, id: Id, state, o: Out):
        """Handles a timeout; returns the next state or ``None`` if
        unchanged. Default: no-op."""
        return None


class ScriptActor(Actor):
    """Sends a series of messages in sequence, waiting for any delivery
    between each — useful as a scripted test client (`actor.rs:411-434`).
    State is the index of the next message to send."""

    def __init__(self, script: List[Tuple[Id, Any]]):
        self.script = list(script)

    def on_start(self, id: Id, o: Out) -> int:
        if self.script:
            dst, msg = self.script[0]
            o.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state: int, src: Id, msg, o: Out):
        if state < len(self.script):
            dst, out_msg = self.script[state]
            o.send(dst, out_msg)
            return state + 1
        return None


def majority(cluster_size: int) -> int:
    """Number of nodes constituting a majority (`actor.rs:437-439`)."""
    return cluster_size // 2 + 1


def peer_ids(self_id: Id, other_ids: Iterable[Id]) -> List[Id]:
    """All ids except ``self_id`` (`actor.rs:442-444`)."""
    return [i for i in other_ids if i != self_id]


def model_timeout() -> Tuple[float, float]:
    """An arbitrary timeout range: duration is irrelevant under the checker
    (`actor/model.rs:74-76`)."""
    return (0.0, 0.0)


def model_peers(self_ix: int, count: int) -> List[Id]:
    """Peer ids for actor ``self_ix`` of ``count`` (`actor/model.rs:80-85`)."""
    return [Id(j) for j in range(count) if j != self_ix]

"""System snapshot for actor models: ``ActorModelState`` and ``Network``.

Counterpart of the reference's `src/actor/model_state.rs` and the
``Network`` alias (`actor/model.rs:69`). The network is a *set* of
envelopes with order-insensitive hashing (`util.rs:123-144`): the same
in-flight messages yield the same fingerprint regardless of insertion
order, and duplicate sends collapse. Iteration order is insertion order,
which is deterministic across runs and processes (the reference relies on
a fixed-key hasher for the same guarantee, `actor/model.rs:217-218`).

For the TPU engine this maps to a struct-of-arrays layout: actor states as
per-type packed words, the network as a bounded multiset of encoded
envelopes, timers as a bitmask — see ``stateright_tpu.tpu.encoding``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Generic, Iterable, List, Optional, TypeVar

from ..fingerprint import fingerprint
from .core import Id

Msg = TypeVar("Msg")

__all__ = ["Envelope", "Network", "ActorModelState"]


@dataclass(frozen=True)
class Envelope(Generic[Msg]):
    """The source and destination for a message (`actor/model.rs:58-60`)."""

    src: Id
    dst: Id
    msg: Msg

    def __repr__(self) -> str:
        return f"Envelope {{ src: {self.src!r}, dst: {self.dst!r}, msg: {self.msg!r} }}"


class Network:
    """A set of in-flight envelopes with order-insensitive identity."""

    __slots__ = ("_envs",)

    def __init__(self, envelopes: Optional[Iterable[Envelope]] = None):
        self._envs: Dict[Envelope, None] = {}
        if envelopes is not None:
            for e in envelopes:
                self._envs[e] = None

    @staticmethod
    def from_iter(envelopes: Iterable[Envelope]) -> "Network":
        return Network(envelopes)

    def copy(self) -> "Network":
        n = Network.__new__(Network)
        n._envs = dict(self._envs)
        return n

    def insert(self, env: Envelope) -> None:
        self._envs[env] = None

    def remove(self, env: Envelope) -> None:
        self._envs.pop(env, None)

    def __contains__(self, env: Envelope) -> bool:
        return env in self._envs

    def __iter__(self):
        return iter(self._envs)

    def __len__(self) -> int:
        return len(self._envs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Network) and self._envs == other._envs

    def __hash__(self) -> int:
        return hash(frozenset(self._envs))

    def __fingerprint__(self):
        return self._envs  # dicts hash order-insensitively

    def __repr__(self) -> str:
        return "{" + ", ".join(repr(e) for e in self._envs) + "}"


class ActorModelState:
    """A snapshot of the actor system (`actor/model_state.rs:10-15`):
    per-actor states, in-flight network, timer flags, and auxiliary
    history. Treated as immutable; ``clone()`` shallow-copies (actor states
    are shared structurally, like the reference's ``Arc`` sharing)."""

    __slots__ = ("actor_states", "network", "is_timer_set", "history", "_fp")

    def __init__(self, actor_states: List, network: Network,
                 is_timer_set: List[bool], history: Any):
        self.actor_states = actor_states
        self.network = network
        self.is_timer_set = is_timer_set
        self.history = history
        self._fp: Optional[int] = None

    def clone(self) -> "ActorModelState":
        s = ActorModelState.__new__(ActorModelState)
        s.actor_states = list(self.actor_states)
        s.network = self.network.copy()
        s.is_timer_set = list(self.is_timer_set)
        s.history = self.history
        s._fp = None
        return s

    def __fingerprint__(self):
        return (self.actor_states, self.history,
                self.is_timer_set, self.network)

    def __eq__(self, other) -> bool:
        return (isinstance(other, ActorModelState)
                and self.actor_states == other.actor_states
                and self.history == other.history
                and self.is_timer_set == other.is_timer_set
                and self.network == other.network)

    def __hash__(self) -> int:
        if self._fp is None:
            self._fp = fingerprint(self)
        return self._fp

    def __repr__(self) -> str:
        return (f"ActorModelState {{ actor_states: {self.actor_states!r}, "
                f"network: {self.network!r}, "
                f"is_timer_set: {self.is_timer_set!r}, "
                f"history: {self.history!r} }}")

    # Symmetry: sorts actor states and rewrites ids embedded in the
    # network/history/timers (`actor/model_state.rs:103-118`). Provided by
    # stateright_tpu.symmetry once a RewritePlan is available.
    def representative(self) -> "ActorModelState":
        from ..symmetry import actor_model_representative

        return actor_model_representative(self)

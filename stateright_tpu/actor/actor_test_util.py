"""Actor-test fixtures: the ping-pong counter pair.

Counterpart of the reference's `src/actor/actor_test_util.rs:4-96`: two
actors bounce Ping/Pong messages, incrementing per-actor counters, with an
optional ``(msgs_in, msgs_out)`` history and seven properties covering
every expectation kind plus the history mechanism.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..model import Expectation
from .core import Actor, Id, Out
from .model import ActorModel

__all__ = ["PingPongActor", "Ping", "Pong", "PingPongCfg"]


@dataclass(frozen=True)
class Ping:
    value: int

    def __repr__(self):
        return f"Ping({self.value})"


@dataclass(frozen=True)
class Pong:
    value: int

    def __repr__(self):
        return f"Pong({self.value})"


class PingPongActor(Actor):
    """Sends Ping(0) on start (if serving) and echoes Pong/Ping, counting
    messages (`actor_test_util.rs:13-37`). State: message count."""

    def __init__(self, serve_to: Optional[Id] = None):
        self.serve_to = serve_to

    def on_start(self, id: Id, o: Out) -> int:
        if self.serve_to is not None:
            o.send(self.serve_to, Ping(0))
        return 0

    def on_msg(self, id: Id, state: int, src: Id, msg, o: Out):
        if type(msg) is Pong and state == msg.value:
            o.send(src, Ping(msg.value + 1))
            return state + 1
        if type(msg) is Ping and state == msg.value:
            o.send(src, Pong(msg.value))
            return state + 1
        return None


@dataclass
class PingPongCfg:
    maintains_history: bool
    max_nat: int

    def into_model(self) -> ActorModel:
        def record_in(cfg, history, env):
            if cfg.maintains_history:
                msgs_in, msgs_out = history
                return (msgs_in + 1, msgs_out)
            return None

        def record_out(cfg, history, env):
            if cfg.maintains_history:
                msgs_in, msgs_out = history
                return (msgs_in, msgs_out + 1)
            return None

        return (
            ActorModel(cfg=self, init_history=(0, 0))
            .actor(PingPongActor(serve_to=Id(1)))
            .actor(PingPongActor(serve_to=None))
            .record_msg_in(record_in)
            .record_msg_out(record_out)
            .with_boundary(lambda cfg, state: all(
                count <= cfg.max_nat for count in state.actor_states))
            .property(Expectation.ALWAYS, "delta within 1", lambda _, state:
                      max(state.actor_states) - min(state.actor_states) <= 1)
            .property(Expectation.SOMETIMES, "can reach max",
                      lambda model, state: any(
                          count == model.cfg.max_nat
                          for count in state.actor_states))
            .property(Expectation.EVENTUALLY, "must reach max",
                      lambda model, state: any(
                          count == model.cfg.max_nat
                          for count in state.actor_states))
            .property(Expectation.EVENTUALLY, "must exceed max",
                      # falsifiable due to the boundary
                      lambda model, state: any(
                          count == model.cfg.max_nat + 1
                          for count in state.actor_states))
            .property(Expectation.ALWAYS, "#in <= #out", lambda _, state:
                      state.history[0] <= state.history[1])
            .property(Expectation.EVENTUALLY, "#out <= #in + 1",
                      lambda _, state:
                      state.history[1] <= state.history[0] + 1)
        )

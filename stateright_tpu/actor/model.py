"""``ActorModel``: adapts a list of actors + network semantics to a ``Model``.

Counterpart of the reference's `src/actor/model.rs`. The checker knows
nothing about actors — ``ActorModel`` implements the plain ``Model``
interface (`actor/model.rs:205-513`): actions are ``Deliver`` (for every
in-flight envelope with a valid destination), ``Drop`` (for every envelope,
if the network is lossy), and ``Timeout`` (for every armed timer); fault
injection is therefore model-level and exhaustive. The ``history`` type
parameter carries auxiliary state updated by ``record_msg_in``/
``record_msg_out`` hooks — Lamport's auxiliary-variable technique — which
is how the consistency testers plug in.
"""

from __future__ import annotations

from dataclasses import dataclass
from pprint import pformat
from typing import Any, Callable, Iterable, List, Optional

from ..model import Model, Property
from .core import Actor, Id, Out, SendCmd, SetTimerCmd
from .model_state import ActorModelState, Envelope, Network

__all__ = [
    "ActorModel",
    "ActorModelAction",
    "DeliverAction",
    "DropAction",
    "TimeoutAction",
]


@dataclass(frozen=True)
class DeliverAction:
    """A message can be delivered to an actor."""
    src: Id
    dst: Id
    msg: Any

    def __repr__(self) -> str:
        return (f"Deliver {{ src: {self.src!r}, dst: {self.dst!r}, "
                f"msg: {self.msg!r} }}")


@dataclass(frozen=True)
class DropAction:
    """A message can be dropped if the network is lossy."""
    envelope: Envelope

    def __repr__(self) -> str:
        return f"Drop({self.envelope!r})"


@dataclass(frozen=True)
class TimeoutAction:
    """An actor can be notified after a timeout."""
    id: Id

    def __repr__(self) -> str:
        return f"Timeout({self.id!r})"


ActorModelAction = (DeliverAction, DropAction, TimeoutAction)


class ActorModel(Model):
    """A system of actors communicating over a simulated network
    (`actor/model.rs:25-39`). ``cfg`` is arbitrary user config exposed to
    property conditions via ``model.cfg``; ``init_history`` seeds the
    auxiliary history."""

    def __init__(self, cfg: Any = None, init_history: Any = None):
        self.actors: List[Actor] = []
        self.cfg = cfg
        self.duplicating_network = True   # default Yes (actor/model.rs:96)
        self.init_history = init_history
        self._init_network: List[Envelope] = []
        self.lossy_network = False        # default No (actor/model.rs:99)
        self._properties: List[Property] = []
        self._record_msg_in: Callable = lambda cfg, history, env: None
        self._record_msg_out: Callable = lambda cfg, history, env: None
        self._within_boundary: Callable = lambda cfg, state: True

    # -- Builder API (actor/model.rs:107-173) ----------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def with_actors(self, actors: Iterable[Actor]) -> "ActorModel":
        self.actors.extend(actors)
        return self

    def with_duplicating_network(self, duplicating: bool) -> "ActorModel":
        """Whether the network duplicates messages: when True (default),
        delivered envelopes stay in the network so redelivery is explored."""
        self.duplicating_network = duplicating
        return self

    def with_init_network(self, envelopes: Iterable[Envelope]) -> "ActorModel":
        self._init_network = list(envelopes)
        return self

    def with_lossy_network(self, lossy: bool) -> "ActorModel":
        """Whether the network loses messages: when True, every in-flight
        envelope also yields a Drop action."""
        self.lossy_network = lossy
        return self

    def property(self, *args):
        """With three arguments ``(expectation, name, condition)``: the
        builder knob adding a property (reference usage). With one argument
        ``(name)``: the ``Model.property`` lookup."""
        if len(args) == 1:
            return Model.property(self, args[0])
        expectation, name, condition = args
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, record: Callable) -> "ActorModel":
        """``record(cfg, history, envelope) -> Optional[new_history]`` for
        incoming (delivered) messages; ``None`` leaves history unchanged."""
        self._record_msg_in = record
        return self

    def record_msg_out(self, record: Callable) -> "ActorModel":
        """Like ``record_msg_in`` but for outgoing (sent) messages."""
        self._record_msg_out = record
        return self

    def with_boundary(self, boundary: Callable) -> "ActorModel":
        """``boundary(cfg, state) -> bool`` prunes the state space
        (the reference's ``within_boundary`` builder knob)."""
        self._within_boundary = boundary
        return self

    # -- Command processing (actor/model.rs:176-202) ---------------------

    def _process_commands(self, id: Id, out: Out,
                          state: ActorModelState) -> None:
        index = int(id)
        for c in out.commands:
            if type(c) is SendCmd:
                env = Envelope(id, c.dst, c.msg)
                history = self._record_msg_out(self.cfg, state.history, env)
                if history is not None:
                    state.history = history
                state.network.insert(env)
            elif type(c) is SetTimerCmd:
                # Resize on demand: actor states may not be initialized yet,
                # and the timer vector's length is part of state identity
                # (actor/model.rs:190-195).
                while len(state.is_timer_set) <= index:
                    state.is_timer_set.append(False)
                state.is_timer_set[index] = True
            else:  # CancelTimerCmd (no-op if the timer was never set)
                if index < len(state.is_timer_set):
                    state.is_timer_set[index] = False

    # -- Model interface (actor/model.rs:205-513) ------------------------

    def init_states(self) -> List[ActorModelState]:
        state = ActorModelState(
            actor_states=[],
            network=Network(self._init_network),
            is_timer_set=[],
            history=self.init_history,
        )
        for index, actor in enumerate(self.actors):
            id = Id(index)
            out = Out()
            actor_state = actor.on_start(id, out)
            state.actor_states.append(actor_state)
            self._process_commands(id, out, state)
        return [state]

    def actions(self, state: ActorModelState, actions: List) -> None:
        for env in state.network:
            # option 1: message is lost
            if self.lossy_network:
                actions.append(DropAction(env))
            # option 2: message is delivered
            if int(env.dst) < len(self.actors):
                actions.append(DeliverAction(env.src, env.dst, env.msg))
        # option 3: actor timeout
        for index, is_scheduled in enumerate(state.is_timer_set):
            if is_scheduled:
                actions.append(TimeoutAction(Id(index)))

    def next_state(self, last_sys_state: ActorModelState,
                   action) -> Optional[ActorModelState]:
        kind = type(action)
        if kind is DropAction:
            next_state = last_sys_state.clone()
            next_state.network.remove(action.envelope)
            return next_state

        if kind is DeliverAction:
            index = int(action.dst)
            # Not all messages can be delivered, so ignore those.
            if index >= len(last_sys_state.actor_states):
                return None
            last_actor_state = last_sys_state.actor_states[index]
            out = Out()
            next_actor_state = self.actors[index].on_msg(
                action.dst, last_actor_state, action.src, action.msg, out)
            # No-op deliveries produce no action (actor/model.rs:278).
            if next_actor_state is None and not out.commands:
                return None
            env = Envelope(action.src, action.dst, action.msg)
            history = self._record_msg_in(
                self.cfg, last_sys_state.history, env)

            next_sys_state = last_sys_state.clone()
            if not self.duplicating_network:
                # Only safe if invariants don't relate to envelope
                # existence (caveat at actor/model.rs:291-295).
                next_sys_state.network.remove(env)
            if next_actor_state is not None:
                next_sys_state.actor_states[index] = next_actor_state
            if history is not None:
                next_sys_state.history = history
            self._process_commands(action.dst, out, next_sys_state)
            return next_sys_state

        # TimeoutAction
        index = int(action.id)
        last_actor_state = last_sys_state.actor_states[index]
        out = Out()
        next_actor_state = self.actors[index].on_timeout(
            action.id, last_actor_state, out)
        # Faithful to the reference (actor/model.rs:313-314): the no-op
        # early exit requires a SetTimer in an empty command list, which
        # is unsatisfiable — timeouts always clear the timer and yield a
        # new state.
        keep_timer = any(type(c) is SetTimerCmd for c in out.commands)
        if next_actor_state is None and not out.commands and keep_timer:
            return None
        next_sys_state = last_sys_state.clone()
        next_sys_state.is_timer_set[index] = False
        if next_actor_state is not None:
            next_sys_state.actor_states[index] = next_actor_state
        self._process_commands(action.id, out, next_sys_state)
        return next_sys_state

    def format_action(self, action) -> str:
        if type(action) is DeliverAction:
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        return repr(action)

    def format_step(self, last_state: ActorModelState,
                    action) -> Optional[str]:
        kind = type(action)
        if kind is DropAction:
            return f"DROP: {action.envelope!r}"
        if kind is DeliverAction:
            index = int(action.dst)
            if index >= len(last_state.actor_states):
                return None
            last_actor_state = last_state.actor_states[index]
            out = Out()
            next_actor_state = self.actors[index].on_msg(
                action.dst, last_actor_state, action.src, action.msg, out)
        else:  # TimeoutAction
            index = int(action.id)
            if index >= len(last_state.actor_states):
                return None
            last_actor_state = last_state.actor_states[index]
            out = Out()
            next_actor_state = self.actors[index].on_timeout(
                action.id, last_actor_state, out)
        lines = [f"OUT: {out!r}", ""]
        if next_actor_state is not None:
            lines += [f"NEXT_STATE: {pformat(next_actor_state)}", "",
                      f"PREV_STATE: {pformat(last_actor_state)}"]
        else:
            lines += [f"UNCHANGED: {pformat(last_actor_state)}"]
        return "\n".join(lines) + "\n"

    def as_svg(self, path) -> Optional[str]:
        """Sequence diagram: per-actor timelines, delivery arrows, timeout
        circles (`actor/model.rs:403-504`)."""
        pairs = path.into_vec()
        actor_count = len(pairs[-1][0].actor_states)

        def plot(x, y):
            return x * 100, y * 30

        svg_w, svg_h = plot(actor_count, len(pairs))
        svg_w += 300  # extra width for event labels
        svg = [
            f"<svg version='1.1' baseProfile='full' "
            f"width='{svg_w}' height='{svg_h}' "
            f"viewbox='-20 -20 {svg_w + 20} {svg_h + 20}' "
            f"xmlns='http://www.w3.org/2000/svg'>",
            "<defs><marker class='svg-event-shape' id='arrow' "
            "markerWidth='12' markerHeight='10' refX='12' refY='5' "
            "orient='auto'><polygon points='0 0, 12 5, 0 10' />"
            "</marker></defs>",
        ]
        for actor_index in range(actor_count):
            x1, y1 = plot(actor_index, 0)
            x2, y2 = plot(actor_index, len(pairs))
            svg.append(f"<line x1='{x1}' y1='{y1}' x2='{x2}' y2='{y2}' "
                       f"class='svg-actor-timeline' />")
            svg.append(f"<text x='{x1}' y='{y1}' "
                       f"class='svg-actor-label'>{actor_index}</text>")

        # Arrows for deliveries; circles for timeouts.
        send_time = {}
        for time, (state, action) in enumerate(pairs):
            time += 1  # action is for the next step
            if type(action) is DeliverAction:
                key = (action.src, action.dst, _msg_key(action.msg))
                src_time = send_time.get(key, 0)
                x1, y1 = plot(int(action.src), src_time)
                x2, y2 = plot(int(action.dst), time)
                svg.append(f"<line x1='{x1}' x2='{x2}' y1='{y1}' y2='{y2}' "
                           f"marker-end='url(#arrow)' class='svg-event-line' />")
                index = int(action.dst)
                if index < len(state.actor_states):
                    out = Out()
                    self.actors[index].on_msg(
                        action.dst, state.actor_states[index],
                        action.src, action.msg, out)
                    for c in out.commands:
                        if type(c) is SendCmd:
                            send_time[(action.dst, c.dst,
                                       _msg_key(c.msg))] = time
            elif type(action) is TimeoutAction:
                x, y = plot(int(action.id), time)
                svg.append(f"<circle cx='{x}' cy='{y}' r='10' "
                           f"class='svg-event-shape' />")
                index = int(action.id)
                if index < len(state.actor_states):
                    out = Out()
                    self.actors[index].on_timeout(
                        action.id, state.actor_states[index], out)
                    for c in out.commands:
                        if type(c) is SendCmd:
                            send_time[(action.id, c.dst,
                                       _msg_key(c.msg))] = time

        # Event labels last so they draw over shapes.
        for time, (_state, action) in enumerate(pairs):
            time += 1
            if type(action) is DeliverAction:
                x, y = plot(int(action.dst), time)
                svg.append(f"<text x='{x}' y='{y}' "
                           f"class='svg-event-label'>{action.msg!r}</text>")
            elif type(action) is TimeoutAction:
                x, y = plot(int(action.id), time)
                svg.append(f"<text x='{x}' y='{y}' "
                           f"class='svg-event-label'>Timeout</text>")
        svg.append("</svg>")
        return "".join(svg)

    def properties(self) -> List[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)


def _msg_key(msg):
    """Hashable key for a message (used by the SVG send tracker)."""
    try:
        hash(msg)
        return msg
    except TypeError:
        return repr(msg)

"""Heterogeneous actor composition with variant-tagged state.

Counterpart of the reference's ``Choice`` actor impl (`actor.rs:285-399`),
which lets one actor list mix several actor types sharing a message type.
Python lists are naturally heterogeneous, so the load-bearing part here is
the *state tag*: in the reference, ``L(x)`` and ``R(x)`` are distinct actor
states even when the inner values compare equal, and the checker must not
conflate them. ``Choice.variant(i, actor)`` reproduces that: its state is
``ChoiceState(index, inner)``, so two variants with equal inner states
fingerprint differently.

Works under both execution modes (checker ``ActorModel`` and the UDP
``spawn`` runtime) like any other actor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from .core import Actor, Id, Out

__all__ = ["Choice", "ChoiceState"]


@dataclass(frozen=True)
class ChoiceState:
    """An inner actor state tagged with its variant index."""

    index: int
    state: Any


class Choice(Actor):
    """One variant of a heterogeneous actor family."""

    def __init__(self, index: int, actor: Actor):
        if index < 0:
            raise ValueError("variant index must be nonnegative")
        self.index = index
        self.actor = actor

    @staticmethod
    def variant(index: int, actor: Actor) -> "Choice":
        return Choice(index, actor)

    # The reference's binary-sum spellings, for familiarity:
    @staticmethod
    def left(actor: Actor) -> "Choice":
        return Choice(0, actor)

    @staticmethod
    def right(actor: Actor) -> "Choice":
        return Choice(1, actor)

    def on_start(self, id: Id, o: Out):
        return ChoiceState(self.index, self.actor.on_start(id, o))

    def on_msg(self, id: Id, state: ChoiceState, src: Id, msg, o: Out):
        if state.index != self.index:
            raise RuntimeError(
                f"Choice actor {int(id)} (variant {self.index}) received "
                f"state tagged for variant {state.index}")
        inner = self.actor.on_msg(id, state.state, src, msg, o)
        return None if inner is None else ChoiceState(self.index, inner)

    def on_timeout(self, id: Id, state: ChoiceState, o: Out):
        if state.index != self.index:
            raise RuntimeError(
                f"Choice actor {int(id)} (variant {self.index}) received "
                f"state tagged for variant {state.index}")
        inner = self.actor.on_timeout(id, state.state, o)
        return None if inner is None else ChoiceState(self.index, inner)

"""UDP actor runtime: run the SAME actors you model-check on real sockets.

Counterpart of the reference's `src/actor/spawn.rs:63-183` — the headline
"run what you check" capability (`README.md:100-105`). One OS thread per
actor; each binds a ``UdpSocket`` from its ``Id`` (bytes 2-5 = IPv4,
6-7 = port, `spawn.rs:9-33`), runs ``on_start``, then loops:

- ``recv`` with a timeout set to the next timer interrupt;
- datagram → ``deserialize`` → ``on_msg`` (malformed or non-IPv4 traffic
  is logged and ignored, `spawn.rs:104-123`);
- timeout elapsed → ``on_timeout``;
- emitted commands: ``SendCmd`` serializes + ``sendto``; ``SetTimerCmd``
  arms the interrupt at a uniform-random duration in the range
  (`spawn.rs:169-177`); ``CancelTimerCmd`` resets it to
  ``practically_never()`` (500 years, `spawn.rs:36-38`).

Serialization is pluggable (``serialize``/``deserialize`` byte codecs);
``spawn_json`` wires in JSON so deployed protocols interop with netcat —
e.g. ``echo '{"Put":{...}}' | nc -u localhost 3000``.
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
import logging
import random
import socket as socketlib
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Tuple

from .core import Actor, CancelTimerCmd, Id, Out, SendCmd, SetTimerCmd

__all__ = ["spawn", "spawn_json", "ActorRuntime", "NativeActorRuntime",
           "make_runtime", "practically_never", "json_serialize",
           "make_json_deserializer"]

log = logging.getLogger(__name__)

_MAX_DATAGRAM = 65_535  # matches the reference's receive buffer


def practically_never() -> float:
    """A monotonic instant 500 years out (`spawn.rs:36-38`)."""
    return time.monotonic() + 3600 * 24 * 365 * 500


def _encode_value(value: Any):
    """serde_json-style encoding (`paxos.rs:363-370` interop): a dataclass
    message encodes like a Rust enum variant — ``"Name"`` when fieldless,
    ``{"Name": field}`` with one field, ``{"Name": [fields...]}`` with
    more — so deployed actors answer hand-written netcat JSON like
    ``{"Put": [52, "X"]}`` exactly as the reference's do."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        fields = [_encode_value(getattr(value, f.name))
                  for f in dataclasses.fields(value)]
        name = type(value).__name__
        if not fields:
            return name
        return {name: fields[0] if len(fields) == 1 else fields}
    if isinstance(value, bool) or value is None:
        return value
    if isinstance(value, int):
        return int(value)  # Id and other int subclasses flatten
    if isinstance(value, (list, tuple)):
        return [_encode_value(v) for v in value]
    return value


def _decode_value(payload: Any, registry: dict):
    """Inverse of ``_encode_value``. JSON arrays decode to *tuples* (model
    messages use tuples; equality with lists would silently fail).
    Variant names are matched against ``registry``; unknown names raise
    ``ValueError`` (→ the runtime logs + ignores the datagram)."""
    if isinstance(payload, str) and payload in registry:
        return registry[payload]()
    if isinstance(payload, dict):
        if len(payload) != 1:
            raise ValueError(f"not a variant object: {payload!r}")
        name, raw = next(iter(payload.items()))
        cls = registry.get(name)
        if cls is None:
            raise ValueError(f"unknown message variant: {name}")
        fields = dataclasses.fields(cls)
        if len(fields) == 1:
            return cls(_decode_value(raw, registry))
        if not isinstance(raw, list) or len(raw) != len(fields):
            raise ValueError(
                f"variant {name} expects {len(fields)} fields: {raw!r}")
        return cls(*(_decode_value(v, registry) for v in raw))
    if isinstance(payload, list):
        return tuple(_decode_value(v, registry) for v in payload)
    return payload


def json_serialize(msg: Any) -> bytes:
    return json.dumps(_encode_value(msg)).encode()


def make_json_deserializer(msg_types: Iterable[type]) -> Callable:
    registry = {cls.__name__: cls for cls in msg_types}
    return lambda data: _decode_value(json.loads(data.decode()), registry)


class _ActorThread(threading.Thread):
    def __init__(self, runtime: "ActorRuntime", id: Id, actor: Actor):
        super().__init__(daemon=True, name=f"actor-{int(id)}")
        self.runtime = runtime
        self.id = id
        self.actor = actor
        self.state = None
        self._sock: Optional[socketlib.socket] = None
        self._next_interrupt = practically_never()
        self._ready = threading.Event()
        self._bind_error: Optional[OSError] = None

    # -- command side-effects (`spawn.rs:143-183`) -----------------------

    def _on_command(self, command) -> None:
        if isinstance(command, SendCmd):
            addr = Id(command.dst).to_addr()
            try:
                self._sock.sendto(
                    self.runtime.serialize(command.msg), addr)
            except (OSError, TypeError, ValueError) as e:
                log.warning("Unable to send. Ignoring. src=%s dst=%s "
                            "err=%r", self.id, addr, e)
        elif isinstance(command, SetTimerCmd):
            lo, hi = command.range
            duration = random.uniform(lo, hi) if lo < hi else lo
            self._next_interrupt = time.monotonic() + duration
        elif isinstance(command, CancelTimerCmd):
            self._next_interrupt = practically_never()

    def run(self) -> None:
        addr = self.id.to_addr()
        try:
            sock = socketlib.socket(
                socketlib.AF_INET, socketlib.SOCK_DGRAM)
            sock.bind(addr)
        except OSError as e:
            self._bind_error = e
            self._ready.set()
            return
        self._sock = sock
        out = Out()
        self.state = self.actor.on_start(self.id, out)
        log.info("Actor started. id=%s state=%r out=%r",
                 addr, self.state, out)
        for c in out:
            self._on_command(c)
        self._ready.set()

        while not self.runtime._stopping.is_set():
            out = Out()
            max_wait = self._next_interrupt - time.monotonic()
            if max_wait > 0:
                # Wake at least every 0.5 s to honor shutdown.
                sock.settimeout(min(max_wait, 0.5))
                try:
                    data, src_addr = sock.recvfrom(_MAX_DATAGRAM)
                except socketlib.timeout:
                    continue
                except OSError as e:
                    if self.runtime._stopping.is_set():
                        break
                    log.warning("Unable to read socket. Ignoring. id=%s "
                                "err=%r", addr, e)
                    continue
                try:
                    msg = self.runtime.deserialize(data)
                except (ValueError, KeyError, TypeError) as e:
                    log.debug("Unable to parse message. Ignoring. id=%s "
                              "src=%s buf=%r err=%r", addr, src_addr,
                              data[:64], e)
                    continue
                src = Id.from_addr(*src_addr[:2])
                log.info("Received message. id=%s src=%s msg=%r",
                         addr, src_addr, msg)
                next_state = self.actor.on_msg(
                    self.id, self.state, src, msg, out)
            else:
                self._next_interrupt = practically_never()
                next_state = self.actor.on_timeout(self.id, self.state, out)

            if next_state is not None:
                self.state = next_state
            if next_state is not None or len(out):
                log.debug("Acted. id=%s state=%r out=%r",
                          addr, self.state, out)
            for c in out:
                self._on_command(c)
        sock.close()


class ActorRuntime:
    """A running set of UDP actors. Use :func:`spawn` (blocking, like the
    reference) or instantiate directly + ``start()`` for embedding."""

    def __init__(self, serialize: Callable[[Any], bytes],
                 deserialize: Callable[[bytes], Any],
                 actors: Iterable[Tuple[Any, Actor]]):
        self.serialize = serialize
        self.deserialize = deserialize
        self._stopping = threading.Event()
        self.threads: List[_ActorThread] = [
            _ActorThread(self, Id(id), actor) for id, actor in actors]

    def start(self) -> "ActorRuntime":
        for t in self.threads:
            t.start()
        for t in self.threads:
            t._ready.wait(timeout=10)
            if t._bind_error is not None:
                self.stop()
                raise OSError(
                    f"unable to bind {t.id.to_addr()}: {t._bind_error}")
        return self

    def stop(self) -> None:
        self._stopping.set()
        for t in self.threads:
            if t.is_alive():
                t.join(timeout=2)

    def join(self) -> None:
        for t in self.threads:
            t.join()

    def __enter__(self) -> "ActorRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class NativeActorRuntime:
    """The native executor: every actor's socket and timer lives in one
    C++ epoll loop (`stateright_tpu/native/reactor.cc`); only handler
    dispatch runs in Python, via a ctypes callback. Same public API and
    observable behavior as :class:`ActorRuntime` (the reference's
    runtime semantics, `spawn.rs:63-183`), without a thread per actor.

    Requires the native toolchain + Linux; :func:`spawn`/:func:`spawn_json`
    select it automatically when available.
    """

    def __init__(self, serialize: Callable[[Any], bytes],
                 deserialize: Callable[[bytes], Any],
                 actors: Iterable[Tuple[Any, Actor]]):
        from ..native.reactor import EVENT_CB, reactor_lib

        self.serialize = serialize
        self.deserialize = deserialize
        self._actors = [(Id(id), actor) for id, actor in actors]
        self._lib = reactor_lib()
        if self._lib is None:
            raise OSError("native reactor unavailable")
        self._handle = self._lib.sr_reactor_create()
        if not self._handle:
            raise OSError("unable to create reactor")
        self._states: List[Any] = [None] * len(self._actors)
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # Keep the callback object alive for the reactor's lifetime.
        self._cb = EVENT_CB(self._on_event)

    def _ip_port(self, id: Id) -> Tuple[int, int]:
        return (int(id) >> 16) & 0xFFFFFFFF, int(id) & 0xFFFF

    def _apply(self, idx: int, out: Out) -> None:
        lib = self._lib
        for command in out:
            if isinstance(command, SendCmd):
                ip, port = self._ip_port(Id(command.dst))
                try:
                    data = self.serialize(command.msg)
                except (TypeError, ValueError) as e:
                    log.warning("Unable to serialize. Ignoring. src=%s "
                                "dst=%s err=%r", self._actors[idx][0],
                                Id(command.dst), e)
                    continue
                rc = lib.sr_reactor_send(self._handle, idx, ip, port,
                                         data, len(data))
                if rc != 0:
                    log.warning("Unable to send. Ignoring. src=%s dst=%s "
                                "errno=%d", self._actors[idx][0],
                                Id(command.dst), -rc)
            elif isinstance(command, SetTimerCmd):
                lo, hi = command.range
                duration = random.uniform(lo, hi) if lo < hi else lo
                lib.sr_reactor_set_timer(self._handle, idx, duration)
            elif isinstance(command, CancelTimerCmd):
                lib.sr_reactor_cancel_timer(self._handle, idx)

    def _on_event(self, idx: int, src_ip: int, src_port: int,
                  buf, length: int) -> int:
        try:
            id, actor = self._actors[idx]
            out = Out()
            if length < 0:
                next_state = actor.on_timeout(id, self._states[idx], out)
            else:
                data = (ctypes.string_at(buf, length) if length else b"")
                try:
                    msg = self.deserialize(data)
                except (ValueError, KeyError, TypeError) as e:
                    log.debug("Unable to parse message. Ignoring. id=%s "
                              "buf=%r err=%r", id, data[:64], e)
                    return 0
                src = Id((src_ip << 16) | src_port)
                log.info("Received message. id=%s src=%s msg=%r",
                         id, src, msg)
                next_state = actor.on_msg(id, self._states[idx], src,
                                          msg, out)
            if next_state is not None:
                self._states[idx] = next_state
            self._apply(idx, out)
        except Exception:  # noqa: BLE001 — a handler bug must not kill IO
            log.exception("Actor handler raised. id=%s",
                          self._actors[idx][0])
        return 0

    def start(self) -> "NativeActorRuntime":
        lib = self._lib
        for idx, (id, actor) in enumerate(self._actors):
            ip, port = self._ip_port(id)
            rc = lib.sr_reactor_add_actor(self._handle, ip, port)
            if rc < 0:
                self.stop()
                raise OSError(
                    f"unable to bind {id.to_addr()}: errno {-rc}")
            assert rc == idx
        # on_start before the loop runs (spawn.rs:84-89); sends/timers go
        # through the already-bound sockets.
        for idx, (id, actor) in enumerate(self._actors):
            out = Out()
            self._states[idx] = actor.on_start(id, out)
            log.info("Actor started. id=%s state=%r out=%r",
                     id.to_addr(), self._states[idx], out)
            self._apply(idx, out)
        self._thread = threading.Thread(
            target=lib.sr_reactor_run, args=(self._handle, self._cb),
            daemon=True, name="actor-reactor")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._lib.sr_reactor_stop(self._handle)
        joined = True
        if self._thread is not None:
            self._thread.join(timeout=5)
            joined = not self._thread.is_alive()
        if joined:
            self._lib.sr_reactor_destroy(self._handle)
            self._handle = None
        # else: a handler is blocking the loop thread — deliberately leak
        # the reactor (fds + arena) rather than free memory the loop is
        # still using; matches the thread runtime leaving daemons behind.

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join()

    def __enter__(self) -> "NativeActorRuntime":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def make_runtime(serialize, deserialize, actors, native=None):
    """Builds the best available runtime: the C++ reactor when the
    extension is loadable (``native=None``/``True``), else the
    thread-per-actor loop. ``native=False`` forces the portable one."""
    if native is not False:
        try:
            from ..native.reactor import REACTOR_AVAILABLE

            if REACTOR_AVAILABLE:
                return NativeActorRuntime(serialize, deserialize, actors)
        except OSError:
            pass
        if native:
            raise OSError("native reactor requested but unavailable")
    return ActorRuntime(serialize, deserialize, actors)


def spawn(serialize: Callable[[Any], bytes],
          deserialize: Callable[[bytes], Any],
          actors: Iterable[Tuple[Any, Actor]],
          native: Optional[bool] = None) -> None:
    """Runs actors over UDP, blocking the calling thread forever
    (`spawn.rs:63-140`). Each element of ``actors`` is ``(id, actor)``
    where ``id`` encodes the IPv4 address + port to bind. Uses the
    native epoll executor when available (``native=False`` opts out)."""
    make_runtime(serialize, deserialize, actors, native).start().join()


def spawn_json(actors: Iterable[Tuple[Any, Actor]],
               msg_types: Iterable[type] = (), block: bool = True,
               native: Optional[bool] = None):
    """``spawn`` with the JSON codec the reference's examples use
    (`paxos.rs:363-370`). ``msg_types`` lists additional message
    dataclasses to decode (the ``RegisterMsg`` variants are always
    registered). With ``block=False`` returns the started runtime
    (caller stops it)."""
    from .register import Get, GetOk, Internal, Put, PutOk

    registry = [Internal, Put, Get, PutOk, GetOk, *msg_types]
    runtime = make_runtime(
        json_serialize, make_json_deserializer(registry), actors, native)
    runtime.start()
    if not block:
        return runtime
    runtime.join()
    return runtime

"""Viewstamped-replication-style primary/backup consensus with view
change — the service corpus's round-14 protocol addition (ROADMAP item
5), built on ``actor/`` so it exercises the actor-model checking path
end to end (host ``ActorModel`` *and* the slot-list device form in
``tpu/models/vsr.py``).

The protocol is single-slot VR (Oki & Liskov's normal case plus the
view-change sub-protocol, specialized to one operation — the Synod
shape): the primary of view ``v`` (replica ``v mod n``) proposes the
value ``v + 1`` on its timer, backups acknowledge with ``PrepareOk``,
and a majority of acks commits. A backup's timer instead *suspects* the
primary and starts a view change: ``StartViewChange(v+1)`` gossip, then
— once a majority is changing views — ``DoViewChange(v+1, op)`` to the
new primary, carrying the sender's accepted operation. The new primary
adopts the **maximum** accepted operation across its majority of
``DoViewChange`` messages (values are ordered by proposing view, so the
max is the latest accepted proposal; quorum intersection guarantees a
committed value is in every such majority) and announces it with
``StartView``; backups re-acknowledge so the carried operation can
commit in the new view. Agreement therefore holds *across* view
changes, which is exactly what the ``agreement`` property checks.

Replica state is eight small integers, deliberately flat so the device
encoding (one ``uint32`` lane per field) is a direct transcription:

- ``view`` / ``status`` (0 = normal, 1 = view-change)
- ``op_val``: the accepted operation's value (0 = none); proposals in
  view ``v`` carry value ``v + 1``, so values order by proposing view
- ``committed``: the committed value (0 = none; never overwritten —
  a disagreeing commit is a *property* violation, not a crash)
- ``oks`` / ``svc`` / ``dvc``: replica bitmasks counting ``PrepareOk``,
  ``StartViewChange``, ``DoViewChange`` quorums
- ``dvc_best``: the maximum operation carried by ``DoViewChange``

Timers re-arm on every timeout, so proposal/suspicion remain enabled
forever; the ``max_view`` boundary is what bounds the state space
(`the same pattern as PingPong's max_nat`).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import List, Optional

from ..model import Expectation
from .core import Actor, Id, Out, majority, model_peers, model_timeout
from .model import ActorModel

__all__ = [
    "VsrCfg", "VsrReplica", "ReplicaState",
    "Prepare", "PrepareOk", "Commit",
    "StartViewChange", "DoViewChange", "StartView",
]


# -- Messages --------------------------------------------------------------


@dataclass(frozen=True)
class Prepare:
    """Primary of ``view`` proposes operation ``val`` (= view + 1)."""
    view: int
    val: int

    def __repr__(self):
        return f"Prepare(v={self.view}, x={self.val})"


@dataclass(frozen=True)
class PrepareOk:
    """Backup acknowledges the accepted operation of ``view``."""
    view: int

    def __repr__(self):
        return f"PrepareOk(v={self.view})"


@dataclass(frozen=True)
class Commit:
    """Primary announces ``val`` committed in ``view``."""
    view: int
    val: int

    def __repr__(self):
        return f"Commit(v={self.view}, x={self.val})"


@dataclass(frozen=True)
class StartViewChange:
    """A replica suspects the primary and proposes moving to ``view``."""
    view: int

    def __repr__(self):
        return f"StartViewChange(v={self.view})"


@dataclass(frozen=True)
class DoViewChange:
    """A majority member hands its accepted operation (``op_val``; 0 =
    none) to the new primary of ``view``."""
    view: int
    op_val: int

    def __repr__(self):
        return f"DoViewChange(v={self.view}, x={self.op_val})"


@dataclass(frozen=True)
class StartView:
    """The new primary of ``view`` announces the adopted operation."""
    view: int
    op_val: int

    def __repr__(self):
        return f"StartView(v={self.view}, x={self.op_val})"


# -- Replica ---------------------------------------------------------------


@dataclass(frozen=True)
class ReplicaState:
    view: int = 0
    status: int = 0        # 0 normal, 1 view-change
    op_val: int = 0        # accepted operation value (0 = none)
    committed: int = 0     # committed value (0 = none)
    oks: int = 0           # PrepareOk bitmask (valid at the primary)
    svc: int = 0           # StartViewChange bitmask
    dvc: int = 0           # DoViewChange bitmask (valid at new primary)
    dvc_best: int = 0      # max op carried by received DoViewChanges


def _primary(view: int, n: int) -> int:
    return view % n


def _popcount(mask: int) -> int:
    return bin(mask).count("1")


class VsrReplica(Actor):
    """One VR replica of an ``n``-replica group. Stateless config; the
    per-run state is the frozen :class:`ReplicaState`."""

    def __init__(self, n: int):
        self.n = n

    # The timer is armed at start and re-armed on every timeout, so the
    # proposal/suspicion actions stay enabled; the cfg boundary prunes
    # runaway view changes.

    def on_start(self, id: Id, o: Out) -> ReplicaState:
        o.set_timer(model_timeout())
        return ReplicaState()

    def on_timeout(self, id: Id, s: ReplicaState,
                   o: Out) -> Optional[ReplicaState]:
        o.set_timer(model_timeout())
        i, n = int(id), self.n
        if s.status == 0 and _primary(s.view, n) == i and s.op_val == 0:
            # Normal-case proposal: value = view + 1 (orders proposals
            # by view, which the view-change max depends on).
            val = s.view + 1
            o.broadcast(model_peers(i, n), Prepare(s.view, val))
            return replace(s, op_val=val, oks=1 << i)
        if s.status == 0 and _primary(s.view, n) != i:
            # Suspect the primary: start changing to view + 1.
            nv = s.view + 1
            o.broadcast(model_peers(i, n), StartViewChange(nv))
            return replace(s, view=nv, status=1, oks=0,
                           svc=1 << i, dvc=0, dvc_best=0)
        return None  # timer re-armed, state unchanged (self-loop)

    def on_msg(self, id: Id, s: ReplicaState, src: Id, msg,
               o: Out) -> Optional[ReplicaState]:
        i, n = int(id), self.n
        kind = type(msg)
        if kind is Prepare:
            return self._on_prepare(i, s, src, msg, o)
        if kind is PrepareOk:
            return self._on_prepare_ok(i, n, s, src, msg, o)
        if kind is Commit:
            return self._on_commit(s, msg)
        if kind is StartViewChange:
            return self._on_start_view_change(i, n, s, src, msg, o)
        if kind is DoViewChange:
            return self._on_do_view_change(i, n, s, src, msg, o)
        if kind is StartView:
            return self._on_start_view(s, src, msg, o)
        return None

    def _on_prepare(self, i, s, src, msg, o):
        if msg.view > s.view:
            # Catch up into the proposing view and accept.
            o.send(src, PrepareOk(msg.view))
            return ReplicaState(view=msg.view, status=0,
                                op_val=msg.val, committed=s.committed)
        if (msg.view == s.view and s.status == 0
                and _primary(msg.view, self.n) != i and s.op_val == 0):
            o.send(src, PrepareOk(msg.view))
            return replace(s, op_val=msg.val)
        return None  # stale view or duplicate

    def _on_prepare_ok(self, i, n, s, src, msg, o):
        if not (msg.view == s.view and s.status == 0
                and _primary(s.view, n) == i
                and s.op_val != 0 and s.committed == 0):
            return None
        oks = s.oks | (1 << int(src)) | (1 << i)
        if oks == s.oks:
            return None  # duplicate ack
        if _popcount(oks) >= majority(n):
            o.broadcast(model_peers(i, n), Commit(s.view, s.op_val))
            return replace(s, oks=oks, committed=s.op_val)
        return replace(s, oks=oks)

    def _on_commit(self, s, msg):
        if s.committed != 0:
            return None  # commits are final; disagreement is the
            #              agreement property's job to surface
        if msg.view > s.view:
            return ReplicaState(view=msg.view, status=0,
                                op_val=msg.val, committed=msg.val)
        return replace(s, committed=msg.val,
                       op_val=s.op_val if s.op_val else msg.val)

    def _on_start_view_change(self, i, n, s, src, msg, o):
        if msg.view > s.view:
            svc = (1 << i) | (1 << int(src))
            o.broadcast(model_peers(i, n), StartViewChange(msg.view))
            if _popcount(svc) >= majority(n):
                o.send(Id(_primary(msg.view, n)),
                       DoViewChange(msg.view, s.op_val))
            return replace(s, view=msg.view, status=1, oks=0,
                           svc=svc, dvc=0, dvc_best=0)
        if msg.view == s.view and s.status == 1:
            svc = s.svc | (1 << int(src))
            if svc == s.svc:
                return None  # duplicate
            if (_popcount(svc) >= majority(n)
                    and _popcount(s.svc) < majority(n)):
                # Quorum first reached: hand our accepted op over.
                o.send(Id(_primary(msg.view, n)),
                       DoViewChange(msg.view, s.op_val))
            return replace(s, svc=svc)
        return None

    def _on_do_view_change(self, i, n, s, src, msg, o):
        if _primary(msg.view, n) != i:
            return None
        if msg.view > s.view:
            dvc = (1 << i) | (1 << int(src))
            best = max(s.op_val, msg.op_val)
            st = replace(s, view=msg.view, status=1, oks=0, svc=0,
                         dvc=dvc, dvc_best=best)
            if _popcount(dvc) >= majority(n):
                return self._complete_view_change(i, n, st, o)
            return st
        if msg.view == s.view and s.status == 1:
            dvc = s.dvc | (1 << int(src)) | (1 << i)
            best = max(s.dvc_best, s.op_val, msg.op_val)
            if dvc == s.dvc and best == s.dvc_best:
                return None  # duplicate
            st = replace(s, dvc=dvc, dvc_best=best)
            if (_popcount(dvc) >= majority(n)
                    and _popcount(s.dvc) < majority(n)):
                return self._complete_view_change(i, n, st, o)
            return st
        return None  # stale, or the view change already completed

    def _complete_view_change(self, i, n, st, o):
        """The new primary adopts the max accepted op across its
        majority (0 = none: a fresh proposal waits for the timer) and
        announces the view."""
        best = st.dvc_best
        o.broadcast(model_peers(i, n), StartView(st.view, best))
        return replace(st, status=0, op_val=best,
                       oks=(1 << i) if best else 0,
                       svc=0, dvc=0, dvc_best=0)

    def _on_start_view(self, s, src, msg, o):
        if msg.view > s.view or (msg.view == s.view and s.status == 1):
            if msg.op_val != 0 and s.committed == 0:
                # Re-acknowledge the carried op so it can commit in the
                # new view.
                o.send(src, PrepareOk(msg.view))
            return ReplicaState(view=msg.view, status=0,
                                op_val=msg.op_val, committed=s.committed)
        return None


# -- Model configuration ---------------------------------------------------


@dataclass
class VsrCfg:
    """``n`` replicas bounded at ``max_view`` view changes. The model
    commits at most one operation; values order by proposing view, so
    ``agreement`` failing would mean quorum intersection was violated."""
    n: int = 3
    max_view: int = 1
    lossy: bool = False
    duplicating: bool = True

    def into_model(self) -> ActorModel:
        def bounded(cfg, state) -> bool:
            return all(s.view <= cfg.max_view
                       for s in state.actor_states)

        def committed(state) -> List[int]:
            return [s.committed for s in state.actor_states
                    if s.committed != 0]

        model = (
            ActorModel(cfg=self)
            .with_actors(VsrReplica(self.n) for _ in range(self.n))
            .with_duplicating_network(self.duplicating)
            .with_lossy_network(self.lossy)
            .with_boundary(bounded)
            .property(Expectation.ALWAYS, "agreement",
                      lambda _, state: len(set(committed(state))) <= 1)
            .property(Expectation.SOMETIMES, "can commit",
                      lambda _, state: bool(committed(state)))
            .property(Expectation.SOMETIMES, "view change completes",
                      lambda _, state: any(
                          s.view > 0 and s.status == 0
                          for s in state.actor_states))
            .property(Expectation.SOMETIMES, "commit survives view change",
                      lambda _, state: any(
                          s.committed != 0 and s.view > 0
                          for s in state.actor_states))
        )

        cfg = self

        def device_model():
            """Lazy: keeps this module importable without jax (the
            same pattern as the examples' into_model hooks)."""
            from ..tpu.models.vsr import VsrDevice

            return VsrDevice(cfg)

        model.device_model = device_model
        return model

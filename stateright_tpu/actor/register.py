"""Register-actor interface: the client protocol shared by all storage
examples, plus consistency-history plumbing.

Counterpart of the reference's `src/actor/register.rs`. ``RegisterMsg``
variants: ``Internal`` (protocol-specific), ``Put``/``Get`` (client
requests), ``PutOk``/``GetOk`` (responses). ``record_invocations`` /
``record_returns`` map these onto a ``ConsistencyTester``'s
``on_invoke``/``on_return`` when passed to ``record_msg_out`` /
``record_msg_in``, so properties can simply check
``state.history.is_consistent()``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..semantics.register import Read, ReadOk, Write, WriteOk
from .core import Actor, Id, Out

__all__ = [
    "Internal", "Put", "Get", "PutOk", "GetOk",
    "record_invocations", "record_returns",
    "RegisterActor", "RegisterClientState", "RegisterServerState",
]


@dataclass(frozen=True)
class Internal:
    """A message specific to the register system's internal protocol."""
    msg: Any

    def __repr__(self):
        return f"Internal({self.msg!r})"


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any

    def __repr__(self):
        return f"Put({self.request_id}, {self.value!r})"


@dataclass(frozen=True)
class Get:
    request_id: int

    def __repr__(self):
        return f"Get({self.request_id})"


@dataclass(frozen=True)
class PutOk:
    request_id: int

    def __repr__(self):
        return f"PutOk({self.request_id})"


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any

    def __repr__(self):
        return f"GetOk({self.request_id}, {self.value!r})"


def record_invocations(cfg, history, env):
    """Pass to ``ActorModel.record_msg_out`` (`register.rs:37-58`): records
    a Write on Put and a Read on Get, keyed by the *sending* actor."""
    msg = env.msg
    if type(msg) is Get:
        history = history.clone()
        try:
            history.on_invoke(env.src, Read())
        except ValueError:
            pass  # invalid histories surface via is_consistent (see ref)
        return history
    if type(msg) is Put:
        history = history.clone()
        try:
            history.on_invoke(env.src, Write(msg.value))
        except ValueError:
            pass
        return history
    return None


def record_returns(cfg, history, env):
    """Pass to ``ActorModel.record_msg_in`` (`register.rs:64-87`): records
    a ReadOk on GetOk and a WriteOk on PutOk, keyed by the *receiving*
    actor."""
    msg = env.msg
    if type(msg) is GetOk:
        history = history.clone()
        try:
            history.on_return(env.dst, ReadOk(msg.value))
        except ValueError:
            pass
        return history
    if type(msg) is PutOk:
        history = history.clone()
        try:
            history.on_return(env.dst, WriteOk())
        except ValueError:
            pass
        return history
    return None


@dataclass(frozen=True)
class RegisterClientState:
    awaiting: Any  # request id or None
    op_count: int

    def __repr__(self):
        return f"Client {{ awaiting: {self.awaiting!r}, op_count: {self.op_count} }}"


@dataclass(frozen=True)
class RegisterServerState:
    state: Any

    def __repr__(self):
        return f"Server({self.state!r})"


class RegisterActor(Actor):
    """Either a scripted client (puts ``put_count`` values round-robin
    across servers then gets) or a wrapped server under validation
    (`register.rs:90-217`). Servers must precede clients in the actor list
    so client ids can derive server destinations by modulo."""

    def __init__(self, *, put_count: int = None, server_count: int = None,
                 server: Actor = None):
        if server is not None:
            assert put_count is None and server_count is None
            self.server = server
            self.put_count = None
            self.server_count = None
        else:
            assert put_count is not None and server_count is not None
            self.server = None
            self.put_count = put_count
            self.server_count = server_count

    @staticmethod
    def client(put_count: int, server_count: int) -> "RegisterActor":
        return RegisterActor(put_count=put_count, server_count=server_count)

    @staticmethod
    def wrap(server: Actor) -> "RegisterActor":
        return RegisterActor(server=server)

    def on_start(self, id: Id, o: Out):
        if self.server is not None:
            return RegisterServerState(self.server.on_start(id, o))
        index = int(id)
        server_count = self.server_count
        if index < server_count:
            raise ValueError(
                "RegisterActor clients must be added to the model after "
                "servers.")
        if self.put_count == 0:
            return RegisterClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index  # next will be 2 * index
        value = chr(ord("A") + (index - server_count))
        o.send(Id(index % server_count), Put(unique_request_id, value))
        return RegisterClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id: Id, state, src: Id, msg, o: Out):
        if self.server is not None:
            inner = self.server.on_msg(id, state.state, src, msg, o)
            if inner is None:
                return None
            return RegisterServerState(inner)
        # Client
        if state.awaiting is None:
            return None
        index = int(id)
        server_count = self.server_count
        if type(msg) is PutOk and msg.request_id == state.awaiting:
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - server_count))
                o.send(Id((index + state.op_count) % server_count),
                       Put(unique_request_id, value))
            else:
                o.send(Id((index + state.op_count) % server_count),
                       Get(unique_request_id))
            return RegisterClientState(awaiting=unique_request_id,
                                       op_count=state.op_count + 1)
        if type(msg) is GetOk and msg.request_id == state.awaiting:
            return RegisterClientState(awaiting=None,
                                       op_count=state.op_count + 1)
        return None

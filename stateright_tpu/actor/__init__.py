"""Actor framework: model-checkable, network-deployable actors.

The same ``Actor`` implementation runs under ``ActorModel`` (exhaustive
interleaving/loss/duplication exploration by the checker) and over real UDP
sockets via ``spawn`` — the reference's headline dual-execution capability
(`README.md:100-105`).
"""

from .choice import Choice, ChoiceState
from .core import (
    Actor,
    CancelTimerCmd,
    Command,
    Id,
    Out,
    ScriptActor,
    SendCmd,
    SetTimerCmd,
    majority,
    model_peers,
    model_timeout,
    peer_ids,
)
from .model import (
    ActorModel,
    ActorModelAction,
    DeliverAction,
    DropAction,
    TimeoutAction,
)
from .model_state import ActorModelState, Envelope, Network

__all__ = [
    "Choice",
    "ChoiceState",
    "Actor",
    "ActorModel",
    "ActorModelAction",
    "ActorModelState",
    "CancelTimerCmd",
    "Command",
    "DeliverAction",
    "DropAction",
    "Envelope",
    "Id",
    "Network",
    "Out",
    "ScriptActor",
    "SendCmd",
    "SetTimerCmd",
    "TimeoutAction",
    "majority",
    "model_peers",
    "model_timeout",
    "peer_ids",
]

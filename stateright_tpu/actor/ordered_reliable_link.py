"""Ordered reliable link (ORL): per-(src, dst) ordering + at-least-once
resend + redelivery suppression over any actor.

Counterpart of `src/actor/ordered_reliable_link.rs:21-139` (loosely after
the "perfect link" of Cachin, Guerraoui & Rodrigues, with ordering). Order
is maintained per source/destination pair only. The wrapper:

1. tags outgoing sends with a sequencer (``OrlDeliver(seq, msg)``) and
   tracks them in ``msgs_pending_ack`` until acked;
2. re-sends everything pending on each resend timer
   (`ordered_reliable_link.rs:113-118`);
3. always acks incoming deliveries (even redeliveries, to stop resends)
   and drops already-delivered sequence numbers
   (`ordered_reliable_link.rs:83-90`);
4. does NOT advance the delivery sequencer when the inner actor ignores
   the message — a no-op delivery stays re-deliverable
   (`ordered_reliable_link.rs:91-96`).

Inner timers are unsupported, as in the reference
(`ordered_reliable_link.rs:126-131` ``todo!``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from .core import Actor, CancelTimerCmd, Id, Out, SendCmd, SetTimerCmd

__all__ = ["ActorWrapper", "OrlDeliver", "OrlAck", "OrlState"]


@dataclass(frozen=True)
class OrlDeliver:
    """A sequenced payload (`MsgWrapper::Deliver`)."""
    seq: int
    msg: Any

    def __repr__(self):
        return f"Deliver({self.seq}, {self.msg!r})"


@dataclass(frozen=True)
class OrlAck:
    """Acknowledges a sequencer (`MsgWrapper::Ack`)."""
    seq: int

    def __repr__(self):
        return f"Ack({self.seq})"


@dataclass(frozen=True)
class OrlState:
    """Link state around the wrapped actor's (`StateWrapper`). The maps
    are sorted tuples of pairs so states stay hashable + canonical."""
    next_send_seq: int
    msgs_pending_ack: Tuple   # ((seq, (dst, msg)), ...)
    last_delivered_seqs: Tuple  # ((src, seq), ...)
    wrapped_state: Any

    def __repr__(self):
        return (f"OrlState(seq={self.next_send_seq}, "
                f"pending={self.msgs_pending_ack!r}, "
                f"delivered={self.last_delivered_seqs!r}, "
                f"wrapped={self.wrapped_state!r})")


def _map_get(pairs: Tuple, key, default=None):
    for k, v in pairs:
        if k == key:
            return v
    return default


def _map_set(pairs: Tuple, key, value) -> Tuple:
    return tuple(sorted(
        [(k, v) for k, v in pairs if k != key] + [(key, value)]))


def _map_remove(pairs: Tuple, key) -> Tuple:
    return tuple((k, v) for k, v in pairs if k != key)


class ActorWrapper(Actor):
    """Wraps ``wrapped_actor`` with the ORL protocol."""

    def __init__(self, wrapped_actor: Actor,
                 resend_interval: Tuple[float, float] = (1.0, 2.0)):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = resend_interval

    @classmethod
    def with_default_timeout(cls, wrapped_actor: Actor) -> "ActorWrapper":
        return cls(wrapped_actor)  # 1–2 s, as the reference

    def _process_output(self, state: OrlState, inner_out: Out,
                        o: Out) -> OrlState:
        """Sequences the inner actor's sends (`ordered_reliable_link.rs:121-139`)."""
        seq = state.next_send_seq
        pending = state.msgs_pending_ack
        for command in inner_out:
            if isinstance(command, (SetTimerCmd, CancelTimerCmd)):
                raise NotImplementedError(
                    "inner timers are not supported by the ORL "
                    "(`ordered_reliable_link.rs:126-131`)")
            assert isinstance(command, SendCmd)
            o.send(command.dst, OrlDeliver(seq, command.msg))
            pending = _map_set(pending, seq, (command.dst, command.msg))
            seq += 1
        return OrlState(seq, pending, state.last_delivered_seqs,
                        state.wrapped_state)

    def on_start(self, id: Id, o: Out) -> OrlState:
        o.set_timer(self.resend_interval)
        inner_out = Out()
        state = OrlState(
            next_send_seq=1,
            msgs_pending_ack=(),
            last_delivered_seqs=(),
            wrapped_state=self.wrapped_actor.on_start(id, inner_out))
        return self._process_output(state, inner_out, o)

    def on_msg(self, id: Id, state: OrlState, src: Id, msg, o: Out):
        if type(msg) is OrlDeliver:
            # Always ack to stop resends; drop if already delivered.
            o.send(src, OrlAck(msg.seq))
            if msg.seq <= _map_get(state.last_delivered_seqs, src, 0):
                return None
            inner_out = Out()
            inner_next = self.wrapped_actor.on_msg(
                id, state.wrapped_state, src, msg.msg, inner_out)
            if inner_next is None and not len(inner_out):
                # Inner no-op: don't advance the sequencer — the message
                # stays deliverable later (`ordered_reliable_link.rs:91-96`).
                return None
            next_state = OrlState(
                state.next_send_seq,
                state.msgs_pending_ack,
                _map_set(state.last_delivered_seqs, src, msg.seq),
                state.wrapped_state if inner_next is None else inner_next)
            return self._process_output(next_state, inner_out, o)
        if type(msg) is OrlAck:
            # Mirrors the reference, which mutates unconditionally
            # (`ordered_reliable_link.rs:107-109`): an Ack is never elided
            # as a no-op even when the seq was already cleared.
            return OrlState(
                state.next_send_seq,
                _map_remove(state.msgs_pending_ack, msg.seq),
                state.last_delivered_seqs,
                state.wrapped_state)
        return None

    def on_timeout(self, id: Id, state: OrlState, o: Out):
        o.set_timer(self.resend_interval)
        for seq, (dst, msg) in state.msgs_pending_ack:
            o.send(dst, OrlDeliver(seq, msg))
        return None

"""Asynchronous host I/O (round 17).

``async_io`` is the bounded background writer the wave loops hand
completed safe-point work to: checkpoint generations, tiered-store
cold-segment spills, and elastic shard writes run off-thread while the
device computes the next waves. See ``async_io.AsyncWriter`` for the
lifecycle and the safe-point join rule.
"""

from .async_io import (ASYNC_IO_ENV, AsyncWriter, SyncWriter,
                       async_io_from_env, writer_from_config)

__all__ = ["ASYNC_IO_ENV", "AsyncWriter", "SyncWriter",
           "async_io_from_env", "writer_from_config"]

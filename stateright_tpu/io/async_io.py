"""Bounded background writer: hide host I/O behind device compute.

Rounds 9/15/16 optimized the device path; every host-side I/O still ran
synchronously inside the wave loop — checkpoint CRC + ``write_atomic``,
tiered-store cold-segment writes, elastic shard writes — so the device
idled while the host serialized. The ``AsyncWriter`` here is the
round-17 answer: ONE daemon thread plus a small bounded task queue (the
"double-buffered snapshot slots") that safe points hand completed work
to.

The contract that keeps the knob bit-identical to the sync path:

* **Capture is synchronous.** The caller snapshots its arrays at the
  rest point (same instant the sync path would), so the bytes handed to
  the writer are exactly what a sync write would have serialized. Only
  CRC/compress/rotate/rename move off-thread.
* **Safe points join first.** ``join()`` waits for every submitted task
  and re-raises the FIRST captured failure, clearing it — so a fault
  injected on the writer thread (``torn_ckpt``, ``spill_fail``,
  ``disk_full``) surfaces at the next safe point on the wave-loop
  thread, where the Supervisor / flight-recorder / trace-lint machinery
  already knows how to handle it. Generation ordering is free: one FIFO
  thread, and the next checkpoint joins any still-pending write before
  submitting its own, so keep-last-2 rotation order is preserved.
* **Bounded queue.** ``submit`` blocks once ``slots`` tasks are
  outstanding — the wave loop can run at most that far ahead of the
  disk, so memory held by captured snapshots stays bounded.

``SyncWriter`` is the knob-off twin: same surface, ``submit`` runs the
task inline (exceptions propagate immediately, exactly the pre-round-17
behavior), ``join`` is a no-op. Call sites stay uniform either way.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, Optional

#: env knob (wave_kernel precedent): unset/""/"0" = off, anything else on.
ASYNC_IO_ENV = "STpu_ASYNC_IO"


def async_io_from_env() -> bool:
    """The env-knob default for the ``async_io`` kwarg."""
    return os.environ.get(ASYNC_IO_ENV, "") not in ("", "0")


def resolve_async_io(knob: Optional[bool]) -> bool:
    """kwarg > env (wave_kernel-knob precedent)."""
    return async_io_from_env() if knob is None else bool(knob)


class SyncWriter:
    """Null-object twin of ``AsyncWriter``: runs every task inline on
    the calling thread. Keeps the same stats surface so telemetry
    consumers read one shape regardless of the knob."""

    enabled = False

    def __init__(self) -> None:
        self._stats: Dict[str, float] = {
            "submitted": 0, "completed": 0, "failed": 0,
            "joins": 0, "join_wait_s": 0.0, "busy_s": 0.0}
        self._by_kind: Dict[str, int] = {}

    def submit(self, fn: Callable[[], None], *, kind: str = "write") -> None:
        self._stats["submitted"] += 1
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
        t0 = time.monotonic()
        try:
            fn()
        except BaseException:
            self._stats["failed"] += 1
            raise
        finally:
            self._stats["busy_s"] += time.monotonic() - t0
            self._stats["completed"] += 1

    def join(self) -> None:
        """No-op: inline tasks finished (or raised) at submit."""

    def drain(self) -> None:
        """No-op twin of the non-raising drain."""

    def reset(self) -> None:
        """No-op: nothing pending, no captured error."""

    def pending(self) -> int:
        return 0

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        s = dict(self._stats)
        s.update(enabled=False, pending=0, overlap_s=0.0,
                 by_kind=dict(self._by_kind))
        s["join_wait_s"] = round(s["join_wait_s"], 6)
        s["busy_s"] = round(s["busy_s"], 6)
        return s


class AsyncWriter:
    """One writer thread + a bounded slot queue. See the module doc for
    the safe-point contract."""

    enabled = True

    def __init__(self, *, slots: int = 2,
                 name: str = "stpu-async-io") -> None:
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(slots)))
        self._cv = threading.Condition()
        self._outstanding = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._stats: Dict[str, float] = {
            "submitted": 0, "completed": 0, "failed": 0,
            "joins": 0, "join_wait_s": 0.0, "busy_s": 0.0}
        self._by_kind: Dict[str, int] = {}
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name)
        self._thread.start()

    # -- caller side -----------------------------------------------------

    def submit(self, fn: Callable[[], None], *, kind: str = "write") -> None:
        """Queues ``fn`` for the writer thread; blocks while both slots
        are full (the wave loop may run at most ``slots`` writes ahead).
        Failures do NOT surface here — they surface at the next
        ``join()``, i.e. the next safe point."""
        with self._cv:
            if self._closed:
                raise RuntimeError("submit() on a closed AsyncWriter")
            self._outstanding += 1
            self._stats["submitted"] += 1
            self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
        self._q.put((fn, kind))

    def join(self) -> None:
        """Waits for every submitted task, then re-raises the first
        captured failure (clearing it). This is THE safe-point rule:
        a fault that fired on the writer thread becomes an ordinary
        wave-loop exception here, on the thread whose Supervisor /
        postmortem machinery expects it."""
        t0 = time.monotonic()
        with self._cv:
            while self._outstanding:
                self._cv.wait()
            self._stats["joins"] += 1
            self._stats["join_wait_s"] += time.monotonic() - t0
            err, self._error = self._error, None
        if err is not None:
            raise err

    def drain(self) -> Optional[BaseException]:
        """Like ``join`` but returns the captured failure instead of
        raising (shutdown paths that must not throw)."""
        with self._cv:
            while self._outstanding:
                self._cv.wait()
            err, self._error = self._error, None
        return err

    def reset(self) -> None:
        """Drops any captured failure after draining — restart_from()
        recovery: the failed generation's error was already surfaced
        (or superseded) by the resume."""
        self.drain()

    def pending(self) -> int:
        with self._cv:
            return self._outstanding

    def close(self) -> None:
        """Drains outstanding work and stops the thread. Never raises;
        a still-captured failure is dropped (close() runs on paths that
        already know the run's outcome)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
        self.drain()
        self._q.put(None)
        self._thread.join(timeout=30.0)

    def stats(self) -> dict:
        with self._cv:
            s = dict(self._stats)
            s.update(enabled=True, pending=self._outstanding,
                     by_kind=dict(self._by_kind))
        # Seconds the writer worked that the wave loop did NOT wait for:
        # the overlap the knob buys.
        s["overlap_s"] = round(max(0.0, s["busy_s"] - s["join_wait_s"]), 6)
        s["join_wait_s"] = round(s["join_wait_s"], 6)
        s["busy_s"] = round(s["busy_s"], 6)
        return s

    # -- writer thread ---------------------------------------------------

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, _kind = item
            t0 = time.monotonic()
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at join
                with self._cv:
                    if self._error is None:
                        self._error = e
                    self._stats["failed"] += 1
            finally:
                with self._cv:
                    self._stats["busy_s"] += time.monotonic() - t0
                    self._stats["completed"] += 1
                    self._outstanding -= 1
                    self._cv.notify_all()


def writer_from_config(async_io: Optional[bool] = None, *,
                       slots: int = 2, name: str = "stpu-async-io"):
    """The knob resolver every component shares: kwarg wins, else the
    ``STpu_ASYNC_IO`` env (""/"0" = off). Returns an armed
    ``AsyncWriter`` or the inline ``SyncWriter``."""
    if resolve_async_io(async_io):
        return AsyncWriter(slots=slots, name=name)
    return SyncWriter()

"""stateright_tpu: a TPU-native model checker for distributed systems.

A from-scratch framework with the capabilities of the reference `stateright`
crate (explicit-state model checking with safety/liveness/reachability
properties, symmetry reduction, an interactive explorer, an actor framework
that can be both exhaustively checked and deployed on a real network, and
linearizability/sequential-consistency testers) — re-designed TPU-first:
the checker advances whole BFS frontiers as batches of fixed-width encoded
states under ``jit``/``vmap``, deduplicates against a device-resident
fingerprint table, and shards the fingerprint space across a
``jax.sharding.Mesh`` for multi-chip runs.

Host engines (``spawn_bfs``/``spawn_dfs``) provide the sequential reference
semantics; ``spawn_tpu_bfs`` is the device engine.
"""

from .fingerprint import fingerprint, register_encoder, stable_encode
from .model import Expectation, Model, Property
from .checker import (
    Checker,
    CheckerBuilder,
    CheckerVisitor,
    NondeterminismError,
    Path,
    PathRecorder,
    StateRecorder,
)
from .symmetry import RewritePlan, rewrite_value, sort_key
from .util import (DenseNatMap, HashableHashMap,
                   HashableHashSet, VectorClock)

__version__ = "0.1.0"

__all__ = [
    "fingerprint",
    "register_encoder",
    "stable_encode",
    "Expectation",
    "Model",
    "Property",
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "NondeterminismError",
    "Path",
    "PathRecorder",
    "StateRecorder",
    "RewritePlan",
    "rewrite_value",
    "sort_key",
    "DenseNatMap",
    "VectorClock",
    "HashableHashSet",
    "HashableHashMap",
    "__version__",
]

"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding code
paths compile and execute without TPU hardware — and so test runs don't
serialize on (or hang waiting for) a tunneled TPU chip.

Note: on images where a sitecustomize imports jax at interpreter startup
(e.g. with ``JAX_PLATFORMS`` pointing at a TPU plugin in the ambient
environment), mutating ``os.environ`` here is too late — jax has already
read it. ``jax.config.update("jax_platforms", ...)`` still works as long
as no backend has been initialized, so we use that, plus ``XLA_FLAGS``
(read lazily at CPU-client creation) for the virtual device count.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge._backends, \
    "a JAX backend was initialized before conftest could force CPU"

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

# -- Tier-1 per-test runtime budget --------------------------------------
#
# The fast suite runs under a hard 870s timeout (ROADMAP tier-1) and
# round 9 left it at ~820s — one slow new test away from zeroing the
# whole verify. This guard makes the regression local and attributable:
# any test NOT marked `slow` that exceeds the per-test budget fails
# with instructions, instead of the suite silently creeping into the
# timeout. The budget is deliberately ~3x the slowest legitimate fast
# test (so a loaded box doesn't flake it); STpu_TEST_BUDGET_S
# overrides, 0 disables.

import time  # noqa: E402

import pytest  # noqa: E402

_TEST_BUDGET_S = float(os.environ.get("STpu_TEST_BUDGET_S", "75"))

#: the hard wall-clock timeout the tier-1 suite runs under (ROADMAP
#: tier-1: ``timeout -k 10 870``); the terminal summary warns loudly
#: when a run crosses 90% of it — the last attributable moment before
#: the whole verify starts zeroing on timeout.
_TIER1_WALL_BUDGET_S = 870.0

_SESSION_T0 = time.monotonic()

#: per-FILE accumulated test seconds (round 15): the 870s timeout is
#: consumed file by file, so the terminal summary prints the top-5
#: files — the margin (and which file to thin next) is visible in
#: every tier-1 log instead of needing a --durations rerun.
_FILE_SECONDS: dict = {}


@pytest.fixture(autouse=True)
def _tier1_per_test_budget(request):
    t0 = time.monotonic()
    yield
    dur = time.monotonic() - t0
    fname = os.path.basename(str(request.node.fspath))
    _FILE_SECONDS[fname] = _FILE_SECONDS.get(fname, 0.0) + dur
    if (_TEST_BUDGET_S > 0 and dur > _TEST_BUDGET_S
            and not request.node.get_closest_marker("slow")):
        pytest.fail(
            f"{request.node.nodeid} ran {dur:.1f}s, over the "
            f"{_TEST_BUDGET_S:.0f}s tier-1 per-test budget: mark it "
            "@pytest.mark.slow or split it (the fast suite runs under "
            "a hard 870s timeout; see ROADMAP tier-1)", pytrace=False)


def pytest_terminal_summary(terminalreporter):
    if not _FILE_SECONDS:
        return
    total = sum(_FILE_SECONDS.values())
    top = sorted(_FILE_SECONDS.items(), key=lambda kv: -kv[1])[:5]
    terminalreporter.write_line(
        f"tier-1 budget: {total:.0f}s of test time measured; "
        "slowest files:")
    for name, sec in top:
        terminalreporter.write_line(
            f"  {sec:7.1f}s  {name} ({100 * sec / max(total, 1e-9):.0f}%)")
    # Wall-clock projection against the tier-1 hard timeout (round 20):
    # wall includes collection/import overhead the per-test accumulator
    # misses, so it is the number the `timeout` wrapper actually kills.
    wall = time.monotonic() - _SESSION_T0
    frac = wall / _TIER1_WALL_BUDGET_S
    terminalreporter.write_line(
        f"tier-1 budget: {wall:.0f}s wall of the "
        f"{_TIER1_WALL_BUDGET_S:.0f}s hard timeout "
        f"({100 * frac:.0f}%)")
    if frac > 0.9:
        terminalreporter.write_line(
            f"*** TIER-1 BUDGET WARNING: {wall:.0f}s wall is over 90% "
            f"of the {_TIER1_WALL_BUDGET_S:.0f}s hard timeout — the "
            "fast suite is one slow test away from zeroing on timeout. "
            "Mark the heaviest tests in the files above "
            "@pytest.mark.slow or split them.", red=True, bold=True)


# The persistent jit cache is NOT enabled for tests. It used to be
# force-enabled on the CPU backend for the ~3x warm-run speedup, on the
# theory that the AOT loader's "could lead to execution errors such as
# SIGILL" warning was cosmetic. It is not cosmetic: cache-deserialized
# XLA:CPU executables mishandle DONATED buffers — runs stayed
# count-correct but the donated visited-table/arena chain read back
# with stale slots, zeros, and heap-pointer garbage (reproduced on the
# seed engine too; ~30-100% of runs once a cached donating dispatch
# program loads). The engines donate everywhere by design, so the
# cache must stay off here; see jit_cache.py.

"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding code
paths compile and execute without TPU hardware — and so test runs don't
serialize on (or hang waiting for) a tunneled TPU chip.

Note: on images where a sitecustomize imports jax at interpreter startup
(e.g. with ``JAX_PLATFORMS`` pointing at a TPU plugin in the ambient
environment), mutating ``os.environ`` here is too late — jax has already
read it. ``jax.config.update("jax_platforms", ...)`` still works as long
as no backend has been initialized, so we use that, plus ``XLA_FLAGS``
(read lazily at CPU-client creation) for the virtual device count.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge._backends, \
    "a JAX backend was initialized before conftest could force CPU"

# Persistent jit cache: this box has one CPU core and the suite's wall
# time is dominated by XLA compiles of the wave programs; warm runs skip
# them. The cache dir is gitignored (machine-local artifact).
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

from stateright_tpu.jit_cache import enable_persistent_jit_cache  # noqa: E402

# Tests force the cache on even on the CPU backend (where it is
# disabled by default over the AOT loader's false SIGILL warning —
# cosmetic here, and warm tests run ~3x faster).
enable_persistent_jit_cache(force=True)

"""The bring-your-own-model walkthrough (examples/sliding_puzzle.py).

Pins SURVEY hard-part 7's deliverable: a user model travels the
documented path host ``Model`` -> ``DeviceModel`` -> ``spawn_tpu_bfs``
with exact parity — the difference between "six ported examples" and a
framework. Full spaces are ``(rows*cols)!/2`` (the half-permutation
invariant): 360 at 2x3.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

from sliding_puzzle import SlidingPuzzle

from tests.test_cli import _run  # shared subprocess CLI runner


def test_host_counts_and_properties():
    model = SlidingPuzzle(2, 3)
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 360  # 6!/2
    assert set(checker.discoveries()) == {"solved"}
    assert checker.discovery("even permutation") is None  # invariant holds


def test_device_parity_2x3():
    """The BYO payoff: the same model on the device engine, exact
    counts and discovery set, solution path replayable."""
    model = SlidingPuzzle(2, 3)
    host = model.checker().spawn_bfs().join()
    dev = model.checker().spawn_tpu_bfs(batch_size=128).join()
    assert dev.unique_state_count() == host.unique_state_count() == 360
    assert set(dev.discoveries()) == {"solved"}
    path = dev.discovery("solved")
    assert path.last_state() == tuple(range(6))
    # Device BFS preserves host level order => shortest solution.
    assert len(path.into_actions()) == len(
        host.discovery("solved").into_actions())


def test_device_parity_3x3_capped():
    """A deeper board, bounded: the device engine explores a prefix of
    the 181,440-state space without incident (full enumeration is the
    CLI demo, not a test)."""
    model = SlidingPuzzle(3, 3)
    dev = (model.checker().target_state_count(20_000)
           .spawn_tpu_bfs(batch_size=512).join())
    assert dev.state_count() >= 20_000
    assert dev.discovery("even permutation") is None


def test_cli_check_tpu():
    stdout = _run("sliding_puzzle.py", "check-tpu", "2", "3")
    assert "unique=360," in stdout, stdout[-500:]

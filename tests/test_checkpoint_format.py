"""The shared checkpoint format module: header round-trip, the
validation errors every engine's reader relies on raising, and the v3
integrity layer (per-section CRC32 + keep-last-2 rotation)."""

import json
import os

import numpy as np
import pytest

from stateright_tpu.checkpoint_format import (CKPT_VERSION, PREV_SUFFIX,
                                              make_header,
                                              validate_header,
                                              verify_file,
                                              verify_sections,
                                              write_atomic)


def _data(**overrides):
    kwargs = dict(model_name="M", state_width=7, state_count=10,
                  unique_count=5, use_symmetry=False,
                  discoveries={"p": 123})
    kwargs.update(overrides)
    return {"header": make_header(**kwargs)}


def test_header_roundtrip():
    header = validate_header(_data(), model_name="M", state_width=7,
                             use_symmetry=False)
    assert header["version"] == CKPT_VERSION
    assert header["state_count"] == 10
    assert header["unique_count"] == 5
    assert header["discoveries"] == {"p": "123"}  # fps stringified


def test_header_rejects_wrong_model():
    with pytest.raises(ValueError, match="model"):
        validate_header(_data(), model_name="Other", state_width=7,
                        use_symmetry=False)


def test_header_rejects_wrong_width():
    with pytest.raises(ValueError, match="state_width"):
        validate_header(_data(), model_name="M", state_width=9,
                        use_symmetry=False)


def test_header_rejects_symmetry_mismatch():
    with pytest.raises(ValueError, match="symmetry"):
        validate_header(_data(), model_name="M", state_width=7,
                        use_symmetry=True)


def test_header_rejects_version_mismatch():
    data = _data()
    header = json.loads(bytes(data["header"].tobytes()).decode())
    header["version"] = 9999
    data["header"] = np.frombuffer(json.dumps(header).encode(), np.uint8)
    with pytest.raises(ValueError, match="version"):
        validate_header(data, model_name="M", state_width=7,
                        use_symmetry=False)


# -- v3 integrity: per-section CRC32 + keep-last-2 rotation ---------------

def _payload(**overrides):
    payload = dict(_data(), visited=np.arange(9, dtype=np.uint64),
                   pending_fps=np.arange(3, dtype=np.uint64))
    payload.update(overrides)
    return payload


def test_write_atomic_records_and_verifies_crcs(tmp_path):
    path = str(tmp_path / "v3.npz")
    write_atomic(path, _payload())
    header = verify_file(path)  # full integrity pass
    assert header["version"] == CKPT_VERSION
    with np.load(path) as data:
        assert "crcs" in data.files
        crcs = json.loads(bytes(data["crcs"].tobytes()).decode())
        assert set(crcs) == {"header", "visited", "pending_fps"}
        validate_header(data, model_name="M", state_width=7,
                        use_symmetry=False)


def test_corrupted_section_rejected_with_clear_message(tmp_path):
    path = str(tmp_path / "bad.npz")
    write_atomic(path, _payload())
    with np.load(path) as data:
        payload = {k: np.array(data[k]) for k in data.files}
    payload["visited"][2] ^= np.uint64(1)  # one flipped bit
    np.savez_compressed(path, **payload)   # keep the original crcs
    with np.load(path) as data:
        with pytest.raises(ValueError, match="CRC32"):
            verify_sections(data)
        with pytest.raises(ValueError, match="CRC32"):
            validate_header(data, model_name="M", state_width=7,
                            use_symmetry=False)
    with pytest.raises(ValueError, match="CRC32"):
        verify_file(path)


def test_torn_file_rejected_with_clear_message(tmp_path):
    path = str(tmp_path / "torn.npz")
    write_atomic(path, _payload())
    with open(path, "r+b") as f:
        f.truncate(50)  # a torn write: truncated zip container
    with pytest.raises(ValueError, match="unreadable"):
        verify_file(path)


def test_pre_v3_snapshot_without_crcs_still_loads():
    # A v1/v2 payload has no crcs section: the integrity check is a
    # documented no-op, not a rejection.
    data = _data()
    header = json.loads(bytes(data["header"].tobytes()).decode())
    header["version"] = 2
    data["header"] = np.frombuffer(json.dumps(header).encode(), np.uint8)
    data["visited"] = np.arange(4, dtype=np.uint64)
    verify_sections(data)
    out = validate_header(data, model_name="M", state_width=7,
                          use_symmetry=False)
    assert out["version"] == 2


def test_keep_last_2_rotation(tmp_path):
    path = str(tmp_path / "rot.npz")
    write_atomic(path, _payload(visited=np.array([1], np.uint64)))
    assert not os.path.exists(path + PREV_SUFFIX)
    write_atomic(path, _payload(visited=np.array([2], np.uint64)))
    write_atomic(path, _payload(visited=np.array([3], np.uint64)))
    # Last two generations on disk, in order.
    with np.load(path) as data:
        assert data["visited"][0] == 3
    with np.load(path + PREV_SUFFIX) as data:
        assert data["visited"][0] == 2
    verify_file(path)
    verify_file(path + PREV_SUFFIX)


def test_torn_current_never_rotates_over_good_prev(tmp_path):
    """Review-driven regression: a KNOWN-TORN current snapshot (left by
    a crashed writer) must not claim the .prev slot on the next write —
    that would destroy the only valid fallback generation."""
    path = str(tmp_path / "rot.npz")
    write_atomic(path, _payload(visited=np.array([1], np.uint64)))
    write_atomic(path, _payload(visited=np.array([2], np.uint64)))
    # gen2 tears (crash mid-write); .prev still holds gen1.
    with open(path, "r+b") as f:
        f.truncate(40)
    write_atomic(path, _payload(visited=np.array([3], np.uint64)))
    with np.load(path) as data:
        assert data["visited"][0] == 3
    with np.load(path + PREV_SUFFIX) as data:
        assert data["visited"][0] == 1, \
            "the torn generation must not have displaced the valid one"

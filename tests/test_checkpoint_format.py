"""The shared checkpoint format module: header round-trip + the
validation errors every engine's reader relies on raising."""

import numpy as np
import pytest

from stateright_tpu.checkpoint_format import (CKPT_VERSION, make_header,
                                              validate_header)


def _data(**overrides):
    kwargs = dict(model_name="M", state_width=7, state_count=10,
                  unique_count=5, use_symmetry=False,
                  discoveries={"p": 123})
    kwargs.update(overrides)
    return {"header": make_header(**kwargs)}


def test_header_roundtrip():
    header = validate_header(_data(), model_name="M", state_width=7,
                             use_symmetry=False)
    assert header["version"] == CKPT_VERSION
    assert header["state_count"] == 10
    assert header["unique_count"] == 5
    assert header["discoveries"] == {"p": "123"}  # fps stringified


def test_header_rejects_wrong_model():
    with pytest.raises(ValueError, match="model"):
        validate_header(_data(), model_name="Other", state_width=7,
                        use_symmetry=False)


def test_header_rejects_wrong_width():
    with pytest.raises(ValueError, match="state_width"):
        validate_header(_data(), model_name="M", state_width=9,
                        use_symmetry=False)


def test_header_rejects_symmetry_mismatch():
    with pytest.raises(ValueError, match="symmetry"):
        validate_header(_data(), model_name="M", state_width=7,
                        use_symmetry=True)


def test_header_rejects_version_mismatch():
    import json

    data = _data()
    header = json.loads(bytes(data["header"].tobytes()).decode())
    header["version"] = 9999
    data["header"] = np.frombuffer(json.dumps(header).encode(), np.uint8)
    with pytest.raises(ValueError, match="version"):
        validate_header(data, model_name="M", state_width=7,
                        use_symmetry=False)

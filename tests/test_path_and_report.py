"""Path reconstruction and report-format tests
(counterpart of checker.rs:416-512 and path.rs:189-225)."""

import io

import pytest

from stateright_tpu import NondeterminismError, Path, fingerprint
from stateright_tpu.test_util import FnModel, LinearEquation


def test_can_build_path_from_fingerprints():
    model = LinearEquation(2, 10, 14)
    fps = [fingerprint((0, 0)), fingerprint((0, 1)),
           fingerprint((1, 1)), fingerprint((2, 1))]
    path = Path.from_fingerprints(model, fps)
    assert path.last_state() == (2, 1)
    assert path.last_state() == Path.final_state(model, fps)


def test_raises_if_unable_to_reconstruct_init_state():
    def fn(prev_state, next_states):
        if prev_state is None:
            next_states.append("UNEXPECTED")

    with pytest.raises(NondeterminismError):
        Path.from_fingerprints(FnModel(fn), [fingerprint("expected")])


def test_raises_if_unable_to_reconstruct_next_state():
    def fn(prev_state, next_states):
        if prev_state is None:
            next_states.append("expected")
        else:
            next_states.append("UNEXPECTED")

    with pytest.raises(NondeterminismError):
        Path.from_fingerprints(
            FnModel(fn), [fingerprint("expected"), fingerprint("expected")])


def test_report_includes_property_names_and_paths():
    """checker.rs:449-511 — exact status lines and discovery summary."""
    # BFS
    w = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_bfs().join().report(w)
    output = w.getvalue()
    assert output.startswith("Done. states=15, unique=12, sec="), output
    assert output.endswith(
        'Discovered "solvable" example Path[3]:\n'
        "- INCREASE_X\n"
        "- INCREASE_X\n"
        "- INCREASE_Y\n"), output

    # DFS
    w = io.StringIO()
    LinearEquation(2, 10, 14).checker().spawn_dfs().join().report(w)
    output = w.getvalue()
    assert output.startswith("Done. states=55, unique=55, sec="), output
    assert output.endswith(
        'Discovered "solvable" example Path[27]:\n'
        + "- INCREASE_Y\n" * 27), output


def test_path_accessors():
    model = LinearEquation(2, 10, 14)
    fps = [fingerprint((0, 0)), fingerprint((1, 0))]
    path = Path.from_fingerprints(model, fps)
    assert len(path) == 2
    assert path.into_states() == [(0, 0), (1, 0)]
    assert len(path.into_actions()) == 1
    assert path.encode() == f"{fingerprint((0, 0))}/{fingerprint((1, 0))}"
    assert path.into_vec()[-1][1] is None


def test_path_from_actions_rejects_bad_input():
    from stateright_tpu.test_util import Guess

    model = LinearEquation(2, 10, 14)
    assert Path.from_actions(model, (5, 5), [Guess.INCREASE_X]) is None
    ok = Path.from_actions(model, (0, 0), [Guess.INCREASE_X])
    assert ok is not None and ok.last_state() == (1, 0)


def test_target_state_count():
    checker = (LinearEquation(2, 4, 7).checker()
               .target_state_count(100).spawn_bfs().join())
    assert checker.state_count() >= 100
    assert not checker.is_done()


def test_target_state_count_multithreaded_join_terminates():
    """Regression: a worker exiting on target_state_count must release
    parked waiters or join() hangs forever (branching factor 1 means work
    is never shared, so one worker stays parked the whole run)."""
    from stateright_tpu import Model, Property

    class Chain(Model):
        def init_states(self):
            return [0]

        def actions(self, s, a):
            a.append("step")

        def next_state(self, s, a):
            return s + 1

        def properties(self):
            return [Property.sometimes("never", lambda m, s: False)]

    checker = (Chain().checker().threads(2)
               .target_state_count(10).spawn_bfs().join())
    assert checker.state_count() >= 10
    assert not checker.is_done()

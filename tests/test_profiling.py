"""Wave-time attribution (`tpu/profiling.py`): the staged timed
dispatches must drive a real frontier and produce a complete breakdown."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

from paxos import PaxosModelCfg

from stateright_tpu.tpu.profiling import measure_wave_breakdown


def test_wave_breakdown_shape_and_progress():
    model = PaxosModelCfg(1, 3).into_model()
    out = measure_wave_breakdown(model, batch_size=128, max_waves=4,
                                 table_capacity=1 << 14)
    assert set(out["stages_sec"]) == {"unpack", "properties", "expand",
                                      "matmul_expand", "fingerprint",
                                      "local_dedup", "dedup_insert",
                                      "compact", "pack", "wave_kernel",
                                      "host"}
    # Paxos is matmul-irregular (sentinel lane domains): the stage is
    # present but unexercised.
    assert out["stages_sec"]["matmul_expand"] == 0.0
    assert out["waves"] >= 1
    assert out["states"] > 0
    assert out["fused_wave_sec"] > 0
    assert out["fused_wave_ladder_sec"] > 0
    # The single-kernel wave is a first-class stage (round 15): timed
    # whenever the VMEM gate admits this config — which this small
    # (128 x F) batch always is on the CPU/interpret path.
    assert out["stages_sec"]["wave_kernel"] > 0
    assert 0.0 <= out["local_dedup_collapse_ratio"] <= 1.0
    assert abs(sum(out["stages_share"].values()) - 1.0) < 0.02

"""Actor-model device-compilation parity tests (ping-pong fixture).

The reference's exact counts (`actor/model.rs:547,629,660`): 14 states at
max_nat=1 lossy; 4,094 at max_nat=5 lossy duplicating; 11 at max_nat=5
with a perfect non-duplicating network. The device engine must reproduce
them and the same property verdicts through the slot-list network
encoding.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import stateright_tpu.actor.actor_test_util as ppmod
from stateright_tpu.actor.actor_test_util import PingPongCfg
from stateright_tpu.tpu.models.pingpong import PingPongDevice


def _device(cfg, **kwargs):
    return PingPongDevice(cfg, ppmod, **kwargs)


def _parity(host_model, dm, batch_size=64, **kwargs):
    host = host_model.checker().spawn_bfs().join()
    tpu = host_model.checker().spawn_tpu_bfs(
        device_model=dm, batch_size=batch_size, **kwargs).join()
    assert tpu.unique_state_count() == host.unique_state_count()
    assert set(tpu.discoveries()) == set(host.discoveries())
    return host, tpu


def test_pingpong_lossy_14():
    cfg = PingPongCfg(maintains_history=False, max_nat=1)
    model = cfg.into_model().with_lossy_network(True)
    host, tpu = _parity(model, _device(cfg, lossy=True))
    assert tpu.unique_state_count() == 14


def test_pingpong_lossy_duplicating_4094():
    cfg = PingPongCfg(maintains_history=False, max_nat=5)
    model = cfg.into_model().with_lossy_network(True)
    host, tpu = _parity(model, _device(cfg, lossy=True), batch_size=256)
    assert tpu.unique_state_count() == 4094
    assert tpu.discovery("delta within 1") is None
    # can lose the first message and get stuck
    assert tpu.discovery("must reach max") is not None


def test_pingpong_perfect_network_11():
    cfg = PingPongCfg(maintains_history=False, max_nat=5)
    model = (cfg.into_model()
             .with_duplicating_network(False).with_lossy_network(False))
    host, tpu = _parity(
        model, _device(cfg, lossy=False, duplicating=False))
    assert tpu.unique_state_count() == 11
    assert tpu.discovery("must reach max") is None
    path = tpu.discovery("must exceed max")
    assert path.last_state().actor_states == [5, 5]


def test_pingpong_history_lanes():
    cfg = PingPongCfg(maintains_history=True, max_nat=3)
    model = cfg.into_model().with_lossy_network(True)
    host, tpu = _parity(model, _device(cfg, lossy=True), batch_size=256)
    assert tpu.discovery("#in <= #out") is None


def test_pingpong_sharded_parity():
    cfg = PingPongCfg(maintains_history=False, max_nat=5)
    model = cfg.into_model().with_lossy_network(True)
    tpu = model.checker().spawn_tpu_bfs(
        device_model=_device(cfg, lossy=True), sharded=True,
        batch_size=64).join()
    assert tpu.unique_state_count() == 4094


@pytest.mark.parametrize("kwargs", [
    {}, {"fused": False}, {"sharded": True},
    {"sharded": True, "fused": False}],
    ids=["fused", "classic", "sharded-fused", "sharded-classic"])
def test_network_overflow_raises(kwargs):
    """The encoding-capacity error lane surfaces as a hard error on
    every engine (a bounded network is a device-encoding artifact; the
    host model has no such bound, so silence would mean missed states)."""
    cfg = PingPongCfg(maintains_history=False, max_nat=5)
    model = cfg.into_model().with_lossy_network(True)
    with pytest.raises(RuntimeError, match="error lane"):
        model.checker().spawn_tpu_bfs(
            device_model=_device(cfg, lossy=True, net_slots=4),
            batch_size=32, **kwargs).join()

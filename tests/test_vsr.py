"""Viewstamped-replication corpus model (round 14): host counts, device
parity, and the differential fuzz gate that admits it into the service
corpus.

The pinned counts come from the host BFS at n=2/max_view=1 (63 unique /
169 generated): small enough for the fast tier including the device
compile, while still reaching a commit, a completed view change, AND a
commit that survives a view change (all three Sometimes witnesses).
The n=3 group (5,531 unique) runs behind ``-m slow``.
"""

import pytest

from stateright_tpu.actor.viewstamped import VsrCfg
from stateright_tpu.service.diff import diff_check, diff_walk, fuzz_gate
from stateright_tpu.tpu.models.vsr import VsrDevice

SOMETIMES = ("can commit", "view change completes",
             "commit survives view change")


def test_vsr_host_counts_and_verdicts():
    model = VsrCfg(n=2, max_view=1).into_model()
    checker = model.checker().spawn_bfs().join()
    assert checker.unique_state_count() == 63
    assert checker.state_count() == 169
    # Agreement never violated; every Sometimes witness reachable —
    # including a commit carried across a view change (the quorum-
    # intersection story the protocol exists for).
    assert set(checker.discoveries()) == set(SOMETIMES)
    checker.assert_properties()


def test_vsr_device_parity_and_walks():
    cfg = VsrCfg(n=2, max_view=1)
    model = cfg.into_model()
    dm = VsrDevice(cfg)
    # Seeded random schedules: per-state successor-set + property
    # agreement between the host semantics and the device step.
    for seed in (0, 1):
        diff_walk(model, dm, seed=seed, steps=12)
    # End-to-end engine parity (counts + verdict sets).
    result = diff_check(model, batch_size=32)
    assert result["device_unique"] == 63
    assert result["device_states"] == 169
    assert result["device_discoveries"] == sorted(SOMETIMES)


def test_vsr_fuzz_gate_admits():
    # The corpus admission gate (walks only here; the engine-parity arm
    # is test_vsr_device_parity_and_walks — no need to compile twice).
    result = fuzz_gate("vsr", params={"n": 2}, seeds=(2,), steps=10,
                       full=False)
    assert result["walks"][0]["transitions"] > 0


@pytest.mark.slow
def test_vsr_three_replicas_parity():
    cfg = VsrCfg(n=3, max_view=1)
    model = cfg.into_model()
    result = diff_check(model, batch_size=256)
    assert result["device_unique"] == 5531
    assert result["device_states"] == 32006
    assert result["device_discoveries"] == sorted(SOMETIMES)


@pytest.mark.slow
def test_vsr_lossy_parity():
    # Drop actions exercise the lossy slot-list path of the actor
    # device layer under the VR message set.
    cfg = VsrCfg(n=2, max_view=1, lossy=True)
    model = cfg.into_model()
    host = model.checker().spawn_bfs().join()
    dev = model.checker().spawn_tpu_bfs(
        device_model=VsrDevice(cfg), batch_size=64).join()
    assert dev.unique_state_count() == host.unique_state_count()
    assert dev.state_count() == host.state_count()
    assert set(dev.discoveries()) == set(host.discoveries())

"""Paxos liveness config (BASELINE.json config 5) + lifted device caps.

- ``PaxosModelCfg(..., liveness=True)`` adds the Eventually "eventually
  chosen" property; on the single-shot-client, perfect-network workload
  the property *holds* (every terminal path passed through a chosen
  value), so the parity pin is "all engines agree: no counterexample,
  full enumeration" — the ebits-clearing path is exercised on every
  state (a bug would surface as a FALSE counterexample). The
  counterexample direction is pinned by the dgraph fixtures
  (`tests/test_eventually.py`) and the native counter-DAG model
  (`tests/test_native_bfs.py`).
- 4 clients now have a device form (widened value/proposal fields,
  2,520-permutation linearizability tables); 5+ fall back to the host
  engine with a warning instead of raising (`check-tpu` at any count).
"""

import os
import sys
import warnings

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import pytest

from paxos import PaxosModelCfg


def test_liveness_parity_1client():
    model = PaxosModelCfg(1, 3, liveness=True).into_model()
    host = model.checker().spawn_bfs().join()
    dev = model.checker().spawn_tpu_bfs(batch_size=128).join()
    assert host.unique_state_count() == dev.unique_state_count() == 265
    assert set(host.discoveries()) == set(dev.discoveries()) \
        == {"value chosen"}
    host.assert_no_discovery("eventually chosen")
    dev.assert_no_discovery("eventually chosen")


@pytest.mark.slow
def test_liveness_parity_2clients_all_engines():
    model = PaxosModelCfg(2, 3, liveness=True).into_model()
    host = model.checker().spawn_bfs().join()
    assert host.unique_state_count() == 16668
    fused = model.checker().spawn_tpu_bfs(batch_size=512).join()
    classic = model.checker().spawn_tpu_bfs(
        batch_size=512, fused=False).join()
    sharded = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=256).join()
    for c in (fused, classic, sharded):
        assert c.unique_state_count() == 16668
        assert set(c.discoveries()) == {"value chosen"}
        c.assert_no_discovery("eventually chosen")
        c.assert_no_discovery("linearizable")


def test_paxos_4clients_device_form_exists():
    """The round-3 cap (<= 3 clients) is lifted: 4 clients encode."""
    model = PaxosModelCfg(4, 3).into_model()
    dm = model.device_model()
    assert dm.state_width == 64  # 24 + 4C + (5C+3) + 1 at C=4
    assert dm.value_bits == 3    # widened from the 2-bit C<=3 layout
    assert dm.native_form() == (0, [4, 0])


@pytest.mark.slow
def test_paxos_4clients_check_tpu_capped():
    """`paxos check 4` runs end to end on the device engine (the
    VERDICT round-4 gate), rate-capped; verdicts match the native
    engine on the same prefix semantics: value chosen found, no
    linearizability counterexample."""
    model = PaxosModelCfg(4, 3).into_model()
    c = model.checker().target_state_count(30000) \
        .spawn_tpu_bfs(batch_size=512).join()
    assert c.state_count() >= 30000
    assert "value chosen" in c.discoveries()
    assert "linearizable" not in c.discoveries()


def test_paxos_5clients_falls_back_to_host():
    model = PaxosModelCfg(5, 3).into_model()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        c = model.checker().target_state_count(2000).spawn_tpu_bfs()
    c.join()
    assert any("falling back" in str(w.message) for w in caught)
    from stateright_tpu.checker.bfs import BfsChecker

    assert isinstance(c, BfsChecker)
    assert c.state_count() >= 2000


def test_paxos_wrong_server_count_falls_back():
    model = PaxosModelCfg(2, 5).into_model()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        c = model.checker().target_state_count(1000).spawn_tpu_bfs()
    c.join()
    assert any("falling back" in str(w.message) for w in caught)

"""Cross-job wave multiplexing (round 16): the differential gate.

The multiplexer's whole contract is that sharing a device wave is
INVISIBLE in every per-job surface — counters, verdicts, discovery
paths, checkpoint bytes. So the tests here are differentials against
solo runs of the same model, plus the queue-policy units (priority,
quota, bounded admission) and the v9 trace-lint attribution window.

The fast tier keeps every run tiny (2pc @ 3 RMs — 288 unique states)
and shares ONE solo reference run across tests; the 8-job soak drill
and the cross-model matrix siblings run behind ``-m slow``.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import trace_lint  # noqa: E402

from stateright_tpu.checkpoint_format import load_checkpoint  # noqa: E402
from stateright_tpu.jit_cache import WaveProgramCache  # noqa: E402
from stateright_tpu.service import (JobQueueFull, JobService,  # noqa: E402
                                    default_registry)
from stateright_tpu.service.jobs import _JobQueue  # noqa: E402
from stateright_tpu.service.mux import MuxGroup  # noqa: E402

#: One corpus shape shared by every fast test: small enough that a
#: full BFS is ~a dozen 32-wide waves, big enough to need several.
KNOBS = {"batch_size": 32, "table_capacity": 1 << 14,
         "checkpoint_every_waves": 1}


@pytest.fixture(scope="module")
def solo_twopc(tmp_path_factory):
    """The solo reference run every differential compares against."""
    d = tmp_path_factory.mktemp("solo")
    ckpt = str(d / "solo.npz")
    model, _ = default_registry().build("twopc", {"rm_count": 3})
    checker = model.checker().spawn_tpu_bfs(
        fused=False, batch_size=32, table_capacity=1 << 14,
        checkpoint_path=ckpt)
    checker.join()
    return {"model": model,
            "states": checker.state_count(),
            "unique": checker.unique_state_count(),
            "discoveries": {k: str(v)
                            for k, v in checker.discoveries().items()},
            "ckpt": ckpt}


def _assert_checkpoint_bytes_equal(path_a, path_b):
    # Per-section byte comparison: npz zip metadata carries timestamps,
    # so whole-file equality would flake; the ARRAYS must match.
    with load_checkpoint(path_a) as a, load_checkpoint(path_b) as b:
        assert sorted(a.files) == sorted(b.files)
        for name in sorted(a.files):
            assert (np.asarray(a[name]).tobytes()
                    == np.asarray(b[name]).tobytes()), name


# -- The differential gate -------------------------------------------------


def test_mux_differential_vs_solo(solo_twopc, tmp_path):
    """Three tenants of one shared-wave group each report exactly the
    solo run's counters, verdicts, and checkpoint bytes — and the
    group trace's per-job attribution sums to its wave totals."""
    cache = WaveProgramCache()
    group_trace = str(tmp_path / "mux.trace.jsonl")
    g = MuxGroup(solo_twopc["model"], knobs=dict(KNOBS),
                 program_cache=cache, program_key=("twopc", 3),
                 trace_path=group_trace)
    ckpts = [str(tmp_path / f"t{i}.npz") for i in range(3)]
    tenant_trace = str(tmp_path / "t0.trace.jsonl")
    handles = [g.admit(f"j-{i}", checkpoint_path=ckpts[i],
                       trace_path=tenant_trace if i == 0 else None)
               for i in range(3)]
    for h in handles:
        h.join()
    g.join(timeout=30)

    for h, ckpt in zip(handles, ckpts):
        assert not h.preempted
        assert h.state_count() == solo_twopc["states"]
        assert h.unique_state_count() == solo_twopc["unique"]
        assert ({k: str(v) for k, v in h.discoveries().items()}
                == solo_twopc["discoveries"])
        _assert_checkpoint_bytes_equal(solo_twopc["ckpt"], ckpt)

    # The group shared ONE compiled program across the three tenants.
    stats = [h.scheduler_stats() for h in handles]
    assert all(s["engine"] == "mux" for s in stats)
    assert sum(s["program_cache"]["hits"] for s in stats) >= 2
    assert max(s["jobs_in_group_high_water"] for s in stats) == 3

    # Group trace: every total's deltas equal the sum of its attributed
    # lines (the lint enforces per-window; here the stream aggregate).
    waves = [json.loads(l) for l in open(group_trace)
             if json.loads(l).get("type") == "wave"]
    totals = [w for w in waves if w["job_id"] is None]
    attr = [w for w in waves if w["job_id"] is not None]
    assert totals and attr
    for field in ("successors", "candidates", "novel"):
        assert (sum(a[field] for a in attr)
                == sum(t[field] for t in totals))
    for path in (group_trace, tenant_trace):
        counts, errors = trace_lint.lint_file(path)
        assert not errors, errors[:3]
        assert counts.get("wave", 0) > 0


def test_mux_preempt_resume_differential(solo_twopc, tmp_path):
    """Preempting ONE tenant at a wave boundary neither disturbs its
    co-scheduled job nor loses work: the resumed run finishes with
    solo-identical counters and checkpoint bytes."""
    cache = WaveProgramCache()
    g = MuxGroup(solo_twopc["model"], knobs=dict(KNOBS),
                 program_cache=cache, program_key=("twopc", 3))
    c0 = str(tmp_path / "t0.npz")
    h0 = g.admit("j-0", checkpoint_path=c0)
    h1 = g.admit("j-1", checkpoint_path=str(tmp_path / "t1.npz"))
    h0.preempt()  # lands at the next wave boundary
    h0.join()
    h1.join()
    g.join(timeout=30)

    # The co-tenant never noticed.
    assert not h1.preempted
    assert h1.state_count() == solo_twopc["states"]
    assert h1.unique_state_count() == solo_twopc["unique"]

    if not h0.preempted:
        # A fast box can drain j-0 before the flag lands — then the
        # run is simply done and must already match solo.
        assert h0.state_count() == solo_twopc["states"]
        return
    # Resume from the drained tenant's checkpoint generation, in a
    # FRESH group (the service does exactly this on resubmission).
    g2 = MuxGroup(solo_twopc["model"], knobs=dict(KNOBS),
                  program_cache=cache, program_key=("twopc", 3))
    h0r = g2.admit("j-0r", checkpoint_path=c0, resume_from=c0)
    h0r.join()
    g2.join(timeout=30)
    assert h0r.state_count() == solo_twopc["states"]
    assert h0r.unique_state_count() == solo_twopc["unique"]
    assert ({k: str(v) for k, v in h0r.discoveries().items()}
            == solo_twopc["discoveries"])
    _assert_checkpoint_bytes_equal(solo_twopc["ckpt"], c0)
    # The resumed admission re-used the already-built shared program.
    assert h0r.scheduler_stats()["program_cache"]["hits"] >= 1


# -- Queue policy ----------------------------------------------------------


def test_queue_priority_quota_and_bounds():
    # Priority: higher first, FIFO within a priority band.
    q = _JobQueue()
    for job, prio in (("a", 0), ("b", 5), ("c", 5), ("d", 1)):
        q.put(job, priority=prio)
    order = [q.pop()[0] for _ in range(4)]
    assert order == ["b", "c", "d", "a"]

    # Quota: a tenant at its running cap is SKIPPED, not starved.
    q = _JobQueue(tenant_quota=1)
    q.put("x", tenant="t")
    q.put("y", tenant="t")
    q.put("z", tenant="u")
    assert q.pop() == ("x", "t")
    assert q.pop() == ("z", "u")  # y skipped: t is at quota
    q.task_done("t")
    assert q.pop() == ("y", "t")

    # Bounded admission: overflow raises, cancel frees the slot.
    q = _JobQueue(max_queued=2)
    q.put("p")
    q.put("q")
    with pytest.raises(JobQueueFull):
        q.put("r")
    assert q.cancel("p")
    assert not q.cancel("p")  # already gone
    q.put("r")
    assert q.qsize() == 2


def test_service_admission_control_and_cancel(tmp_path):
    """Bounded-queue 429 semantics and DELETE-on-queued at the service
    layer, deterministically: the sole tenant is pinned at quota so
    its submissions can never be popped."""
    svc = JobService(workers=1, data_dir=str(tmp_path / "svc"),
                     max_queued=1, tenant_quota=1)
    try:
        # Pin tenant "t" at its running quota: queued jobs stay put.
        with svc._queue._cv:
            svc._queue._active["t"] = 1
        spec = {"model": "twopc", "knobs": {"batch_size": 32},
                "tenant": "t", "priority": 3}
        j1 = svc.submit(spec)
        assert svc.status(j1["id"])["state"] == "queued"
        assert svc.status(j1["id"])["priority"] == 3
        assert svc.status(j1["id"])["tenant"] == "t"

        # Queue full: the overflow is rejected AND leaves no record.
        with pytest.raises(JobQueueFull):
            svc.submit(spec)
        assert [p["id"] for p in svc.jobs()] == [j1["id"]]

        # DELETE on a queued job cancels it outright (nothing ran, so
        # nothing to resume) and frees the queue slot.
        out = svc.preempt(j1["id"])
        assert out["state"] == "cancelled"
        j2 = svc.submit(spec)
        assert svc.status(j2["id"])["state"] == "queued"

        # The cancelled job's trace pairs its submit with the abort.
        events = [json.loads(l)
                  for l in open(svc.trace_file(j1["id"]))]
        aborts = [e for e in events if e.get("type") == "job_abort"]
        assert aborts and aborts[0]["reason"] == "cancelled"
        _, errors = trace_lint.lint_file(svc.trace_file(j1["id"]))
        assert not errors, errors[:3]
    finally:
        svc.close()


def test_http_429_on_full_queue(tmp_path):
    from stateright_tpu.explorer import serve_service

    import service_client as sc

    # max_queued=0: every submission overflows — the HTTP mapping is
    # what's under test, not the queue.
    service, server = serve_service(
        addresses=("127.0.0.1", 0), block=False, workers=1,
        data_dir=str(tmp_path), max_queued=0)
    host, port = server.server_address[:2]
    try:
        # Round 21: a 429 is an admission DECISION the client handles,
        # not an exception — submit returns the shed payload.
        payload = sc.submit(f"http://{host}:{port}",
                            {"model": "twopc",
                             "knobs": {"batch_size": 32}})
        assert payload.get("shed") is True
        assert "full" in payload["error"]
    finally:
        server.shutdown()
        server.server_close()
        service.close()


# -- The v9 lint window ----------------------------------------------------


def _wave_line(run, wave, *, job_id=None, jobs_in_wave=None, succ=10,
               cand=8, novel=4, states=100, unique=50):
    return json.dumps({
        "type": "wave", "schema_version": 9, "engine": "mux",
        "run": run, "wave": wave, "t": 1.0 + wave, "states": states,
        "unique": unique, "bucket": 32, "waves": 1, "inflight": 0,
        "compiled": False, "successors": succ, "candidates": cand,
        "novel": novel, "out_rows": 64, "capacity": 1024,
        "load_factor": 0.1, "overflow": False, "bytes_per_state": 28,
        "arena_bytes": None, "table_bytes": 8192, "worker": None,
        "seq": None, "epoch": None, "round": None,
        "tier_device_rows": None, "tier_device_bytes": None,
        "tier_host_rows": None, "tier_host_bytes": None,
        "tier_disk_rows": None, "tier_disk_bytes": None,
        "kernel_path": "xla", "rows": 8, "job_id": job_id,
        "jobs_in_wave": jobs_in_wave})


def test_trace_lint_mux_attribution_window():
    """The v9 stream invariant, schema-level: a mux TOTAL wave must be
    followed by exactly ``jobs_in_wave`` attributed lines whose deltas
    sum to the total's, before anything else happens to the run."""
    # A correct window: total, then its two attributed lines.
    good = [_wave_line("r0", 0, jobs_in_wave=2, succ=10, cand=8,
                       novel=4),
            _wave_line("r0", 1, job_id="j-1", jobs_in_wave=2, succ=6,
                       cand=5, novel=3),
            _wave_line("r0", 2, job_id="j-2", jobs_in_wave=2, succ=4,
                       cand=3, novel=1)]
    _, errors = trace_lint.lint_lines(good)
    assert not errors, errors

    # A per-JOB trace file: attributed lines with no window are fine.
    _, errors = trace_lint.lint_lines(
        [_wave_line("t0", 0, job_id="j-1", jobs_in_wave=2),
         _wave_line("t0", 1, job_id="j-1", jobs_in_wave=2,
                    states=110, unique=55)])
    assert not errors, errors

    # Short attribution at end-of-stream.
    _, errors = trace_lint.lint_lines(good[:2])
    assert len(errors) == 1 and "never followed" in errors[0]

    # Short attribution cut off by run_end.
    run_end = json.dumps({"type": "run_end", "schema_version": 9,
                          "engine": "mux", "run": "r0", "t": 9.0,
                          "dur": 1.0, "counters": {}})
    _, errors = trace_lint.lint_lines(good[:2] + [run_end])
    assert len(errors) == 1 and "still awaiting" in errors[0]

    # A new total while the previous window is open.
    _, errors = trace_lint.lint_lines(
        [good[0],
         _wave_line("r0", 1, jobs_in_wave=2, succ=10, cand=8, novel=4),
         good[1].replace('"wave": 1', '"wave": 2'),
         good[2].replace('"wave": 2', '"wave": 3')])
    assert any("still awaits" in e for e in errors)

    # jobs_in_wave disagreement between a total and its attribution.
    _, errors = trace_lint.lint_lines(
        [good[0],
         _wave_line("r0", 1, job_id="j-1", jobs_in_wave=3, succ=6,
                    cand=5, novel=3),
         good[2]])
    assert len(errors) == 1 and "jobs_in_wave=3" in errors[0]

    # Deltas that don't sum to the total: fabricated accounting.
    _, errors = trace_lint.lint_lines(
        [good[0],
         _wave_line("r0", 1, job_id="j-1", jobs_in_wave=2, succ=3,
                    cand=5, novel=3),
         good[2]])
    assert len(errors) == 1 and "successors" in errors[0]

    # A solo wave inside an open window.
    _, errors = trace_lint.lint_lines(
        [good[0], _wave_line("r0", 1)])
    assert any("solo wave inside" in e for e in errors)


# -- Slow arms: the soak drill and the matrix siblings ---------------------


@pytest.mark.slow
def test_mux_soak_drill(tmp_path):
    """Eight concurrent same-shape jobs through the SERVICE, mux on vs
    off: identical per-job counters either way (the bench soak arm
    measures the throughput side of this same drill)."""
    spec = {"model": "twopc", "knobs": {"batch_size": 32}}
    results = {}
    for mux in (True, False):
        svc = JobService(workers=8, data_dir=str(tmp_path / f"m{mux}"),
                         mux=mux)
        try:
            ids = [svc.submit(spec)["id"] for _ in range(8)]
            deadline = time.monotonic() + 420
            while time.monotonic() < deadline:
                states = [svc.status(i)["state"] for i in ids]
                if all(s in ("done", "failed", "preempted")
                       for s in states):
                    break
                time.sleep(0.1)
            payloads = [svc.status(i) for i in ids]
            assert all(p["state"] == "done" for p in payloads), \
                [(p["id"], p["state"], p["error"]) for p in payloads]
            results[mux] = [(p["states"], p["unique"])
                            for p in payloads]
            assert all(p["jit_cache"]["shared"] for p in payloads)
            assert sum(p["jit_cache"]["hits"] for p in payloads) > 0
        finally:
            svc.close()
    assert results[True] == results[False]
    assert all(c == (1146, 288) for c in results[True])


@pytest.mark.slow
def test_mux_matrix_siblings():
    """The differential holds beyond 2pc: two tenants per group across
    other corpus shapes report solo-identical counters."""
    for name, params in (("pingpong", None), ("vsr", {"n": 2}),
                         ("increment_lock", None)):
        model, _ = default_registry().build(name, params)
        solo = model.checker().spawn_tpu_bfs(
            fused=False, batch_size=32, table_capacity=1 << 14)
        solo.join()
        g = MuxGroup(model, knobs={"batch_size": 32,
                                   "table_capacity": 1 << 14},
                     program_cache=WaveProgramCache(),
                     program_key=(name,))
        handles = [g.admit(f"{name}-{i}") for i in range(2)]
        for h in handles:
            h.join()
        g.join(timeout=60)
        for h in handles:
            assert h.state_count() == solo.state_count(), name
            assert h.unique_state_count() == \
                solo.unique_state_count(), name
            assert sorted(h.discoveries()) == \
                sorted(solo.discoveries()), name


@pytest.mark.slow
def test_mux_early_stop_equals_solo_at_effective_width():
    """The identity-scope boundary, pinned exactly: a run that stops
    EARLY (every property discovered before exhaustion) halts at a
    wave boundary, and the boundary's position depends on rows per
    wave — already true solo (batch 16 vs 32 stop at different
    counts). Two co-tenants splitting a 32-row wave see 16 rows each,
    so they match a SOLO batch-16 run bit-for-bit; exhaustive runs
    (every other differential here) are width-invariant and match
    solo at any batch size."""
    model, _ = default_registry().build("increment", None)
    solo16 = model.checker().spawn_tpu_bfs(
        fused=False, batch_size=16, table_capacity=1 << 14)
    solo16.join()
    assert solo16.discoveries()  # it DOES early-stop ('fin' violated)
    g = MuxGroup(model, knobs={"batch_size": 32,
                               "table_capacity": 1 << 14},
                 program_cache=WaveProgramCache(),
                 program_key=("increment",))
    handles = [g.admit(f"i-{i}") for i in range(2)]
    for h in handles:
        h.join()
    g.join(timeout=60)
    for h in handles:
        assert h.state_count() == solo16.state_count()
        assert h.unique_state_count() == solo16.unique_state_count()

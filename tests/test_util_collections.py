"""VectorClock + DenseNatMap behavior (counterparts of the reference's
`vector_clock.rs:108-273` and `densenatmap.rs:231-322` test suites)."""

import pytest

from stateright_tpu.actor import Id
from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.symmetry import RewritePlan
from stateright_tpu.util import DenseNatMap, VectorClock


# -- VectorClock ---------------------------------------------------------

def test_clock_can_display():
    assert str(VectorClock([1, 2, 3, 4])) == "<1, 2, 3, 4, ...>"
    # Equal clocks don't necessarily display the same.
    assert str(VectorClock()) == "<...>"
    assert str(VectorClock([0])) == "<0, ...>"


def test_clock_can_equate_ignoring_padding():
    assert VectorClock() == VectorClock([0, 0, 0])
    assert VectorClock([1, 2]) == VectorClock([1, 2, 0])
    assert VectorClock([1, 2]) != VectorClock([1, 2, 3])
    assert VectorClock([0, 1]) != VectorClock([1])


def test_clock_hash_and_fingerprint_ignore_padding():
    assert hash(VectorClock([1, 2])) == hash(VectorClock([1, 2, 0, 0]))
    assert fingerprint(VectorClock([1, 2])) == \
        fingerprint(VectorClock([1, 2, 0, 0]))
    assert fingerprint(VectorClock()) == fingerprint(VectorClock([0]))
    assert fingerprint(VectorClock([1])) != fingerprint(VectorClock([0, 1]))


def test_clock_can_increment():
    assert VectorClock().incremented(2) == VectorClock([0, 0, 1])
    assert VectorClock([1, 2]).incremented(0) == VectorClock([2, 2])
    # incremented is functional: the original is unchanged
    c = VectorClock([1])
    assert c.incremented(0) == VectorClock([2])
    assert c == VectorClock([1])


def test_clock_can_merge():
    assert VectorClock.merge_max(
        VectorClock([1, 0, 3]), VectorClock([0, 2])) == \
        VectorClock([1, 2, 3])
    assert VectorClock.merge_max(VectorClock(), VectorClock()) == \
        VectorClock()


def test_clock_partial_order():
    assert VectorClock([1, 2]).partial_cmp(VectorClock([1, 2, 0])) == 0
    assert VectorClock([1, 2]) <= VectorClock([1, 2])
    assert VectorClock([1, 2]) < VectorClock([1, 3])
    assert VectorClock([1, 2]) < VectorClock([2, 2, 1])
    assert VectorClock([2, 0]) > VectorClock([1])
    # Concurrent clocks are incomparable in every direction.
    a, b = VectorClock([1, 0, 2]), VectorClock([0, 1, 2])
    assert a.partial_cmp(b) is None
    assert not a < b and not a <= b and not a > b and not a >= b


def test_clock_rejects_negative():
    with pytest.raises(ValueError):
        VectorClock([-1])


# -- DenseNatMap ---------------------------------------------------------

def test_densenatmap_insert_in_order_or_overwrite():
    m = DenseNatMap(key=Id)
    assert m.insert(Id(0), "first") is None
    assert m.insert(Id(1), "second") is None
    assert m.insert(Id(0), "FIRST") == "first"  # overwrite returns previous
    assert m.values() == ["FIRST", "second"]
    with pytest.raises(IndexError):
        m.insert(Id(5), "sparse")


def test_densenatmap_from_pairs_any_order():
    m = DenseNatMap.from_pairs(
        [(Id(1), "second"), (Id(0), "first")], key=Id)
    assert m.values() == ["first", "second"]
    assert m[Id(1)] == "second"
    assert m.get(Id(7)) is None
    with pytest.raises(ValueError):
        DenseNatMap.from_pairs([(Id(0), "a"), (Id(2), "c")])


def test_densenatmap_iteration_yields_typed_keys():
    m = DenseNatMap(["a", "b"], key=Id)
    assert list(m) == [(Id(0), "a"), (Id(1), "b")]
    assert all(type(k) is Id for k, _ in m.items())
    assert len(m) == 2


def test_densenatmap_identity():
    assert DenseNatMap(["a", "b"]) == DenseNatMap(["a", "b"])
    assert DenseNatMap(["a"]) != DenseNatMap(["a", "b"])
    assert fingerprint(DenseNatMap(["a", "b"])) == \
        fingerprint(DenseNatMap(["a", "b"]))
    assert fingerprint(DenseNatMap(["a"])) != fingerprint(DenseNatMap(["b"]))


def test_densenatmap_symmetry_rewrite():
    # Plan that sorts the values ["b", "a"] -> swap indices 0 and 1; the
    # map's keys reindex and embedded Ids in values rewrite.
    plan = RewritePlan.from_values_to_sort(["b", "a"])
    m = DenseNatMap([Id(0), Id(1)], key=Id)
    rewritten = m.__rewrite__(plan)
    # key 0 -> 1 and value Id(0) -> Id(1) (and vice versa): the map is
    # permuted AND its embedded ids remapped.
    assert rewritten.values() == [Id(0), Id(1)]
    m2 = DenseNatMap(["x", "y"], key=Id)
    assert m2.__rewrite__(plan).values() == ["y", "x"]

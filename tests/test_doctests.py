"""Runs the public API's doc examples (the reference's doc-test layer,
SURVEY §4: `lib.rs:40-116`, `vector_clock.rs` etc. run under rustdoc)."""

import doctest

import stateright_tpu.model
import stateright_tpu.util


def _run(module):
    results = doctest.testmod(module, verbose=False)
    assert results.attempted > 0, f"no doctests found in {module.__name__}"
    assert results.failed == 0


def test_model_doc_examples():
    _run(stateright_tpu.model)


def test_util_doc_examples():
    _run(stateright_tpu.util)

"""Checkpoint/resume for the device engines.

The reference has no checkpointing (`checker state is purely in-memory`,
a killed run restarts from scratch); here the (visited fingerprints,
frontier blocks, discoveries, parent map) tuple is written at safe
points and a fresh checker resumes from it — on either engine, since
the snapshot is table-layout-agnostic.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

import pytest

from two_phase_commit import TwoPhaseSys


def _full_run(model):
    return model.checker().spawn_bfs().join()


def test_checkpoint_and_resume_completes_identically(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "2pc.ckpt.npz")

    # Stop partway (target_state_count), snapshot at exit.
    partial = model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    assert os.path.exists(ckpt)
    assert partial.unique_state_count() < full.unique_state_count()

    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())
    # Discovery paths replay through the host model (parent map survived).
    for name, path in resumed.discoveries().items():
        assert path.last_state() is not None


def test_periodic_checkpoint_midrun_is_resumable(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "mid.ckpt.npz")
    # Snapshot every wave; tiny batches force many waves, so the file is
    # written well before the run completes and then repeatedly replaced.
    model.checker().spawn_tpu_bfs(
        batch_size=16, checkpoint_path=ckpt,
        checkpoint_every_waves=1).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_cross_engine_resume_single_to_sharded(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "cross.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=32, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_cross_engine_resume_sharded_to_single(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "cross2.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        sharded=True, batch_size=16, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_checkpoint_while_running_raises(tmp_path):
    model = TwoPhaseSys(3)
    checker = model.checker().spawn_tpu_bfs(batch_size=16)
    # Race-free: either the guard fires (still running) or the call
    # succeeds because the run genuinely finished first.
    try:
        checker.checkpoint(str(tmp_path / "racy.npz"))
        assert checker.is_done()
    except RuntimeError:
        pass
    checker.join()
    # After join it's a safe point.
    checker.checkpoint(str(tmp_path / "done.npz"))
    assert os.path.exists(tmp_path / "done.npz")


def test_resume_rejects_mismatched_model(tmp_path):
    ckpt = str(tmp_path / "m.ckpt.npz")
    TwoPhaseSys(4).checker().target_state_count(200).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    with pytest.raises(ValueError, match="state_width"):
        TwoPhaseSys(5).checker().spawn_tpu_bfs(resume_from=ckpt)

def test_pipelined_early_exit_checkpoint_is_not_torn(tmp_path):
    """With pipelining forced on, hitting target_state_count while a wave
    is in flight must drain it before the final snapshot — otherwise the
    abandoned wave's states sit in the visited table with their subtrees
    permanently lost on resume."""
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "pipe.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, fused=False, pipeline=True,
        checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


# -- Native (C++) engine interop ----------------------------------------

def _paxos2():
    from paxos import PaxosModelCfg

    return PaxosModelCfg(2, 3).into_model()


def test_native_checkpoint_resume_native(tmp_path):
    """Capped native run -> snapshot -> native resume completes with the
    exact full-run counts (every (state, action) edge generated once
    across the boundary)."""
    model = _paxos2()
    ckpt = str(tmp_path / "native.ckpt.npz")
    partial = model.checker().target_state_count(8000) \
        .spawn_native_bfs(model.device_model()).join()
    assert not partial.is_done()
    partial.checkpoint(ckpt)
    resumed = model.checker().spawn_native_bfs(
        model.device_model(), resume_from=ckpt).join()
    assert resumed.unique_state_count() == 16668
    assert resumed.state_count() == 32971  # == an uncapped run's total
    assert set(resumed.discoveries()) == {"value chosen"}
    # Paths reconstruct across the resume boundary (parent map merged).
    path = resumed.discoveries()["value chosen"]
    prop = model.property("value chosen")
    assert prop.condition(model, path.last_state())


def test_cross_engine_resume_native_to_fused(tmp_path):
    model = _paxos2()
    ckpt = str(tmp_path / "n2f.ckpt.npz")
    model.checker().target_state_count(8000) \
        .spawn_native_bfs(model.device_model()).join().checkpoint(ckpt)
    fused = model.checker().spawn_tpu_bfs(batch_size=256,
                                          resume_from=ckpt)
    fused.join()
    assert fused.unique_state_count() == 16668
    assert set(fused.discoveries()) == {"value chosen"}


def test_cross_engine_resume_fused_to_native(tmp_path):
    model = _paxos2()
    ckpt = str(tmp_path / "f2n.ckpt.npz")
    g = model.checker().target_state_count(6000).spawn_tpu_bfs(
        batch_size=256)
    g.join()
    g.checkpoint(ckpt)
    resumed = model.checker().spawn_native_bfs(
        model.device_model(), resume_from=ckpt).join()
    assert resumed.unique_state_count() == 16668
    assert set(resumed.discoveries()) == {"value chosen"}


def test_native_checkpoint_while_running_raises(tmp_path):
    from paxos import PaxosModelCfg

    big = PaxosModelCfg(3, 3).into_model()
    c = big.checker().spawn_native_bfs(big.device_model())
    try:
        if not c._thread.is_alive():  # pragma: no cover — timing guard
            pytest.skip("run finished before the race could be exercised")
        with pytest.raises(RuntimeError, match="running"):
            c.checkpoint(str(tmp_path / "never-written.npz"))
    finally:
        c.stop()
        c.join()


def test_native_resume_rejects_mismatched_model(tmp_path):
    model = _paxos2()
    ckpt = str(tmp_path / "sc.ckpt.npz")
    from single_copy_register import SingleCopyModelCfg

    sc = SingleCopyModelCfg(client_count=2, server_count=1).into_model()
    c = sc.checker().spawn_native_bfs(sc.device_model()).join()
    c.checkpoint(ckpt)
    with pytest.raises(ValueError, match="model"):
        model.checker().spawn_native_bfs(model.device_model(),
                                         resume_from=ckpt)


def test_native_multithreaded_capped_checkpoint_resume(tmp_path):
    """Eight workers hit the cap, park their frontiers, snapshot, and a
    resumed run still completes to the exact full-space counts — the
    parked-frontier paths under real thread interleaving."""
    model = _paxos2()
    ckpt = str(tmp_path / "mt.ckpt.npz")
    partial = model.checker().threads(8).target_state_count(8000) \
        .spawn_native_bfs(model.device_model()).join()
    assert not partial.is_done()
    # The cap is approximate (in-flight blocks finish), but it must have
    # actually stopped the run well short of the 32,971-state full space
    # — if parked jobs were re-popped past the cap, workers would run to
    # completion.
    assert 8000 <= partial.state_count() < 32971
    partial.checkpoint(ckpt)
    resumed = model.checker().threads(8).spawn_native_bfs(
        model.device_model(), resume_from=ckpt).join()
    assert resumed.unique_state_count() == 16668
    assert resumed.state_count() == 32971
    assert set(resumed.discoveries()) == {"value chosen"}

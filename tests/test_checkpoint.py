"""Checkpoint/resume for the device engines.

The reference has no checkpointing (`checker state is purely in-memory`,
a killed run restarts from scratch); here the (visited fingerprints,
frontier blocks, discoveries, parent map) tuple is written at safe
points and a fresh checker resumes from it — on either engine, since
the snapshot is table-layout-agnostic.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

import pytest

from two_phase_commit import TwoPhaseSys


def _full_run(model):
    return model.checker().spawn_bfs().join()


def test_checkpoint_and_resume_completes_identically(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "2pc.ckpt.npz")

    # Stop partway (target_state_count), snapshot at exit.
    partial = model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    assert os.path.exists(ckpt)
    assert partial.unique_state_count() < full.unique_state_count()

    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())
    # Discovery paths replay through the host model (parent map survived).
    for name, path in resumed.discoveries().items():
        assert path.last_state() is not None


def test_periodic_checkpoint_midrun_is_resumable(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "mid.ckpt.npz")
    # Snapshot every wave; tiny batches force many waves, so the file is
    # written well before the run completes and then repeatedly replaced.
    model.checker().spawn_tpu_bfs(
        batch_size=16, checkpoint_path=ckpt,
        checkpoint_every_waves=1).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_cross_engine_resume_single_to_sharded(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "cross.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=32, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_cross_engine_resume_sharded_to_single(tmp_path):
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "cross2.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        sharded=True, batch_size=16, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_checkpoint_while_running_raises(tmp_path):
    model = TwoPhaseSys(3)
    checker = model.checker().spawn_tpu_bfs(batch_size=16)
    # Race-free: either the guard fires (still running) or the call
    # succeeds because the run genuinely finished first.
    try:
        checker.checkpoint(str(tmp_path / "racy.npz"))
        assert checker.is_done()
    except RuntimeError:
        pass
    checker.join()
    # After join it's a safe point.
    checker.checkpoint(str(tmp_path / "done.npz"))
    assert os.path.exists(tmp_path / "done.npz")


def test_resume_rejects_mismatched_model(tmp_path):
    ckpt = str(tmp_path / "m.ckpt.npz")
    TwoPhaseSys(4).checker().target_state_count(200).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt).join()
    with pytest.raises(ValueError, match="state_width"):
        TwoPhaseSys(5).checker().spawn_tpu_bfs(resume_from=ckpt)

def test_pipelined_early_exit_checkpoint_is_not_torn(tmp_path):
    """With pipelining forced on, hitting target_state_count while a wave
    is in flight must drain it before the final snapshot — otherwise the
    abandoned wave's states sit in the visited table with their subtrees
    permanently lost on resume."""
    model = TwoPhaseSys(4)
    full = _full_run(model)
    ckpt = str(tmp_path / "pipe.ckpt.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, fused=False, pipeline=True,
        checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())

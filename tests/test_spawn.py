"""UDP actor runtime tests (`src/actor/spawn.rs:185-205` codec tests,
plus end-to-end loopback runs of checked actors — the "run what you
check" capability the reference exercises manually via netcat,
`paxos.rs:350-370`)."""

import json
import socket
import time

import pytest

from stateright_tpu.actor import Actor, Id, Out
from stateright_tpu.actor.register import Get, GetOk, Internal, Put, PutOk
from stateright_tpu.actor.spawn import (
    json_serialize, make_json_deserializer, spawn_json)


def test_can_encode_id():
    # `spawn.rs:185-195`: bytes 2-5 = IP, 6-7 = port.
    id = Id.from_addr("1.2.3.4", 5)
    assert int(id).to_bytes(8, "big") == bytes([0, 0, 1, 2, 3, 4, 0, 5])


def test_can_decode_id():
    addr = ("1.2.3.4", 5)
    assert Id.from_addr(*addr).to_addr() == addr


def test_json_codec_round_trip():
    # serde-style variant encoding: {"Name": fields}, unit variants as
    # bare strings, JSON arrays -> tuples.
    decode = make_json_deserializer([Internal, Put, Get, PutOk, GetOk])
    for msg in [Put(7, "X"), Get(3), PutOk(7), GetOk(3, "X"),
                Internal(Put(1, "Y"))]:
        assert decode(json_serialize(msg)) == msg
    assert json.loads(json_serialize(Put(7, "X"))) == {"Put": [7, "X"]}
    assert json.loads(json_serialize(Get(3))) == {"Get": 3}


def test_json_codec_rejects_unknown():
    decode = make_json_deserializer([Put])
    with pytest.raises(ValueError):
        decode(b'{"Nope": 1}')


class _Echo(Actor):
    """Replies to any Put with PutOk, counting messages."""

    def on_start(self, id, o):
        return 0

    def on_msg(self, id, state, src, msg, o: Out):
        if type(msg) is Put:
            o.send(src, PutOk(msg.request_id))
            return state + 1
        return None


def _free_udp_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _request(sock, addr, payload: bytes, timeout=5.0):
    sock.settimeout(timeout)
    sock.sendto(payload, addr)
    data, _ = sock.recvfrom(65_535)
    return json.loads(data.decode())


@pytest.mark.parametrize("native", [True, False],
                         ids=["native-reactor", "thread-per-actor"])
def test_udp_round_trip(native):
    from stateright_tpu.native.reactor import REACTOR_AVAILABLE

    if native and not REACTOR_AVAILABLE:
        pytest.skip("native reactor unavailable on this machine")
    port = _free_udp_port()
    actor_id = Id.from_addr("127.0.0.1", port)
    runtime = spawn_json([(actor_id, _Echo())], block=False,
                         native=native)
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.bind(("127.0.0.1", 0))
            # Netcat-style raw JSON in, JSON out.
            reply = _request(sock, ("127.0.0.1", port), b'{"Put": [42, "v"]}')
            assert reply == {"PutOk": 42}
            # Malformed datagrams are ignored, the actor stays up.
            sock.sendto(b"not json", ("127.0.0.1", port))
            reply = _request(sock, ("127.0.0.1", port), b'{"Put": [43, "w"]}')
            assert reply == {"PutOk": 43}
    finally:
        runtime.stop()


@pytest.mark.parametrize("native", [True, False],
                         ids=["native-reactor", "thread-per-actor"])
def test_timers_fire_and_cancel(native):
    from stateright_tpu.native.reactor import REACTOR_AVAILABLE

    if native and not REACTOR_AVAILABLE:
        pytest.skip("native reactor unavailable on this machine")

    class _Beacon(Actor):
        """Pings ``target`` on a short timer; cancels after the first."""

        def __init__(self, target, cancel_immediately=False):
            self.target = target
            self.cancel_immediately = cancel_immediately

        def on_start(self, id, o: Out):
            o.set_timer((0.05, 0.05))
            if self.cancel_immediately:
                o.cancel_timer()
            return 0

        def on_timeout(self, id, state, o: Out):
            o.send(self.target, Put(state, "tick"))
            o.set_timer((0.05, 0.05))
            return state + 1

    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
        sock.bind(("127.0.0.1", 0))
        target = Id.from_addr("127.0.0.1", sock.getsockname()[1])
        fires = Id.from_addr("127.0.0.1", _free_udp_port())
        quiet = Id.from_addr("127.0.0.1", _free_udp_port())
        runtime = spawn_json(
            [(fires, _Beacon(target)),
             (quiet, _Beacon(target, cancel_immediately=True))],
            block=False, native=native)
        try:
            sock.settimeout(5.0)
            data, src = sock.recvfrom(65_535)
            # Only the un-cancelled beacon ever fires.
            assert src[1] == fires.to_addr()[1]
            assert json.loads(data.decode()) == {"Put": [0, "tick"]}
            data, _ = sock.recvfrom(65_535)  # timer re-arms
            assert json.loads(data.decode()) == {"Put": [1, "tick"]}
        finally:
            runtime.stop()


def test_spawned_paxos_answers_put_get():
    # The dual-execution headline: the SAME PaxosActor code that the
    # checker verifies (16,668 states) deployed on loopback UDP answers a
    # client Put then Get (`README.md:100-105`, `paxos.rs:350-370`).
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
    from paxos import Accept, Accepted, Decided, PaxosActor, Prepare, Prepared

    ports = [_free_udp_port() for _ in range(3)]
    ids = [Id.from_addr("127.0.0.1", p) for p in ports]
    runtime = spawn_json(
        [(ids[i], PaxosActor([ids[j] for j in range(3) if j != i]))
         for i in range(3)],
        msg_types=[Prepare, Prepared, Accept, Accepted, Decided],
        block=False)
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.bind(("127.0.0.1", 0))
            server = ("127.0.0.1", ports[0])
            sock.sendto(b'{"Put": [0, "X"]}', server)
            # Paxos answers the Put once a quorum accepts + decides.
            deadline = time.monotonic() + 10
            reply = None
            sock.settimeout(0.5)
            while time.monotonic() < deadline:
                try:
                    data, _ = sock.recvfrom(65_535)
                except socket.timeout:
                    sock.sendto(b'{"Put": [0, "X"]}', server)
                    continue
                reply = json.loads(data.decode())
                if reply == {"PutOk": 0}:
                    break
            assert reply == {"PutOk": 0}, reply
            reply = _request(sock, server, b'{"Get": 1}')
            assert reply == {"GetOk": [1, "X"]}
    finally:
        runtime.stop()

"""Service-level observability (round 18): deterministic latency
histograms, the SLO/health surface, and slow-wave anomaly attribution.

Contracts pinned here:

- **Deterministic and mergeable**: the fixed power-of-two bucket
  ladder means the same sample sequence always produces the same
  snapshot, and two histograms of one series merge by element-wise
  addition; the Prometheus exposition's cumulative ``le`` buckets are
  exact over it.
- **Disarmed means free**: with no ``STpu_HIST``/``STpu_SLO``/
  ``STpu_ANOMALY`` knob set the engines hold the shared ``NULL_OBS``
  singleton and the wave loop NEVER calls into it (the null methods
  are poisoned) — mirroring the round-8 tracer contract.
- **Armed end to end**: an armed engine run emits schema-v11
  ``hist_snapshot`` events that lint clean, export to
  ``_bucket``/``_sum``/``_count`` families, and surface p50/p99 in
  ``tools/trace_summary.py``; counts stay bit-identical to a host run.
- **SLO lifecycle**: breaches are edge-triggered (one event per
  transition), recovery is silent, ``/.healthz`` answers 200/503, and
  a disarmed server still answers 200.
- **Anomaly attribution**: the per-key EWMA+MAD detector names the
  cause — compile, io_stall, straggler, spill — from gauges the wave
  entry already carries.

The full service soak (jobs + live /.healthz + /.metrics mid-run)
runs behind ``-m slow``.
"""

import json
import os
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "examples"))
sys.path.insert(0, os.path.join(_REPO, "tools"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.obs import SCHEMA_VERSION, validate_event  # noqa: E402
from stateright_tpu.obs.anomaly import (SlowWaveDetector,  # noqa: E402
                                        detector_from_env)
from stateright_tpu.obs.hist import (BUCKET_BOUNDS, NULL_OBS,  # noqa: E402
                                     Histogram, HistogramSet,
                                     NullWaveObs, WaveObs,
                                     bucket_quantile, parse_series_key,
                                     prometheus_hist_lines, series_key,
                                     wave_obs_from_env)
from stateright_tpu.obs.slo import (MIN_SAMPLES, SloTracker,  # noqa: E402
                                    prometheus_slo_lines, slo_from_env)

import trace_export  # noqa: E402
import trace_lint  # noqa: E402
import trace_summary  # noqa: E402

_OBS_KNOBS = ("STpu_HIST", "STpu_SLO", "STpu_ANOMALY", "STpu_HIST_SNAP_S")


def _events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _disarm(monkeypatch):
    for knob in _OBS_KNOBS:
        monkeypatch.delenv(knob, raising=False)


# -- Histogram core --------------------------------------------------------


def test_histogram_deterministic_and_mergeable():
    samples = [1e-6, 0.003, 0.003, 0.8, 50.0, 100.0]
    a, b = Histogram(), Histogram()
    for s in samples:
        a.observe(s)
        b.observe(s)
    assert a.snapshot() == b.snapshot()
    snap = a.snapshot()
    assert snap["count"] == len(samples)
    assert sum(snap["buckets"]) == snap["count"]  # NON-cumulative
    assert snap["sum"] == pytest.approx(sum(samples))
    # 100 s is beyond the 64 s top bound: the implicit +Inf bucket.
    assert snap["buckets"][len(BUCKET_BOUNDS)] == 1
    # Merge is element-wise addition — doubling every count.
    a.merge(b)
    merged = a.snapshot()
    assert merged["count"] == 2 * len(samples)
    assert merged["buckets"] == [2 * c for c in snap["buckets"]]


def test_bucket_quantile_estimates():
    h = Histogram()
    assert h.quantile(0.5) is None
    for _ in range(99):
        h.observe(0.001)
    h.observe(10.0)
    # p50 reports the bucket upper bound holding 0.001.
    p50 = h.quantile(0.5)
    assert p50 in BUCKET_BOUNDS and 0.001 <= p50 <= 0.002
    assert h.quantile(0.99) == p50
    assert h.quantile(1.0) >= 10.0
    # The +Inf bucket saturates to the last finite bound.
    top = Histogram()
    top.observe(1e9)
    assert top.quantile(0.5) == BUCKET_BOUNDS[-1]


def test_series_key_roundtrip():
    key = series_key("wave_latency_seconds",
                     {"kernel_path": "fused", "engine": "classic"})
    # Labels sort — one deterministic identity per series.
    assert key == ('wave_latency_seconds{engine="classic",'
                   'kernel_path="fused"}')
    assert parse_series_key(key) == (
        "wave_latency_seconds",
        {"engine": "classic", "kernel_path": "fused"})
    assert parse_series_key("plain") == ("plain", {})


def test_prometheus_hist_lines_cumulative():
    hs = HistogramSet()
    for v in (0.001, 0.004, 0.004, 30.0, 1000.0):
        hs.observe("wave_latency_seconds", v, engine="classic",
                   kernel_path="none")
    lines = prometheus_hist_lines(hs.snapshot())
    assert "# TYPE stpu_wave_latency_seconds histogram" in lines
    buckets = [ln for ln in lines if "_bucket{" in ln]
    # One line per finite bound plus +Inf, cumulative and monotone.
    assert len(buckets) == len(BUCKET_BOUNDS) + 1
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert buckets[-1].startswith(
        'stpu_wave_latency_seconds_bucket{engine="classic",'
        'kernel_path="none",le="+Inf"}')
    assert counts[-1] == 5
    sums = [ln for ln in lines if ln.startswith(
        "stpu_wave_latency_seconds_sum")]
    assert float(sums[0].rsplit(" ", 1)[1]) == pytest.approx(1030.009)
    assert any(ln.endswith(" 5") and "_count{" in ln for ln in lines)


# -- Disarmed cost ---------------------------------------------------------


def test_obs_disarmed_zero_cost(monkeypatch):
    """No obs knob set: the engines hold the NULL_OBS singleton and
    the wave loop never calls into it — every null method is poisoned,
    so a single stray call (= a stray per-wave cost with the subsystem
    off) fails the run."""
    _disarm(monkeypatch)
    assert wave_obs_from_env("classic") is NULL_OBS

    def _boom(name):
        def poisoned(self, *a, **k):
            raise AssertionError(
                f"NullWaveObs.{name} called with obs disarmed")
        return poisoned

    for name in ("wave", "job", "elastic_report", "maybe_snapshot",
                 "close"):
        monkeypatch.setattr(NullWaveObs, name, _boom(name))

    model = TwoPhaseSys(3)
    c = model.checker().spawn_tpu_bfs(batch_size=64, fused=False).join()
    assert c._wave_obs is NULL_OBS
    host = model.checker().spawn_bfs().join()
    assert host._wave_obs is NULL_OBS
    assert c.unique_state_count() == host.unique_state_count()


# -- Armed end to end ------------------------------------------------------


def test_armed_engine_snapshots_lint_export_summary(tmp_path,
                                                    monkeypatch):
    """An armed classic run: hist_snapshot events ride the trace,
    lint clean under v11, export to cumulative Prometheus families,
    surface p50/p99 in trace_summary — and discovery counts stay
    bit-identical to a disarmed host run."""
    path = tmp_path / "armed.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    monkeypatch.setenv("STpu_HIST", "1")
    monkeypatch.setenv("STpu_SLO", "1")
    monkeypatch.setenv("STpu_ANOMALY", "1")
    monkeypatch.setenv("STpu_HIST_SNAP_S", "0.05")
    model = TwoPhaseSys(3)
    c = model.checker().spawn_tpu_bfs(batch_size=64, fused=False).join()
    for knob in ("STpu_TRACE",) + _OBS_KNOBS:
        monkeypatch.delenv(knob)

    events = _events(path)
    snaps = [e for e in events if e["type"] == "hist_snapshot"]
    assert snaps, "no hist_snapshot rode the trace"
    for e in snaps:
        assert validate_event(e) == [], e
        assert e["schema_version"] == SCHEMA_VERSION
        for key, data in e["hists"].items():
            assert sum(data["buckets"]) == data["count"], key
    # Cumulative across snapshots: counts never shrink.
    last = snaps[-1]["hists"]
    key = next(k for k in last if k.startswith("wave_latency_seconds"))
    assert last[key]["count"] >= snaps[0]["hists"].get(
        key, {"count": 0})["count"]
    _, labels = parse_series_key(key)
    assert labels["engine"] == "classic"

    counts, errors = trace_lint.lint_file(str(path))
    assert not errors, errors[:3]
    assert counts["hist_snapshot"] == len(snaps)

    prom = trace_export.to_prometheus(events)
    assert "stpu_wave_latency_seconds_bucket" in prom
    assert 'le="+Inf"' in prom
    assert "stpu_wave_latency_seconds_count" in prom

    table = trace_summary.format_table(trace_summary.summarize(events))
    assert "p50_ms" in table and "p99_ms" in table
    # The classic row carries numeric quantiles, not "-". (The name
    # column is "classic <run>" — two tokens — so p50/p99 sit at 5/6.)
    row = next(ln for ln in table.splitlines() if "classic" in ln)
    assert row.split()[5] != "-" and row.split()[6] != "-"

    # The live facade agrees with the stream.
    assert c._wave_obs.enabled
    assert c._wave_obs.slo_status()["healthy"]
    host = model.checker().spawn_bfs().join()
    assert c.unique_state_count() == host.unique_state_count()
    assert c.state_count() == host.state_count()


def test_trace_summary_gap_fallback():
    """v10-and-older captures (no hist_snapshot): p50/p99 fall back to
    exact percentiles over the raw wave time gaps."""
    events = [{"type": "wave", "engine": "classic", "run": "r0",
               "t": 1.0 + 0.01 * i, "states": 10 * i}
              for i in range(12)]
    rows = trace_summary.summarize(events)
    r = rows["classic r0"]
    assert not r["hist"] and len(r["gaps"]) == 11
    table = trace_summary.format_table(rows)
    row = next(ln for ln in table.splitlines() if "classic" in ln)
    assert row.split()[5] == "10.0"  # 10 ms gaps, exact


# -- SLO lifecycle ---------------------------------------------------------


def test_slo_breach_edge_triggered_and_recovers():
    slo = SloTracker({"wave_success": (None, 0.9)}, window_s=60.0)
    t = 100.0
    for _ in range(MIN_SAMPLES):
        assert slo.observe("wave_success", ok=True, t=t) is None
        t += 0.1
    assert slo.healthy
    # Push the good ratio under target: exactly one breach payload.
    breaches = []
    for _ in range(5):
        evt = slo.observe("wave_success", ok=False, t=t)
        t += 0.1
        if evt is not None:
            breaches.append(evt)
    assert len(breaches) == 1
    evt = breaches[0]
    assert evt["objective"] == "wave_success"
    assert evt["burn"] > 1.0
    assert evt["good"] + evt["bad"] == MIN_SAMPLES + evt["bad"]
    st = slo.status()
    assert not st["healthy"]
    assert st["objectives"]["wave_success"]["breaching"]
    assert st["objectives"]["wave_success"]["breaches"] == 1
    # Recovery is silent: the bad events age out of the window.
    t += 120.0
    for _ in range(2 * MIN_SAMPLES):
        assert slo.observe("wave_success", ok=True, t=t) is None
        t += 0.1
    assert slo.healthy
    assert slo.status()["objectives"]["wave_success"]["breaches"] == 1
    # A second dip is a second edge.
    for _ in range(2 * MIN_SAMPLES):
        slo.observe("wave_success", ok=False, t=t)
        t += 0.1
    assert slo.status()["objectives"]["wave_success"]["breaches"] == 2


def test_slo_latency_objective_and_status_lines():
    slo = SloTracker({"job_latency": (0.5, 0.9)}, window_s=60.0)
    for _ in range(MIN_SAMPLES):
        slo.observe("job_latency", value=0.01)
    st = slo.status()
    assert st["healthy"]
    assert st["objectives"]["job_latency"]["ratio"] == 1.0
    lines = prometheus_slo_lines(st)
    assert "stpu_slo_healthy 1" in lines
    assert 'stpu_slo_burn{objective="job_latency"} 0.0' in lines
    assert ('stpu_slo_breaches_total{objective="job_latency"} 0'
            in lines)
    # Unknown objective name: ignored, not a crash.
    assert slo.observe("nope", ok=False) is None


def test_slo_from_env_overrides(monkeypatch):
    monkeypatch.delenv("STpu_SLO", raising=False)
    assert slo_from_env() is None
    monkeypatch.setenv("STpu_SLO", "0")
    assert slo_from_env() is None
    monkeypatch.setenv("STpu_SLO",
                       "job_latency=0.25,window=30,wave_success=0.5,"
                       "bogus=7,junk")
    slo = slo_from_env()
    assert slo.window_s == 30.0
    assert slo._objs["job_latency"]["threshold"] == 0.25
    assert slo._objs["wave_success"]["target"] == 0.5


# -- Anomaly attribution ---------------------------------------------------


def _warm(det, key, n=8, dur=0.01):
    for _ in range(n):
        assert det.observe(key, dur, {}) is None


def test_anomaly_attribution_causes():
    det = SlowWaveDetector(k=4.0, warmup=8, floor=0.001)
    _warm(det, "c|none")
    evt = det.observe("c|none", 1.0, {"compiled": True})
    assert evt["cause"] == "compile"
    assert evt["baseline_s"] == pytest.approx(0.01)

    _warm(det, "io|none")
    evt = det.observe("io|none", 1.0, {"io_stall_s": 0.9})
    assert evt["cause"] == "io_stall"

    _warm(det, "el|none")
    evt = det.observe("el|none", 1.0, {}, wait_s=0.8)
    assert evt["cause"] == "straggler"

    det.observe("sp|none", 0.01, {"tier_host_bytes": 100})
    _warm(det, "sp|none", n=7)
    evt = det.observe("sp|none", 1.0, {"tier_host_bytes": 500})
    assert evt["cause"] == "spill"

    _warm(det, "u|none")
    evt = det.observe("u|none", 1.0, {})
    assert evt["cause"] == "unknown"

    recent = det.recent()
    assert [e["cause"] for e in recent] == [
        "compile", "io_stall", "straggler", "spill", "unknown"]
    assert det.stats()["total"] == 5
    # A fast wave never trips; the baseline keeps adapting.
    assert det.observe("u|none", 0.01, {}) is None


def test_anomaly_detector_from_env(monkeypatch):
    monkeypatch.delenv("STpu_ANOMALY", raising=False)
    assert detector_from_env() is None
    monkeypatch.setenv("STpu_ANOMALY", "k=6,warmup=4,floor=0.01,bad=x")
    det = detector_from_env()
    assert (det.k, det.warmup, det.floor) == (6.0, 4, 0.01)


# -- Facade ----------------------------------------------------------------


class _StubTracer:
    enabled = True

    def __init__(self):
        self.events = []

    def event(self, etype, **fields):
        self.events.append((etype, fields))


def test_wave_obs_facade_jobs_and_snapshots():
    obs = WaveObs("service", hist=HistogramSet(),
                  slo=SloTracker({"queue_wait": (0.5, 0.9)}),
                  snap_s=9999.0)
    tr = _StubTracer()
    obs.job(queue_s=0.01, run_s=0.2, total_s=0.21, engine="classic",
            tracer=tr)
    snap = obs.hist.snapshot()
    for fam in ("job_queue_seconds", "job_run_seconds",
                "job_latency_seconds"):
        assert series_key(fam, {"engine": "classic"}) in snap
    obs.elastic_report("w0", compute_s=0.4, wait_s=0.1)
    assert series_key("elastic_compute_seconds",
                      {"worker": "w0"}) in obs.hist.snapshot()
    # close() flushes a final snapshot even before the cadence.
    obs.close(tr)
    assert tr.events and tr.events[-1][0] == "hist_snapshot"
    assert tr.events[-1][1]["snap"] == 1
    # The stamped variant (flight-recorder hook) validates standalone.
    evt = obs.final_snapshot_event()
    assert validate_event(evt) == []
    assert evt["snap"] == 2 and evt["run"] == "hist-service"


def test_flight_dump_carries_final_snapshot(tmp_path):
    from stateright_tpu.obs.flight import FlightRecorder

    obs = WaveObs("classic", hist=HistogramSet())
    obs.hist.observe("wave_latency_seconds", 0.01, engine="classic",
                     kernel_path="none")
    fr = FlightRecorder("classic", capacity=8,
                        directory=str(tmp_path))
    fr.set_hist_source(obs.final_snapshot_event)
    fr.record_event("fault", point="expand", hit=1, mode="crash")
    path = fr.dump("test")
    events = _events(path)
    assert events[0]["type"] == "postmortem"
    assert events[-1]["type"] == "hist_snapshot"
    assert "wave_latency_seconds" in str(events[-1]["hists"])
    counts, errors = trace_lint.lint_file(path)
    assert not errors, errors[:3]


# -- Lint invariants -------------------------------------------------------


def _snap_evt(run, snap, count, bucket0, total=None, t=1.0):
    return {"type": "hist_snapshot", "schema_version": SCHEMA_VERSION,
            "engine": "classic", "run": run, "t": t,
            "hists": {"wave_latency_seconds": {
                "buckets": [bucket0], "sum": total
                if total is not None else 0.01 * count,
                "count": count}},
            "snap": snap}


def test_lint_catches_hist_snapshot_violations(tmp_path):
    ok = tmp_path / "ok.jsonl"
    with open(ok, "w") as f:
        f.write(json.dumps(_snap_evt("r0", 1, 2, 2)) + "\n")
        f.write(json.dumps(_snap_evt("r0", 2, 5, 5, t=2.0)) + "\n")
    counts, errors = trace_lint.lint_file(str(ok))
    assert not errors and counts["hist_snapshot"] == 2

    def check(name, *evts):
        bad = tmp_path / name
        with open(bad, "w") as f:
            for e in evts:
                f.write(json.dumps(e) + "\n")
        _, errors = trace_lint.lint_file(str(bad))
        assert errors, name
        return errors

    # Buckets that don't sum to count.
    check("sum.jsonl", _snap_evt("r0", 1, 3, 2))
    # Count shrank between snapshots (cumulative violated).
    check("mono.jsonl", _snap_evt("r0", 1, 5, 5),
          _snap_evt("r0", 2, 2, 2, t=2.0))
    # snap sequence not strictly increasing.
    check("seq.jsonl", _snap_evt("r0", 2, 2, 2),
          _snap_evt("r0", 2, 5, 5, t=2.0))
    # sum shrank while count grew.
    check("sumdec.jsonl", _snap_evt("r0", 1, 2, 2, total=5.0),
          _snap_evt("r0", 2, 4, 4, total=1.0, t=2.0))


# -- Health / ops surface --------------------------------------------------


def test_healthz_and_ops_surface(monkeypatch):
    from stateright_tpu.explorer import Explorer

    _disarm(monkeypatch)
    monkeypatch.setenv("STpu_HIST", "1")
    monkeypatch.setenv("STpu_SLO", "1")
    c = TwoPhaseSys(3).checker().spawn_bfs().join()
    _disarm(monkeypatch)
    ex = Explorer(c)
    status, payload = ex.healthz()
    assert status == 200 and payload["healthy"]
    assert "host_bfs" in payload["participants"]

    # The small host run finishes in one worker block (one wave, no
    # gap yet): seed a couple of latency samples so the hist surface
    # has something to serve — the engine wiring itself is pinned by
    # test_armed_engine_snapshots_lint_export_summary.
    c._wave_obs.hist.observe("wave_latency_seconds", 0.004,
                             engine="host_bfs", kernel_path="none")
    c._wave_obs.hist.observe("wave_latency_seconds", 0.009,
                             engine="host_bfs", kernel_path="none")
    ops = ex.ops()
    part = ops["participants"]["host_bfs"]
    assert part["slo"]["healthy"]
    key = next(k for k in part["hist"]
               if k.startswith("wave_latency_seconds"))
    h = part["hist"][key]
    assert h["count"] >= 1 and h["p50"] in BUCKET_BOUNDS

    # /.metrics carries the histogram + SLO families live.
    metrics = ex.metrics()
    assert "stpu_wave_latency_seconds_bucket" in metrics
    assert "stpu_slo_healthy 1" in metrics

    # Force a breach: the health surface flips to 503.
    for _ in range(2 * MIN_SAMPLES):
        c._wave_obs.slo.observe("wave_success", ok=False)
    status, payload = ex.healthz()
    assert status == 503 and not payload["healthy"]
    assert not ex.ops()["healthy"]
    assert "stpu_slo_healthy 0" in ex.metrics()


def test_healthz_disarmed_still_200(monkeypatch):
    from stateright_tpu.explorer import Explorer

    _disarm(monkeypatch)
    c = TwoPhaseSys(3).checker().spawn_bfs().join()
    status, payload = Explorer(c).healthz()
    assert status == 200
    assert payload == {"healthy": True, "slo": "disarmed"}


# -- bench_compare ---------------------------------------------------------


def _bench_compare(*args):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools",
                                      "bench_compare.py"), *args],
        capture_output=True, text=True)
    return proc.returncode, proc.stdout, proc.stderr


def test_bench_compare_rounds():
    r07 = os.path.join(_REPO, "BENCH_r07.json")
    r09 = os.path.join(_REPO, "BENCH_r09.json")
    rc, out, _ = _bench_compare(r07, r09)
    assert rc == 0
    assert "headline" in out and "value" in out
    assert "host_states_per_sec" in out
    # Reversed under a tight gate: the headline drop fails the run.
    rc, _, err = _bench_compare(r09, r07, "--max-regress", "2")
    assert rc == 1 and "FAIL" in err
    # --max-regress 0 disables the gate.
    rc, _, _ = _bench_compare(r09, r07, "--max-regress", "0")
    assert rc == 0
    # Trajectory mode over three rounds.
    rc, out, _ = _bench_compare(
        os.path.join(_REPO, "BENCH_r05.json"), r07, r09)
    assert rc == 0
    assert "r05" in out and "delta%" in out


# -- Service soak (slow) ---------------------------------------------------


@pytest.mark.slow
def test_service_soak_armed_observability(tmp_path, monkeypatch):
    """The acceptance soak: an armed job service under live traffic —
    /.healthz answers 200 and /.metrics serves _bucket/_sum/_count
    families MID-RUN, every job trace lints clean under v11, and the
    scheduler stats carry the SLO surface."""
    import service_client as sc

    from stateright_tpu.explorer import serve_service

    monkeypatch.setenv("STpu_HIST", "1")
    monkeypatch.setenv("STpu_SLO", "1")
    monkeypatch.setenv("STpu_ANOMALY", "1")
    monkeypatch.setenv("STpu_HIST_SNAP_S", "0.1")
    service, server = serve_service(
        addresses=("127.0.0.1", 0), block=False, workers=2,
        data_dir=str(tmp_path))
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    spec = {"model": "twopc", "params": {"rm_count": 3},
            "knobs": {"batch_size": 64}}
    try:
        ids = [sc.submit(base, spec)["id"] for _ in range(4)]
        # Mid-run: health + histogram families served live.
        health = sc.request(base, "/.healthz")
        assert health["healthy"]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            states = {sc.status(base, j)["state"] for j in ids}
            metrics = sc.request(base, "/.metrics")
            if states == {"done"}:
                break
            time.sleep(0.1)
        assert states == {"done"}
        # After the jobs: job-latency families present and consistent.
        metrics = sc.request(base, "/.metrics")
        assert "stpu_job_latency_seconds_bucket" in metrics
        assert "stpu_job_latency_seconds_count" in metrics
        assert "stpu_slo_healthy 1" in metrics
        ops = sc.request(base, "/.ops")
        assert ops["healthy"] and "service" in ops["participants"]
        for j in ids:
            counts, errors = trace_lint.lint_file(
                service.trace_file(j))
            assert not errors, errors[:3]
    finally:
        server.shutdown()
        server.server_close()
        service.close()

"""Pallas visited-table kernel vs the XLA probe loop.

The kernel (``tpu/pallas_table.py``) must be bit-identical to
``engine.dedup_and_insert`` on every output — new-candidate mask, count,
and the table contents — since checkpoints and cross-engine gates treat
the table as interchangeable state; both the XLA-side-mask variant and
the fused in-kernel local dedup (VMEM scratch) variant are gated. Runs
in interpret mode on the CPU backend (the TPU lowering is A/B'd in the
hardware session, MEASUREMENTS round-5 plan).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import jax.numpy as jnp

from stateright_tpu.tpu.engine import (dedup_and_insert,
                                       first_occurrence_candidates,
                                       host_table_insert)
from stateright_tpu.tpu.hashing import SENTINEL
from stateright_tpu.tpu.pallas_table import (PALLAS_AVAILABLE,
                                             dedup_and_insert_pallas,
                                             pallas_table_capacity_limit)

pytestmark = pytest.mark.skipif(
    not PALLAS_AVAILABLE, reason="pallas not available in this jax build")


def _random_stream(rng, n, resident):
    """Candidates with duplicates, sentinels, and revisits of resident
    fingerprints — every dedup case."""
    fresh = rng.integers(1, 1 << 62, n, dtype=np.uint64)
    out = fresh.copy()
    dup_rows = rng.random(n) < 0.3
    out[dup_rows] = rng.choice(fresh, dup_rows.sum())
    if len(resident):
        rev_rows = rng.random(n) < 0.2
        out[rev_rows] = rng.choice(resident, rev_rows.sum())
    out[rng.random(n) < 0.1] = SENTINEL
    return out


@pytest.mark.parametrize("capacity", [1 << 14, 1 << 15])
@pytest.mark.parametrize("fuse_local", [True, False])
def test_kernel_matches_xla_loop(capacity, fuse_local):
    import jax

    rng = np.random.default_rng(7)
    resident = rng.integers(1, 1 << 62, capacity // 8, dtype=np.uint64)
    table = np.full(capacity, SENTINEL, np.uint64)
    host_table_insert(table, resident)

    # Jit once per capacity: un-jitted calls would recompile the probe
    # while_loop per round (minutes of XLA time for zero extra signal).
    # Stream sizes keep the load factor under 1/2 across all rounds —
    # the engine's growth invariant; an overfull table would spin the
    # probe loop forever (no empty slot ever found).
    j_xla = jax.jit(lambda f, t: dedup_and_insert(f, t, capacity))
    j_pls = jax.jit(lambda f, t: dedup_and_insert_pallas(
        f, t, capacity, fuse_local=fuse_local))
    j_first = jax.jit(first_occurrence_candidates)

    for round_i in range(4):
        fps = _random_stream(rng, 1024, resident)
        d_fps = jnp.asarray(fps)
        m_x, c_x, t_x = j_xla(d_fps, jnp.asarray(table))
        m_p, c_p, cand_p, t_p = j_pls(d_fps, jnp.asarray(table))
        assert np.array_equal(np.asarray(m_x), np.asarray(m_p)), \
            f"mask mismatch round {round_i}"
        assert int(c_x) == int(c_p)
        # The kernel's candidate count must equal the reference local
        # dedup's distinct count (whichever side computed the mask).
        assert int(cand_p) == int(np.asarray(j_first(d_fps)).sum())
        # Tables must agree as SETS (probe claims can land in different
        # slots only if the claim order differs — it must not: same
        # probe sequence, same winner rule).
        assert np.array_equal(np.asarray(t_x), np.asarray(t_p)), \
            f"table mismatch round {round_i}"
        table = np.asarray(t_x)
        resident = table[table != SENTINEL]


def test_engine_parity_2pc():
    """Full engine runs with table_impl='pallas' count identically."""
    from two_phase_commit import TwoPhaseSys

    model = TwoPhaseSys(3)
    xla = model.checker().spawn_tpu_bfs(table_impl="xla").join()
    pls = model.checker().spawn_tpu_bfs(table_impl="pallas").join()
    assert xla.unique_state_count() == pls.unique_state_count() == 288
    assert set(xla.discoveries()) == set(pls.discoveries())


def test_capacity_limit_is_sane():
    """The VMEM-derived gate is a power of two in a plausible range
    (falls back to 2^20 when the backend exposes no budget — the CPU
    backend here usually doesn't)."""
    limit = pallas_table_capacity_limit()
    assert limit >= 1 << 12
    assert limit & (limit - 1) == 0
    assert pallas_table_capacity_limit() == limit  # cached, stable


def test_capacity_fallback_warns_once():
    """A capacity beyond the VMEM budget degrades to the XLA table with
    a warning (mid-run growth must survive) — emitted once per
    capacity, not once per compiled wave program."""
    import warnings as _w

    from stateright_tpu.tpu import engine
    from stateright_tpu.tpu.engine import dedup_impl

    too_big = pallas_table_capacity_limit() * 2
    engine._PALLAS_DEGRADE_WARNED.discard(too_big)
    with pytest.warns(RuntimeWarning, match="pallas visited table"):
        fn = dedup_impl("pallas", too_big)
    with _w.catch_warnings():
        _w.simplefilter("error")  # the repeat build must stay silent
        fn = dedup_impl("pallas", too_big)
    fps = jnp.asarray(np.array([3, 5, 3, SENTINEL], np.uint64))
    table = jnp.full((too_big,), jnp.uint64(SENTINEL))
    mask, count, cand, _ = fn(fps, table)
    assert int(count) == 2
    assert int(cand) == 2

"""The bit-packed state arena (tpu/packing.py + ISSUE 4 acceptance).

Four contracts:

- **Layout compiler**: pack∘unpack == id on random in-range rows
  (plain and sentinel lanes, word-straddling fields, numpy and jitted
  codecs agree); invalid specs are rejected at BUILD time.
- **Bit-identical parity matrix**: counts, discoveries, and parent maps
  identical with ``pack_arena`` on vs off, on all four device engines,
  on 2pc and paxos — the sharded pair on the 8-device virtual mesh
  (covering the packed all-to-all exchange).
- **Cross-version checkpoint matrix**: v1-style unpacked snapshots
  resume on packed engines and vice versa (including the native C++
  reader), byte-for-byte identical continuation counts.
- **Telemetry**: wave events carry the v2 bandwidth gauges,
  ``scheduler_stats()["packing"]`` reports the real widths, and the
  north-star model actually achieves the >= 2.5x row-byte cut.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.tpu.packing import compile_layout  # noqa: E402


def _spawn(model, engine, B, **kwargs):
    b = model.checker()
    if engine == "fused":
        return b.spawn_tpu_bfs(batch_size=B, fused=True, **kwargs)
    if engine == "classic":
        return b.spawn_tpu_bfs(batch_size=B, fused=False, **kwargs)
    if engine == "sharded-fused":
        return b.spawn_tpu_bfs(batch_size=B, sharded=True, **kwargs)
    assert engine == "sharded-classic"
    return b.spawn_tpu_bfs(batch_size=B, sharded=True, fused=False,
                           **kwargs)


ENGINES = ("fused", "classic", "sharded-fused", "sharded-classic")


# -- Layout compiler -------------------------------------------------------

def _random_rows(layout, rng, n=257):
    """Random in-range rows for a layout (sentinel lanes mix real
    values and the sentinel)."""
    cols = []
    for l in layout.lanes:
        if l.sentinel is None:
            hi = (1 << l.bits) if l.bits < 32 else (1 << 32)
            cols.append(rng.integers(0, hi, n, dtype=np.uint64))
        else:
            vals = rng.integers(0, (1 << l.bits) - 1, n, dtype=np.uint64)
            sent = rng.random(n) < 0.3
            cols.append(np.where(sent, np.uint64(l.sentinel), vals))
    return np.stack(cols, axis=1).astype(np.uint32)


def test_pack_unpack_roundtrip_random_layouts():
    rng = np.random.default_rng(9)
    for trial in range(25):
        w = int(rng.integers(1, 60))
        specs = []
        for _ in range(w):
            bits = int(rng.integers(1, 33))
            if bits < 32 and rng.random() < 0.25:
                specs.append((bits, 0xFFFFFFFF))
            else:
                specs.append(bits)
        layout = compile_layout(specs, w)
        rows = _random_rows(layout, rng)
        packed = layout.pack_np(rows)
        assert packed.shape == (len(rows), layout.packed_width)
        assert (layout.unpack_np(packed) == rows).all(), (trial, specs)
        layout.check_fits(rows)  # in-range rows must pass the guard
        # Single-lane extraction agrees with the full unpack.
        lane = int(rng.integers(0, w))
        assert (layout.lane_np(packed, lane) == rows[:, lane]).all()


def test_pack_unpack_jit_matches_numpy():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    specs = [3, (7, 0xFFFFFFFF), 32, 17, (30, 0xFFFFFFFF), 1, 13, 29]
    layout = compile_layout(specs, len(specs))
    rows = _random_rows(layout, rng, n=64)
    packed_np = layout.pack_np(rows)
    packed_j = np.asarray(jax.jit(layout.pack)(jnp.asarray(rows)))
    assert (packed_j == packed_np).all()
    back = np.asarray(jax.jit(layout.unpack)(jnp.asarray(packed_np)))
    assert (back == rows).all()
    lane = np.asarray(jax.jit(
        lambda p: layout.lane(p, 1))(jnp.asarray(packed_np)))
    assert (lane == rows[:, 1]).all()


def test_layout_rejects_invalid_specs_at_build_time():
    with pytest.raises(ValueError, match="outside 1..32"):
        compile_layout([0, 4], 2)
    with pytest.raises(ValueError, match="outside 1..32"):
        compile_layout([33], 1)
    with pytest.raises(ValueError, match="state_width"):
        compile_layout([4, 4, 4], 2)
    with pytest.raises(ValueError, match="sentinel"):
        # sentinel inside the value range would be ambiguous
        compile_layout([(8, 100)], 1)
    with pytest.raises(ValueError, match="bits.*or"):
        compile_layout([(8, 1, 2)], 1)


def test_check_fits_catches_wrong_declaration():
    layout = compile_layout([2, 4], 2)
    layout.check_fits(np.array([[3, 15]], np.uint32))
    with pytest.raises(ValueError, match="lane 0"):
        layout.check_fits(np.array([[4, 15]], np.uint32))


def test_identity_layout_for_conservative_default():
    layout = compile_layout(None, 5)
    assert not layout.packs
    assert layout.packed_width == 5


def test_model_layouts_roundtrip_reachable_states():
    """Every packing-declaring model family: encode real reachable
    states and prove the declared widths hold them (the lane_bits
    contract, checked against the actual host enumeration)."""
    from increment import IncrementModel
    from linearizable_register import AbdModelCfg
    from paxos import PaxosModelCfg
    from single_copy_register import SingleCopyModelCfg

    for model in (TwoPhaseSys(4), IncrementModel(3),
                  PaxosModelCfg(1, 3).into_model(),
                  AbdModelCfg(2, 2).into_model(),
                  SingleCopyModelCfg(2, 1).into_model()):
        dm = model.device_model()
        layout = compile_layout(dm.lane_bits(), dm.state_width)
        assert layout.packs, type(model).__name__
        states = [s for s, _ in zip(_iter_states(model), range(4000))]
        assert states
        rows = np.stack([np.asarray(dm.encode(s), np.uint32)
                         for s in states])
        layout.check_fits(rows)
        assert (layout.unpack_np(layout.pack_np(rows)) == rows).all()


def _iter_states(model):
    """Host BFS enumeration (the reachable universe the packed widths
    must cover)."""
    from collections import deque

    seen = set()
    queue = deque(model.init_states())
    while queue:
        s = queue.popleft()
        if s in seen:
            continue
        seen.add(s)
        yield s
        actions = []
        model.actions(s, actions)
        for a in actions:
            nxt = model.next_state(s, a)
            if nxt is not None and model.within_boundary(nxt):
                queue.append(nxt)


def test_north_star_row_cut_at_least_2_5x():
    """ISSUE 4 acceptance: bytes_per_state on paxos check 3 (W=55)
    drops >= 2.5x under the model-derived layout."""
    from paxos import PaxosModelCfg

    dm = PaxosModelCfg(3, 3).into_model().device_model()
    layout = compile_layout(dm.lane_bits(), dm.state_width)
    assert dm.state_width == 55
    assert dm.state_width / layout.packed_width >= 2.5, layout


# -- Bit-identical parity matrix -------------------------------------------

@pytest.mark.parametrize("engine", [
    "fused",
    # tier-1 budget: the sharded pair's shard_map compiles (and, since
    # round 15, the classic sibling) ride in the slow set; the fused
    # arm stays the fast gate.
    pytest.param("classic", marks=pytest.mark.slow),
    pytest.param("sharded-fused", marks=pytest.mark.slow),
    pytest.param("sharded-classic", marks=pytest.mark.slow)])
def test_pack_arena_bit_identical_2pc(engine):
    """pack_arena on vs off: counts, discoveries, and parent maps
    identical on all four engines (the sharded pair exercises the
    packed all-to-all on the 8-device virtual mesh)."""
    model = TwoPhaseSys(4)
    runs = []
    for on in (True, False):
        c = _spawn(model, engine, 48, pack_arena=on).join()
        runs.append((c.unique_state_count(), c.state_count(),
                     frozenset(c.discoveries()), dict(c._parent_map())))
    assert runs[0] == runs[1], engine


@pytest.mark.slow  # the 2pc matrix above is the fast-set gate
@pytest.mark.parametrize("engine", ENGINES)
def test_pack_arena_bit_identical_paxos(engine):
    from paxos import PaxosModelCfg

    model = PaxosModelCfg(1, 3).into_model()
    runs = []
    for on in (True, False):
        c = _spawn(model, engine, 128, pack_arena=on).join()
        runs.append((c.unique_state_count(), c.state_count(),
                     frozenset(c.discoveries()), dict(c._parent_map())))
    assert runs[0] == runs[1], engine


@pytest.mark.slow  # round-15 tier-1 budget: the layout-roundtrip
# test above keeps these models' lane_bits contracts fast-covered.
def test_pack_arena_bit_identical_register_workloads():
    """ABD and single-copy (the other register-workload layouts) under
    a forced-packed fused run: full-enumeration counts and discoveries
    match the host reference — the CPU suite must exercise these
    layouts end to end even though the backend-aware default would
    leave them unpacked here."""
    from linearizable_register import AbdModelCfg
    from single_copy_register import SingleCopyModelCfg

    for model in (AbdModelCfg(2, 2).into_model(),
                  SingleCopyModelCfg(2, 1).into_model()):
        ref = model.checker().spawn_bfs().join()
        c = model.checker().spawn_tpu_bfs(batch_size=64,
                                          pack_arena=True).join()
        assert c._pack_on is True, type(model).__name__
        assert c.unique_state_count() == ref.unique_state_count()
        assert c.state_count() == ref.state_count()
        assert set(c.discoveries()) == set(ref.discoveries())


def test_pack_arena_no_layout_is_identity():
    """A model without lane_bits (conservative default) runs with
    pack_arena on as a no-op — same rows, same checkpoint bytes."""
    from stateright_tpu.test_util import LinearEquation

    model = LinearEquation(2, 10, 14)
    c = model.checker().spawn_tpu_bfs(batch_size=32, fused=False,
                                      pack_arena=True).join()
    assert c._pack_on is False
    assert c._Wrow == c._W


def test_pack_arena_default_is_backend_aware():
    """pack_arena=None resolves by backend, like the pipeline knob: on
    the CPU backend (this suite) the codec is pure compute overhead and
    auto means off; the forced knob still engages, and the achievable
    cut is reported either way for the bench record."""
    model = TwoPhaseSys(3)
    auto = model.checker().spawn_tpu_bfs(batch_size=64,
                                         fused=False).join()
    assert auto._pack_on is False          # CPU backend in tests
    assert auto._Wrow == auto._W
    stats = auto.scheduler_stats()["packing"]
    assert stats["enabled"] is False
    assert stats["packed_width"] < stats["state_width"]
    assert stats["packable_ratio"] > 1.0
    forced = model.checker().spawn_tpu_bfs(batch_size=64, fused=False,
                                           pack_arena=True).join()
    assert forced._pack_on is True
    assert forced.unique_state_count() == auto.unique_state_count()


# -- Cross-version checkpoint matrix ---------------------------------------

def _rewrite_header_v1(path):
    """Rewrites a v2 unpacked checkpoint into the literal v1 header
    form (no row_format keys, version 1) — a faithful old-snapshot
    fixture without keeping binary artifacts in the tree."""
    data = dict(np.load(path))
    header = json.loads(bytes(data["header"].tobytes()).decode())
    assert header.get("row_format", "u32") == "u32"
    header.pop("row_format", None)
    header.pop("lane_bits", None)
    header.pop("packed_width", None)
    header["version"] = 1
    data["header"] = np.frombuffer(json.dumps(header).encode(), np.uint8)
    np.savez_compressed(path, **data)


@pytest.mark.slow  # ~12s: the writer/reader matrix spans four engine
# spawns; test_checkpoint_format + the resilience suite cover the v3
# fast path
def test_checkpoint_cross_version_matrix(tmp_path):
    """v1 unpacked snapshots resume on packed engines, packed v2
    snapshots resume on unpacked engines (and the reverse), with
    identical continuation counts."""
    model = TwoPhaseSys(4)
    full = model.checker().spawn_bfs().join()
    want = (full.unique_state_count(), set(full.discoveries()))

    # Writer matrix: packed and unpacked mid-run snapshots.
    packed = str(tmp_path / "packed.npz")
    plain = str(tmp_path / "plain.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, pack_arena=True, checkpoint_path=packed).join()
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, pack_arena=False, checkpoint_path=plain).join()
    with np.load(packed) as d:
        hdr = json.loads(bytes(d["header"].tobytes()).decode())
        assert hdr["row_format"] == "packed"
        assert hdr["version"] == 2
        assert d["pending_vecs"].shape[1] == hdr["packed_width"]
    v1 = str(tmp_path / "v1.npz")
    import shutil

    shutil.copy(plain, v1)
    _rewrite_header_v1(v1)

    # Reader matrix: every stored format onto every engine format.
    for src in (packed, plain, v1):
        for on in (True, False):
            r = model.checker().spawn_tpu_bfs(
                batch_size=64, pack_arena=on, resume_from=src).join()
            got = (r.unique_state_count(), set(r.discoveries()))
            assert got == want, (src, on, got)


def test_checkpoint_packed_resumes_on_native(tmp_path):
    """The native C++ reader consumes a packed v2 snapshot via the
    self-described layout (pending_rows unpacks for it)."""
    from stateright_tpu.native.host_bfs import HOSTBFS_AVAILABLE

    if not HOSTBFS_AVAILABLE:
        pytest.skip("native extension unavailable")
    model = TwoPhaseSys(4)
    full = model.checker().spawn_bfs().join()
    ckpt = str(tmp_path / "packed.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, pack_arena=True, checkpoint_path=ckpt).join()
    r = model.checker().spawn_native_bfs(
        model.device_model(), resume_from=ckpt).join()
    assert r.unique_state_count() == full.unique_state_count()
    assert set(r.discoveries()) == set(full.discoveries())


def test_checkpoint_resume_rejects_out_of_range_rows(tmp_path):
    """A packed engine resuming an unpacked snapshot runs the
    check_fits guard: a pending row outside the model's declared lane
    widths fails loudly instead of resuming from truncated states."""
    model = TwoPhaseSys(4)
    ckpt = str(tmp_path / "plain.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, pack_arena=False, checkpoint_path=ckpt).join()
    data = dict(np.load(ckpt))
    assert data["pending_vecs"].shape[0] > 0
    data["pending_vecs"][0, 0] = 7  # RM lane is declared 2 bits
    # This simulates a WRITER that emitted out-of-range rows (wrong
    # model config), not disk corruption — drop the v3 integrity table
    # the in-place edit invalidated, so the check_fits guard (the
    # target of this test) is what fires.
    data.pop("crcs", None)
    np.savez_compressed(ckpt, **data)
    with pytest.raises(ValueError, match="lane 0"):
        model.checker().spawn_tpu_bfs(batch_size=32, pack_arena=True,
                                      resume_from=ckpt).join()


def test_checkpoint_newer_version_refused(tmp_path):
    from stateright_tpu.checkpoint_format import validate_header

    model = TwoPhaseSys(3)
    ckpt = str(tmp_path / "c.npz")
    model.checker().spawn_tpu_bfs(batch_size=64,
                                  checkpoint_path=ckpt).join()
    data = dict(np.load(ckpt))
    header = json.loads(bytes(data["header"].tobytes()).decode())
    header["version"] = 99
    data["header"] = np.frombuffer(json.dumps(header).encode(), np.uint8)
    with pytest.raises(ValueError, match="newer than this build"):
        validate_header(data, model_name="TwoPhaseSys",
                        state_width=6, use_symmetry=False)


# -- Telemetry -------------------------------------------------------------

def test_wave_events_carry_bandwidth_gauges(tmp_path, monkeypatch):
    from stateright_tpu.obs import SCHEMA_VERSION

    model = TwoPhaseSys(3)
    path = tmp_path / "t.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    c = model.checker().spawn_tpu_bfs(batch_size=64, fused=True,
                                      pack_arena=True).join()
    monkeypatch.delenv("STpu_TRACE")
    waves = [json.loads(l) for l in path.read_text().splitlines()
             if '"wave"' in l]
    waves = [e for e in waves if e.get("type") == "wave"]
    assert waves
    layout = compile_layout(model.device_model().lane_bits(),
                            model.device_model().state_width)
    for e in waves:
        assert e["schema_version"] == SCHEMA_VERSION
        assert e["bytes_per_state"] == 4 * layout.packed_width
        assert e["arena_bytes"] > 0
        assert e["table_bytes"] == e["capacity"] * 8
    stats = c.scheduler_stats()["packing"]
    assert stats["enabled"] is True
    assert stats["packed_width"] == layout.packed_width
    assert stats["row_width"] == layout.packed_width
    assert stats["bytes_per_state"] == 4 * layout.packed_width
    assert stats["ratio"] > 1.0
    assert stats["arena_bytes_high_water"] >= max(
        e["arena_bytes"] for e in waves)
    assert stats["table_bytes_high_water"] == max(
        e["table_bytes"] for e in waves)


def test_schema_v1_wave_still_validates_and_v3_rejected():
    """trace_lint satellite: old captures validate against their own
    field set; captures from a NEWER schema fail with one clear
    upgrade message, not a field-set mismatch cascade."""
    from stateright_tpu.obs import (SCHEMA_VERSION, WAVE_FIELDS_V1,
                                    validate_event)

    v1_wave = {"type": "wave", "schema_version": 1, "engine": "classic",
               "run": "x", "wave": 0, "t": 1.0, "states": 1, "unique": 1,
               "bucket": 64, "waves": 1, "inflight": 0, "compiled": False,
               "successors": 0, "candidates": 0, "novel": 0,
               "out_rows": None, "capacity": 4096, "load_factor": 0.1,
               "overflow": False}
    assert set(v1_wave) == set(WAVE_FIELDS_V1)
    assert validate_event(v1_wave) == []
    # A v1 wave with v2 riders is NOT valid — additions go through a
    # version bump.
    bad = dict(v1_wave, bytes_per_state=8)
    assert any("unexpected" in e for e in validate_event(bad))
    newer = dict(v1_wave, schema_version=SCHEMA_VERSION + 1)
    errs = validate_event(newer)
    assert len(errs) == 1 and "newer than this validator" in errs[0]


def test_profiling_breakdown_stages_pack_codec():
    """The staged breakdown attributes pack/unpack as first-class
    stages and the codec stays a small share of the staged wave (the
    <5%-of-wave-time amortization proof runs on real hardware; on the
    CPU backend we gate that the stages exist and are sane)."""
    from stateright_tpu.tpu.profiling import measure_wave_breakdown

    bd = measure_wave_breakdown(TwoPhaseSys(4), batch_size=64,
                                table_capacity=1 << 14, max_waves=4)
    assert "unpack" in bd["stages_sec"] and "pack" in bd["stages_sec"]
    assert bd["waves"] >= 1
    # The codec must not dominate: well under half the staged total
    # even on the CPU backend (the real gate is the hardware A/B).
    codec = bd["stages_share"]["unpack"] + bd["stages_share"]["pack"]
    assert codec < 0.5, bd["stages_share"]

"""Matmul-form frontier expansion differential suite (ISSUE 15).

The transition-structure compiler (``tpu/matmul_wave.py``) lowers a
*regular* model's successor generation to one dense product per key
group; everything it knows comes from probing the model's own jitted
``step``, so the only correctness claim that matters is bit-identity
with the vmapped path — pinned here three ways: (1) seeded random
in-domain frontiers through ``matmul_expand`` vs ``expand_frontier``
for every regular corpus model, (2) the knob-on/off engine matrix
(counts, discoveries, parent maps, checkpoint payload bytes) on all
four device engines including the megakernel composition, and (3) the
capability gate — every corpus model classifies deterministically with
a stable reason string, and an irregular model with the knob on warns
once and runs the step path with identical results.

Tier-1 budget: the fused/classic engine pair is the fast gate; the
sharded pair (shard_map interpret compiles) rides ``-m slow``.
"""

import os
import sys
import warnings

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "examples"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.tpu import engine as eng  # noqa: E402
from stateright_tpu.tpu.engine import expand_frontier  # noqa: E402
from stateright_tpu.tpu.matmul_wave import (  # noqa: E402
    KEY_DOMAIN_CAP, LANE_DOMAIN_CAP, classify, matmul_expand, plan_bytes)
from stateright_tpu.tpu.packing import compile_layout  # noqa: E402


def _spawn(model, engine, B, **kwargs):
    b = model.checker()
    if engine == "fused":
        return b.spawn_tpu_bfs(batch_size=B, fused=True, **kwargs)
    if engine == "classic":
        return b.spawn_tpu_bfs(batch_size=B, fused=False, **kwargs)
    if engine == "sharded-fused":
        return b.spawn_tpu_bfs(batch_size=B, sharded=True, **kwargs)
    assert engine == "sharded-classic"
    return b.spawn_tpu_bfs(batch_size=B, sharded=True, fused=False,
                           **kwargs)


def _ckpt_payload(path):
    """Every npz member's raw bytes (member-wise, not whole-file: the
    zip container embeds timestamps; the PAYLOAD is what must match)."""
    with np.load(path) as data:
        return {k: data[k].tobytes() for k in sorted(data.files)}


def _random_frontier(rng, dm, n):
    """``n`` uniform in-domain state rows (uint32 [n, W]) straight from
    the model's declared lane domains — the fuzz inputs are *arbitrary*
    in-domain vectors, not only reachable states, so the tables must be
    right everywhere the contract says they are."""
    layout = compile_layout(dm.lane_bits(), dm.state_width)
    cols = [rng.integers(0, 1 << lane.bits, size=n, dtype=np.uint32)
            for lane in layout.lanes]
    return np.stack(cols, axis=1)


# -- The compiler: corpus classification pins ------------------------------

#: Every corpus model's verdict at the registry defaults — the gate is
#: part of the public surface (scheduler_stats()["wave_matmul"]
#: .reason), so these strings are pinned, not pattern-matched. A model
#: change that flips one is a contract change and must edit this table.
CORPUS_REASONS = {
    "abd": "sentinel lane domains",
    "increment": "regular (6 key groups, 816 macs/row)",
    "increment_lock": "regular (9 key groups, 1776 macs/row)",
    "paxos": "sentinel lane domains",
    "pingpong": "undeclared lane_bits",
    "single_copy": "sentinel lane domains",
    "sliding_puzzle": "undeclared lane_bits",
    "twopc": "regular (8 key groups, 1640 macs/row)",
    "vsr": "undeclared lane_bits",
}


def test_corpus_classification_is_pinned():
    from stateright_tpu.service import default_registry

    r = default_registry()
    assert set(CORPUS_REASONS) == set(r.names())
    for name in r.names():
        model, _ = r.build(name)
        cls = classify(model.device_model())
        assert cls.reason == CORPUS_REASONS[name], name
        assert cls.regular == cls.reason.startswith("regular"), name
        assert (cls.plan is not None) == cls.regular, name


def test_classification_is_memoized_by_native_form():
    """Probing costs thousands of step evaluations; engines classify at
    spawn time, so same canonical model form -> the same plan object."""
    a = classify(TwoPhaseSys(3).device_model())
    b = classify(TwoPhaseSys(3).device_model())
    assert a is b
    assert a.plan is not None


def test_plan_shape_and_bytes_accounting():
    """The VMEM term the megakernel gate budgets: the widest one-hot
    block at the batch plus every resident table, and 0 for no plan."""
    plan = classify(TwoPhaseSys(3).device_model()).plan
    assert plan.matmul_ops == sum(g.domain * g.table.shape[1]
                                  for g in plan.groups)
    assert plan.table_bytes == sum(g.table.nbytes for g in plan.groups)
    for g in plan.groups:
        assert g.domain <= KEY_DOMAIN_CAP
        assert all((1 << 0) <= g.domain <= LANE_DOMAIN_CAP ** len(g.keys)
                   for _ in g.keys)
        # Tabulated entries are exact f32 integers below 2^16 — the
        # invariant the uint32 reconstruction leans on.
        assert float(np.abs(plan.groups[0].table).max()) < (1 << 16)
    widest = max(g.domain for g in plan.groups)
    assert plan_bytes(plan, 64) == 4 * 64 * widest + plan.table_bytes
    assert plan_bytes(None, 64) == 0


# -- The compiler: differential fuzz ---------------------------------------

@pytest.mark.parametrize("make", [
    pytest.param(lambda: TwoPhaseSys(3), id="twopc3"),
    pytest.param(lambda: TwoPhaseSys(4), id="twopc4"),
    pytest.param(lambda: __import__("increment").IncrementModel(2),
                 id="increment2"),
    pytest.param(
        lambda: __import__("increment_lock").IncrementLockModel(2),
        id="increment_lock2"),
])
def test_matmul_expand_matches_step_on_random_frontiers(make):
    """Seeded random in-domain frontiers: every return of
    ``matmul_expand`` — successor rows, validity, count, terminal mask
    — bit-identical to the vmapped ``step`` path."""
    model = make()
    dm = model.device_model()
    cls = classify(dm)
    assert cls.regular, cls.reason
    B = 64
    j_ref = jax.jit(lambda v, m: expand_frontier(dm, v, m))
    j_mm = jax.jit(lambda v, m: matmul_expand(dm, cls.plan, v, m))
    for seed in range(5):
        rng = np.random.default_rng(seed)
        vecs = jnp.asarray(_random_frontier(rng, dm, B))
        valid = jnp.asarray(rng.random(B) < 0.9)
        ref, mm = j_ref(vecs, valid), j_mm(vecs, valid)
        for i, (a, b) in enumerate(zip(ref, mm)):
            a, b = np.asarray(a), np.asarray(b)
            if i == 0:  # successor rows: garbage where invalid
                keep = np.asarray(ref[1])
                assert np.array_equal(a[keep], b[keep]), (seed, i)
            else:
                assert np.array_equal(a, b), (seed, i)


@pytest.mark.slow
@pytest.mark.parametrize("make", [
    pytest.param(lambda: TwoPhaseSys(5), id="twopc5"),
    pytest.param(lambda: __import__("increment").IncrementModel(3),
                 id="increment3"),
    pytest.param(
        lambda: __import__("increment_lock").IncrementLockModel(3),
        id="increment_lock3"),
])
def test_matmul_expand_fuzz_wide(make):
    """The slow-tier arm: bigger configs, 30 seeds."""
    model = make()
    dm = model.device_model()
    cls = classify(dm)
    assert cls.regular, cls.reason
    B = 128
    j_ref = jax.jit(lambda v, m: expand_frontier(dm, v, m))
    j_mm = jax.jit(lambda v, m: matmul_expand(dm, cls.plan, v, m))
    for seed in range(30):
        rng = np.random.default_rng(seed)
        vecs = jnp.asarray(_random_frontier(rng, dm, B))
        valid = jnp.asarray(rng.random(B) < 0.9)
        ref, mm = j_ref(vecs, valid), j_mm(vecs, valid)
        keep = np.asarray(ref[1])
        assert np.array_equal(np.asarray(ref[0])[keep],
                              np.asarray(mm[0])[keep]), seed
        for a, b in zip(ref[1:], mm[1:]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), seed


# -- Engine-level parity matrix --------------------------------------------

@pytest.mark.parametrize("engine", [
    "fused", "classic",
    pytest.param("sharded-fused", marks=pytest.mark.slow),
    pytest.param("sharded-classic", marks=pytest.mark.slow)])
def test_wave_matmul_bit_identical_2pc(engine, tmp_path):
    """ISSUE 15 acceptance: wave_matmul on vs off — counts,
    discoveries, parent maps, and checkpoint payload bytes bit-identical
    on all four engines; attribution records the executed path."""
    model = TwoPhaseSys(3)
    runs = {}
    for on in (True, False):
        path = str(tmp_path / f"{engine}-{on}.npz")
        c = _spawn(model, engine, 48, checkpoint_path=path,
                   wave_matmul=on).join()
        runs[on] = (c.unique_state_count(), c.state_count(),
                    set(c.discoveries()), dict(c._parent_map()),
                    _ckpt_payload(path))
        wm = c.scheduler_stats()["wave_matmul"]
        assert wm["enabled"] is on
        assert wm["active"] is on
        assert wm["expand_impl"] == ("matmul" if on else "step")
        if on:
            assert wm["reason"] == CORPUS_REASONS["twopc"]
            assert wm["matmul_ops"] == 1640
            assert c.kernel_path().endswith("+matmul")
            assert all(e["expand_impl"] == "matmul"
                       for e in c.dispatch_log)
        else:
            assert not c.kernel_path().endswith("+matmul")
    assert runs[True][:4] == runs[False][:4], engine
    assert runs[True][4] == runs[False][4], \
        f"{engine}: checkpoint payload bytes differ with wave_matmul on"


def test_wave_matmul_composes_with_megakernel(tmp_path):
    """Both knobs on: the matmul expand runs INSIDE the single-kernel
    wave (tables ride as pallas operands) and attribution carries both
    axes — still bit-identical to both knobs off."""
    from stateright_tpu.tpu.pallas_table import PALLAS_AVAILABLE

    if not PALLAS_AVAILABLE:
        pytest.skip("pallas not available in this jax build")
    model = TwoPhaseSys(3)
    runs = {}
    for on in (True, False):
        path = str(tmp_path / f"mega-{on}.npz")
        c = _spawn(model, "classic", 48, checkpoint_path=path,
                   wave_kernel=on, wave_matmul=on).join()
        runs[on] = (c.unique_state_count(), c.state_count(),
                    set(c.discoveries()), dict(c._parent_map()),
                    _ckpt_payload(path))
        if on:
            assert c.kernel_path() == "interpret+matmul"
    assert runs[True] == runs[False]


@pytest.mark.slow
def test_wave_matmul_bit_identical_2pc5_fused():
    """A deeper regular workload (2pc @ 5 RMs) through the fused
    engine, knob on vs off (slow tier)."""
    model = TwoPhaseSys(5)
    runs = {}
    for on in (True, False):
        c = _spawn(model, "fused", 256, wave_matmul=on).join()
        runs[on] = (c.unique_state_count(), c.state_count(),
                    set(c.discoveries()), dict(c._parent_map()))
    assert runs[True] == runs[False]


# -- The capability gate ---------------------------------------------------

def test_irregular_model_gates_to_fallback():
    """Paxos with the knob on: one RuntimeWarning naming the reason,
    then the vmapped step path with counts identical to knob-off — a
    tenant flipping the knob on an irregular model must never see a
    different answer (or a crash)."""
    from paxos import PaxosModelCfg

    model = PaxosModelCfg(1, 3).into_model()
    eng._WAVE_MATMUL_GATE_WARNED.discard("PaxosDevice")
    with pytest.warns(RuntimeWarning, match="not matmul-regular "
                                            r"\(sentinel lane domains\)"):
        on = _spawn(model, "classic", 64, wave_matmul=True).join()
    wm = on.scheduler_stats()["wave_matmul"]
    assert wm == {"enabled": True, "active": False,
                  "expand_impl": "step",
                  "reason": "sentinel lane domains", "matmul_ops": 0}
    assert not on.kernel_path().endswith("+matmul")
    # Once per model type, not per spawn.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        again = _spawn(model, "classic", 64, wave_matmul=True).join()
    off = _spawn(model, "classic", 64, wave_matmul=False).join()
    assert on.unique_state_count() == off.unique_state_count() \
        == again.unique_state_count()
    assert on.state_count() == off.state_count()
    assert set(on.discoveries()) == set(off.discoveries())


def test_ad_hoc_model_without_lane_bits_is_irregular():
    class Anon:
        state_width, max_fanout = 2, 2

    cls = classify(Anon())
    assert (cls.regular, cls.plan) == (False, None)
    assert cls.reason == "undeclared lane_bits"


def test_env_knob_resolution(monkeypatch):
    """wave_matmul=None follows STpu_WAVE_MATMUL; explicit kwargs win.
    The resolved activation is part of the shared program-cache key."""
    model = TwoPhaseSys(2)
    monkeypatch.setenv("STpu_WAVE_MATMUL", "1")
    c = model.checker().spawn_tpu_bfs(batch_size=16, fused=False).join()
    assert c._wave_matmul_on is True
    assert c._matmul_plan is not None
    monkeypatch.setenv("STpu_WAVE_MATMUL", "0")
    c = model.checker().spawn_tpu_bfs(batch_size=16, fused=False).join()
    assert c._wave_matmul_on is False
    assert c._matmul_plan is None
    monkeypatch.setenv("STpu_WAVE_MATMUL", "1")
    c = model.checker().spawn_tpu_bfs(batch_size=16, fused=False,
                                      wave_matmul=False).join()
    assert c._wave_matmul_on is False


# -- Observability and service surface -------------------------------------

def test_wave_events_carry_expand_impl(tmp_path):
    """Wave events gain the v12 key: expand_impl names the executed
    expansion; the traced stream schema-validates line by line and the
    matmul_ops gauge lands once at run start."""
    import json

    from stateright_tpu.obs.schema import validate_line

    trace = str(tmp_path / "trace.jsonl")
    model = TwoPhaseSys(3)
    c = _spawn(model, "fused", 48, wave_matmul=True,
               trace_path=trace).join()
    waves, gauges = 0, []
    with open(trace) as f:
        for line in f:
            assert validate_line(line) == [], line
            evt = json.loads(line)
            if evt.get("type") == "wave":
                waves += 1
                assert evt["expand_impl"] == "matmul"
                assert evt["kernel_path"].endswith("+matmul")
            if evt.get("type") == "gauge" and \
                    evt.get("name") == "matmul_ops":
                gauges.append(evt["value"])
    assert waves == len(c.dispatch_log)
    assert gauges == [1640.0]


def test_schema_v11_field_map_excludes_v12_keys():
    """A v11 wave with the v12 rider is NOT valid, and a v12 wave
    missing it is NOT valid — additions go through the version bump,
    one schema per version."""
    from stateright_tpu.obs.schema import (WAVE_FIELDS, WAVE_FIELDS_V11,
                                           validate_event)

    assert "expand_impl" not in WAVE_FIELDS_V11
    assert "expand_impl" in WAVE_FIELDS
    base = {"type": "wave", "schema_version": 11, "engine": "classic",
            "run": "x", "wave": 0, "t": 1.0}
    for k in WAVE_FIELDS_V11:
        base.setdefault(k, None)
    base.update(states=1, unique=1, bucket=4, waves=1, inflight=0,
                compiled=False, successors=0, candidates=0, novel=0,
                overflow=False)
    assert validate_event(base) == []
    bad = dict(base, expand_impl="matmul")
    assert any("unexpected" in e for e in validate_event(bad))
    v12 = dict(base, schema_version=12)
    assert any("missing field 'expand_impl'" in e
               for e in validate_event(v12))
    assert validate_event(dict(v12, expand_impl=None)) == []


def test_service_allowlists_wave_matmul_knob():
    """Tenants may A/B the knob through the job API; unknown knobs
    still 400."""
    from stateright_tpu.service.jobs import _KNOBS

    assert _KNOBS.get("wave_matmul") is bool


def test_profiling_times_matmul_expand_for_regular_model():
    """The first-class profiling stage: nonzero on a regular model
    (the irregular-model zero is pinned in test_profiling.py)."""
    from stateright_tpu.tpu.profiling import measure_wave_breakdown

    out = measure_wave_breakdown(TwoPhaseSys(3), batch_size=64,
                                 max_waves=3, table_capacity=1 << 14)
    assert out["stages_sec"]["matmul_expand"] > 0

"""Tiered state store suite (round 13): device arena -> host RAM ->
disk segments.

Covers the correctness contract (a run with a device tier capped far
below the state-space size completes with totals, discoveries, and
final checkpoint content bit-identical to an uncapped run, on every
engine and the elastic runtime), the cold-segment-IS-a-checkpoint
layout, the torn-segment rotation fallback, checkpoint format v5
cold refs (resume with AND without a store on the resume side, plus a
fresh-process arm), the obs schema v6 spill/page_in/pressure stream
(e2e lint + unit-level invariant violations), and the live
``/.metrics`` tier families.

Every fast arm uses tiny caps (<=1 MiB device budgets on 2pc); the
large-spill arms (paxos 16,668, the sharded-fused arena-span drill)
ride the ``slow`` set.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.checkpoint_format import (content_hash,  # noqa: E402
                                              load_checkpoint,
                                              verify_file)
from stateright_tpu.resilience import (FAULTS_ENV,  # noqa: E402
                                       reset_fault_plans)
from stateright_tpu.store.tiered import (NULL_STORE,  # noqa: E402
                                         TieredStore, _parse_bytes,
                                         map_segment_visited,
                                         store_from_config)

#: Engine knob sets that provably exercise the store on a 2pc(4)
#: space (1,568 unique / 8,258 total): the classic engines evict
#: visited partitions when growth would exceed ``tier_device_bytes``;
#: the fused engine spills expanded arena spans to the host parent
#: log. Budgets are far under 1 MiB, keeping the arms fast.
TIER_CFGS = {
    "classic": dict(fused=False, batch_size=32, table_capacity=4096,
                    tier_device_bytes=4096 * 8, tier_host_bytes=4096),
    "fused": dict(batch_size=32, table_capacity=4096,
                  arena_capacity=1024, tier_device_bytes=100_000,
                  tier_host_bytes=1 << 20),
    "sharded-classic": dict(sharded=True, fused=False, batch_size=32,
                            table_capacity=2048,
                            tier_device_bytes=2048 * 8 * 8,
                            tier_host_bytes=4096),
    "sharded-fused": dict(sharded=True, batch_size=32,
                          table_capacity=2048, arena_capacity=256,
                          tier_device_bytes=300_000,
                          tier_host_bytes=1 << 20),
}

_CLEAN: dict = {}


def _base_kwargs(engine):
    cfg = {k: v for k, v in TIER_CFGS[engine].items()
           if not k.startswith("tier_") and k != "arena_capacity"}
    return cfg


def _totals(c):
    return (c.state_count(), c.unique_state_count(),
            tuple(sorted(c.discoveries())))


def _clean(engine, rms=4):
    key = (engine, rms)
    if key not in _CLEAN:
        _CLEAN[key] = _totals(TwoPhaseSys(rms).checker().spawn_tpu_bfs(
            **_base_kwargs(engine)).join())
    return _CLEAN[key]


def _capped(engine, tmp_path, rms=4, **extra):
    cfg = dict(TIER_CFGS[engine])
    cfg.update(extra)
    return TwoPhaseSys(rms).checker().spawn_tpu_bfs(
        tier_dir=str(tmp_path), **cfg)


# -- Store units (no engine) ----------------------------------------------

def test_parse_bytes_and_factory(tmp_path, monkeypatch):
    assert _parse_bytes("4096") == 4096
    assert _parse_bytes("64k") == 64 * 1024
    assert _parse_bytes("1.5MiB") == (3 << 20) // 2
    assert _parse_bytes("2g") == 2 << 30
    assert _parse_bytes(None) is None
    assert _parse_bytes("0") is None
    # Nothing configured -> the shared disarmed store.
    for var in ("STpu_TIER_DEVICE_BYTES", "STpu_TIER_HOST_BYTES",
                "STpu_TIER_DIR"):
        monkeypatch.delenv(var, raising=False)
    assert store_from_config() is NULL_STORE
    assert not NULL_STORE.active
    assert NULL_STORE.stats() == {"enabled": False}
    # Any knob arms it; explicit kwargs beat the environment.
    monkeypatch.setenv("STpu_TIER_HOST_BYTES", "64k")
    s = store_from_config(segment_dir=str(tmp_path))
    assert s.active and s.host_budget == 64 * 1024
    assert s.segment_dir == str(tmp_path)


def test_spill_mask_takes_whole_partitions_round_robin():
    s = TieredStore(n_partitions=4)
    fps = np.arange(64, dtype=np.uint64)
    mask = s.spill_mask(fps, lambda keep: len(keep) <= 48)
    # Exactly one whole fp%4 partition evicted (16 rows covers it).
    assert mask.sum() == 16
    assert len(set(int(f) % 4 for f in fps[mask])) == 1
    # never-enough evicts everything, in deterministic order.
    s2 = TieredStore(n_partitions=4)
    assert s2.spill_mask(fps, lambda keep: False).all()


def test_cold_segment_is_a_checkpoint_shard(tmp_path):
    s = TieredStore(host_budget=64, segment_dir=str(tmp_path),
                    n_partitions=2,
                    meta={"model_name": "M", "state_width": 3,
                          "use_symmetry": False})
    fps = np.arange(0, 100, 2, dtype=np.uint64)  # one partition
    s.spill_visited(fps)
    st = s.stats()
    assert st["disk"]["rows"] == 50 and st["disk"]["segments"] == 1
    (part,) = s._cold.values()
    # The segment file passes full checkpoint verification and its
    # header self-describes the partition + content hash.
    verify_file(part.path)
    with load_checkpoint(part.path) as data:
        header = json.loads(bytes(np.asarray(data["header"])))
    assert header["version"] >= 5
    assert header["store_segment"]["rows"] == 50
    assert header["store_segment"]["sha"] == part.sha
    # The memmap fast path reads the exact fingerprints back.
    got = np.asarray(map_segment_visited(part.path))
    assert np.array_equal(got, np.unique(fps))
    assert content_hash(got) == part.sha
    # Membership: every spilled row answers True, others False.
    assert s.probe(fps).all()
    assert not s.probe(np.arange(1, 99, 2, dtype=np.uint64)).any()


def test_torn_cold_segment_falls_back_no_loss(tmp_path):
    """An injected ``page_in_torn`` at the cold write truncates the
    landed segment; the store's immediate CRC re-verify catches it,
    restores the rotation predecessor, and keeps the pushed rows warm
    — no fingerprint is ever lost, and the next budget pass lands a
    fresh generation."""
    s = TieredStore(host_budget=64, segment_dir=str(tmp_path),
                    n_partitions=2)
    gen1 = np.arange(0, 100, 2, dtype=np.uint64)
    s.spill_visited(gen1)
    assert s.stats()["disk"]["rows"] == 50
    os.environ[FAULTS_ENV] = "page_in_torn@n=1"
    reset_fault_plans()
    try:
        s.spill_visited(np.arange(100, 200, 2, dtype=np.uint64))
    finally:
        del os.environ[FAULTS_ENV]
        reset_fault_plans()
    # Every fingerprint of both generations still answers membership.
    assert s.probe(np.arange(0, 200, 2, dtype=np.uint64)).all()
    # The retry after the fallback landed the full union cold.
    assert s.stats()["disk"]["rows"] == 100


def test_checkpoint_refs_keep_inherited_segment_dirs(tmp_path):
    """A segment attached from a previous checkpoint may live outside
    the resuming store's tier_dir; the next checkpoint's refs must
    record its real home or a second-generation resume fails."""
    dir_a = tmp_path / "a"
    dir_b = tmp_path / "b"
    dir_b.mkdir()
    s1 = TieredStore(host_budget=64, segment_dir=str(dir_a),
                     n_partitions=2)
    fps = np.arange(0, 100, 2, dtype=np.uint64)
    s1.spill_visited(fps)
    refs1 = s1.checkpoint_refs()
    # Resume under a DIFFERENT tier_dir: segments stay in dir_a.
    s2 = TieredStore(segment_dir=str(dir_b), n_partitions=2)
    assert s2.attach_refs(refs1) == 50
    refs2 = s2.checkpoint_refs()
    assert refs2["segment_dir"] == str(dir_b)
    assert all(r["dir"] == str(dir_a) for r in refs2["cold"])
    # Generation 3 resolves through the per-ref home.
    s3 = TieredStore(segment_dir=str(dir_b), n_partitions=2)
    assert s3.attach_refs(refs2) == 50
    assert s3.probe(fps).all()


def test_attach_refs_falls_back_to_rotation_predecessor(tmp_path):
    s = TieredStore(host_budget=64, segment_dir=str(tmp_path),
                    n_partitions=2)
    s.spill_visited(np.arange(0, 100, 2, dtype=np.uint64))
    refs = s.checkpoint_refs()
    assert refs is not None and len(refs["cold"]) == 1
    (part,) = s._cold.values()
    # Age the current generation to .prev, then tear the current file:
    # resume must find the referenced hash in the predecessor.
    import shutil

    shutil.copy(part.path, part.path + ".prev")
    with open(part.path, "r+b") as f:
        f.truncate(64)
    fresh = TieredStore(segment_dir=str(tmp_path), n_partitions=2)
    assert fresh.attach_refs(refs) == 50
    assert fresh.probe(np.arange(0, 100, 2, dtype=np.uint64)).all()
    # A reference no generation satisfies is a clear error.
    bad = {"segment_dir": str(tmp_path),
           "cold": [{"partition": 0, "file": "missing.npz",
                     "sha": "0" * 16, "rows": 1}]}
    with pytest.raises(ValueError, match="missing or corrupt"):
        fresh.attach_refs(bad)


def test_lint_v6_invariant_units():
    from trace_lint import lint_lines

    def wave(run="r1", seq=0, engine="classic", **over):
        base = {"type": "wave", "schema_version": 6, "engine": engine,
                "run": run, "t": 0.1, "wave": seq, "states": 10,
                "unique": 5, "bucket": 8, "waves": 1, "inflight": 0,
                "compiled": False, "successors": 9, "candidates": 9,
                "novel": 5, "out_rows": 5, "capacity": 16,
                "load_factor": 0.3, "overflow": False,
                "bytes_per_state": 8, "arena_bytes": None,
                "table_bytes": 128, "worker": None, "seq": None,
                "epoch": None, "round": None, "tier_device_rows": 5,
                "tier_device_bytes": 128, "tier_host_rows": 0,
                "tier_host_bytes": 0, "tier_disk_rows": None,
                "tier_disk_bytes": None}
        base.update(over)
        return json.dumps(base)

    def evt(etype, run="r1", **fields):
        base = {"type": etype, "schema_version": 6, "engine": "classic",
                "run": run, "t": 0.2}
        base.update(fields)
        return json.dumps(base)

    spill = dict(tier="disk", kind="frontier", rows=4, bytes=64)
    # A frontier spill with no page_in and no run end = lost work.
    _, errors = lint_lines([wave(), evt("spill", **spill)])
    assert any("never followed by a page_in" in e for e in errors)
    # ... resolved by a page_in,
    _, errors = lint_lines([
        wave(), evt("spill", **spill),
        evt("page_in", tier="disk", kind="frontier", rows=4, bytes=64)])
    assert not errors
    # ... or by the producing run ending.
    _, errors = lint_lines([
        wave(), evt("spill", **spill),
        evt("run_end", states=10, unique=5, dur=0.1, counters={})])
    assert not errors
    # Tier byte gauges shrinking without a pressure reset = truncated
    # or reordered stream; with the marker it lints clean.
    shrink = wave(seq=1, tier_host_bytes=512)
    _, errors = lint_lines([wave(tier_host_bytes=1024), shrink])
    assert any("tier_host_bytes went backwards" in e for e in errors)
    _, errors = lint_lines([
        wave(tier_host_bytes=1024),
        evt("pressure", tier="host", used=512, budget=256), shrink])
    assert not errors
    # v6 withdraws the host-engine null allowance for occupancy gauges.
    _, errors = lint_lines([wave(engine="host_bfs", capacity=None)])
    assert any("host store occupancy gauges are required" in e
               for e in errors)
    # ... but v5 captures still lint under their own (null-ok) rules.
    v5 = json.loads(wave(engine="host_bfs", capacity=None))
    v5["schema_version"] = 5
    for k in ("tier_device_rows", "tier_device_bytes", "tier_host_rows",
              "tier_host_bytes", "tier_disk_rows", "tier_disk_bytes"):
        del v5[k]
    _, errors = lint_lines([json.dumps(v5)])
    assert not errors


# -- Engine parity under memory pressure ----------------------------------

def test_classic_capped_parity_spills_all_tiers(tmp_path, monkeypatch):
    """The headline drill: a classic run whose device table is capped
    below the space evicts visited partitions warm, pushes them cold
    under host pressure, and still finishes bit-identical — with the
    whole degradation story observable (trace events, store stats,
    live /.metrics)."""
    trace = tmp_path / "spill.trace.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(trace))
    c = _capped("classic", tmp_path)
    c.join()
    monkeypatch.delenv("STpu_TRACE")
    assert _totals(c) == _clean("classic")
    st = c.scheduler_stats()["store"]
    assert st["spills"]["host"] > 0 and st["disk"]["segments"] > 0
    assert st["probes"] > 0 and st["probe_hits"] > 0
    assert 0 < st["resident_ratio"] < 1
    # scheduler_stats()["store"] IS the store stats block.
    assert st["device"]["budget"] == TIER_CFGS["classic"][
        "tier_device_bytes"]
    events = [json.loads(line) for line in trace.open()]
    spills = [e for e in events if e["type"] == "spill"]
    assert {e["tier"] for e in spills} >= {"host", "disk"}
    assert any(e["type"] == "pressure" for e in events)
    # Wave events carry the v6 per-tier gauges while the store is hot.
    waves = [e for e in events if e["type"] == "wave"]
    assert any(isinstance(e.get("tier_host_rows"), int)
               and e["tier_host_rows"] > 0 for e in waves)
    # The whole capture lints clean (spill/page_in pairing included).
    from trace_lint import lint_lines

    with trace.open() as f:
        _, errors = lint_lines(f)
    assert not errors, errors[:5]
    # Live Prometheus families off the same engine.
    from stateright_tpu.explorer import Explorer

    text = Explorer(c).metrics()
    assert "stpu_tier_rows" in text and "stpu_tier_bytes" in text
    assert "stpu_tier_spills_total" in text
    assert "stpu_tier_resident_ratio" in text


def test_fused_arena_span_parity(tmp_path):
    c = _capped("fused", tmp_path)
    c.join()
    assert _totals(c) == _clean("fused")
    st = c.scheduler_stats()["store"]
    assert st["arena_spans"]["spills"] > 0
    assert st["arena_spans"]["rows"] > 0


@pytest.mark.slow  # round-15 tier-1 budget: the classic capped-parity
# arm above is the fast representative; this sharded sibling and the
# sharded-fused arena-roll arm both ride slow.
def test_sharded_classic_capped_parity(tmp_path):
    c = _capped("sharded-classic", tmp_path)
    c.join()
    assert _totals(c) == _clean("sharded-classic")
    st = c.scheduler_stats()["store"]
    assert st["spills"]["host"] > 0
    assert st["probes"] > 0


def test_sharded_fused_capped_completes(tmp_path):
    """Fast arm: on 2pc(4) the sharded-fused arena floor (sized for
    one full dispatch's fan-out) never refills, so the budget records
    device pressure and the run completes bit-identical. The arm that
    provably fires the per-shard span spill needs a bigger space and
    rides the slow set."""
    c = _capped("sharded-fused", tmp_path)
    c.join()
    assert _totals(c) == _clean("sharded-fused")


@pytest.mark.slow
def test_sharded_fused_arena_span_parity_slow(tmp_path):
    """2pc(6) (50,816 unique / 402,306 total) with a 512-row per-shard
    arena under a 300 KB device budget: the per-shard roll fires (every
    shard's live window re-based by its own head) and totals stay
    bit-identical — pinned against the novel-count re-base regression
    (tails move down, so the tails-sum baseline must move with them)."""
    base = TwoPhaseSys(6).checker().spawn_tpu_bfs(
        sharded=True, batch_size=8, table_capacity=4096).join()
    c = TwoPhaseSys(6).checker().spawn_tpu_bfs(
        sharded=True, batch_size=8, table_capacity=4096,
        arena_capacity=512, tier_device_bytes=300_000,
        tier_host_bytes=1 << 20, tier_dir=str(tmp_path))
    c.join()
    assert _totals(c) == _totals(base)
    assert c.scheduler_stats()["store"]["arena_spans"]["spills"] > 0


# -- Cross-tier checkpoint / resume matrix --------------------------------

def _spilled_checkpoint(tmp_path):
    """A mid-run checkpoint of a PROVABLY spilled classic run (cold
    segments on disk, v5 cold refs in the header)."""
    ckpt = str(tmp_path / "spilled.ckpt.npz")
    c = (TwoPhaseSys(4).checker().target_state_count(5000)
         .spawn_tpu_bfs(tier_dir=str(tmp_path),
                        checkpoint_path=ckpt, checkpoint_every_waves=4,
                        **TIER_CFGS["classic"]))
    c.join()
    st = c.scheduler_stats()["store"]
    assert st["spills"]["host"] > 0 and st["disk"]["segments"] > 0
    c.checkpoint(ckpt)
    with load_checkpoint(ckpt) as data:
        header = json.loads(bytes(np.asarray(data["header"])))
    assert header["version"] == 5
    assert len(header["store"]["cold"]) == st["disk"]["segments"]
    return ckpt


def _final_visited(checker, tmp_path, name):
    """The run's final checkpoint's LOGICAL visited set (cold refs
    materialized) — the payload the parity matrix pins."""
    from stateright_tpu.store.tiered import load_cold_refs

    path = str(tmp_path / f"{name}.final.npz")
    checker.checkpoint(path)
    with load_checkpoint(path) as data:
        header = json.loads(bytes(np.asarray(data["header"])))
        visited = np.asarray(data["visited"], np.uint64)
    refs = header.get("store")
    if refs:
        visited = np.concatenate([visited, load_cold_refs(refs)])
    # np.unique, not sort: a spilled fingerprint that was re-generated
    # is re-admitted to the device tier by design, so the hot section
    # and a cold segment can both carry it — the LOGICAL set is the
    # payload under test.
    return (header["state_count"], header["unique_count"],
            np.unique(visited))


def test_spilled_checkpoint_resume_matrix(tmp_path):
    """Spill mid-run, checkpoint, resume — with a store (cold segments
    re-attach by content hash; only hot+warm bytes moved) and without
    one (cold refs materialize into the device tier) — and pin totals,
    discoveries, and the FINAL checkpoint's visited payload
    bit-identical to an unspilled run."""
    want = _clean("classic")
    ckpt = _spilled_checkpoint(tmp_path)
    clean_engine = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        **_base_kwargs("classic")).join()
    want_payload = _final_visited(clean_engine, tmp_path, "clean")

    resumed = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        resume_from=ckpt, tier_dir=str(tmp_path),
        **TIER_CFGS["classic"])
    resumed.join()
    assert _totals(resumed) == want
    got = _final_visited(resumed, tmp_path, "spilled")
    assert got[0] == want_payload[0] and got[1] == want_payload[1]
    assert np.array_equal(got[2], want_payload[2])

    storeless = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        resume_from=ckpt, **_base_kwargs("classic"))
    storeless.join()
    assert _totals(storeless) == want


@pytest.mark.slow  # round-15 tier-1 budget: the in-process resume
# matrix above is the fast representative of the v5 resume surface.
def test_spilled_resume_in_fresh_process(tmp_path):
    """The checkpoint/resume matrix's fresh-process arm: a different
    interpreter (no shared jit caches, no store object) resumes the
    spilled checkpoint and reaches the exact totals."""
    want = _clean("classic")
    ckpt = _spilled_checkpoint(tmp_path)
    cfg = TIER_CFGS["classic"]
    script = f"""
import sys
sys.path.insert(0, {os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")!r})
from two_phase_commit import TwoPhaseSys
c = TwoPhaseSys(4).checker().spawn_tpu_bfs(
    resume_from={ckpt!r}, tier_dir={str(tmp_path)!r}, **{cfg!r})
c.join()
print("TOTALS", c.state_count(), c.unique_state_count(),
      sorted(c.discoveries()))
"""
    env = dict(os.environ)
    env.pop("STpu_TRACE", None)
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("TOTALS")][0]
    assert line == (f"TOTALS {want[0]} {want[1]} {list(want[2])}")


@pytest.mark.slow
def test_paxos_capped_parity_slow(tmp_path):
    """The north-star workload under memory pressure: paxos(2,3) with
    the device table capped below its 16,668-state space completes to
    the exact full space with real spill traffic."""
    from paxos import PaxosModelCfg

    model = PaxosModelCfg(2, 3).into_model()
    c = model.checker().spawn_tpu_bfs(
        fused=False, batch_size=64, table_capacity=8192,
        tier_device_bytes=8192 * 8, tier_host_bytes=64 * 1024,
        tier_dir=str(tmp_path))
    c.join()
    assert c.unique_state_count() == 16668
    assert c.state_count() == 32971
    assert set(c.discoveries()) == {"value chosen"}
    assert c.scheduler_stats()["store"]["spill_bytes"] > 0


# -- Elastic runtime -------------------------------------------------------

def test_elastic_tier_parity(tmp_path, monkeypatch):
    """Elastic workers under a host-RAM budget spill whole partitions'
    visited sets into the store (warm -> cold) and the coordinated run
    stays bit-identical; the coordinator aggregates per-worker store
    summaries off the wave replies."""
    from functools import partial

    from stateright_tpu.resilience.elastic import ElasticChecker

    base = ElasticChecker(partial(TwoPhaseSys, 3), workers=2,
                          n_partitions=8, batch_rows=64,
                          transport="thread").join()
    monkeypatch.setenv("STpu_TIER_HOST_BYTES", "256")
    monkeypatch.setenv("STpu_TIER_DIR", str(tmp_path))
    c = ElasticChecker(partial(TwoPhaseSys, 3), workers=2,
                       n_partitions=8, batch_rows=64,
                       transport="thread").join()
    assert _totals(c) == _totals(base)
    st = c.scheduler_stats()["store"]
    assert st["enabled"] and st["spilled_rows"] > 0
    assert any(w["spilled_rows"] > 0 for w in st["workers"].values())


def test_elastic_tier_migration_prunes_casualty_store(tmp_path,
                                                      monkeypatch):
    """A killed worker's tier summary must not keep feeding the
    coordinator's store aggregate after migration rebuilds its
    partitions into survivors (stale spill counts would drive the
    coordinator's tier_host gauges negative)."""
    from functools import partial

    from stateright_tpu.resilience.elastic import ElasticChecker

    base = ElasticChecker(partial(TwoPhaseSys, 3), workers=2,
                          n_partitions=8, batch_rows=64,
                          transport="thread").join()
    monkeypatch.setenv("STpu_TIER_HOST_BYTES", "256")
    monkeypatch.setenv("STpu_TIER_DIR", str(tmp_path))
    c = ElasticChecker(partial(TwoPhaseSys, 3), workers=2,
                       n_partitions=8, batch_rows=64,
                       transport="thread",
                       checkpoint_path=str(tmp_path / "mig.npz"),
                       checkpoint_every_rounds=2,
                       kill_at={4: "w1"}).join()
    assert _totals(c) == _totals(base)
    st = c.scheduler_stats()["store"]
    assert set(st["workers"]) == {"w0"}, st["workers"]
    for evt in c.dispatch_log:
        for key in ("tier_host_rows", "tier_host_bytes"):
            val = evt.get(key)
            assert val is None or val >= 0, (key, val)

"""Elastic multi-worker sharding: migration/rebalance bit-identity,
membership + ownership units, per-shard checkpoint round-trips, and
the membership lint invariant.

The load-bearing suites are the bit-identity pins: a 2-worker elastic
run that LOSES a worker mid-run (migration: rollback to the newest
per-shard generation + rendezvous adoption + epoch bump) and a run
that GAINS a worker mid-run (rebalance at a drained barrier) must both
finish with totals — state count, unique count, discovery set, final
checkpoint payload — bit-identical to an unfaulted single-process
sharded run of the same model. The fast tier runs the in-process
(thread-transport) runtime on 2pc; the OS-process transport and the
paxos 16,668 matrix ride in ``-m slow`` (conftest budget guard).

Expensive runs are computed once at module scope and shared across the
assertions that read them (totals, lifecycle events, trace lint,
checkpoint payload), so the fast tier pays for each scenario once.
"""

import json
import os
import sys
from functools import partial

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.checkpoint_format import (CKPT_VERSION,  # noqa: E402
                                              load_checkpoint,
                                              make_header, shard_path,
                                              validate_header,
                                              verify_file, write_atomic)
from stateright_tpu.resilience import (ElasticChecker,  # noqa: E402
                                       Membership, OwnerMap,
                                       reset_fault_plans)

RMS = 3
WANT_STATES, WANT_UNIQUE = 1146, 288


def _totals(c):
    return (c.state_count(), c.unique_state_count(),
            tuple(sorted(c.discoveries())))


#: lazily-built shared runs: scenario -> (checker, ckpt_path, trace).
_RUNS: dict = {}


def _sharded_reference(tmp_root):
    if "sharded" not in _RUNS:
        ckpt = str(tmp_root / "sharded.npz")
        c = TwoPhaseSys(RMS).checker().spawn_tpu_bfs(
            batch_size=32, sharded=True, fused=False,
            checkpoint_path=ckpt).join()
        _RUNS["sharded"] = (c, ckpt, None)
    return _RUNS["sharded"]


def _elastic_run(tmp_root, scenario, **kwargs):
    if scenario not in _RUNS:
        ckpt = str(tmp_root / f"{scenario}.npz")
        trace = str(tmp_root / f"{scenario}.trace.jsonl")
        os.environ["STpu_TRACE"] = trace
        # Flight-recorder postmortems land beside the scenario's other
        # artifacts (worker_lost dumps are part of what the drills
        # assert).
        os.environ["STpu_FLIGHT_DIR"] = str(tmp_root)
        try:
            c = ElasticChecker(
                partial(TwoPhaseSys, RMS), workers=2, n_partitions=8,
                batch_rows=64, transport="thread",
                checkpoint_path=ckpt, checkpoint_every_rounds=2,
                **kwargs).join()
        finally:
            os.environ.pop("STpu_TRACE", None)
            os.environ.pop("STpu_FLIGHT_DIR", None)
        _RUNS[scenario] = (c, ckpt, trace)
    return _RUNS[scenario]


@pytest.fixture(scope="module")
def tmp_root(tmp_path_factory):
    return tmp_path_factory.mktemp("elastic")


# -- Bit-identity: clean / kill / join ------------------------------------

def test_elastic_clean_run_matches_single_process_sharded(tmp_root):
    ref, _, _ = _sharded_reference(tmp_root)
    c, _, _ = _elastic_run(tmp_root, "clean")
    assert _totals(c) == _totals(ref)
    assert c.state_count() == WANT_STATES
    assert c.unique_state_count() == WANT_UNIQUE
    assert c.epoch == 0 and not c.events


def test_elastic_kill_one_worker_bit_identical(tmp_root):
    """The acceptance drill: a 2-worker run loses one worker mid-run
    (simulated SIGKILL at round 4); membership turns it into
    worker_lost -> migration (rollback to the newest per-shard
    generation, rendezvous adoption, epoch bump) and the run completes
    bit-identical to the unfaulted single-process sharded run."""
    ref, _, _ = _sharded_reference(tmp_root)
    c, _, _ = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    assert _totals(c) == _totals(ref)
    kinds = [e["type"] for e in c.events]
    assert kinds == ["worker_lost", "migrate_done"]
    assert c.events[0]["worker"] == "w1"
    # The survivor adopts exactly the dead worker's rendezvous share.
    w1_share = OwnerMap(8, ["w0", "w1"]).partitions_of("w1")
    assert c.events[1]["to"] == "w0"
    assert c.events[1]["partitions"] == len(w1_share) >= 1
    assert c.epoch == 1
    assert c.workers() == ["w0"]
    assert c.scheduler_stats()["elastic"]["migrations"] == 1


def test_elastic_join_one_worker_bit_identical(tmp_root):
    """A worker added mid-run triggers a logged rebalance (rendezvous
    handoff of the partitions it wins, via fresh per-shard snapshots at
    a drained barrier — no rollback) and the totals stay bit-identical
    to the unfaulted single-process sharded run."""
    ref, _, _ = _sharded_reference(tmp_root)
    c, _, _ = _elastic_run(tmp_root, "join", join_at={3: "w2"})
    assert _totals(c) == _totals(ref)
    kinds = [e["type"] for e in c.events]
    assert kinds == ["worker_join", "rebalance"]
    reb = c.events[1]
    assert reb["to"] == "w2" and 1 <= reb["partitions"] < 8
    assert c.epoch == 1
    assert sorted(c.workers()) == ["w0", "w1", "w2"]
    assert c.scheduler_stats()["elastic"]["rebalances"] == 1


def test_elastic_kill_trace_lints_clean(tmp_root):
    """The kill run's ONE merged trace passes trace_lint end to end —
    the v4 membership invariant (worker_lost eventually migrate_done),
    per-run wave monotonicity across the migration's tracer rotation,
    and the v5 distributed invariants (per-worker seq order, worker
    attribution on every relayed wave) — and contains every worker's
    own wave stream plus per-round straggler records."""
    import trace_lint

    _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    _, _, trace = _RUNS["kill"]
    counts, errors = trace_lint.lint_file(trace)
    assert not errors, errors[:5]
    assert counts.get("worker_lost", 0) == 1
    assert counts.get("migrate_done", 0) == 1
    assert counts.get("recover", 0) >= 1
    assert counts.get("wave", 0) > 0
    # The tentpole acceptance: the merged stream carries the workers'
    # OWN wave events (both of them — the casualty's last rounds
    # included), in causal (epoch, round, worker, seq) order, plus the
    # coordinator's summaries and straggler attribution.
    with open(trace, encoding="utf-8") as f:
        events = [json.loads(line) for line in f if line.strip()]
    waves = [e for e in events if e.get("type") == "wave"]
    by_worker = {}
    for w in waves:
        by_worker.setdefault(w.get("worker"), []).append(w)
    assert set(by_worker) >= {None, "w0", "w1"}  # None = coordinator
    for w in ("w0", "w1"):
        assert all(e["engine"] == "elastic_worker"
                   for e in by_worker[w])
        seqs = [e["seq"] for e in by_worker[w]]
        assert seqs == sorted(seqs)
        assert all(e["round"] is not None for e in by_worker[w])
    assert counts.get("straggler", 0) > 0


def test_elastic_join_trace_lints_clean(tmp_root):
    """The join drill's merged trace lints clean too — the joiner's
    relayed stream appears mid-file (its handoff reassignment rotates
    its run), and every one of the three workers is attributed."""
    import trace_lint

    _elastic_run(tmp_root, "join", join_at={3: "w2"})
    _, _, trace = _RUNS["join"]
    counts, errors = trace_lint.lint_file(trace)
    assert not errors, errors[:5]
    assert counts.get("worker_join", 0) == 1
    assert counts.get("rebalance", 0) == 1
    with open(trace, encoding="utf-8") as f:
        events = [json.loads(line) for line in f if line.strip()]
    workers = {e.get("worker") for e in events
               if e.get("type") == "wave"}
    assert workers >= {"w0", "w1", "w2"}


def test_elastic_kill_leaves_postmortem(tmp_root):
    """The always-on flight recorder's acceptance half: a killed
    worker leaves a postmortem. The casualty cannot dump its own ring
    (a SIGKILL has no exception handler), so the coordinator dumps ITS
    ring — which holds the merged recent events, the casualty's last
    relayed waves included — named for the casualty, and the
    worker_lost event carries the path."""
    c, _, _ = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    lost = c.events[0]
    assert lost["type"] == "worker_lost"
    dump = lost.get("dump")
    assert dump and os.path.exists(dump)
    assert dump in c.elastic_obs()["postmortems"]
    with open(dump, encoding="utf-8") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert lines[0]["type"] == "postmortem"
    assert "w1" in lines[0]["reason"]
    assert lines[0]["events"] == len(lines) - 1
    # The ring saw the casualty's own relayed waves.
    assert any(e.get("type") == "wave" and e.get("worker") == "w1"
               for e in lines[1:])


def test_elastic_obs_straggler_stats(tmp_root):
    """scheduler_stats()['elastic_obs']: per-worker straggler gauges
    aggregated from the round attributions — every round timed, both
    workers segmented, wait share a sane fraction, and the merge
    counters accounting for the relayed streams."""
    c, _, trace = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    stats = c.scheduler_stats()
    obs = stats["elastic_obs"]
    # >= because every EXECUTED round is timed, while the round index
    # rewinds with the migration rollback.
    assert obs["rounds_timed"] >= stats["elastic"]["rounds"] > 0
    assert 0.0 <= obs["max_wait_share"] <= 1.0
    assert set(obs["workers"]) == {"w0", "w1"}
    for seg in obs["workers"].values():
        assert seg["waves"] > 0 and seg["compute_s"] >= 0.0
        assert 0.0 <= seg["wait_share"] <= 1.0
    assert sum(obs["slowest"].values()) == obs["rounds_timed"]
    assert obs["merged_events"] > 0 and obs["dropped_events"] == 0
    # The straggler events on the trace agree with the aggregate.
    with open(trace, encoding="utf-8") as f:
        stragglers = [json.loads(line) for line in f
                      if '"type":"straggler"' in line]
    assert len(stragglers) == obs["rounds_timed"]
    assert max(s["wait_share"] for s in stragglers) \
        == obs["max_wait_share"]


def test_elastic_metrics_endpoint(tmp_root):
    """GET /.metrics on an elastic checker: the straggler aggregates
    export as live per-worker Prometheus families (the aggregated
    view, read from running counters — no stream re-scan per
    scrape)."""
    from stateright_tpu.explorer import Explorer

    c, _, _ = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    text = Explorer(c).metrics()
    assert "stpu_elastic_max_wait_share" in text
    assert 'stpu_elastic_worker_wait_share{worker="w0"}' in text
    assert 'stpu_elastic_worker_states_per_sec{worker="w1"}' in text
    assert "stpu_elastic_postmortems_total 1" in text
    # Round-19: the deprecated bare counter duals are gone.
    assert "stpu_elastic_postmortems 1" not in text
    assert f"stpu_states_total {c.state_count()}" in text


def test_trace_summary_cli_on_merged_trace(tmp_root):
    """tools/trace_summary.py smoke: the per-worker table renders from
    the kill drill's merged trace (and from the postmortem dump)."""
    import subprocess

    c, _, trace = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "trace_summary.py"),
         trace], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "coordinator" in out.stdout
    assert "w0" in out.stdout and "w1" in out.stdout
    assert "wait%" in out.stdout
    # The postmortem dump is valid input too.
    dump = c.events[0]["dump"]
    out2 = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "trace_summary.py"),
         dump], capture_output=True, text=True, timeout=60)
    assert out2.returncode == 0, out2.stderr
    assert "w1" in out2.stdout


def test_elastic_final_checkpoint_payload_matches_sharded(tmp_root):
    """Checkpoint payload bit-identity: the elastic run's final
    generation (manifest counters + the union of the per-shard visited
    sections) equals the single-process sharded engine's final
    snapshot — same reachable set, same counters, both frontiers
    empty. Pinned on the MIGRATED run: redone work must not leak into
    the durable payload either."""
    ref, ref_ckpt, _ = _sharded_reference(tmp_root)
    c, ckpt, _ = _elastic_run(tmp_root, "kill", kill_at={4: "w1"})

    with load_checkpoint(ref_ckpt) as data:
        ref_visited = np.sort(np.asarray(data["visited"], np.uint64))
        assert len(np.asarray(data["pending_fps"])) == 0

    manifest = verify_file(ckpt)
    assert manifest["state_count"] == ref.state_count()
    assert manifest["unique_count"] == ref.unique_state_count()
    elastic_hdr = manifest["elastic"]
    assert elastic_hdr["partitions"] == 8

    shards = []
    for p in range(8):
        with load_checkpoint(shard_path(ckpt, p)) as data:
            header = validate_header(
                data, model_name="TwoPhaseSys", state_width=ref._W,
                use_symmetry=False, expect_shard=(p, 8))
            assert header["shard"]["round"] == elastic_hdr["round"]
            assert len(np.asarray(data["pending_fps"])) == 0
            shards.append(np.asarray(data["visited"], np.uint64))
    got = np.sort(np.concatenate(shards))
    assert got.shape == ref_visited.shape
    assert (got == ref_visited).all()


def test_elastic_injected_worker_crash_migrates(tmp_root, monkeypatch):
    """STpu_FAULTS=worker_crash: the registered fault point kills a
    worker at a deterministic coordinated round; the run migrates and
    stays bit-identical (fault -> recover pairing rides the same
    stream the supervisor uses)."""
    monkeypatch.setenv("STpu_FAULTS", "worker_crash@n=3")
    monkeypatch.setenv("STpu_FLIGHT_DIR", str(tmp_root / "crash-dumps"))
    os.makedirs(str(tmp_root / "crash-dumps"), exist_ok=True)
    reset_fault_plans()
    try:
        ckpt = str(tmp_root / "crash.npz")
        c = ElasticChecker(
            partial(TwoPhaseSys, RMS), workers=2, n_partitions=8,
            batch_rows=64, transport="thread", checkpoint_path=ckpt,
            checkpoint_every_rounds=2).join()
    finally:
        reset_fault_plans()
    assert (c.state_count(), c.unique_state_count()) == (WANT_STATES,
                                                         WANT_UNIQUE)
    assert [e["type"] for e in c.events] == ["worker_lost",
                                             "migrate_done"]
    # The dying worker dumped its OWN flight ring on the injected
    # fault (unlike a SIGKILL, an InjectedFault is catchable), and the
    # dump's LAST event is the fault point — the flight recorder's
    # whole job.
    victim = c.events[0]["worker"]
    dump = str(tmp_root / "crash-dumps"
               / f"stpu-postmortem-{victim}.jsonl")
    assert os.path.exists(dump)
    with open(dump, encoding="utf-8") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert lines[0]["type"] == "postmortem"
    assert lines[-1]["type"] == "fault"
    assert lines[-1]["point"] == "worker_crash"
    assert lines[-1]["worker"] == victim


def test_elastic_resume_from_manifest(tmp_root):
    """The preemption story end to end: a completed run's manifest +
    shard files resume a FRESH coordinator (new workers, same
    generations) to the same totals — this is what a supervisor
    wrapping an elastic factory hands to the first retry."""
    c, ckpt, _ = _elastic_run(tmp_root, "clean")
    # resume_from and checkpoint_path are DIFFERENT stores: the resumed
    # run reads the old generations and writes its own fresh ones.
    resumed = ElasticChecker(
        partial(TwoPhaseSys, RMS), workers=2, n_partitions=8,
        batch_rows=64, transport="thread",
        checkpoint_path=str(tmp_root / "resumed-fresh.npz"),
        resume_from=ckpt).join()
    assert _totals(resumed) == _totals(c)
    assert os.path.exists(str(tmp_root / "resumed-fresh.npz"))
    # An explicit '...prev' manifest (what newest_valid_checkpoint
    # returns after a torn current write) also resumes: shard files are
    # probed beside the BASE path, and the matching .prev generations
    # are found by their recorded round.
    from stateright_tpu.checkpoint_format import PREV_SUFFIX
    assert os.path.exists(ckpt + PREV_SUFFIX)
    resumed_prev = ElasticChecker(
        partial(TwoPhaseSys, RMS), workers=2, n_partitions=8,
        batch_rows=64, transport="thread",
        checkpoint_path=str(tmp_root / "resumed-prev.npz"),
        resume_from=ckpt + PREV_SUFFIX).join()
    assert resumed_prev.unique_state_count() == c.unique_state_count()


# -- OwnerMap / Membership units ------------------------------------------

def test_owner_map_identity_and_remap():
    m = OwnerMap.identity(8)
    assert m.is_identity and m.epoch == 0
    assert [m.owner_of(p) for p in range(8)] == list(range(8))
    assert m.owner(17) == 17 % 8
    perm = [(i + 3) % 8 for i in range(8)]
    m2 = m.with_assignment(perm)
    assert m2.epoch == 1 and not m2.is_identity
    assert m2.owner(17) == perm[17 % 8]
    moves = m2.moves_from(m)
    assert len(moves) == 8  # a full rotation moves everything
    with pytest.raises(ValueError, match="owner"):
        OwnerMap(4, ["a"], assignment=["a", "b", "a", "a"])


def test_owner_map_rendezvous_minimal_migration():
    """The rendezvous property the migration cost rides on: losing a
    worker moves ONLY its partitions; a join moves ONLY partitions the
    joiner wins. Assignment is deterministic across processes."""
    m = OwnerMap(32, ["w0", "w1", "w2"])
    m_again = OwnerMap(32, ["w0", "w1", "w2"])
    assert m.assignment() == m_again.assignment()
    assert set(m.assignment()) == {"w0", "w1", "w2"}

    lost = m.with_owners(["w0", "w1"])  # w2 dies
    for p, (old, new) in lost.moves_from(m).items():
        assert old == "w2" and new in ("w0", "w1")
    assert set(lost.moves_from(m)) == set(m.partitions_of("w2"))

    joined = m.with_owners(["w0", "w1", "w2", "w3"])
    for p, (old, new) in joined.moves_from(m).items():
        assert new == "w3"
    assert joined.epoch == m.epoch + 1


def test_membership_lease_expiry():
    clock = [0.0]
    ms = Membership(lease_s=5.0, clock=lambda: clock[0])
    ms.add("w0")
    ms.add("w1")
    clock[0] = 4.0
    ms.beat("w1")
    assert ms.expired() == []
    clock[0] = 6.0
    assert ms.expired() == ["w0"]
    assert ms.remaining("w1") > 0 > ms.remaining("w0")
    ms.drop("w0")
    assert ms.workers() == ["w1"]
    clock[0] = 20.0
    assert ms.expired() == ["w1"]


@pytest.mark.slow  # round-15 tier-1 budget: the elastic kill/join
# drills (fast tier) exercise the same epoch machinery end to end.
def test_sharded_engine_epoch_remap_bit_identical(tmp_path,
                                                  monkeypatch):
    """The fast in-process epoch sibling: a single-process sharded run
    crashes mid-run, ownership is remapped by a permutation at the
    rest point (epoch bump), and restart_from completes under the new
    assignment with bit-identical totals — the epoch-keyed wave cache
    and the assignment-aware device routing both exercised without any
    multi-process arm."""
    monkeypatch.setenv("STpu_FAULTS", "wave_crash@n=3")
    reset_fault_plans()
    ckpt = str(tmp_path / "s.npz")
    c = TwoPhaseSys(RMS).checker().spawn_tpu_bfs(
        batch_size=32, sharded=True, fused=False,
        checkpoint_path=ckpt, checkpoint_every_waves=1)
    with pytest.raises(RuntimeError):
        c.join()
    reset_fault_plans()
    n = c._n_shards
    assert c.owner_epoch == 0
    with pytest.raises(RuntimeError, match="rest point"):
        # Guard probed while stopped is fine; simulate running state.
        c._done.clear()
        c.set_owner_assignment([(i + 1) % n for i in range(n)])
    c._done.set()
    c.set_owner_assignment([(i + 1) % n for i in range(n)])
    assert c.owner_epoch == 1
    c.restart_from(ckpt).join()
    assert (c.state_count(), c.unique_state_count()) == (WANT_STATES,
                                                         WANT_UNIQUE)
    assert sorted(c.discoveries()) == ["abort agreement",
                                      "commit agreement"]


# -- Per-shard checkpoint format (v4) -------------------------------------

def _shard_payload(p, of, round_=7, epoch=2):
    header = make_header(
        model_name="M", state_width=3, state_count=4, unique_count=4,
        use_symmetry=False, discoveries={},
        shard={"index": p, "of": of, "round": round_, "epoch": epoch})
    return dict(header=header,
                visited=np.arange(4, dtype=np.uint64),
                pending_vecs=np.zeros((2, 3), np.uint32),
                pending_fps=np.arange(2, dtype=np.uint64),
                pending_ebits=np.zeros(2, np.uint32))


def test_shard_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "run.npz")
    write_atomic(shard_path(path, 3), _shard_payload(3, 8))
    header = verify_file(shard_path(path, 3))
    assert header["version"] == CKPT_VERSION
    assert header["shard"] == {"index": 3, "of": 8, "round": 7,
                               "epoch": 2}
    with load_checkpoint(shard_path(path, 3)) as data:
        validate_header(data, model_name="M", state_width=3,
                        use_symmetry=False, expect_shard=(3, 8))
        with pytest.raises(ValueError, match="wrong shard"):
            validate_header(data, model_name="M", state_width=3,
                            use_symmetry=False, expect_shard=(5, 8))


def test_v3_single_shard_file_still_loads(tmp_path):
    """A pre-v4 header (no shard section) is accepted as an adopted
    partition — expect_shard only pins headers that DECLARE a shard."""
    path = str(tmp_path / "v3.npz")
    header = json.loads(bytes(make_header(
        model_name="M", state_width=3, state_count=1, unique_count=1,
        use_symmetry=False, discoveries={}).tobytes()).decode())
    header["version"] = 3
    del header["row_format"]  # a genuinely old writer
    data = {
        "header": np.frombuffer(json.dumps(header).encode(), np.uint8),
        "visited": np.arange(2, dtype=np.uint64)}
    write_atomic(path, data)
    with load_checkpoint(path) as loaded:
        out = validate_header(loaded, model_name="M", state_width=3,
                              use_symmetry=False, expect_shard=(0, 8))
    assert out["version"] == 3 and "shard" not in out


def test_newer_checkpoint_version_refused(tmp_path):
    path = str(tmp_path / "future.npz")
    header = json.loads(bytes(make_header(
        model_name="M", state_width=3, state_count=1, unique_count=1,
        use_symmetry=False, discoveries={}).tobytes()).decode())
    header["version"] = CKPT_VERSION + 1
    write_atomic(path, {
        "header": np.frombuffer(json.dumps(header).encode(), np.uint8),
        "visited": np.arange(2, dtype=np.uint64)})
    with pytest.raises(ValueError, match="newer than this build"):
        verify_file(path)


# -- Lint: the membership invariant ---------------------------------------

def test_lint_membership_invariant():
    import trace_lint

    def evt(etype, **kw):
        base = {"type": etype, "schema_version": 4, "engine": "elastic",
                "run": "r", "t": 1.0}
        base.update(kw)
        return json.dumps(base)

    lost = evt("worker_lost", worker="w1", epoch=0)
    migrated = evt("migrate_done", partitions=4, to="w0", epoch=1)
    rebalance = evt("rebalance", partitions=2, to="w2", epoch=2)
    abort = evt("abort", reason="gave up", attempts=1)
    fault = evt("fault", point="worker_crash", hit=1, mode="raise")
    retry = evt("retry", attempt=1, backoff_s=0.1, jitter_s=0.01,
                resumed_from=None)

    _, errors = trace_lint.lint_lines([lost])
    assert errors and "never followed by a migrate_done" in errors[0]
    _, errors = trace_lint.lint_lines([lost, migrated, rebalance])
    assert not errors
    _, errors = trace_lint.lint_lines([lost, lost, abort])
    assert not errors, "terminal abort retires every outstanding loss"
    _, errors = trace_lint.lint_lines([lost, lost, migrated])
    assert len(errors) == 1, "one migrate_done retires one loss"
    # Schema v4: a supervisor retry retires a fault like a recover.
    _, errors = trace_lint.lint_lines([fault, retry])
    assert not errors


# -- Supervisor jitter (satellite) ----------------------------------------

def test_supervisor_backoff_jitter_recorded_and_seeded():
    import random

    from stateright_tpu.resilience import Supervisor

    boom = {"n": 0}

    def factory(resume_from=None):
        class C:
            def join(self):
                boom["n"] += 1
                if boom["n"] < 3:
                    raise RuntimeError("boom")
                return self
        return C()

    slept = []
    sup = Supervisor(factory, backoff_s=0.1, backoff_factor=2.0,
                     jitter_frac=0.5, rng=random.Random(7),
                     sleep=slept.append)
    sup.run()
    assert len(sup.recoveries) == 2
    for rec, base in zip(sup.recoveries, (0.1, 0.2)):
        assert rec["backoff_s"] == base
        assert 0.0 <= rec["jitter_s"] <= 0.5 * base
    for got, rec in zip(slept, sup.recoveries):
        # records round to 4 decimals; the sleep gets the exact draw
        assert got == pytest.approx(rec["backoff_s"] + rec["jitter_s"],
                                    abs=1e-4)
    # Seeded: the same rng draws the same jitter (replayable records).
    boom["n"] = 0
    slept2 = []
    sup2 = Supervisor(factory, backoff_s=0.1, backoff_factor=2.0,
                      jitter_frac=0.5, rng=random.Random(7),
                      sleep=slept2.append)
    sup2.run()
    assert slept2 == slept
    # jitter_frac=0 restores the exact pre-v4 schedule.
    boom["n"] = 0
    slept3 = []
    Supervisor(factory, backoff_s=0.1, backoff_factor=2.0,
               jitter_frac=0.0, sleep=slept3.append).run()
    assert slept3 == [0.1, 0.2]


# -- Multi-process arms (slow) --------------------------------------------

@pytest.mark.slow
def test_elastic_process_transport_kill_2pc(tmp_path):
    """The real thing: one OS process per worker (spawn context, own
    JAX CPU backend each), a real SIGKILL mid-run, migration, and
    bit-identical totals."""
    ckpt = str(tmp_path / "proc.npz")
    c = ElasticChecker(
        partial(TwoPhaseSys, RMS), workers=2, n_partitions=8,
        batch_rows=64, transport="process", checkpoint_path=ckpt,
        checkpoint_every_rounds=2, kill_at={4: "w0"}).join()
    assert (c.state_count(), c.unique_state_count()) == (WANT_STATES,
                                                         WANT_UNIQUE)
    assert [e["type"] for e in c.events] == ["worker_lost",
                                             "migrate_done"]
    assert c.workers() == ["w1"]


@pytest.mark.slow
def test_elastic_paxos_kill_and_join_exact_space(tmp_path):
    """The north-star workload through the elastic path: paxos(2,3)
    with BOTH a mid-run worker loss and a mid-run join completes to
    the exact full space (16,668 unique / 32,971 states) with the
    expected lifecycle — the elastic sibling of the round-10 paxos
    crash matrix."""
    from paxos import PaxosModelCfg

    def factory():
        return PaxosModelCfg(2, 3).into_model()

    ckpt = str(tmp_path / "paxos.npz")
    c = ElasticChecker(
        factory, workers=2, n_partitions=8, batch_rows=512,
        transport="thread", checkpoint_path=ckpt,
        checkpoint_every_rounds=4,
        kill_at={6: "w1"}, join_at={10: "w2"}).join()
    assert c.unique_state_count() == 16668
    assert c.state_count() == 32971
    assert sorted(c.discoveries()) == ["value chosen"]
    kinds = [e["type"] for e in c.events]
    assert kinds == ["worker_lost", "migrate_done", "worker_join",
                     "rebalance"]

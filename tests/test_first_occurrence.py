"""Oracle tests for the sort-free intra-wave dedup.

``first_occurrence_candidates`` (engine.py) is where the XLA and Pallas
table paths' bit-identical-outputs contract starts; since round 5 it is
a scatter-min group-resolution loop instead of a stable argsort, so pin
its exact semantics — True at the earliest frontier-order occurrence of
each non-sentinel fingerprint — against a reference oracle, including
the adversarial shapes that stress the loop (same-fp floods, shared
probe steps, all-sentinel waves).
"""
import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from stateright_tpu.tpu.engine import first_occurrence_candidates  # noqa: E402
from stateright_tpu.tpu.hashing import SENTINEL  # noqa: E402


def oracle(fps):
    seen, out = set(), []
    for f in fps:
        f = int(f)
        if f == SENTINEL or f in seen:
            out.append(False)
        else:
            seen.add(f)
            out.append(True)
    return np.array(out, bool)


def check(fps):
    fps = np.asarray(fps, np.uint64)
    got = np.asarray(first_occurrence_candidates(jnp.asarray(fps)))
    want = oracle(fps)
    assert (got == want).all(), np.nonzero(got != want)[0][:5]


def test_all_identical():
    check(np.full(37, 12345, np.uint64))


def test_all_distinct():
    rng = np.random.default_rng(0)
    check(rng.integers(1, 2**63, 1000, dtype=np.uint64))


def test_triplicated_with_sentinels():
    rng = np.random.default_rng(1)
    x = rng.integers(1, 2**63, 300, dtype=np.uint64)
    check(np.concatenate([x, x, x, np.full(50, SENTINEL, np.uint64)]))


def test_all_sentinel():
    check(np.full(8, SENTINEL, np.uint64))


def test_singleton_and_tiny():
    check(np.array([SENTINEL], np.uint64))
    check(np.array([7, 7, SENTINEL, 7, 9], np.uint64))


def test_realistic_wave_shape():
    rng = np.random.default_rng(2)
    base = rng.integers(1, 2**63, 7500, dtype=np.uint64)
    wave = np.concatenate([base, rng.choice(base, 22528 - len(base))])
    rng.shuffle(wave)
    check(wave)


def test_shared_probe_steps():
    # fps differing only in high bits share low-bit-derived quantities;
    # stresses groups that keep colliding across rounds.
    rng = np.random.default_rng(3)
    check((rng.integers(1, 2**20, 5000, dtype=np.uint64) << np.uint64(44))
          | np.uint64(5))


@pytest.mark.slow  # ~11s randomized oracle fuzz; the adversarial
# deterministic streams in test_local_dedup stay the fast gate
def test_random_fuzz_vs_oracle():
    rng = np.random.default_rng(4)
    for _ in range(25):
        n = int(rng.integers(1, 400))
        pool = rng.integers(1, 50, size=max(n // 2, 1), dtype=np.uint64)
        fps = rng.choice(
            np.concatenate([pool, np.array([SENTINEL], np.uint64)]),
            size=n)
        check(fps)

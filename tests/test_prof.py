"""Continuous wave profiler (stateright_tpu/obs/prof.py + schema v13).

Contracts pinned here:

- **v13 events validate and lint**: ``profile_snapshot`` events pass
  ``validate_event`` and ``trace_lint``'s v13 invariants (per-run
  strictly increasing ``snap``, finite positive ``measured_s`` /
  ``cost_ratio``, ``intensity == flops/bytes``); corrupted variants
  are rejected. Old v12 wave captures (no cost fields) still validate.
- **One cost surface, every engine**: all four device engines, armed
  (``STpu_PROF=1``), stamp the three nullable cost fields on every
  wave event with the exact v13 field set, capture XLA's own
  ``cost_analysis()`` flops/bytes for every compiled program, and emit
  at least one ``profile_snapshot`` with a finite ``cost_ratio`` per
  program — and arming changes no checking result.
- **Disarmed means free**: ``STpu_PROF`` unset gets the shared
  ``NULL_PROF`` singleton and the wave loop never calls into it (every
  null method is poisoned) — one attribute check per dispatch, zero
  cost lookups.
- **Deterministic cadence**: ``should_sample`` is a pure function of
  the dispatch sequence — every Nth dispatch plus the first dispatch
  of each new program key.
- **Per-arm A/B attribution**: the matmul-vs-step A/B captures a
  distinct cost model per arm (the prof key prefix encodes the active
  plan), with identical checking results.
"""

import json
import math
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "examples"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.obs import validate_event  # noqa: E402
from stateright_tpu.obs.prof import (NULL_PROF, NullWaveProfiler,
                                     WaveProfiler, clear_program_records,
                                     prof_from_env,
                                     prometheus_prof_lines)  # noqa: E402

sys.path.insert(0, os.path.join(_REPO, "tools"))
import trace_lint  # noqa: E402

ENGINES = ("classic", "fused", "sharded", "sharded_fused")


def _spawn(model, engine, **kw):
    b = model.checker()
    if engine == "classic":
        return b.spawn_tpu_bfs(batch_size=64, fused=False, **kw)
    if engine == "fused":
        return b.spawn_tpu_bfs(batch_size=64, fused=True, **kw)
    if engine == "sharded":
        return b.spawn_tpu_bfs(batch_size=32, sharded=True, fused=False,
                               **kw)
    assert engine == "sharded_fused"
    return b.spawn_tpu_bfs(batch_size=32, sharded=True, **kw)


@pytest.fixture(autouse=True)
def _fresh_cost_table():
    # The static cost table is process-wide by design; isolate tests.
    clear_program_records()
    yield
    clear_program_records()


def _events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# -- v13 schema + lint units ------------------------------------------------

_START = {"schema_version": 13, "engine": "classic", "run": "r-0",
          "type": "run_start", "t": 1.0, "unix_t": 1.0, "meta": {}}

#: A known-good snapshot (field values from a real classic 2pc
#: capture); intensity == flops / bytes to the lint tolerance.
_SNAP = {"schema_version": 13, "engine": "classic", "run": "r-0",
         "type": "profile_snapshot", "t": 1.5,
         "flops": 193085.0, "bytes": 1494572.0, "peak_bytes": 1109737,
         "flops_per_s": 92284493.494, "bytes_per_s": 714326954.502,
         "intensity": 0.129191, "key": "classic|aa|(64, 65536, 768)",
         "kernel_path": "xla", "expand_impl": "step", "snap": 1,
         "measured_s": 0.002092, "cost_ratio": 1.0}


def _lint(tmp_path, events, name="t.jsonl"):
    p = tmp_path / name
    p.write_text("\n".join(json.dumps(e) for e in events) + "\n",
                 encoding="utf-8")
    return trace_lint.lint_file(str(p))


def test_profile_snapshot_validates():
    assert validate_event(_SNAP) == []
    # Null-cost snapshots (lazy-jit programs) are legal: the roofline
    # gauges are nullable, the measurement fields are not.
    nulled = dict(_SNAP, flops=None, bytes=None, peak_bytes=None,
                  flops_per_s=None, bytes_per_s=None, intensity=None)
    assert validate_event(nulled) == []
    assert validate_event({k: v for k, v in _SNAP.items()
                           if k != "key"}) != []
    assert validate_event(dict(_SNAP, cost_ratio="fast")) != []


def test_lint_accepts_good_snapshot_stream(tmp_path):
    snap2 = dict(_SNAP, snap=2, t=1.6, measured_s=0.0011,
                 cost_ratio=0.525812, flops_per_s=175531818.182,
                 bytes_per_s=1358701818.182)
    counts, errors = _lint(tmp_path, [_START, _SNAP, snap2])
    assert errors == []
    assert counts["profile_snapshot"] == 2


@pytest.mark.parametrize("bad, expect", [
    (dict(_SNAP, snap=2, t=1.4), "snap"),          # then snap=1 below
    (dict(_SNAP, measured_s=-0.001), "measured_s"),
    (dict(_SNAP, measured_s=0.0), "measured_s"),
    (dict(_SNAP, cost_ratio=float("inf")), "cost_ratio"),
    (dict(_SNAP, intensity=0.5), "intensity"),
])
def test_lint_rejects_bad_snapshots(tmp_path, bad, expect):
    events = ([_START, bad, _SNAP] if expect == "snap"
              else [_START, bad])
    _, errors = _lint(tmp_path, events)
    assert errors, bad
    assert any(expect in e for e in errors), (expect, errors)


def test_v12_wave_capture_still_validates():
    """A pre-profiler capture (schema 12, no cost fields) must keep
    linting clean — and a v13 wave must carry the cost fields."""
    from stateright_tpu.obs.schema import WAVE_FIELDS, WAVE_FIELDS_V12

    v13 = {k: None for k in WAVE_FIELDS}
    v13.update({"schema_version": 13, "engine": "classic", "run": "r",
                "type": "wave", "t": 1.0, "wave": 0, "states": 1,
                "unique": 1, "bucket": 64, "waves": 1, "inflight": 0,
                "compiled": False, "successors": 0, "candidates": 0,
                "novel": 0, "capacity": 64, "overflow": False,
                "rows": 1, "out_rows": 64, "io_stall_s": 0.0})
    assert validate_event(v13) == []
    v12 = {k: v for k, v in v13.items()
           if k in WAVE_FIELDS_V12 or k in ("schema_version", "engine",
                                            "run", "type", "t")}
    v12["schema_version"] = 12
    assert validate_event(v12) == []
    # Exact field set both directions: a v13 wave MISSING the cost
    # fields is invalid, as is a v12 wave carrying them.
    assert validate_event(dict(v12, schema_version=13)) != []
    assert validate_event(dict(v12, schema_version=12,
                               cost_flops=1.0)) != []


# -- Armed: every engine ----------------------------------------------------

def test_cost_capture_across_engines(tmp_path, monkeypatch):
    """All four device engines, armed with per-dispatch sampling: v13
    traces lint clean, every wave carries the exact field set, every
    compiled program's snapshots have XLA cost-model flops/bytes and a
    finite positive cost_ratio — and arming changes no result."""
    from stateright_tpu.obs.schema import WAVE_FIELDS

    model = TwoPhaseSys(3)
    ref = model.checker().spawn_bfs().join()  # disarmed reference
    for engine in ENGINES:
        clear_program_records()
        path = tmp_path / f"{engine}.jsonl"
        monkeypatch.setenv("STpu_TRACE", str(path))
        monkeypatch.setenv("STpu_PROF", "1")
        monkeypatch.setenv("STpu_PROF_SAMPLE", "1")
        c = _spawn(model, engine).join()
        monkeypatch.delenv("STpu_TRACE")

        assert c.unique_state_count() == ref.unique_state_count(), engine
        assert c.state_count() == ref.state_count(), engine
        assert set(c.discoveries()) == set(ref.discoveries()), engine

        _, errors = trace_lint.lint_file(str(path))
        assert errors == [], (engine, errors[:3])
        events = _events(path)
        waves = [e for e in events if e.get("type") == "wave"]
        snaps = [e for e in events
                 if e.get("type") == "profile_snapshot"]
        assert waves and snaps, engine
        assert {frozenset(w) for w in waves} == {frozenset(WAVE_FIELDS)}
        # Sampled every dispatch: every wave carries a measured ratio
        # and the statically captured program cost.
        for w in waves:
            assert w["cost_flops"] and w["cost_flops"] > 0, (engine, w)
            assert w["cost_bytes"] and w["cost_bytes"] > 0, (engine, w)
            assert (w["cost_ratio"] is not None
                    and math.isfinite(w["cost_ratio"])
                    and w["cost_ratio"] > 0), (engine, w)
        for s in snaps:
            assert s["flops"] and s["flops"] > 0, (engine, s)
            assert s["intensity"] == pytest.approx(
                s["flops"] / s["bytes"], rel=1e-3), engine
        # The live stats surface mirrors the stream.
        prof = c.scheduler_stats()["prof"]
        assert prof["sampled"] == len(snaps), engine
        assert prof["dispatches"] >= prof["sampled"], engine
        assert set(prof["programs"]) == {s["key"] for s in snaps}, engine
        # And it renders as the stpu_prof_* exposition families.
        lines = prometheus_prof_lines(prof, engine)
        assert any(line.startswith("stpu_prof_flops{") for line in lines)


# -- Disarmed: poisoned null ------------------------------------------------

def test_disarmed_prof_is_shared_null_and_never_called(monkeypatch):
    """STpu_PROF unset: the engines hold the NULL_PROF singleton and
    the wave loop never calls into it — every null method is poisoned,
    so a single stray cost lookup in the hot loop fails the run."""
    monkeypatch.delenv("STpu_PROF", raising=False)
    assert prof_from_env("classic") is NULL_PROF

    def _boom(name):
        def poisoned(self, *a, **k):
            raise AssertionError(
                f"NullWaveProfiler.{name} called with profiling "
                "disarmed")
        return poisoned

    for name in ("capture", "should_sample", "wave", "stats", "close"):
        monkeypatch.setattr(NullWaveProfiler, name, _boom(name))
    c = _spawn(TwoPhaseSys(3), "classic").join()
    assert c.unique_state_count() > 0
    assert c.scheduler_stats()["prof"] is None
    # Disarmed waves carry no cost fields at all (they are stamped by
    # the collector as nulls only when some OTHER producer is armed).
    assert all("cost_flops" not in e or e["cost_flops"] is None
               for e in c.dispatch_log)


# -- Sampling cadence -------------------------------------------------------

def test_sampling_cadence_deterministic():
    seq = ["k1"] * 6 + ["k2"] + ["k1"] * 5
    pa, pb = WaveProfiler("a", 4), WaveProfiler("b", 4)
    a = [pa.should_sample(k) for k in seq]
    b = [pb.should_sample(k) for k in seq]
    assert a == b  # same dispatch sequence, same sampled set
    # Every Nth dispatch (0, 4, 8) plus the first of each new key (k2
    # at index 6).
    assert a == [i % 4 == 0 or i == 6 for i in range(len(seq))]
    assert pa.stats()["dispatches"] == len(seq)


# -- Matmul-vs-step A/B: per-arm cost capture -------------------------------

def _matmul_ab(model, engine):
    arms = {}
    for on in (True, False):
        clear_program_records()
        c = _spawn(model, engine, wave_matmul=on).join()
        prof = c.scheduler_stats()["prof"]
        assert prof is not None and prof["programs"], on
        for key, snap in prof["programs"].items():
            assert snap["flops"] and snap["flops"] > 0, (on, key)
            assert snap["bytes"] and snap["bytes"] > 0, (on, key)
            assert math.isfinite(snap["cost_ratio"]), (on, key)
        arms[on] = (c.state_count(), c.unique_state_count(),
                    tuple(sorted(c.discoveries())),
                    frozenset(prof["programs"]))
    # Identical results; DISTINCT cost models (the prof key prefix
    # encodes whether the matmul plan was compiled in).
    assert arms[True][:3] == arms[False][:3]
    assert arms[True][3].isdisjoint(arms[False][3])


def test_matmul_vs_step_ab_captures_both_arms(monkeypatch):
    monkeypatch.setenv("STpu_PROF", "1")
    monkeypatch.setenv("STpu_PROF_SAMPLE", "1")
    _matmul_ab(TwoPhaseSys(3), "classic")


@pytest.mark.slow
def test_matmul_vs_step_ab_increment_fused(monkeypatch):
    from increment import IncrementModel

    monkeypatch.setenv("STpu_PROF", "1")
    monkeypatch.setenv("STpu_PROF_SAMPLE", "1")
    _matmul_ab(IncrementModel(3), "fused")
    _matmul_ab(TwoPhaseSys(4), "fused")

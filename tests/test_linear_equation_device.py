"""LinearEquation on the device engines (BASELINE rows 13-15).

The unsolvable config {a:2, b:4, c:7} forces full-space enumeration —
the reference's 256x256 = 65,536-state gate (`bfs.rs:367-372`) — and the
solvable config pins discovery existence + validity.
"""

import pytest

from stateright_tpu.test_util import LinearEquation


def test_full_space_65536_fused():
    c = (LinearEquation(2, 4, 7).checker()
         .spawn_tpu_bfs(batch_size=1024).join())
    assert c.unique_state_count() == 65536
    assert c.discoveries() == {}


@pytest.mark.slow
def test_full_space_65536_all_engines():
    for kwargs in ({"fused": False}, {"sharded": True},
                   {"sharded": True, "fused": False}):
        c = (LinearEquation(2, 4, 7).checker()
             .spawn_tpu_bfs(batch_size=256, **kwargs).join())
        assert c.unique_state_count() == 65536, kwargs
        assert c.discoveries() == {}, kwargs


def test_solvable_discovery():
    model = LinearEquation(2, 10, 14)
    host = model.checker().spawn_bfs().join()
    tpu = model.checker().spawn_tpu_bfs(batch_size=64).join()
    for c in (host, tpu):
        x, y = c.discovery("solvable").last_state()
        assert (2 * x + 10 * y) % 256 == 14
    # Single-device BFS preserves host level order: identical solution.
    assert (tpu.discovery("solvable").last_state()
            == host.discovery("solvable").last_state())

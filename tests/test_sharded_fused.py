"""The fused multi-chip engine (`stateright_tpu/tpu/sharded_fused.py`).

The sharded paths of the device battery exercise it implicitly (it is
the ``spawn_tpu_bfs(sharded=True)`` default); these pin its specifics:
discovery identity vs the classic sharded engine, on-device growth of
the per-shard tables/arenas, checkpoint round-trips, and ABD parity.
"""

import pytest
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

from stateright_tpu.tpu.sharded_fused import ShardedFusedTpuBfsChecker
from stateright_tpu.tpu.sharded import ShardedTpuBfsChecker
from two_phase_commit import TwoPhaseSys


def test_spawn_sharded_selects_fused_by_default():
    c = (TwoPhaseSys(3).checker()
         .spawn_tpu_bfs(sharded=True, batch_size=16).join())
    assert isinstance(c, ShardedFusedTpuBfsChecker)
    assert c.unique_state_count() == 288


def test_matches_classic_sharded_engine_bit_for_bit():
    model = TwoPhaseSys(4)
    classic = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=32, fused=False).join()
    fused = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=32).join()
    assert isinstance(classic, ShardedTpuBfsChecker)
    assert not isinstance(classic, ShardedFusedTpuBfsChecker)
    assert fused.unique_state_count() == classic.unique_state_count()
    assert fused.state_count() == classic.state_count()
    assert set(fused.discoveries()) == set(classic.discoveries())
    for name in fused.discoveries():
        assert (fused.discovery(name).encode()
                == classic.discovery(name).encode())


def test_on_device_growth_paths():
    model = TwoPhaseSys(4)
    ref = model.checker().spawn_bfs().join()
    grown = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=8, table_capacity=1 << 12,
        arena_capacity=1 << 10, waves_per_dispatch=2).join()
    assert grown.unique_state_count() == ref.unique_state_count()
    assert set(grown.discoveries()) == set(ref.discoveries())


def test_checkpoint_crosses_into_single_device_engine(tmp_path):
    """A sharded-fused snapshot resumes on the single-device fused
    engine (and back): ownership/table layout are rebuilt from data."""
    model = TwoPhaseSys(4)
    full = model.checker().spawn_bfs().join()

    ckpt = str(tmp_path / "shf.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        sharded=True, batch_size=32, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=64, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())

    ckpt2 = str(tmp_path / "single.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=64, checkpoint_path=ckpt2).join()
    resumed2 = model.checker().spawn_tpu_bfs(
        sharded=True, batch_size=32, resume_from=ckpt2).join()
    assert resumed2.unique_state_count() == full.unique_state_count()
    assert set(resumed2.discoveries()) == set(full.discoveries())


@pytest.mark.slow  # ~11s; single-device symmetry parity stays in
# the fast set, the sharded pair's symmetry rides here
def test_symmetry_on_sharded_engines():
    """Symmetry reduction composes with sharding: dedup (and therefore
    ownership) keys on the representative's fingerprint while paths keep
    original-state fingerprints (the dfs.rs:258-267 rule).

    Because the 2pc device representative is an EXACT canonical form,
    the quotient size is the true orbit count — 314 at 5 RMs —
    independent of wave composition, so the sharded engines count
    identically to the single-device ones. (The reference's value-only
    sort is order-dependent: 665 under its DFS, `2pc.rs:138`.)"""
    for fused in (True, False):
        c = (TwoPhaseSys(5).checker().symmetry()
             .spawn_tpu_bfs(sharded=True, batch_size=32,
                            fused=fused).join())
        assert c.unique_state_count() == 314, fused
        assert set(c.discoveries()) == {"abort agreement",
                                        "commit agreement"}, fused


def test_abd_sharded_fused_544():
    """The linearizable-register parity gate on the fused multi-chip
    path (`examples/linearizable-register.rs:256`)."""
    from linearizable_register import AbdModelCfg

    model = AbdModelCfg(2, 2).into_model()
    c = model.checker().spawn_tpu_bfs(sharded=True, batch_size=64).join()
    assert c.unique_state_count() == 544
    assert set(c.discoveries()) == {"value chosen"}
    c.assert_properties()

"""Semantics-layer tests (counterpart of semantics/{register,vec,
linearizability,sequential_consistency}.rs test suites)."""

import pytest

from stateright_tpu.semantics import (
    Len, LenOk, LinearizabilityTester, Pop, PopOk, Push, PushOk, Read,
    ReadOk, Register, SequentialConsistencyTester, VecSpec, Write, WriteOk,
)


# -- Register ref object (register.rs:50-85) -----------------------------

def test_register_models_expected_semantics():
    r = Register("A")
    assert r.invoke(Read()) == ReadOk("A")
    assert r.invoke(Write("B")) == WriteOk()
    assert r.invoke(Read()) == ReadOk("B")


def test_register_histories():
    assert Register("A").is_valid_history([])
    assert Register("A").is_valid_history([
        (Read(), ReadOk("A")),
        (Write("B"), WriteOk()),
        (Read(), ReadOk("B")),
        (Write("C"), WriteOk()),
        (Read(), ReadOk("C")),
    ])
    assert not Register("A").is_valid_history([
        (Read(), ReadOk("B")),
        (Write("B"), WriteOk()),
    ])
    assert not Register("A").is_valid_history([
        (Write("B"), WriteOk()),
        (Read(), ReadOk("A")),
    ])


# -- Vec ref object (vec.rs:47-93) ---------------------------------------

def test_vec_models_expected_semantics():
    v = VecSpec(["A"])
    assert v.invoke(Len()) == LenOk(1)
    assert v.invoke(Push("B")) == PushOk()
    assert v.invoke(Len()) == LenOk(2)
    assert v.invoke(Pop()) == PopOk("B")
    assert v.invoke(Pop()) == PopOk("A")
    assert v.invoke(Pop()) == PopOk(None)


def test_vec_histories():
    assert VecSpec().is_valid_history([])
    assert VecSpec().is_valid_history([
        (Push(10), PushOk()), (Push(20), PushOk()),
        (Len(), LenOk(2)),
        (Pop(), PopOk(20)), (Len(), LenOk(1)),
        (Pop(), PopOk(10)), (Len(), LenOk(0)),
        (Pop(), PopOk(None)),
    ])
    assert not VecSpec().is_valid_history([
        (Push(10), PushOk()), (Push(20), PushOk()),
        (Len(), LenOk(1)), (Push(30), PushOk()),
    ])
    assert not VecSpec().is_valid_history([
        (Push(10), PushOk()), (Push(20), PushOk()),
        (Pop(), PopOk(10)),
    ])


# -- Linearizability (linearizability.rs:268-453) ------------------------

def test_lin_rejects_invalid_history():
    t = LinearizabilityTester(Register("A"))
    t.on_invoke(99, Write("B"))
    with pytest.raises(ValueError, match="already has an operation"):
        t.on_invoke(99, Write("C"))

    t = LinearizabilityTester(Register("A"))
    t.on_invret(99, Write("B"), WriteOk())
    t.on_invret(99, Write("C"), WriteOk())
    with pytest.raises(ValueError, match="no in-flight invocation"):
        t.on_return(99, WriteOk())


def test_lin_identifies_linearizable_register_history():
    t = LinearizabilityTester(Register("A"))
    t.on_invoke(0, Write("B"))
    t.on_invret(1, Read(), ReadOk("A"))
    assert t.serialized_history() == [(Read(), ReadOk("A"))]

    t = LinearizabilityTester(Register("A"))
    t.on_invoke(0, Read())
    t.on_invoke(1, Write("B"))
    t.on_return(0, ReadOk("B"))
    assert t.serialized_history() == [
        (Write("B"), WriteOk()), (Read(), ReadOk("B"))]


def test_lin_identifies_unlinearizable_register_history():
    t = LinearizabilityTester(Register("A"))
    t.on_invret(0, Read(), ReadOk("B"))
    assert t.serialized_history() is None

    t = LinearizabilityTester(Register("A"))
    t.on_invret(0, Read(), ReadOk("B"))
    t.on_invoke(1, Write("B"))
    assert t.serialized_history() is None  # SC but not linearizable


def test_lin_identifies_linearizable_vec_history():
    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    assert t.serialized_history() == []

    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() == [(Pop(), PopOk(None))]

    t = LinearizabilityTester(VecSpec())
    t.on_invoke(0, Push(10))
    t.on_invret(1, Pop(), PopOk(10))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Pop(), PopOk(10))]

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(0, Push(20))
    t.on_invret(1, Len(), LenOk(1))
    t.on_invret(1, Pop(), PopOk(20))
    t.on_invret(1, Pop(), PopOk(10))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Len(), LenOk(1)), (Push(20), PushOk()),
        (Pop(), PopOk(20)), (Pop(), PopOk(10))]

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(1, Len())
    t.on_invoke(0, Push(20))
    t.on_return(1, LenOk(2))
    assert t.serialized_history() == [
        (Push(10), PushOk()), (Push(20), PushOk()), (Len(), LenOk(2))]


def test_lin_identifies_unlinearizable_vec_history():
    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() is None  # SC but not linearizable

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(1, Len())
    t.on_invoke(0, Push(20))
    t.on_return(1, LenOk(0))
    assert t.serialized_history() is None

    t = LinearizabilityTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invoke(0, Push(20))
    t.on_invret(1, Len(), LenOk(2))
    t.on_invret(1, Pop(), PopOk(10))
    t.on_invret(1, Pop(), PopOk(20))
    assert t.serialized_history() is None


# -- Sequential consistency (sequential_consistency.rs:224-344) ----------

def test_sc_accepts_sc_but_not_linearizable_histories():
    t = SequentialConsistencyTester(Register("A"))
    t.on_invret(0, Read(), ReadOk("B"))
    t.on_invoke(1, Write("B"))
    assert t.serialized_history() == [
        (Write("B"), WriteOk()), (Read(), ReadOk("B"))]

    t = SequentialConsistencyTester(VecSpec())
    t.on_invret(0, Push(10), PushOk())
    t.on_invret(1, Pop(), PopOk(None))
    assert t.serialized_history() == [
        (Pop(), PopOk(None)), (Push(10), PushOk())]


def test_sc_rejects_inconsistent_histories():
    t = SequentialConsistencyTester(Register("A"))
    t.on_invret(0, Read(), ReadOk("B"))
    assert t.serialized_history() is None


def test_testers_are_cloneable_and_hashable():
    t = LinearizabilityTester(Register("A"))
    t.on_invoke(0, Write("B"))
    c = t.clone()
    assert t == c and hash(t) == hash(c)
    c.on_return(0, WriteOk())
    assert t != c
    # original untouched by the clone's mutation
    assert 0 in t.in_flight_by_thread

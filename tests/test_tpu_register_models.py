"""Device forms of the register-workload examples, built on the
declarative ``RegisterWorkloadDevice`` layer: single-copy register and
the ABD quorum register. Parity gates: single-copy 93 @ 2 clients / 1
server (`single-copy-register.rs:98`) and the 2-server linearizability
counterexample (`single-copy-register.rs:118`); ABD 544 @ 2+2 on both
the single-device and sharded engines (`linearizable-register.rs:256`)."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))


@pytest.fixture(scope="module")
def single_copy():
    from single_copy_register import SingleCopyModelCfg

    return SingleCopyModelCfg


@pytest.fixture(scope="module")
def abd():
    from linearizable_register import AbdModelCfg

    return AbdModelCfg


def test_single_copy_device_93(single_copy):
    model = single_copy(2, 1).into_model()
    host = model.checker().spawn_bfs().join()
    tpu = model.checker().spawn_tpu_bfs(batch_size=64).join()
    assert host.unique_state_count() == 93
    assert tpu.unique_state_count() == 93
    assert set(tpu.discoveries()) == set(host.discoveries()) == \
        {"value chosen"}


def test_single_copy_device_finds_counterexample(single_copy):
    tpu = (single_copy(2, 2).into_model()
           .checker().spawn_tpu_bfs(batch_size=64).join())
    # Two servers are NOT linearizable; the on-device predicate must find
    # the counterexample, and its replayed path must prove it on host.
    path = tpu.assert_any_discovery("linearizable")
    final = path.last_state()
    assert final.history.serialized_history() is None


@pytest.mark.slow
def test_abd_device_544(abd):
    model = abd(2, 2).into_model()
    host = model.checker().spawn_bfs().join()
    tpu = model.checker().spawn_tpu_bfs(batch_size=128).join()
    assert host.unique_state_count() == 544
    assert tpu.unique_state_count() == 544
    assert set(tpu.discoveries()) == set(host.discoveries()) == \
        {"value chosen"}


@pytest.mark.slow
def test_abd_device_sharded_544(abd):
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("shard",))
    sharded = (abd(2, 2).into_model()
               .checker().spawn_tpu_bfs(mesh=mesh, batch_size=32).join())
    assert sharded.unique_state_count() == 544
    assert set(sharded.discoveries()) == {"value chosen"}


@pytest.mark.slow
def test_abd_device_step_differential(abd):
    """Every host-reachable state: codec round-trips and the device step
    produces exactly the host's successor set (no-op elision included)."""
    from collections import deque

    import jax
    import jax.numpy as jnp

    from stateright_tpu.fingerprint import fingerprint

    model = abd(2, 2).into_model()
    dm = model.device_model()
    step = jax.jit(dm.step)
    seen = set()
    queue = deque()
    for s in model.init_states():
        seen.add(fingerprint(s))
        queue.append(s)
    checked = 0
    while queue:
        state = queue.popleft()
        vec = dm.encode(state)
        assert fingerprint(dm.decode(vec)) == fingerprint(state)
        if checked < 60:  # cap the expensive device-vs-host comparison
            host_succ = {fingerprint(ns)
                         for _, ns in model.next_steps(state)}
            succ, valid = step(jnp.asarray(vec))
            dev_succ = {fingerprint(dm.decode(np.asarray(succ[i])))
                        for i in range(succ.shape[0]) if bool(valid[i])}
            assert dev_succ == host_succ, state
            checked += 1
        for _, ns in model.next_steps(state):
            fp = fingerprint(ns)
            if fp not in seen:
                seen.add(fp)
                queue.append(ns)
    assert len(seen) == 544

"""Differential fuzzing: native C++ consistency search vs Python search.

Random concurrent register histories (random interleavings of Write/Read
invocations and returns across threads) must get identical verdicts from
the Python backtracking search (`serialized_history()`) and the native
fast path (`native/consistency.cc`) — for both linearizability (with its
real-time happened-before edges) and sequential consistency.
"""

import random

import pytest

from stateright_tpu.native import NATIVE_AVAILABLE
from stateright_tpu.semantics import (LinearizabilityTester, Register,
                                      SequentialConsistencyTester)
from stateright_tpu.semantics.register import (Read, ReadOk, Write,
                                               WriteOk)

SEEDS = list(range(8)) + [pytest.param(i, marks=pytest.mark.slow)
                          for i in range(8, 30)]


def _random_history(rng, tester):
    """Drives a random schedule of invokes/returns; returns may violate
    the spec deliberately (random read values) so both verdicts occur."""
    n_threads = rng.randint(1, 3)
    values = [10, 20, 30]
    pending = {}  # thread -> op
    ops_left = {t: rng.randint(1, 3) for t in range(n_threads)}
    steps = rng.randint(2, 14)
    for _ in range(steps):
        t = rng.randrange(n_threads)
        if t in pending:
            op = pending.pop(t)
            if isinstance(op, Write):
                tester = tester.on_return(t, WriteOk())
            else:
                # Sometimes the "right" value, sometimes a random one.
                tester = tester.on_return(
                    t, ReadOk(rng.choice(values + [None])))
        elif ops_left[t] > 0:
            ops_left[t] -= 1
            op = (Write(rng.choice(values)) if rng.random() < 0.5
                  else Read())
            pending[t] = op
            tester = tester.on_invoke(t, op)
    return tester


@pytest.mark.skipif(not NATIVE_AVAILABLE, reason="no native toolchain")
@pytest.mark.parametrize("seed", SEEDS)
def test_native_matches_python_search(seed):
    rng = random.Random(7000 + seed)
    for trial in range(40):
        for cls in (LinearizabilityTester, SequentialConsistencyTester):
            tester = _random_history(rng, cls(Register(None)))
            native = tester._native_is_consistent()
            assert native is not None, "native path not taken"
            python = tester.serialized_history() is not None
            assert native == python, (
                cls.__name__, seed, trial,
                tester.history_by_thread, tester.in_flight_by_thread)

"""BFS engine parity tests (counterpart of bfs.rs:344-395 tests)."""

from stateright_tpu import StateRecorder
from stateright_tpu.test_util import Guess, LinearEquation


def test_visits_states_in_bfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_bfs().join()
    assert accessor() == [
        (0, 0),                  # distance == 0
        (1, 0), (0, 1),          # distance == 1
        (2, 0), (1, 1), (0, 2),  # distance == 2
        (3, 0), (2, 1),          # distance == 3
    ]


def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_bfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 12

    # BFS found this example... (2*2 + 10*1) % 256 == 14
    assert checker.discovery("solvable").into_actions() == [
        Guess.INCREASE_X, Guess.INCREASE_X, Guess.INCREASE_Y]
    # ...but there are other solutions: (2*0 + 10*27) % 256 == 14
    checker.assert_discovery("solvable", [Guess.INCREASE_Y] * 27)


def test_exact_state_counts_on_early_exit():
    """checker.rs:458-460: states=15, unique=12."""
    checker = LinearEquation(2, 10, 14).checker().spawn_bfs().join()
    assert checker.state_count() == 15
    assert checker.unique_state_count() == 12


def test_multithreaded_parity():
    checker = LinearEquation(2, 4, 7).checker().threads(4).spawn_bfs().join()
    assert checker.unique_state_count() == 256 * 256
    checker.assert_no_discovery("solvable")

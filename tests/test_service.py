"""Checking-as-a-service (round 14): the corpus registry, the
differential fuzz gate, and the multi-tenant job service end to end
over real HTTP — including the acceptance gate: two concurrent jobs
sharing a cached wave program, a preemption resumed to bit-identical
final counters, per-job traces that lint clean, and the ``stpu_job_*``
metric families.

The fast tier keeps every job tiny (2pc @ 3 RMs — 288 states); the
fused-engine arm and the corpus-wide walk sweep run behind ``-m slow``.
"""

import json
import os
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import service_client as sc  # noqa: E402
import trace_lint  # noqa: E402
import trace_summary  # noqa: E402

from stateright_tpu.obs.schema import validate_line  # noqa: E402
from stateright_tpu.service import (DiffMismatch, JobError,  # noqa: E402
                                    JobService, default_registry,
                                    diff_walk, fuzz_gate)

TWOPC = {"model": "twopc", "params": {"rm_count": 3},
         "knobs": {"batch_size": 64}}


# -- Registry --------------------------------------------------------------


def test_registry_corpus():
    r = default_registry()
    names = r.names()
    # The 8 existing models + the round-14 VR addition.
    assert names == ["abd", "increment", "increment_lock", "paxos",
                     "pingpong", "single_copy", "sliding_puzzle",
                     "twopc", "vsr"]
    with pytest.raises(KeyError):
        r.entry("raft")
    with pytest.raises(ValueError):
        r.resolve_params("twopc", {"rms": 5})  # unknown key
    # Coercion: JSON submissions arrive stringly/floaty.
    assert r.resolve_params("twopc", {"rm_count": "5"}) == {"rm_count": 5}
    # Canonical program keys: same params (any spelling) — same key.
    assert r.program_key("twopc", {"rm_count": 3}) == \
        r.program_key("twopc", None)
    assert r.program_key("twopc", {"rm_count": 5}) != \
        r.program_key("twopc", None)
    listing = r.describe()
    assert any(e["name"] == "vsr" and e["params"]["n"] == 3
               for e in listing)


def test_submit_validation():
    svc = JobService(workers=1)
    try:
        with pytest.raises(JobError):
            svc.submit({"model": "raft"})
        with pytest.raises(JobError):
            svc.submit({"model": "twopc", "engine": "warp"})
        with pytest.raises(JobError):
            svc.submit({"model": "twopc", "knobs": {"donate": True}})
        with pytest.raises(JobError):
            svc.submit({"model": "twopc", "properties": ["nope"]})
        with pytest.raises(JobError):
            svc.submit({"model": "twopc", "params": {"rm_count": "x"}})
    finally:
        svc.close()


# -- Differential fuzz gate ------------------------------------------------


def test_diff_walk_catches_broken_device_model():
    """The gate's reason to exist: a device form with a deliberately
    wrong transition must not pass."""
    import stateright_tpu.actor.actor_test_util as ppmod
    from stateright_tpu.actor.actor_test_util import PingPongCfg
    from stateright_tpu.tpu.models.pingpong import PingPongDevice

    class BrokenPingPong(PingPongDevice):
        def deliver(self, body, env):
            import jax.numpy as jnp

            new_body, handled, outs = super().deliver(body, env)
            # Deliberate bug: drop every delivery's validity — the
            # device silently loses all message-driven successors.
            return new_body, handled & jnp.zeros((), bool), outs

    cfg = PingPongCfg(maintains_history=False, max_nat=2)
    model = cfg.into_model()
    with pytest.raises(DiffMismatch, match="successor sets disagree"):
        diff_walk(model, BrokenPingPong(cfg, ppmod), seed=0, steps=10)


def test_diff_walk_catches_broken_property():
    import stateright_tpu.actor.actor_test_util as ppmod
    from stateright_tpu.actor.actor_test_util import PingPongCfg
    from stateright_tpu.tpu.models.pingpong import PingPongDevice

    class WrongProperty(PingPongDevice):
        def device_properties(self):
            import jax.numpy as jnp

            props = super().device_properties()
            props["can reach max"] = lambda v: jnp.ones((), bool)
            return props

    cfg = PingPongCfg(maintains_history=False, max_nat=2)
    model = cfg.into_model()
    with pytest.raises(DiffMismatch, match="property"):
        diff_walk(model, WrongProperty(cfg, ppmod), seed=0, steps=10)


@pytest.mark.slow
def test_fuzz_gate_walks_twopc():
    # Covered in spirit by the corpus-wide sweep below; kept as the
    # single-model CLI-shaped arm.
    result = fuzz_gate("twopc", seeds=(0,), steps=20, full=False)
    assert result["walks"][0]["transitions"] > 0


# -- The service end to end (acceptance gate) ------------------------------


def _wait(base, job_id, timeout=120.0):
    return sc.wait_for(base, job_id, timeout=timeout, poll_s=0.1)


def test_service_end_to_end_http(tmp_path):
    from stateright_tpu.explorer import serve_service

    service, server = serve_service(
        addresses=("127.0.0.1", 0), block=False, workers=2,
        data_dir=str(tmp_path))
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        # Corpus listing over HTTP.
        assert any(e["name"] == "vsr" for e in sc.corpus(base))

        # Two CONCURRENT same-model jobs: submitted back to back into a
        # 2-worker pool, so they race — the per-key build lock means
        # one pays the XLA compile and the other HITS the shared cache.
        j1 = sc.submit(base, TWOPC)
        j2 = sc.submit(base, TWOPC)
        s1, s2 = _wait(base, j1["id"]), _wait(base, j2["id"])
        assert s1["state"] == s2["state"] == "done"
        assert s1["unique"] == s2["unique"] == 288
        assert s1["states"] == s2["states"] == 1146
        assert s1["jit_cache"]["shared"] and s2["jit_cache"]["shared"]
        assert s1["jit_cache"]["hits"] + s2["jit_cache"]["hits"] > 0
        # Verdicts ride the status payload, explorer-style.
        names = {name for _, name, _ in s1["properties"]}
        assert "consistent" in names

        # Preempt over HTTP -> resumable checkpoint -> resubmission
        # finishes with BIT-IDENTICAL final counters.
        j3 = sc.submit(base, {"model": "twopc",
                              "knobs": {"batch_size": 8,
                                        "checkpoint_every_waves": 1}})
        while sc.status(base, j3["id"])["state"] == "queued":
            time.sleep(0.02)
        sc.preempt(base, j3["id"])
        s3 = _wait(base, j3["id"])
        # (A very fast box may finish before the preempt lands — then
        # the run is simply done and there is nothing to resume.)
        if s3["state"] == "preempted":
            assert s3["checkpoint"]
            j4 = sc.resume(base, j3["id"])
            # Second resume of the same job: 409 — two supervisors on
            # one checkpoint rotation would corrupt the generation.
            with pytest.raises(sc.ServiceError) as err:
                sc.resume(base, j3["id"])
            assert err.value.http_status == 409
            s4 = _wait(base, j4["id"])
            assert s4["state"] == "done"
            assert s4["resume_of"] == j3["id"]
            assert (s4["states"], s4["unique"]) == (1146, 288)

        # Per-job traces lint clean, job lifecycle pairing included.
        for payload in sc.jobs(base):
            counts, errors = trace_lint.lint_file(
                service.trace_file(payload["id"]))
            assert not errors, errors[:3]
            assert counts.get("job_submit") == 1
        # Every line of a job trace is schema-valid v7.
        for line in sc.trace_lines(base, j1["id"]):
            assert not validate_line(line)

        # The trace_summary per-job table.
        events = trace_summary.load_events(
            service.trace_file(j1["id"]))
        jobs_tbl = trace_summary.summarize_jobs(events)
        assert jobs_tbl[j1["id"]]["outcome"] == "done"
        assert jobs_tbl[j1["id"]]["states"] == 1146
        assert j1["id"] in trace_summary.format_job_table(jobs_tbl)

        # stpu_job_* metric families on /.metrics.
        metrics = sc.request(base, "/.metrics")
        assert 'stpu_jobs{state="done"}' in metrics
        assert "stpu_job_program_cache_hits_total" in metrics
        assert (f'stpu_job_states_total{{job="{j1["id"]}"}} 1146'
                in metrics)
        # Round-19: the deprecated bare counter duals are gone.
        assert f'stpu_job_states{{job="{j1["id"]}"}}' not in metrics

        # Error mapping: 400 bad spec, 404 unknown id, 409 conflict.
        for bad, code in ((lambda: sc.submit(base, {"model": "nope"}),
                           400),
                          (lambda: sc.status(base, "j-9999"), 404),
                          (lambda: sc.resume(base, j1["id"]), 409)):
            with pytest.raises(sc.ServiceError) as err:
                bad()
            assert err.value.http_status == code

        # The CLI entry points answer against a live service.
        assert sc.main(["--url", base, "corpus"]) == 0
        assert sc.main(["--url", base, "status", j1["id"]]) == 0
        assert sc.main(["--url", base, "trace", j1["id"],
                        "--tail", "3"]) == 0
    finally:
        server.shutdown()
        server.server_close()
        service.close()


def test_job_trace_lint_pairing_unit(tmp_path):
    """The v7 stream invariant, schema-level: an unpaired job_submit
    fails the lint; done/abort pair by exact job id."""
    def line(etype, job, **extra):
        evt = {"type": etype, "schema_version": 7, "engine": "service",
               "run": "r0", "t": 1.0, "job": job}
        evt.update(extra)
        return json.dumps(evt)

    good = [line("job_submit", "j-1", model="twopc",
                 job_engine="classic"),
            line("job_submit", "j-2", model="vsr",
                 job_engine="fused"),
            line("job_abort", "j-2", reason="preempted"),
            line("job_done", "j-1", states=10, unique=5)]
    counts, errors = trace_lint.lint_lines(good)
    assert not errors and counts["job_submit"] == 2

    lost = good[:2]  # two submits, nothing resolved
    _, errors = trace_lint.lint_lines(lost)
    assert len(errors) == 2
    assert all("job_submit" in e for e in errors)

    # Exact-key pairing: j-2's abort cannot retire j-1's submit.
    crossed = [good[0], line("job_abort", "j-2", reason="failed: x")]
    _, errors = trace_lint.lint_lines(crossed)
    assert len(errors) == 1 and "'j-1'" in errors[0]


@pytest.mark.slow
def test_service_fused_jobs_and_host_engine(tmp_path):
    """Fused-engine jobs share dispatch programs too; host-engine jobs
    run (and refuse preemption while running)."""
    svc = JobService(workers=2, data_dir=str(tmp_path))
    try:
        f1 = svc.submit(dict(TWOPC, engine="fused"))
        f2 = svc.submit(dict(TWOPC, engine="fused"))
        h1 = svc.submit({"model": "pingpong", "engine": "host",
                         "params": {"max_nat": 2}})
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            states = [svc.status(j["id"])["state"]
                      for j in (f1, f2, h1)]
            if all(s not in ("queued", "running") for s in states):
                break
            time.sleep(0.1)
        sf1, sf2 = svc.status(f1["id"]), svc.status(f2["id"])
        assert sf1["state"] == sf2["state"] == "done"
        assert sf1["unique"] == sf2["unique"] == 288
        assert sf1["jit_cache"]["hits"] + sf2["jit_cache"]["hits"] > 0
        sh = svc.status(h1["id"])
        assert sh["state"] == "done" and sh["jit_cache"] is None
    finally:
        svc.close()


@pytest.mark.slow
def test_fuzz_gate_corpus_walks():
    """Every corpus model passes seeded random-schedule walks — the
    cheap cross-validation gate future additions run through."""
    for name, params in [("twopc", None), ("pingpong", None),
                         ("increment", None), ("increment_lock", None),
                         ("sliding_puzzle", None),
                         ("vsr", {"n": 2})]:
        fuzz_gate(name, params=params, seeds=(0, 1), steps=15,
                  full=False)

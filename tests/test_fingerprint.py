"""Stable fingerprinting unit tests (counterpart of util.rs hashing tests)."""

import subprocess
import sys
from dataclasses import dataclass
from enum import Enum

import pytest

from stateright_tpu import fingerprint


def test_nonzero_64bit():
    for v in [None, 0, 1, "", "a", (), (1, 2), frozenset(), {}]:
        fp = fingerprint(v)
        assert 0 < fp < 2**64


def test_distinct_primitives():
    values = [None, False, True, 0, 1, "", "0", b"0", 0.0, (), (0,),
              frozenset(), frozenset([0]), {}, {0: 0}]
    fps = [fingerprint(v) for v in values]
    assert len(set(fps)) == len(fps)


def test_tuple_list_equivalent():
    # Sequences hash structurally: [1,2] and (1,2) are the same shape.
    assert fingerprint([1, 2]) == fingerprint((1, 2))


def test_set_order_insensitive():
    """Same fingerprint regardless of insertion order (util.rs:194-208)."""
    a = frozenset(["x", "y", "z"])
    b = frozenset(["z", "x", "y"])
    assert fingerprint(a) == fingerprint(b)
    assert fingerprint({1: "a", 2: "b"}) == fingerprint({2: "b", 1: "a"})


def test_set_vs_tuple_distinct():
    assert fingerprint(frozenset([1, 2])) != fingerprint((1, 2))


def test_nested_structures():
    v1 = ((1, frozenset([(2, "a"), (3, "b")])), {"k": [1, 2]})
    v2 = ((1, frozenset([(3, "b"), (2, "a")])), {"k": [1, 2]})
    assert fingerprint(v1) == fingerprint(v2)


def test_dataclass_and_enum():
    @dataclass(frozen=True)
    class S:
        x: int
        y: tuple

    class E(Enum):
        A = 0
        B = 1

    assert fingerprint(S(1, (2,))) == fingerprint(S(1, (2,)))
    assert fingerprint(S(1, (2,))) != fingerprint(S(2, (2,)))
    assert fingerprint(E.A) != fingerprint(E.B)


def test_large_ints():
    assert fingerprint(2**100) != fingerprint(2**100 + 1)
    assert fingerprint(-1) != fingerprint(1)
    assert fingerprint(2**63) != fingerprint(-(2**63))


def test_stable_across_processes():
    """The whole point: fingerprints must not vary across runs
    (lib.rs:331-344). Python's builtin hash is randomized; ours is keyed."""
    code = ("import sys; sys.path.insert(0, %r); "
            "from stateright_tpu import fingerprint; "
            "print(fingerprint(('paxos', 42, frozenset([1, 2, 3]))))"
            % sys.path[0])
    outs = set()
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, check=True)
        outs.add(r.stdout.strip())
    assert len(outs) == 1
    assert outs.pop() == str(fingerprint(("paxos", 42, frozenset([1, 2, 3]))))


def test_bignum_encoding_injective():
    """Regression: bignums had an in-band marker colliding with i64
    payloads starting 0xff."""
    from stateright_tpu import stable_encode

    assert stable_encode((2559, "a\x00")) != stable_encode(
        (1789334175158500327424, None))
    assert fingerprint((2559, "a\x00")) != fingerprint(
        (1789334175158500327424, None))


def test_custom_encoders_include_type():
    """Regression: two custom types with equal payloads must not collide."""
    from stateright_tpu import register_encoder

    class A:
        def __init__(self, x):
            self.x = x

    class B:
        def __init__(self, x):
            self.x = x

    register_encoder(A, lambda v, buf: buf.extend(v.x.to_bytes(4, "big")))
    register_encoder(B, lambda v, buf: buf.extend(v.x.to_bytes(4, "big")))
    assert fingerprint(A(7)) != fingerprint(B(7))

    class C:
        def __fingerprint__(self):
            return (1, 2)

    class D:
        def __fingerprint__(self):
            return (1, 2)

    assert fingerprint(C()) != fingerprint(D())


def test_unhashable_raises():
    class Opaque:
        pass

    with pytest.raises(TypeError):
        fingerprint(Opaque())

"""The run-telemetry subsystem (stateright_tpu/obs + tools/trace_*).

Contracts pinned here:

- **One wave schema, every engine**: all four device engines AND the
  host BFS emit wave events with the exact same field set for the same
  2pc run, schema-validated by ``tools/trace_lint.py``'s validator —
  one consumer, no per-engine parsers.
- **Disabled means free**: with ``STpu_TRACE`` unset the engines hold
  the shared ``NULL_TRACER`` singleton and the wave loop NEVER calls
  into it (the null methods are poisoned for the test) — the disabled
  subsystem is one attribute check, zero events, zero allocations.
- **Telemetry never changes discovery results**: traced and untraced
  runs produce identical counts and discovery sets (the bit-identity
  contract; the wider 4-engine parity suites are the main guard).
- **Tooling round trip**: a capture lints clean (this is the tier-1
  wiring of trace_lint), exports to a Chrome/Perfetto trace, and dumps
  Prometheus text; the device_session event family validates too.
"""

import io
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "examples"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.obs import (NULL_TRACER, SCHEMA_VERSION, WAVE_FIELDS,
                                NullTracer, RunTracer, tracer_from_env,
                                validate_event)  # noqa: E402

sys.path.insert(0, os.path.join(_REPO, "tools"))
import trace_export  # noqa: E402
import trace_lint  # noqa: E402


def _events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _spawn(model, engine):
    b = model.checker()
    if engine == "host_bfs":
        return b.spawn_bfs()
    if engine == "classic":
        return b.spawn_tpu_bfs(batch_size=64, fused=False)
    if engine == "fused":
        return b.spawn_tpu_bfs(batch_size=64, fused=True)
    if engine == "sharded":
        return b.spawn_tpu_bfs(batch_size=32, sharded=True, fused=False)
    assert engine == "sharded_fused"
    return b.spawn_tpu_bfs(batch_size=32, sharded=True)


ENGINES = ("host_bfs", "classic", "fused", "sharded", "sharded_fused")


def test_wave_schema_identical_across_engines(tmp_path, monkeypatch):
    """All four device engines + host BFS: same 2pc run, same wave
    field set, schema-valid stream, counts consistent with the
    checker's own totals — and tracing changes no result."""
    model = TwoPhaseSys(3)
    ref = model.checker().spawn_bfs().join()  # untraced reference
    field_sets = {}
    for engine in ENGINES:
        path = tmp_path / f"{engine}.jsonl"
        monkeypatch.setenv("STpu_TRACE", str(path))
        c = _spawn(model, engine).join()
        monkeypatch.delenv("STpu_TRACE")

        # Telemetry must not perturb checking.
        assert c.unique_state_count() == ref.unique_state_count(), engine
        assert c.state_count() == ref.state_count(), engine
        assert set(c.discoveries()) == set(ref.discoveries()), engine

        counts, errors = trace_lint.lint_file(str(path))
        assert errors == [], (engine, errors[:3])
        events = _events(path)
        waves = [e for e in events if e.get("type") == "wave"]
        assert waves, engine
        assert all(e["engine"] == engine for e in waves)
        assert {e["type"] for e in events} >= {"run_start", "wave",
                                               "run_end"}
        field_sets[engine] = {frozenset(w) for w in waves}
        # Cumulative totals on the last wave match the checker.
        assert waves[-1]["states"] == c.state_count(), engine
        assert waves[-1]["unique"] == c.unique_state_count(), engine
        # Per-dispatch deltas fold back to the totals.
        assert (sum(w["successors"] for w in waves)
                == c.state_count() - 1), engine
        assert (sum(w["novel"] for w in waves)
                == c.unique_state_count() - 1), engine

    # THE schema contract: one exact field set, every engine.
    expected = {frozenset(WAVE_FIELDS)}
    for engine, sets in field_sets.items():
        assert sets == expected, (engine, sets)


def test_trace_disabled_zero_events_zero_allocations(monkeypatch):
    """STpu_TRACE unset: the engines get the NULL_TRACER singleton and
    the wave loop never calls into it — every null method is poisoned,
    so a single stray emit (= a single stray event-dict allocation in
    the hot loop) fails the run."""
    monkeypatch.delenv("STpu_TRACE", raising=False)
    assert tracer_from_env("classic") is NULL_TRACER

    def _boom(name):
        def poisoned(self, *a, **k):
            raise AssertionError(
                f"NullTracer.{name} called with tracing disabled")
        return poisoned

    for name in ("wave", "event", "counter", "gauge", "span_event"):
        monkeypatch.setattr(NullTracer, name, _boom(name))

    model = TwoPhaseSys(3)
    c = model.checker().spawn_tpu_bfs(batch_size=64, fused=False).join()
    assert c._tracer is NULL_TRACER
    host = model.checker().spawn_bfs().join()
    assert host._tracer is NULL_TRACER
    assert c.unique_state_count() == host.unique_state_count()


def test_tracer_spans_counters_nested(tmp_path):
    tr = RunTracer(str(tmp_path / "t.jsonl"), "bench", meta={"k": 1})
    with tr.span("outer"):
        with tr.span("inner", detail="x"):
            pass
    tr.counter("widgets", 2)
    tr.counter("widgets", 3)
    tr.gauge("pressure", 0.5)
    tr.close()
    tr.close()  # idempotent
    events = _events(tmp_path / "t.jsonl")
    assert [e["type"] for e in events] == [
        "run_start", "span", "span", "counter", "counter", "gauge",
        "run_end"]
    for e in events:
        assert validate_event(e) == [], e
        assert e["schema_version"] == SCHEMA_VERSION
    inner, outer = events[1], events[2]  # inner closes first
    assert (inner["name"], inner["depth"]) == ("inner", 1)
    assert (outer["name"], outer["depth"]) == ("outer", 0)
    assert inner["attrs"] == {"detail": "x"}
    assert outer["dur"] >= inner["dur"]
    assert events[4]["value"] == 5  # counter accumulates
    assert events[-1]["counters"] == {"widgets": 5}


def test_trace_lint_cli_and_session_events(tmp_path, monkeypatch):
    """trace_lint runs standalone (the tier-1 wiring) on an engine
    capture, validates the device_session event family, and actually
    rejects malformed streams."""
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    _spawn(TwoPhaseSys(3), "classic").join()
    monkeypatch.delenv("STpu_TRACE")
    # A device_session-style event shares the stream format.
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps({"event": "init", "platform": "cpu",
                            "schema_version": SCHEMA_VERSION,
                            "t": 1.0, "unix_t": 2.0}) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_lint.py"),
         str(path)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout

    # Corruption trips it: a wave missing a schema field.
    bad = tmp_path / "bad.jsonl"
    events = _events(path)
    wave = next(e for e in events if e.get("type") == "wave").copy()
    del wave["load_factor"]
    wave["rider"] = 1
    bad.write_text(json.dumps(wave) + "\nnot json\n")
    counts, errors = trace_lint.lint_file(str(bad))
    assert any("load_factor" in e for e in errors)
    assert any("rider" in e for e in errors)
    assert any("invalid JSON" in e for e in errors)


def test_trace_export_chrome_and_prometheus(tmp_path, monkeypatch):
    path = tmp_path / "run.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    c = _spawn(TwoPhaseSys(3), "fused").join()
    monkeypatch.delenv("STpu_TRACE")
    out = tmp_path / "run.chrome.json"
    prom = tmp_path / "run.prom"
    rc = trace_export.main([str(path), "-o", str(out),
                            "--prom", str(prom)])
    assert rc == 0
    chrome = json.loads(out.read_text())
    evs = chrome["traceEvents"]
    assert evs and {"ph", "pid", "name"} <= set(evs[0])
    slices = [e for e in evs if e["ph"] == "X"]
    assert slices and all(e["dur"] >= 0 and e["ts"] >= 0
                          for e in slices)
    assert any(e["ph"] == "C" for e in evs)  # counter tracks
    text = prom.read_text()
    assert f"stpu_states_total{{engine=\"fused\"" in text
    assert str(c.state_count()) in text


def test_session_schema_version_lockstep():
    """tools/device_session.py duplicates the schema version as a
    literal (it must emit before any package import); keep it pinned
    to the real one."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_device_session", os.path.join(_REPO, "tools",
                                        "device_session.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.SESSION_SCHEMA_VERSION == SCHEMA_VERSION
    # And its emit() output validates as a session event.
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        mod.emit({"event": "init", "platform": "cpu"})
    evt = json.loads(buf.getvalue())
    assert validate_event(evt) == []


def test_report_flushes_and_prints_rate():
    class FlushCounting(io.StringIO):
        flushes = 0

        def flush(self):
            self.flushes += 1
            super().flush()

    from stateright_tpu.test_util import LinearEquation

    w = FlushCounting()
    (LinearEquation(2, 10, 14).checker().spawn_bfs()
     .report(w, period_s=0.01))
    out = w.getvalue()
    assert out.startswith("Done. states=15, unique=12, sec=")
    assert "states/s=" in out
    assert w.flushes >= 1


def test_metrics_endpoint_prometheus():
    """GET /.metrics serves live Prometheus text for any checker; with
    a device engine it includes load factor + wave cadence."""
    from stateright_tpu.explorer import Explorer

    c = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        batch_size=64, fused=False).join()
    text = Explorer(c).metrics()
    metrics = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            metrics[name] = float(value)
    assert metrics["stpu_states_total"] == c.state_count()
    assert metrics["stpu_unique_states_total"] == c.unique_state_count()
    assert metrics["stpu_done"] == 1.0
    assert 0.0 < metrics["stpu_table_load_factor"] <= 0.5
    assert metrics["stpu_waves_total"] == len(c.dispatch_log)
    assert "stpu_wave_seconds" in metrics


def test_profiling_deadline_bounds_warmup():
    """deadline_s=0: over budget before the first stage completes —
    the mid-wave check must stop the warm-up instead of running every
    remaining compile (previously only the loop top looked)."""
    from stateright_tpu.tpu.profiling import measure_wave_breakdown

    model = TwoPhaseSys(3)
    bd = measure_wave_breakdown(model, batch_size=32,
                                table_capacity=1 << 12, max_waves=4,
                                deadline_s=0.0)
    assert bd["waves"] == 0
    assert bd["states"] == 0
    # An untimed run still works and records warm waves.
    bd2 = measure_wave_breakdown(model, batch_size=32,
                                 table_capacity=1 << 12, max_waves=3)
    assert bd2["waves"] >= 1


def test_profiling_emits_spans(tmp_path, monkeypatch):
    from stateright_tpu.tpu.profiling import measure_wave_breakdown

    path = tmp_path / "prof.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    measure_wave_breakdown(TwoPhaseSys(3), batch_size=32,
                           table_capacity=1 << 12, max_waves=2)
    monkeypatch.delenv("STpu_TRACE")
    events = _events(path)
    spans = {e["name"] for e in events if e.get("type") == "span"}
    assert {"properties", "expand", "fingerprint", "local_dedup",
            "dedup_insert", "compact", "fused_wave"} <= spans
    assert all(validate_event(e) == [] for e in events)


def test_overflow_and_grow_events(tmp_path, monkeypatch):
    """A forced-overflow run records overflow_redispatch events AND the
    per-wave overflow flag; growth shows up as grow events; and
    scheduler_stats — a view over the same stream — agrees."""
    from stateright_tpu.tpu.engine import TpuBfsChecker

    monkeypatch.setattr(
        TpuBfsChecker, "_pick_out_rows",
        lambda self, B: 8 if self._succ_ladder_on
        else self._succ_full_rows(B))
    path = tmp_path / "overflow.jsonl"
    monkeypatch.setenv("STpu_TRACE", str(path))
    c = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        batch_size=64, fused=False, table_capacity=1 << 12).join()
    monkeypatch.delenv("STpu_TRACE")
    events = _events(path)
    overflows = [e for e in events
                 if e.get("type") == "overflow_redispatch"]
    assert overflows
    flagged = sum(1 for e in events
                  if e.get("type") == "wave" and e["overflow"])
    assert flagged == len(overflows)
    stats = c.scheduler_stats()
    assert stats["succ_ladder"]["overflow_redispatches"] == flagged
    assert any(e.get("type") == "grow" for e in events), \
        "2pc-4 at 2^12 must grow the table at least once"

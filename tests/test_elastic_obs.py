"""Distributed observability units: relay/collector merge order, the
always-on flight recorder (armed cost, disarmed cost, dump format,
supervisor attachment), the v5 lint invariants, and the exporters on
synthetic merged streams.

The elastic end-to-end halves (merged kill/join drills linting clean,
worker-crash postmortems, straggler aggregates) live in
``tests/test_elastic.py`` where they share the module-scope runs; this
file is the cheap tier — synthetic events plus a couple of small
classic-engine runs.
"""

import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "examples"))
sys.path.insert(0, os.path.join(_REPO, "tools"))

from two_phase_commit import TwoPhaseSys  # noqa: E402

from stateright_tpu.obs import (FlightRecorder, NULL_RECORDER,  # noqa: E402
                                NullFlightRecorder, RelayTracer,
                                RunTracer, SCHEMA_VERSION,
                                TraceCollector, postmortem_path,
                                recorder_from_env, validate_event)

import trace_export  # noqa: E402
import trace_lint  # noqa: E402
import trace_summary  # noqa: E402


def _wave(i, *, states, unique, epoch=0, rnd=None, extra=None):
    evt = {"t": 1.0 + i, "states": states, "unique": unique,
           "bucket": 4, "waves": 1, "inflight": 0, "compiled": i == 0,
           "successors": 4, "candidates": 4, "novel": 2,
           # Real host-store occupancy gauges (schema v6 withdrew the
           # elastic producers' permanent-null allowance).
           "out_rows": 2, "capacity": 8,
           "load_factor": round(unique / 8, 4),
           "overflow": False, "bytes_per_state": 8, "arena_bytes": None,
           "table_bytes": 8 * unique, "epoch": epoch,
           "round": (i + 1 if rnd is None else rnd)}
    evt.update(extra or {})
    return evt


# -- RelayTracer -----------------------------------------------------------

def test_relay_tracer_stamps_and_rotates():
    relay = RelayTracer("w7", meta={"transport": "thread"})
    relay.wave(_wave(0, states=4, unique=2))
    relay.rotate({"reassigned_at_epoch": 1})
    relay.wave(_wave(0, states=3, unique=1, epoch=1, rnd=2))
    relay.close()
    batch, dropped = relay.drain(limit=100)
    assert dropped == 0
    assert [e["type"] for e in batch] == [
        "run_start", "wave", "run_end", "run_start", "wave", "run_end"]
    # Every event: worker-stamped, strictly increasing seq, valid.
    seqs = [e["seq"] for e in batch]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    for e in batch:
        assert e["worker"] == "w7"
        assert validate_event(e) == [], e
    # Rotation: a NEW run id, wave numbering restarts, seq does not.
    runs = [e["run"] for e in batch]
    assert runs[0] == runs[1] == runs[2] != runs[3]
    assert batch[1]["wave"] == 0 and batch[4]["wave"] == 0
    assert batch[1]["engine"] == "elastic_worker"


def test_relay_tracer_bounded_buffer_counts_drops(monkeypatch):
    monkeypatch.setattr(RelayTracer, "_CAPACITY", 4)
    relay = RelayTracer("w0")
    for i in range(10):
        relay.gauge("g", i)
    batch, dropped = relay.drain(limit=100)
    assert len(batch) == 4
    assert dropped == 7  # run_start + 6 gauges fell off the ring
    # Drain in bounded batches, FIFO.
    relay.gauge("g", 10)
    relay.gauge("g", 11)
    batch, _ = relay.drain(limit=1)
    assert len(batch) == 1 and batch[0]["value"] == 10


def test_relay_unbuffered_mirrors_to_flight():
    """relay_trace off (coordinator untraced): nothing queues for
    shipping, but the flight ring still sees every stamped event —
    dark runs keep their postmortems."""
    flight = FlightRecorder("w0", capacity=8)
    relay = RelayTracer("w0", buffering=False, mirror=flight.record)
    relay.wave(_wave(0, states=4, unique=2))
    batch, dropped = relay.drain()
    assert batch == [] and dropped == 0
    ring = flight.snapshot()
    assert [e["type"] for e in ring] == ["run_start", "wave"]
    assert ring[1]["worker"] == "w0"


# -- TraceCollector --------------------------------------------------------

def test_collector_merges_in_causal_order(tmp_path):
    """Batches arriving interleaved across workers come out sorted by
    (epoch, round, worker, seq), with rotation markers inheriting
    their worker's position (they must never sort ahead of the waves
    they follow)."""
    path = str(tmp_path / "merged.jsonl")
    tracer = RunTracer(path, "elastic")
    col = TraceCollector(tracer)

    r0, r1 = RelayTracer("w0"), RelayTracer("w1")
    r0.wave(_wave(0, states=4, unique=2))
    r0.wave(_wave(1, states=8, unique=4))
    r1.wave(_wave(0, states=5, unique=3))
    r1.rotate({})
    r1.wave(_wave(0, states=2, unique=1, epoch=1, rnd=3))
    # w1's batch lands FIRST: the merge must still put round-1 events
    # before round-2 before round-3, and w0 before w1 within a round.
    col.add_batch("w1", r1.drain(limit=100)[0])
    col.add_batch("w0", r0.drain(limit=100)[0])
    assert col.flush() > 0
    tracer.close()

    counts, errors = trace_lint.lint_file(path)
    assert errors == [], errors[:5]
    with open(path, encoding="utf-8") as f:
        events = [json.loads(line) for line in f if line.strip()]
    waves = [e for e in events if e["type"] == "wave"]
    assert [(w["round"], w["worker"]) for w in waves] == [
        (1, "w0"), (1, "w1"), (2, "w0"), (3, "w1")]
    # Per-worker seq order is preserved in file order.
    for worker in ("w0", "w1"):
        seqs = [e["seq"] for e in events
                if e.get("worker") == worker and "seq" in e]
        assert seqs == sorted(seqs)


def test_collector_straggler_attribution_math():
    col = TraceCollector(tracer=None)
    rec = col.straggler(5, 1, {
        "w0": {"compute_s": 0.4, "exchange_s": 0.1, "successors": 400,
               "queued": 30},
        "w1": {"compute_s": 0.1, "exchange_s": 0.0, "successors": 50,
               "queued": 10}})
    assert rec["slowest"] == "w0"
    assert rec["workers"]["w0"]["wait_s"] == 0.0
    assert rec["workers"]["w1"]["wait_s"] == pytest.approx(0.3)
    # wait share: 0.3 waited of 2 workers * 0.4 max = 0.375
    assert rec["wait_share"] == pytest.approx(0.375, abs=1e-4)
    assert rec["workers"]["w0"]["states_s"] == pytest.approx(1000.0)
    assert rec["workers"]["w0"]["load_share"] == pytest.approx(0.75)
    summary = col.summary()
    assert summary["rounds_timed"] == 1
    assert summary["max_wait_share"] == pytest.approx(0.375, abs=1e-4)
    assert summary["slowest"] == {"w0": 1}
    assert summary["workers"]["w1"]["wait_share"] == pytest.approx(0.75)


# -- Flight recorder -------------------------------------------------------

def test_flight_ring_bounded_and_dump_format(tmp_path):
    fl = FlightRecorder("unit", capacity=3, directory=str(tmp_path))
    for i in range(7):
        fl.record(_wave(i, states=4 * (i + 1), unique=2 * (i + 1)))
    fl.record_event("fault", point="wave_crash", hit=1, mode="crash")
    path = fl.dump("unit test reason")
    assert path == postmortem_path("unit", str(tmp_path))
    assert path == fl.last_dump
    with open(path, encoding="utf-8") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    header, events = lines[0], lines[1:]
    assert header["type"] == "postmortem"
    assert header["reason"] == "unit test reason"
    assert header["events"] == len(events) == 3  # capacity bound
    # Bare entries were stamped into schema-valid wave events; the
    # recorded fault kept its own stamp; every line validates.
    for line in lines:
        assert validate_event(line) == [], line
    assert events[-1]["type"] == "fault"
    assert [e["states"] for e in events[:-1]] == [24, 28]  # newest kept


def test_flight_dump_lints_clean_and_never_clobbers(tmp_path):
    """A postmortem is a bounded WINDOW onto a failure: trace_lint
    accepts one even though its waves start mid-run and its last event
    is an unretired fault (the file's reason to exist) — and a second
    dump at the same name lands beside, not over, the first (a
    supervised retry's record must keep naming the file that
    describes THAT attempt)."""
    fl = FlightRecorder("coord", capacity=16, directory=str(tmp_path))
    # Interleave bare round entries with typed events, the coordinator
    # ring's actual shape — the bare ordinals are non-contiguous after
    # stamping, which only dump mode tolerates.
    for i in range(3):
        fl.record(_wave(i, states=4 * (i + 1), unique=2 * (i + 1)))
        fl.record_event("straggler", round=i + 1, epoch=0,
                        slowest="w0", wait_share=0.1, workers={})
    fl.record_event("fault", point="worker_crash", hit=1, mode="crash",
                    worker="w1")
    first = fl.dump("attempt 1")
    counts, errors = trace_lint.lint_file(first)
    assert errors == [], errors[:5]
    assert counts["postmortem"] == 1 and counts["fault"] == 1
    second = fl.dump("attempt 2")
    assert second != first and os.path.exists(first)
    with open(first, encoding="utf-8") as f:
        assert json.loads(f.readline())["reason"] == "attempt 1"
    with open(second, encoding="utf-8") as f:
        assert json.loads(f.readline())["reason"] == "attempt 2"


def test_relay_run_end_duration_is_per_run():
    relay = RelayTracer("w0")
    relay.rotate({})
    relay.close()
    batch, _ = relay.drain(limit=100)
    ends = [e for e in batch if e["type"] == "run_end"]
    assert len(ends) == 2
    # Both runs were (near-)instant; a cumulative-since-birth duration
    # bug would make the second include the first run's span.
    for e in ends:
        assert 0.0 <= e["dur"] < 1.0


def test_flight_disarmed_zero_cost(monkeypatch):
    """STpu_FLIGHT=0: the engines get the NULL_RECORDER singleton and
    the wave loop never calls into it — every null method is poisoned,
    mirroring the round-8 poisoned-null tracer test (zero recording,
    zero allocation when idle)."""
    monkeypatch.setenv("STpu_FLIGHT", "0")
    assert recorder_from_env("classic") is NULL_RECORDER

    def _boom(name):
        def poisoned(self, *a, **k):
            raise AssertionError(
                f"NullFlightRecorder.{name} called while disarmed")
        return poisoned

    for name in ("record", "record_event", "dump"):
        monkeypatch.setattr(NullFlightRecorder, name, _boom(name))

    c = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        batch_size=64, fused=False).join()
    assert c._flight is NULL_RECORDER
    assert c.flight_dump is None
    assert c.unique_state_count() == 288


def test_flight_armed_by_default_records_waves(monkeypatch):
    """Default (env unset): the ring holds the engine's recent wave
    entries — the same dicts dispatch_log already owns, so recording
    allocates nothing extra — and a clean run dumps nothing."""
    monkeypatch.delenv("STpu_FLIGHT", raising=False)
    c = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        batch_size=64, fused=False).join()
    assert c._flight.armed
    ring = c._flight.snapshot()
    assert 0 < len(ring) <= c._flight.capacity
    assert ring[-1]["states"] == c.state_count()
    assert ring[-1] is not c.dispatch_log[-1]  # snapshot stamps a copy
    assert c.flight_dump is None


def test_supervisor_attaches_flight_dump(tmp_path, monkeypatch):
    """A supervised engine crash leaves a postmortem and the retry
    record (and obs event) names it — the dark-run diagnosis path."""
    from stateright_tpu.resilience import Supervisor, reset_fault_plans

    monkeypatch.setenv("STpu_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("STpu_FAULTS", "wave_crash@n=2")
    reset_fault_plans()
    ckpt = str(tmp_path / "sup.npz")

    def factory(resume_from=None):
        return TwoPhaseSys(3).checker().spawn_tpu_bfs(
            batch_size=64, fused=False, checkpoint_path=ckpt,
            resume_from=resume_from)

    try:
        sup = Supervisor(factory, checkpoint_path=ckpt, max_retries=2,
                         backoff_s=0.01, sleep=lambda s: None)
        done = sup.run()
    finally:
        monkeypatch.delenv("STpu_FAULTS")
        reset_fault_plans()
    assert done.unique_state_count() == 288
    assert len(sup.recoveries) == 1
    dump = sup.recoveries[0]["dump"]
    assert dump and os.path.exists(dump)
    with open(dump, encoding="utf-8") as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert lines[0]["type"] == "postmortem"
    assert "InjectedFault" in lines[0]["reason"]
    assert any(e["type"] == "wave" for e in lines[1:])


# -- v5 lint invariants ----------------------------------------------------

def _evt(etype, **kw):
    base = {"type": etype, "schema_version": SCHEMA_VERSION,
            "engine": "elastic", "run": "r", "t": 1.0}
    base.update(kw)
    return json.dumps(base)


def _worker_wave(worker, seq, run="rw", **kw):
    fields = _wave(0, states=kw.pop("states", 4),
                   unique=kw.pop("unique", 2), rnd=kw.pop("rnd", 1))
    fields.update({"type": "wave", "schema_version": SCHEMA_VERSION,
                   "engine": "elastic_worker", "run": run,
                   "wave": kw.pop("wave", 0), "worker": worker,
                   "seq": seq,
                   # v6 tier gauges + v8 kernel-path keys (the tracer
                   # stamps these for real producers; raw-JSON
                   # builders stamp them here).
                   "tier_device_rows": None, "tier_device_bytes": None,
                   "tier_host_rows": None, "tier_host_bytes": None,
                   "tier_disk_rows": None, "tier_disk_bytes": None,
                   "kernel_path": None, "rows": None,
                   "job_id": None, "jobs_in_wave": None,
                   "io_stall_s": None, "expand_impl": None,
                   # v13 profiler cost fields (null when the program's
                   # cost model was never captured).
                   "cost_flops": None, "cost_bytes": None,
                   "cost_ratio": None})
    fields.update(kw)
    return json.dumps(fields)


def test_lint_per_worker_seq_monotonicity():
    ok = [_worker_wave("w0", 1), _worker_wave("w0", 2, wave=1,
                                              states=8, unique=4)]
    _, errors = trace_lint.lint_lines(ok)
    assert not errors, errors
    # A seq regression is a merge-order loss, even across runs.
    bad = [_worker_wave("w0", 2), _worker_wave("w0", 1, run="rw2")]
    _, errors = trace_lint.lint_lines(bad)
    assert errors and "per-worker order lost" in errors[0]


def test_lint_elastic_wave_requires_attribution():
    line = json.loads(_worker_wave("w0", 1))
    line["worker"] = None
    _, errors = trace_lint.lint_lines([json.dumps(line)])
    assert any("without 'worker'" in e for e in errors)
    # Coordinator waves need their merge position too.
    coord = json.loads(_worker_wave("x", 1))
    coord.update(engine="elastic", worker=None, seq=None, epoch=None)
    _, errors = trace_lint.lint_lines([json.dumps(coord)])
    assert any("without 'epoch'" in e for e in errors)
    # v4 captures predate the keys: no retroactive failures.
    old = json.loads(_worker_wave("x", 1))
    old.update(engine="elastic", schema_version=4)
    for key in ("worker", "seq", "epoch", "round",
                "tier_device_rows", "tier_device_bytes",
                "tier_host_rows", "tier_host_bytes",
                "tier_disk_rows", "tier_disk_bytes",
                "kernel_path", "rows", "job_id", "jobs_in_wave",
                "io_stall_s", "expand_impl",
                "cost_flops", "cost_bytes", "cost_ratio"):
        old.pop(key, None)
    _, errors = trace_lint.lint_lines([json.dumps(old)])
    assert not errors, errors


def test_lint_worker_fault_pairing_across_rotation():
    fault_w1 = _evt("fault", point="worker_crash", hit=1, mode="crash",
                    worker="w1")
    lost_w1 = _evt("worker_lost", worker="w1", epoch=0)
    migrated = _evt("migrate_done", partitions=4, to="w0", epoch=1)
    _, errors = trace_lint.lint_lines([fault_w1, lost_w1, migrated])
    assert not errors, errors
    # Unmigrated worker fault at end-of-stream: flagged per worker.
    _, errors = trace_lint.lint_lines([fault_w1, lost_w1])
    assert any("fault on worker 'w1'" in e for e in errors)
    # Two casualties cannot retire each other's faults: w1's
    # migrate_done must not silence w2's fault.
    fault_w2 = _evt("fault", point="worker_crash", hit=2, mode="crash",
                    worker="w2")
    lost_w2 = _evt("worker_lost", worker="w2", epoch=1)
    stream = [fault_w1, fault_w2, lost_w1, lost_w2, migrated]
    _, errors = trace_lint.lint_lines(stream)
    assert any("worker 'w2'" in e for e in errors)
    assert not any("worker 'w1'" in e and "fault" in e for e in errors)
    # The terminal abort retires everything (acknowledged, not silent).
    _, errors = trace_lint.lint_lines(
        stream + [_evt("abort", reason="gave up", attempts=1)])
    assert not errors, errors


# -- Exporters on merged streams -------------------------------------------

def test_export_one_track_per_worker(tmp_path):
    lines = [
        _evt("run_start", unix_t=0.0, meta={}),
        json.dumps(dict(json.loads(_worker_wave("w0", 1)))),
        json.dumps(dict(json.loads(_worker_wave("w1", 1, run="rx")))),
        # a rotated run for w0 must land on the SAME track
        json.dumps(dict(json.loads(
            _worker_wave("w0", 2, run="rw2", states=9, unique=5,
                         rnd=2)))),
        _evt("worker_lost", worker="w1", epoch=0),
        _evt("migrate_done", partitions=2, to="w0", epoch=1),
        _evt("straggler", round=1, epoch=0, slowest="w0",
             wait_share=0.25,
             workers={"w0": {"compute_s": 0.2, "wait_s": 0.0},
                      "w1": {"compute_s": 0.1, "wait_s": 0.1}}),
    ]
    path = tmp_path / "merged.jsonl"
    path.write_text("\n".join(lines) + "\n")
    chrome = trace_export.to_chrome(trace_export.load_events(str(path)))
    names = {e["args"]["name"] for e in chrome["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"elastic coordinator", "elastic worker w0",
                     "elastic worker w1"}
    instants = {e["name"] for e in chrome["traceEvents"]
                if e.get("ph") == "i"}
    assert {"worker_lost", "migrate_done", "straggler"} <= instants
    prom = trace_export.to_prometheus(
        trace_export.load_events(str(path)))
    assert 'stpu_worker_wait_seconds_total{worker="w1"} 0.1' in prom
    assert "stpu_max_wait_share 0.25" in prom


def test_export_accepts_postmortem_dump(tmp_path):
    fl = FlightRecorder("w3", capacity=4, directory=str(tmp_path))
    fl.record(_wave(0, states=4, unique=2))
    fl.record_event("fault", point="worker_crash", hit=1, mode="crash",
                    worker="w3")
    dump = fl.dump("drill")
    events = trace_export.load_events(dump)
    chrome = trace_export.to_chrome(events)
    instants = {e["name"] for e in chrome["traceEvents"]
                if e.get("ph") == "i"}
    assert {"postmortem", "fault"} <= instants
    # And the summary CLI tabulates it.
    rows = trace_summary.summarize(events)
    assert rows["w3"]["faults"] == 1

"""Gather-form vs flattened-combo serialization predicates.

``observation_tables`` (the gather-form linearizability/SC predicate
that runs on device) must agree with ``serialization_tables`` (the
original flattened-combo reduction, kept as the reference oracle) on
EVERY syntactic history — including violating ones that reachable
register-workload states never produce. The fast test samples widely;
the slow test is exhaustive at 2 clients (57,600 histories)."""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paxos as paxos_mod
from stateright_tpu.tpu.models.paxos import PaxosDevice
from stateright_tpu.tpu.register_workload import serialization_tables


def _combo_form_search(dm, vec, real_time_edges):
    """The original flattened-combo reduction, in numpy — the oracle."""
    include, wbefore, later0, later1 = [
        np.asarray(t) for t in serialization_tables(dm.C)]
    c, hist_off = dm.C, dm.hist_off
    status = np.array([vec[hist_off + 3 * j] for j in range(c)])
    rets = np.array([vec[hist_off + 3 * j + 1] for j in range(c)])
    hbs = np.array([vec[hist_off + 3 * j + 2] for j in range(c)])
    p = include.shape[0]
    w_placed = (status >= 2)[None, :] | ((status == 1)[None, :] & include)
    r_placed = (status == 4)[None, :] | ((status == 3)[None, :] & include)
    wpp = np.concatenate([w_placed, np.zeros((p, 1), bool)], axis=1)
    ok = np.ones(p, bool)
    for t in range(c):
        rp = r_placed[:, t]
        v = np.zeros(p, np.uint32)
        for slot in range(c - 1, -1, -1):
            j = wbefore[:, t, slot]
            placed_j = wpp[np.arange(p), j]
            v = np.where(placed_j, (j + 1).astype(np.uint32), v)
        ok &= ~((status[t] == 4) & rp) | (v == rets[t])
        if real_time_edges:
            edge_ok = np.ones(p, bool)
            for j in range(c):
                if j == t:
                    continue
                edge = (hbs[t] >> (2 * j)) & 3
                edge_ok &= ~(((edge >= 1) & later0[:, t, j])
                             | ((edge >= 2) & later1[:, t, j]))
            ok &= ~rp | edge_ok
    return bool(ok.any())


def _diff(dm, histories):
    props = dm.device_properties()
    lin = jax.jit(props["linearizable"])
    sc = jax.jit(props["sequentially consistent"])
    n_false = 0
    for combo in histories:
        vec = np.zeros(dm.state_width, np.uint32)
        for t, (st, ret, hb) in enumerate(combo):
            base = dm.hist_off + 3 * t
            vec[base], vec[base + 1], vec[base + 2] = st, ret, hb
        jvec = jnp.asarray(vec)
        expect_lin = _combo_form_search(dm, vec, True)
        assert bool(lin(jvec)) == expect_lin, combo
        assert bool(sc(jvec)) == _combo_form_search(dm, vec, False), combo
        n_false += not expect_lin
    return n_false


def _per_client_domain(c):
    return list(itertools.product(range(5), range(c + 1),
                                  range(1 << (2 * c))))


def test_predicates_agree_sampled():
    rng = np.random.default_rng(11)
    for c, n in ((1, 60), (2, 600), (3, 600)):
        dm = PaxosDevice(c, 3, paxos_mod)
        domain = _per_client_domain(c)
        histories = [
            tuple(domain[rng.integers(len(domain))] for _ in range(c))
            for _ in range(n)]
        _diff(dm, histories)


@pytest.mark.slow
def test_predicates_agree_exhaustive_2clients():
    dm = PaxosDevice(2, 3, paxos_mod)
    histories = itertools.product(_per_client_domain(2), repeat=2)
    n_false = _diff(dm, histories)
    assert n_false > 9000  # the violating region is genuinely covered

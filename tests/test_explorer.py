"""Explorer server tests, mirroring `src/checker/explorer.rs:242-448`:
handler-level tests on the JSON contract plus an end-to-end HTTP smoke
test over a real socket."""

import json
import urllib.request

from stateright_tpu.actor.actor_test_util import PingPongCfg
from stateright_tpu.explorer import Explorer, Snapshot, serve
from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.test_util import BinaryClock


def _explorer(model):
    return Explorer(model.checker().spawn_bfs().join())


def test_can_init():
    # `explorer.rs:247-255`: the empty path returns the init states.
    ex = _explorer(BinaryClock())
    status, views = ex.states("/")
    assert status == 200
    assert [v["state"] for v in views] == ["0", "1"]
    assert all("action" not in v and "outcome" not in v for v in views)
    assert views[0]["fingerprint"] == str(fingerprint(0))


def test_can_next():
    # `explorer.rs:257-276`: following fingerprints yields the next steps.
    ex = _explorer(BinaryClock())
    path = f"/{fingerprint(1)}/{fingerprint(0)}"
    status, views = ex.states(path)
    assert status == 200
    assert len(views) == 1
    assert views[0]["action"] == "GO_HIGH"  # our enum formats Debug-style
    assert views[0]["state"] == "1"
    assert views[0]["fingerprint"] == str(fingerprint(1))


def test_err_for_invalid_fingerprint():
    # `explorer.rs:278-286`.
    ex = _explorer(BinaryClock())
    status, msg = ex.states("/one/two/three")
    assert status == 404 and msg == "Unable to parse fingerprints /one/two/three"
    status, msg = ex.states("/1/2/3")
    assert status == 404
    assert msg == "Unable to find state following fingerprints /1/2/3"


def test_smoke_test_states():
    # `explorer.rs:288-373`: ping-pong lossy non-duplicating; the state
    # after the first envelope has two candidate steps (Drop + Deliver).
    model = (PingPongCfg(max_nat=2, maintains_history=True)
             .into_model()
             .with_duplicating_network(False)
             .with_lossy_network(True))
    ex = Explorer(model.checker().spawn_bfs().join())
    status, init_views = ex.states("/")
    assert status == 200 and len(init_views) == 1
    assert "svg" in init_views[0]  # sequence diagram present
    first_fp = init_views[0]["fingerprint"]

    status, views = ex.states(f"/{first_fp}")
    assert status == 200 and len(views) == 2
    actions = [v["action"] for v in views]
    assert any(a.startswith("Drop(") for a in actions)
    assert any("→" in a for a in actions)  # Deliver formats "src → msg → dst"
    # Every non-ignored view carries state + fingerprint + svg.
    for v in views:
        assert {"state", "fingerprint", "svg"} <= set(v)


def test_smoke_test_status():
    # `explorer.rs:375-431`: ping-pong max_nat=2 perfect network = 5 states.
    model = (PingPongCfg(max_nat=2, maintains_history=True)
             .into_model()
             .with_duplicating_network(False)
             .with_lossy_network(False))
    snapshot = Snapshot()
    checker = model.checker().visitor(snapshot).spawn_bfs().join()
    status = Explorer(checker, snapshot).status()

    assert status["done"] is True
    assert status["state_count"] == 5
    assert status["unique_state_count"] == 5
    assert "ActorModel" in status["model"]

    def assert_discovery(expectation, name, has_discovery):
        assert any(
            e == expectation and n == name and (d is not None) == has_discovery
            for e, n, d in status["properties"]), (
            expectation, name, has_discovery, status["properties"])

    assert_discovery("Always", "delta within 1", False)
    assert_discovery("Sometimes", "can reach max", True)
    assert_discovery("Eventually", "must reach max", False)
    assert_discovery("Eventually", "must exceed max", True)
    assert_discovery("Always", "#in <= #out", False)
    assert_discovery("Eventually", "#out <= #in + 1", False)
    assert status["recent_path"].startswith("[")


def test_discovery_path_encodes_fingerprints():
    # Discovery paths in /.status are `/`-joined fingerprints the /.states
    # route can replay (`path.rs:160-165`).
    model = (PingPongCfg(max_nat=2, maintains_history=True)
             .into_model()
             .with_duplicating_network(False)
             .with_lossy_network(False))
    checker = model.checker().spawn_bfs().join()
    ex = Explorer(checker)
    status = ex.status()
    encoded = next(d for e, n, d in status["properties"]
                   if n == "can reach max")
    http_status, views = ex.states("/" + encoded)
    assert http_status == 200 and views  # replayable end state


def test_serve_end_to_end():
    # Real socket round-trip: /.status, /.states, /, /app.js.
    builder = BinaryClock().checker()
    checker, server = serve(builder, ("127.0.0.1", 0), block=False)
    try:
        checker.join()
        host, port = server.server_address[:2]
        base = f"http://{host}:{port}"

        with urllib.request.urlopen(f"{base}/.status", timeout=10) as r:
            status = json.loads(r.read())
        assert status["unique_state_count"] == 2

        with urllib.request.urlopen(f"{base}/.states/", timeout=10) as r:
            views = json.loads(r.read())
        assert [v["state"] for v in views] == ["0", "1"]

        for route, marker in [("/", b"Explorer"), ("/app.js", b"fetch")]:
            with urllib.request.urlopen(base + route, timeout=10) as r:
                assert marker in r.read()
    finally:
        server.shutdown()
        server.server_close()

"""DFS engine parity tests (counterpart of dfs.rs:343-481 tests)."""

from dataclasses import dataclass
from typing import Tuple

from stateright_tpu import Model, PathRecorder, Property, StateRecorder
from stateright_tpu.test_util import Guess, LinearEquation
import pytest


def test_visits_states_in_dfs_order():
    recorder, accessor = StateRecorder.new_with_accessor()
    LinearEquation(2, 10, 14).checker().visitor(recorder).spawn_dfs().join()
    assert accessor() == [(0, y) for y in range(28)]


@pytest.mark.slow
def test_can_complete_by_enumerating_all_states():
    checker = LinearEquation(2, 4, 7).checker().spawn_dfs().join()
    assert checker.is_done()
    checker.assert_no_discovery("solvable")
    assert checker.unique_state_count() == 256 * 256


def test_can_complete_by_eliminating_properties():
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    checker.assert_properties()
    assert checker.unique_state_count() == 55

    # DFS found this example: (2*0 + 10*27) % 256 == 14
    assert checker.discovery("solvable").into_actions() == \
        [Guess.INCREASE_Y] * 27
    checker.assert_discovery("solvable", [
        Guess.INCREASE_X, Guess.INCREASE_Y, Guess.INCREASE_X])


def test_exact_state_counts_on_early_exit():
    """checker.rs:477-478: states=55, unique=55."""
    checker = LinearEquation(2, 10, 14).checker().spawn_dfs().join()
    assert checker.state_count() == 55
    assert checker.unique_state_count() == 55


# -- Symmetry reduction (dfs.rs:392-481) ---------------------------------

@dataclass(frozen=True)
class SysState:
    """Each process advances Loading -> Running <-> Paused. See the
    reference's regression narrative at dfs.rs:399-425: the path must
    continue with the original (not canonicalized) state."""
    procs: Tuple[str, ...]

    def representative(self) -> "SysState":
        return SysState(tuple(sorted(self.procs)))


_NEXT = {"Loading": "Running", "Running": "Paused", "Paused": "Running"}


class Sys(Model):
    def init_states(self):
        return [SysState(("Loading", "Loading"))]

    def actions(self, state, actions):
        actions.extend([0, 1])

    def next_state(self, state, action):
        procs = list(state.procs)
        procs[action] = _NEXT[procs[action]]
        return SysState(tuple(procs))

    def properties(self):
        return [
            Property.always("visit all states", lambda _, s: True),
            Property.sometimes(
                "a process pauses",
                lambda _, s: "Paused" in s.procs),
        ]


def test_can_apply_symmetry_reduction():
    # 9 states without symmetry reduction.
    assert Sys().checker().spawn_dfs().join().unique_state_count() == 9
    assert Sys().checker().spawn_bfs().join().unique_state_count() == 9

    # 6 states with symmetry reduction. PathRecorder raises on invalid
    # paths, which catches the canonicalized-path bug.
    visitor, _ = PathRecorder.new_with_accessor()
    checker = Sys().checker().symmetry().visitor(visitor).spawn_dfs().join()
    assert checker.unique_state_count() == 6

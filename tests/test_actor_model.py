"""Actor-model parity tests (counterpart of actor/model.rs:515-853 and
actor.rs:446-501 tests)."""

from dataclasses import dataclass

from stateright_tpu import Expectation, StateRecorder
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    ActorModelState,
    DeliverAction,
    DropAction,
    Envelope,
    Id,
    Network,
    Out,
    ScriptActor,
    majority,
    model_timeout,
    peer_ids,
)
from stateright_tpu.actor.actor_test_util import Ping, PingPongCfg, Pong


def _states_and_network(states, envelopes):
    return ActorModelState(
        actor_states=list(states),
        network=Network.from_iter(envelopes),
        is_timer_set=[],
        history=(0, 0),
    )


def test_visits_expected_states():
    """actor/model.rs:525-618: max_nat=1, lossy — exactly 14 states."""
    recorder, accessor = StateRecorder.new_with_accessor()
    checker = (PingPongCfg(maintains_history=False, max_nat=1)
               .into_model()
               .with_lossy_network(True)
               .checker().visitor(recorder).spawn_bfs().join())
    assert checker.unique_state_count() == 14

    state_space = accessor()
    assert len(state_space) == 14
    e01_ping0 = Envelope(Id(0), Id(1), Ping(0))
    e10_pong0 = Envelope(Id(1), Id(0), Pong(0))
    e01_ping1 = Envelope(Id(0), Id(1), Ping(1))
    expected = [
        # When the network loses no messages...
        _states_and_network([0, 0], [e01_ping0]),
        _states_and_network([0, 1], [e01_ping0, e10_pong0]),
        _states_and_network([1, 1], [e01_ping0, e10_pong0, e01_ping1]),
        # When the network loses the message for state (0, 0)...
        _states_and_network([0, 0], []),
        # When the network loses a message for state (0, 1)...
        _states_and_network([0, 1], [e10_pong0]),
        _states_and_network([0, 1], [e01_ping0]),
        _states_and_network([0, 1], []),
        # When the network loses a message for state (1, 1)...
        _states_and_network([1, 1], [e10_pong0, e01_ping1]),
        _states_and_network([1, 1], [e01_ping0, e01_ping1]),
        _states_and_network([1, 1], [e01_ping0, e10_pong0]),
        _states_and_network([1, 1], [e01_ping1]),
        _states_and_network([1, 1], [e10_pong0]),
        _states_and_network([1, 1], [e01_ping0]),
        _states_and_network([1, 1], []),
    ]
    assert set(state_space) == set(expected)


def test_maintains_fixed_delta_despite_lossy_duplicating_network():
    checker = (PingPongCfg(maintains_history=False, max_nat=5)
               .into_model()
               .with_lossy_network(True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 4094
    checker.assert_no_discovery("delta within 1")


def test_may_never_reach_max_on_lossy_network():
    checker = (PingPongCfg(maintains_history=False, max_nat=5)
               .into_model()
               .with_lossy_network(True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 4094
    # can lose the first message and get stuck, for example
    checker.assert_discovery("must reach max", [
        DropAction(Envelope(Id(0), Id(1), Ping(0)))])


def test_eventually_reaches_max_on_perfect_delivery_network():
    checker = (PingPongCfg(maintains_history=False, max_nat=5)
               .into_model()
               .with_duplicating_network(False)
               .with_lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    checker.assert_no_discovery("must reach max")


def test_can_reach_max():
    checker = (PingPongCfg(maintains_history=False, max_nat=5)
               .into_model()
               .with_lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    assert checker.discovery(
        "can reach max").last_state().actor_states == [4, 5]


def test_might_never_reach_beyond_max():
    # A falsifiable liveness property (due to the boundary).
    checker = (PingPongCfg(maintains_history=False, max_nat=5)
               .into_model()
               .with_duplicating_network(False)
               .with_lossy_network(False)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 11
    assert checker.discovery(
        "must exceed max").last_state().actor_states == [5, 5]


def test_history_properties():
    """The history mechanism: (#in, #out) tracked via record hooks."""
    checker = (PingPongCfg(maintains_history=True, max_nat=3)
               .into_model()
               .checker().spawn_bfs().join())
    checker.assert_no_discovery("#in <= #out")
    checker.assert_no_discovery("#out <= #in + 1")


class _NoopActor(Actor):
    def on_start(self, id, o):
        return ()


def test_handles_undeliverable_messages():
    """actor/model.rs:701-711: envelopes to unknown actors are inert."""
    checker = (ActorModel()
               .actor(_NoopActor())
               .property(Expectation.ALWAYS, "unused", lambda _, __: True)
               .with_init_network([Envelope(Id(0), Id(99), ())])
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 1


class _TimerActor(Actor):
    def on_start(self, id, o):
        o.set_timer(model_timeout())
        return ()


def test_resets_timer():
    """actor/model.rs:713-734: timer set at init, cleared by timeout."""
    checker = (ActorModel()
               .actor(_TimerActor())
               .property(Expectation.ALWAYS, "unused", lambda _, __: True)
               .checker().spawn_bfs().join())
    assert checker.unique_state_count() == 2


def test_vec_can_serve_as_actor():
    """actor.rs:467-500: scripted actors; network contents per state."""
    recorder, accessor = StateRecorder.new_with_accessor()
    (ActorModel()
     .actor(ScriptActor([(Id(1), "A"), (Id(1), "B")]))
     .actor(ScriptActor([(Id(0), "C"), (Id(0), "D")]))
     .property(Expectation.ALWAYS, "", lambda _, __: True)
     .checker().visitor(recorder).spawn_bfs().join())
    messages_by_state = [
        sorted(e.msg for e in s.network) for s in accessor()]
    # Same 4-state space as the reference; level-1 visit order differs
    # because our network iterates in insertion order, not hash order.
    assert messages_by_state == [
        ["A", "C"],
        ["A", "C", "D"],
        ["A", "B", "C"],
        ["A", "B", "C", "D"],
    ]


def test_heterogeneous_actors():
    """Counterpart of the choice_test (actor/model.rs:737-852): Python
    actor lists are naturally heterogeneous. A->B->C round-robin with an
    out-count history and a boundary of 8; exact 7-state DFS trace."""

    class A(Actor):
        def __init__(self, b):
            self.b = b

        def on_start(self, id, o):
            return 1

        def on_msg(self, id, state, src, msg, o):
            o.send(self.b, ())
            return (state + 1) % 256

    class B(Actor):
        def __init__(self, c):
            self.c = c

        def on_start(self, id, o):
            return "a"

        def on_msg(self, id, state, src, msg, o):
            o.send(self.c, ())
            return chr((ord(state) + 1) % 256)

    class C(Actor):
        def __init__(self, a):
            self.a = a

        def on_start(self, id, o):
            o.send(self.a, ())
            return "I"

        def on_msg(self, id, state, src, msg, o):
            o.send(self.a, ())
            return state + "I"

    recorder, accessor = StateRecorder.new_with_accessor()
    (ActorModel(cfg=None, init_history=0)
     .actor(A(Id(1)))
     .actor(B(Id(2)))
     .actor(C(Id(0)))
     .with_duplicating_network(False)
     .record_msg_out(lambda cfg, out_count, env: out_count + 1)
     .property(Expectation.ALWAYS, "true", lambda _, __: True)
     .with_boundary(lambda cfg, state: state.history < 8)
     .checker().visitor(recorder).spawn_dfs().join())
    states = [s.actor_states for s in accessor()]
    assert states == [
        [1, "a", "I"],
        [2, "a", "I"],
        [2, "b", "I"],
        [2, "b", "II"],
        [3, "b", "II"],
        [3, "c", "II"],
        [3, "c", "III"],
    ]


def test_majority_and_peers():
    assert [majority(n) for n in (1, 2, 3, 4, 5)] == [1, 2, 2, 3, 3]
    ids = [Id(i) for i in range(3)]
    assert list(peer_ids(ids[1], ids)) == [Id(0), Id(2)]


def test_logical_clock_counterexample():
    """The actor.rs module doc example (actor.rs:11-78): logical clocks
    disprove 'clock < 3'."""

    @dataclass(frozen=True)
    class MsgWithTimestamp:
        ts: int

    class LogicalClockActor(Actor):
        def __init__(self, bootstrap_to_id=None):
            self.bootstrap_to_id = bootstrap_to_id

        def on_start(self, id, o):
            if self.bootstrap_to_id is not None:
                o.send(self.bootstrap_to_id, MsgWithTimestamp(1))
                return 1
            return 0

        def on_msg(self, id, state, src, msg, o):
            if msg.ts > state:
                o.send(src, MsgWithTimestamp(msg.ts + 1))
                return msg.ts + 1
            return None

    checker = (ActorModel()
               .actor(LogicalClockActor())
               .actor(LogicalClockActor(bootstrap_to_id=Id(0)))
               .property(Expectation.ALWAYS, "less than max",
                         lambda _, state: all(
                             s < 3 for s in state.actor_states))
               .checker().spawn_bfs().join())
    checker.assert_discovery("less than max", [
        DeliverAction(Id(1), Id(0), MsgWithTimestamp(1)),
        DeliverAction(Id(0), Id(1), MsgWithTimestamp(2)),
    ])
    assert checker.discovery(
        "less than max").last_state().actor_states == [2, 3]

"""The adaptive wave scheduler (engine.py / fused.py / sharded*.py).

Three contracts:

- **Cross-B parity**: counts, discoveries, parent pointers, and
  checkpoints are identical whatever dispatch width the scheduler picks
  — the bucket ladder is purely a performance schedule. Pinned across
  all four device engines on 2pc and paxos.
- **Donation**: table growth / rehash never retains the pre-growth
  buffer (the arena doubling stops doubling peak memory).
- **Telemetry**: dispatch_log / scheduler_stats report the ladder, the
  buckets actually used, and the pipeline depth achieved — bench.py's
  steady-rate and BENCH attribution depend on them.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

import numpy as np
import pytest

from stateright_tpu.tpu.engine import batch_bucket_ladder, pick_bucket
from two_phase_commit import TwoPhaseSys


def _spawn(model, engine, B, **kwargs):
    b = model.checker()
    if engine == "fused":
        return b.spawn_tpu_bfs(batch_size=B, fused=True, **kwargs)
    if engine == "classic":
        return b.spawn_tpu_bfs(batch_size=B, fused=False, **kwargs)
    if engine == "sharded-fused":
        return b.spawn_tpu_bfs(batch_size=B, sharded=True, **kwargs)
    assert engine == "sharded-classic"
    return b.spawn_tpu_bfs(batch_size=B, sharded=True, fused=False,
                           **kwargs)


def test_bucket_ladder_shape():
    assert batch_bucket_ladder(1024, None) == (1024,)
    assert batch_bucket_ladder(1024, 1024) == (1024,)
    assert batch_bucket_ladder(1024, 16384) == (
        1024, 2048, 4096, 8192, 16384)
    # Non-power-of-two top rounds up; base is kept verbatim.
    assert batch_bucket_ladder(64, 200) == (64, 128, 256)
    assert pick_bucket((64, 128, 256), 1) == 64
    assert pick_bucket((64, 128, 256), 65) == 128
    assert pick_bucket((64, 128, 256), 10 ** 9) == 256


@pytest.mark.parametrize("engine", [
    "fused",
    # The sharded pair compiles three shard_map programs each (~85s of
    # the tier-1 budget); round 15 moved the classic arm out too (the
    # fused arm is the fast-set representative; cross-B independence is
    # engine-generic — the dedup rule, not the host loop).
    pytest.param("classic", marks=pytest.mark.slow),
    pytest.param("sharded-fused", marks=pytest.mark.slow),
    pytest.param("sharded-classic", marks=pytest.mark.slow)])
def test_cross_batch_parity_2pc(engine):
    """Same model at three batch buckets: identical unique counts,
    total counts, and discovery identities (B-independence is what
    makes the adaptive ladder safe)."""
    model = TwoPhaseSys(4)
    ref = model.checker().spawn_bfs().join()
    for B in (32, 128, 512):
        c = _spawn(model, engine, B).join()
        assert c.unique_state_count() == ref.unique_state_count(), \
            (engine, B)
        assert c.state_count() == ref.state_count(), (engine, B)
        assert set(c.discoveries()) == set(ref.discoveries()), (engine, B)


@pytest.mark.slow  # the 2pc parity above is the fast-set gate; the
# paxos workload re-runs the same matrix at ~40s (tier-1 headroom)
@pytest.mark.parametrize("engine", ["fused", "classic"])
def test_cross_batch_parity_paxos(engine):
    from paxos import PaxosModelCfg

    model = PaxosModelCfg(1, 3).into_model()
    results = []
    for B in (64, 512):
        c = _spawn(model, engine, B).join()
        results.append((c.unique_state_count(), c.state_count(),
                        frozenset(c.discoveries())))
    assert results[0] == results[1]


def test_adaptive_ladder_matches_fixed_batch():
    """A run under the adaptive scheduler (multi-rung ladder, several
    buckets actually exercised) is bit-identical to the fixed-width
    run, and the telemetry shows the ladder was used."""
    model = TwoPhaseSys(4)
    ref = model.checker().spawn_tpu_bfs(batch_size=256).join()
    c = model.checker().spawn_tpu_bfs(
        batch_size=16, max_batch_size=256, waves_per_dispatch=2).join()
    assert c.unique_state_count() == ref.unique_state_count()
    assert c.state_count() == ref.state_count()
    assert set(c.discoveries()) == set(ref.discoveries())
    stats = c.scheduler_stats()
    assert stats["bucket_ladder"] == [16, 32, 64, 128, 256]
    used = {int(b) for b in stats["bucket_dispatches"]}
    assert used <= set(stats["bucket_ladder"])
    assert len(used) >= 2, "the ladder should actually adapt"
    assert stats["dispatches"] == len(c.dispatch_log)


def _ckpt_payload(path):
    """Every npz member's raw bytes (member-wise, not whole-file: the
    zip container embeds timestamps; the PAYLOAD is what must match)."""
    with np.load(path) as data:
        return {k: data[k].tobytes() for k in sorted(data.files)}


def _succ_knobs(engine, on):
    """The successor-path knobs each engine accepts (ISSUE 2):
    ``succ_ladder`` everywhere (the fused engines accept and ignore it),
    ``exchange_novel_only`` on the sharded pair."""
    kw = {"succ_ladder": on}
    if engine.startswith("sharded"):
        kw["exchange_novel_only"] = on
    return kw


@pytest.mark.parametrize("engine", [
    "fused",
    # round-15 tier-1 budget: one fast representative.
    pytest.param("classic", marks=pytest.mark.slow),
    pytest.param("sharded-fused", marks=pytest.mark.slow),
    pytest.param("sharded-classic", marks=pytest.mark.slow)])
def test_succ_path_opts_bit_identical_2pc(engine, tmp_path):
    """ISSUE 2 acceptance: intra-wave local dedup + successor ladder ON
    vs OFF — counts, discoveries, parent maps, and checkpoint payload
    bytes bit-identical on all four engines (the sharded pair runs on
    the 8-device virtual mesh, covering the novelty-routed exchange's
    discovery parity)."""
    model = TwoPhaseSys(4)
    runs = {}
    for on in (True, False):
        path = str(tmp_path / f"{engine}-{on}.npz")
        c = _spawn(model, engine, 48, checkpoint_path=path,
                   **_succ_knobs(engine, on)).join()
        runs[on] = (c.unique_state_count(), c.state_count(),
                    set(c.discoveries()), dict(c._parent_map()),
                    _ckpt_payload(path))
    assert runs[True][:4] == runs[False][:4], engine
    assert runs[True][4] == runs[False][4], \
        f"{engine}: checkpoint payload bytes differ with succ opts on"


@pytest.mark.slow  # the 2pc matrix above is the fast-set gate; this
# adds the paxos workload for all four engines (tier-1 budget headroom)
@pytest.mark.parametrize("engine", ["fused", "classic",
                                    "sharded-fused", "sharded-classic"])
def test_succ_path_opts_bit_identical_paxos(engine):
    from paxos import PaxosModelCfg

    model = PaxosModelCfg(1, 3).into_model()
    results = []
    for on in (True, False):
        c = _spawn(model, engine, 128, **_succ_knobs(engine, on)).join()
        results.append((c.unique_state_count(), c.state_count(),
                        frozenset(c.discoveries()),
                        dict(c._parent_map())))
    assert results[0] == results[1], engine


def test_scheduler_stats_report_succ_telemetry():
    """bench.py / device_session forward scheduler_stats verbatim, so
    the successor-path keys must be present and self-consistent."""
    c = TwoPhaseSys(4).checker().spawn_tpu_bfs(
        batch_size=64, fused=False).join()
    stats = c.scheduler_stats()
    sl = stats["succ_ladder"]
    assert sl["enabled"] is True
    assert sum(sl["out_rows_dispatches"].values()) == stats["dispatches"]
    ld = stats["local_dedup"]
    assert ld["distinct_candidates"] <= ld["successors"]
    assert 0.0 <= ld["collapse_ratio"] <= 1.0


@pytest.mark.slow  # ~16s; cross-B checkpoint BYTE parity — the
# fast set keeps cross-B count/discovery parity (classic+fused)
def test_checkpoints_identical_across_buckets(tmp_path):
    """End-of-run checkpoints carry the same visited set and the same
    parent map whatever the batch bucket, and a checkpoint written at
    one bucket resumes at another."""
    model = TwoPhaseSys(4)
    snaps = {}
    for B in (32, 256):
        path = str(tmp_path / f"b{B}.npz")
        model.checker().spawn_tpu_bfs(
            batch_size=B, checkpoint_path=path).join()
        with np.load(path) as data:
            snaps[B] = {
                "visited": frozenset(data["visited"].tolist()),
                "parents": dict(zip(data["parent_child"].tolist(),
                                    data["parent_parent"].tolist())),
            }
    assert snaps[32]["visited"] == snaps[256]["visited"]
    assert snaps[32]["parents"] == snaps[256]["parents"]

    # Cross-bucket resume: a mid-run snapshot from B=32 finishes under
    # B=256 with the full-space counts.
    full = model.checker().spawn_bfs().join()
    ckpt = str(tmp_path / "mid.npz")
    model.checker().target_state_count(400).spawn_tpu_bfs(
        batch_size=32, checkpoint_path=ckpt).join()
    resumed = model.checker().spawn_tpu_bfs(
        batch_size=256, resume_from=ckpt).join()
    assert resumed.unique_state_count() == full.unique_state_count()
    assert set(resumed.discoveries()) == set(full.discoveries())


def test_pipelined_dispatches_keep_parity():
    """Depth-3 pipelining with single-wave dispatches (maximum overlap
    pressure): counts identical, and the telemetry proves dispatches
    were actually in flight together."""
    model = TwoPhaseSys(4)
    ref = model.checker().spawn_bfs().join()
    c = model.checker().spawn_tpu_bfs(
        batch_size=64, waves_per_dispatch=1, inflight_dispatches=3,
        fused=True).join()
    assert c.unique_state_count() == ref.unique_state_count()
    assert set(c.discoveries()) == set(ref.discoveries())
    assert c.scheduler_stats()["max_inflight"] >= 2


def test_growth_releases_pre_growth_buffers():
    """The donation regression gate: grow/rehash consume their input —
    the pre-growth arena/table buffer is released, not retained."""
    import jax.numpy as jnp

    from stateright_tpu.tpu.hashing import SENTINEL

    c = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        batch_size=32, fused=True).join()
    rehash = c._rehash_fn(1 << 12, 1 << 13)
    old_table = jnp.full((1 << 12,), jnp.uint64(SENTINEL))
    new_table = rehash(old_table)
    assert old_table.is_deleted(), "rehash retained the old table"
    assert new_table.shape == (1 << 13,)

    grow = c._grow_fn(1 << 10, 1 << 11, jnp.uint32, c._W)
    old_arena = jnp.zeros((1 << 10, c._W), jnp.uint32)
    new_arena = grow(old_arena)
    assert old_arena.is_deleted(), "grow retained the old arena"
    assert new_arena.shape == (1 << 11, c._W)


def test_growth_releases_pre_growth_buffers_sharded():
    import jax.numpy as jnp

    from stateright_tpu.tpu.hashing import SENTINEL

    c = TwoPhaseSys(3).checker().spawn_tpu_bfs(
        batch_size=16, sharded=True).join()
    n = c._n
    rehash = c._rehash_fn(1 << 10, 1 << 11)
    old_table = jnp.full((n << 10,), jnp.uint64(SENTINEL))
    new_table = rehash(old_table)
    assert old_table.is_deleted()
    assert new_table.shape == (n << 11,)


def test_steady_rate_excludes_compile_time():
    """bench._steady_rate subtracts AOT compile spans and drops
    lazily-flagged intervals, so a mid-run bucket compile cannot be
    charged to throughput."""
    import bench

    class Fake:
        wave_log = [(0.0, 0)]
        # 10 s wall, of which 6 s was one AOT compile; 4 s of real work
        # produced 400 states.
        dispatch_log = [
            {"t": 7.0, "states": 100, "bucket": 64, "compiled": False,
             "waves": 1, "inflight": 1},
            {"t": 10.0, "states": 400, "bucket": 128, "compiled": False,
             "waves": 1, "inflight": 1},
        ]
        compile_log = [(6.5, 6.0)]

    assert abs(bench._steady_rate(Fake()) - 100.0) < 1e-6

    class Lazy(Fake):
        compile_log = []
        dispatch_log = [
            {"t": 7.0, "states": 100, "bucket": 64, "compiled": True,
             "waves": 1, "inflight": 1},
            {"t": 10.0, "states": 400, "bucket": 128, "compiled": False,
             "waves": 1, "inflight": 1},
        ]

    assert abs(bench._steady_rate(Lazy()) - 100.0) < 1e-6


def test_parity_gate_uses_device_counts(monkeypatch):
    """When the device child streamed back its own parity counts, the
    gate compares the HOST reference against those (the backend that
    produced the headline), without a local device rerun."""
    import bench

    class Host:
        def unique_state_count(self):
            return 8832

        def discoveries(self):
            return {"atomicity": None}

    monkeypatch.setitem(bench._PARITY, "status", "pending")
    monkeypatch.setattr(bench, "_host_bfs",
                        lambda model, cap=None: (Host(), 100.0, 1.0))

    def boom(*a, **k):
        raise AssertionError("local device parity rerun not expected")

    monkeypatch.setattr(bench, "_tpu_bfs", boom)
    monkeypatch.setenv("BENCH_PARITY_RMS", "5")
    bench.RESULT["device_parity"] = {
        "platform": "tpu", "rms": 5, "unique": 8832,
        "discoveries": ["atomicity"], "rate": 123.0, "finished": True}
    try:
        bench._stage_parity_gate("tpu")
        assert bench._PARITY["status"] == "ok"
        assert bench.RESULT["parity_backend"] == "tpu"
        assert "tpu backend" in bench.RESULT["parity"]
        # Mismatched counts must fail the gate.
        bench._PARITY["status"] = "pending"
        bench.RESULT["device_parity"]["unique"] = 8831
        with pytest.raises(AssertionError, match="unique-state mismatch"):
            bench._stage_parity_gate("tpu")
    finally:
        bench.RESULT.pop("device_parity", None)
        bench.RESULT.pop("parity_backend", None)
        bench.RESULT.pop("parity", None)
        bench.RESULT.pop("parity_host_states_per_sec", None)
        bench.RESULT.pop("parity_tpu_states_per_sec", None)
        bench._PARITY["status"] = "pending"

"""Eventually-property semantics, including the documented false negatives
(counterpart of checker.rs:349-413)."""

from stateright_tpu import Property
from stateright_tpu.test_util import DGraph


def eventually_odd() -> Property:
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_can_validate():
    (DGraph.with_property(eventually_odd())
     .with_path([1])          # satisfied at terminal init
     .with_path([2, 3])       # satisfied at nonterminal init
     .with_path([2, 6, 7])    # satisfied at terminal next
     .with_path([4, 9, 10])   # satisfied at nonterminal next
     .check().assert_properties())
    # Repeat with distinct state spaces (defense in depth: stateful
    # checking skips visited states).
    for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
        DGraph.with_property(eventually_odd()).with_path(
            path).check().assert_properties()


def test_can_discover_counterexample():
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1]).with_path([0, 2])
            .check().discovery("odd").into_states()) == [0, 2]
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1]).with_path([2, 4])
            .check().discovery("odd").into_states()) == [2, 4]
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1, 4, 6]).with_path([2, 4, 8])
            .check().discovery("odd").into_states()) == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    """Pins the reference's documented revisit/cycle false negative
    (checker.rs:400-413) — preserved deliberately for behavioral parity."""
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4, 2])  # cycle
            .check().discovery("odd")) is None
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4])
            .with_path([1, 4, 6])     # revisiting 4
            .check().discovery("odd")) is None

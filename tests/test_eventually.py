"""Eventually-property semantics, including the documented false negatives
(counterpart of checker.rs:349-413)."""

import pytest

from stateright_tpu import Property
from stateright_tpu.test_util import DGraph


def eventually_odd() -> Property:
    return Property.eventually("odd", lambda _, s: s % 2 == 1)


def test_can_validate():
    (DGraph.with_property(eventually_odd())
     .with_path([1])          # satisfied at terminal init
     .with_path([2, 3])       # satisfied at nonterminal init
     .with_path([2, 6, 7])    # satisfied at terminal next
     .with_path([4, 9, 10])   # satisfied at nonterminal next
     .check().assert_properties())
    # Repeat with distinct state spaces (defense in depth: stateful
    # checking skips visited states).
    for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
        DGraph.with_property(eventually_odd()).with_path(
            path).check().assert_properties()


def test_can_discover_counterexample():
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1]).with_path([0, 2])
            .check().discovery("odd").into_states()) == [0, 2]
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1]).with_path([2, 4])
            .check().discovery("odd").into_states()) == [2, 4]
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 1, 4, 6]).with_path([2, 4, 8])
            .check().discovery("odd").into_states()) == [2, 4, 6]


def test_fixme_can_miss_counterexample_when_revisiting_a_state():
    """Pins the reference's documented revisit/cycle false negative
    (checker.rs:400-413) — preserved deliberately for behavioral parity."""
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4, 2])  # cycle
            .check().discovery("odd")) is None
    assert (DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4])
            .with_path([1, 4, 6])     # revisiting 4
            .check().discovery("odd")) is None


# -- The same semantics on the device engines (TpuBfsChecker ebits ride as
# a per-row uint32 bitmask, sharded engine clears bits pre-all-to-all) ----

def _dev(graph):
    return graph.with_device_predicate(
        "odd", lambda v: (v[0] % 2 == 1))


def _engines(graph):
    model = _dev(graph)
    # All four device engines: fused + classic, single-device + sharded.
    yield model.checker().spawn_tpu_bfs(batch_size=8).join()
    yield model.checker().spawn_tpu_bfs(batch_size=8, fused=False).join()
    yield model.checker().spawn_tpu_bfs(sharded=True, batch_size=4).join()
    yield model.checker().spawn_tpu_bfs(sharded=True, batch_size=4,
                                        fused=False).join()


@pytest.mark.slow  # ~23s full device liveness validation; the
# counterexample/discovery device tests below stay the fast gate
def test_device_can_validate():
    graph = (DGraph.with_property(eventually_odd())
             .with_path([1]).with_path([2, 3])
             .with_path([2, 6, 7]).with_path([4, 9, 10]))
    for checker in _engines(graph):
        checker.assert_properties()
    for path in ([1], [2, 3], [2, 6, 7], [4, 9, 10]):
        for checker in _engines(
                DGraph.with_property(eventually_odd()).with_path(path)):
            checker.assert_properties()


def test_device_can_discover_counterexample():
    cases = [
        ([[0, 1], [0, 2]], [0, 2]),
        ([[0, 1], [2, 4]], [2, 4]),
        ([[0, 1, 4, 6], [2, 4, 8]], [2, 4, 6]),
    ]
    for paths, expected in cases:
        graph = DGraph.with_property(eventually_odd())
        for p in paths:
            graph = graph.with_path(p)
        # Single-device BFS preserves host level order: exact path parity.
        tpu = _dev(graph).checker().spawn_tpu_bfs(batch_size=8).join()
        assert tpu.discovery("odd").into_states() == expected
        # Sharded wave composition is not a global level order
        # (checker.rs:115-118 analog): assert a valid counterexample — a
        # terminal path on which the condition never holds.
        sh = _dev(graph).checker().spawn_tpu_bfs(
            sharded=True, batch_size=4).join()
        states = sh.discovery("odd").into_states()
        assert all(s % 2 == 0 for s in states)
        assert states[-1] not in graph._edges  # terminal


def test_device_fixme_can_miss_counterexample_when_revisiting_a_state():
    for graph in (
            DGraph.with_property(eventually_odd()).with_path([0, 2, 4, 2]),
            DGraph.with_property(eventually_odd())
            .with_path([0, 2, 4]).with_path([1, 4, 6])):
        for checker in _engines(graph):
            assert checker.discovery("odd") is None

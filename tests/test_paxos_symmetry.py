"""Client-exchangeability symmetry for the paxos workload (driver
config 5: "paxos check 4 + symmetry reduction + liveness").

The reference's paxos example has no symmetry arm, so there is no
reference pin; the orbit counts here are pinned by cross-engine
agreement (Python DFS / device BFS / native C++ DFS share the partition
by construction — same encoding, same rewrite maps) plus the structural
invariants below. Derivation (register_workload.py sym section): client
destinations are index-derived mod S (`register.rs:169-196`), so the
group is the product of symmetric groups over client residue classes —
trivial below 4 clients at 3 servers, exactly {id, swap(client 0,
client 3)} at 4.

Pinned at 4 clients (MEASUREMENTS.md round 5):

- full space 2,372,188 unique states (round 4, three-way agreement)
- orbits 1,194,428 => sigma-fixed states 2*1,194,428 - 2,372,188
  = 16,668 (orbit counting: fixed = 2*orbits - total for a 2-group)
"""

import itertools
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

from paxos import PaxosModelCfg

C4_ORBITS = 1_194_428
C4_TOTAL = 2_372_188  # pinned round 4 (MEASUREMENTS.md three-way gate)


def _model(c, liveness=False):
    return PaxosModelCfg(c, 3, liveness=liveness).into_model()


def _reachable_sample(model, n_states=1500, stride=7):
    from collections import deque

    seen, q = {}, deque()
    for s in model.init_states():
        seen[s] = None
        q.append(s)
    while q and len(seen) < n_states:
        s = q.popleft()
        for _, s2 in model.next_steps(s):
            if s2 is not None and s2 not in seen:
                seen[s2] = None
                q.append(s2)
    return list(itertools.islice(seen, 0, n_states, stride))


def test_group_is_trivial_below_4_clients():
    for c in (1, 2, 3):
        dm = _model(c).device_model()
        assert dm.client_permutations() == []
    dm4 = _model(4).device_model()
    assert dm4.client_permutations() == [(3, 1, 2, 0)]


def test_rewrite_involution_codec_and_commutation():
    """The transposition rewrite must be an involution, land inside the
    codec's range (decode->encode round-trips), and commute with the
    host model's successor function (the automorphism property that
    makes the reduction sound)."""
    model = _model(4)
    dm = model.device_model()
    (t,) = dm._sym_tables()
    states = _reachable_sample(model)
    assert len(states) > 100
    for s in states:
        vec = np.asarray(dm.encode(s), np.uint32)
        r = np.asarray(dm._sym_rewrite(vec, t, np), np.uint32)
        rr = np.asarray(dm._sym_rewrite(r, t, np), np.uint32)
        assert np.array_equal(rr, vec), "rewrite is not an involution"
        assert np.array_equal(
            np.asarray(dm.encode(dm.decode(r)), np.uint32), r), \
            "rewrite left the codec range"
    for s in states[:20]:
        vec = np.asarray(dm.encode(s), np.uint32)
        r = np.asarray(dm._sym_rewrite(vec, t, np), np.uint32)
        succ_orig = sorted(
            np.asarray(dm._sym_rewrite(
                np.asarray(dm.encode(x), np.uint32), t, np),
                np.uint32).tobytes()
            for _, x in model.next_steps(s) if x is not None)
        succ_rewr = sorted(
            np.asarray(dm.encode(x), np.uint32).tobytes()
            for _, x in model.next_steps(dm.decode(r)) if x is not None)
        assert succ_orig == succ_rewr, \
            "rewrite does not commute with step (not an automorphism)"


def test_host_and_device_representative_agree():
    import jax.numpy as jnp

    model = _model(4)
    dm = model.device_model()
    for s in _reachable_sample(model, n_states=400, stride=11):
        vec_h = np.asarray(dm.encode(dm.host_representative(s)), np.uint32)
        vec_d = np.asarray(
            dm.representative(jnp.asarray(dm.encode(s))), np.uint32)
        assert np.array_equal(vec_h, vec_d)


def test_trivial_group_counts_match_plain_check_native():
    """At 2 clients the group is trivial: check-sym == check exactly."""
    model = _model(2)
    checker = (model.checker().symmetry()
               .spawn_native_dfs(model.device_model()).join())
    assert checker.unique_state_count() == 16_668


def test_c4_orbits_native():
    """The flagship gate: full 4-client space under symmetry on the
    native C++ DFS (seconds)."""
    model = _model(4)
    checker = (model.checker().symmetry()
               .spawn_native_dfs(model.device_model()).join())
    assert checker.unique_state_count() == C4_ORBITS
    assert set(checker.discoveries()) == {"value chosen"}


def test_c4_orbits_native_liveness():
    """Driver config 5 exactly: 4 clients + symmetry + the eventually
    property. The liveness property holds on the full enumeration
    (single-shot clients on a perfect network cannot wedge), so the only
    discovery stays "value chosen"."""
    model = _model(4, liveness=True)
    checker = (model.checker().symmetry()
               .spawn_native_dfs(model.device_model()).join())
    assert checker.unique_state_count() == C4_ORBITS
    assert set(checker.discoveries()) == {"value chosen"}


def test_orbit_equation():
    """For the 2-element group, |orbits| = (|states| + |fixed|) / 2 with
    |fixed| >= 0 and consistent with the pinned totals."""
    fixed = 2 * C4_ORBITS - C4_TOTAL
    assert 0 <= fixed <= C4_TOTAL
    assert fixed == 16_668


def test_single_copy_symmetry_47_orbits_all_engines():
    """At 1 server every client shares residue class 0: the full
    symmetric group applies. Pin: 47 orbits of the 93-state space at 2
    clients (one sigma-fixed state), agreed by the Python DFS (host
    representative), fused device BFS, and native DFS."""
    from single_copy_register import SingleCopyModelCfg

    model = SingleCopyModelCfg(2, 1).into_model()
    dm = model.device_model()
    py = (model.checker().symmetry_fn(dm.host_representative)
          .spawn_dfs().join())
    dev = model.checker().symmetry().spawn_tpu_bfs().join()
    nat = (model.checker().symmetry()
           .spawn_native_dfs(model.device_model()).join())
    assert (py.unique_state_count() == dev.unique_state_count()
            == nat.unique_state_count() == 47)


def test_single_copy_commutation():
    """The automorphism property for the single-copy rewrite."""
    from single_copy_register import SingleCopyModelCfg

    model = SingleCopyModelCfg(2, 1).into_model()
    dm = model.device_model()
    (t,) = dm._sym_tables()
    for s in _reachable_sample(model, n_states=93, stride=1):
        vec = np.asarray(dm.encode(s), np.uint32)
        r = np.asarray(dm._sym_rewrite(vec, t, np), np.uint32)
        assert np.array_equal(
            np.asarray(dm._sym_rewrite(r, t, np), np.uint32), vec)
        succ_orig = sorted(
            np.asarray(dm._sym_rewrite(
                np.asarray(dm.encode(x), np.uint32), t, np),
                np.uint32).tobytes()
            for _, x in model.next_steps(s) if x is not None)
        succ_rewr = sorted(
            np.asarray(dm.encode(x), np.uint32).tobytes()
            for _, x in model.next_steps(dm.decode(r)) if x is not None)
        assert succ_orig == succ_rewr


def test_abd_symmetry_trivial_and_ambiguity_guard():
    """Every device-encodable ABD config has a trivial client group
    (nontrivial ones collide on request-id products and are rejected);
    check-sym == check at 2+2, and 3 clients / 2 servers degrades to
    the host engine with the ambiguity warning."""
    from linearizable_register import AbdModelCfg

    model = AbdModelCfg(2, 2).into_model()
    assert model.device_model().client_permutations() == []
    nat = (model.checker().symmetry()
           .spawn_native_dfs(model.device_model()).join())
    assert nat.unique_state_count() == 544

    bad = AbdModelCfg(3, 2).into_model()
    with pytest.warns(RuntimeWarning, match="request ids collide"):
        checker = bad.checker().target_state_count(200).spawn_tpu_bfs()
    checker.join()
    assert type(checker).__name__ == "BfsChecker"  # host fallback


@pytest.mark.slow
def test_c2_symmetry_device_parity():
    """Trivial-group plumbing through the fused device engine."""
    model = _model(2)
    checker = model.checker().symmetry().spawn_tpu_bfs().join()
    assert checker.unique_state_count() == 16_668


@pytest.mark.slow
def test_c2_symmetry_python_dfs():
    """Trivial-group plumbing through the Python DFS via the shared
    host representative."""
    model = _model(2)
    dm = model.device_model()
    checker = (model.checker().symmetry_fn(dm.host_representative)
               .spawn_dfs().join())
    assert checker.unique_state_count() == 16_668


@pytest.mark.slow
def test_c4_orbits_device():
    """Cross-engine orbit gate: the fused device BFS (different
    traversal order, different canonical-member choice path) must count
    the same orbits as the native DFS."""
    model = _model(4)
    checker = model.checker().symmetry().spawn_tpu_bfs(
        batch_size=4096, table_capacity=1 << 22).join()
    assert checker.unique_state_count() == C4_ORBITS


def test_single_copy_sigma_fixed_counted_directly():
    """Closes the Burnside loop on the small nontrivial group without
    relying on the orbit equation: enumerate the RAW space with the
    fused engine, apply the non-identity client permutation to every
    arena row, and count exact fixed points. 93 raw states, 47 orbits
    => exactly 2*47 - 93 = 1 sigma-fixed state. (The C=4 paxos analog
    — 16,668 fixed of 2,372,188, measured the same way — is recorded in
    MEASUREMENTS.md; it runs minutes, this runs milliseconds.)"""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from single_copy_register import SingleCopyModelCfg

    model = SingleCopyModelCfg(2, 1).into_model()
    dm = model.device_model()
    tables = [t for t in dm._sym_tables()
              if tuple(t["sigma"]) != tuple(range(dm.C))]
    assert len(tables) == 1, "2 clients on 1 server: one swap"
    c = model.checker().spawn_tpu_bfs(fused=True).join()
    assert c.unique_state_count() == 93
    vecs = c._unpack_np(np.asarray(c._arena[0])[:c._arena_tail])
    sv = np.asarray(jax.jit(jax.vmap(
        lambda v: dm._sym_rewrite(v, tables[0], jnp)))(jnp.asarray(vecs)))
    fixed = int((sv == vecs).all(axis=1).sum())
    assert fixed == 1
    # Burnside, with every term measured independently:
    assert (93 + fixed) // 2 == 47


@pytest.mark.slow
def test_c4_raw_full_space_fused_and_direct_sigma_fixed():
    """The fused DEVICE engine's full raw C=4 enumeration (~70 s on the
    CPU backend post round-5 optimizations — it was a 6.5-minute
    measurement, not a gate, in round 4), plus the direct Burnside
    closure: apply the client swap to every arena row and count exact
    fixed points. Pins all three independently-measured terms of
    (|states| + |fixed|) / 2 = |orbits|."""
    import jax
    import jax.numpy as jnp

    model = PaxosModelCfg(4, 3).into_model()
    dm = model.device_model()
    c = model.checker().spawn_tpu_bfs(
        batch_size=1024, table_capacity=1 << 23,
        arena_capacity=1 << 22, fused=True).join()
    assert c.unique_state_count() == C4_TOTAL
    assert set(c.discoveries()) == {"value chosen"}
    vecs = c._unpack_np(np.asarray(c._arena[0])[:c._arena_tail])
    assert len(vecs) == C4_TOTAL
    sigma = [t for t in dm._sym_tables()
             if tuple(t["sigma"]) != tuple(range(dm.C))]
    assert len(sigma) == 1
    j_s = jax.jit(jax.vmap(lambda v: dm._sym_rewrite(v, sigma[0], jnp)))
    j_rep = jax.jit(jax.vmap(dm.representative))
    fixed = reps = 0
    for i in range(0, len(vecs), 1 << 16):
        chunk = vecs[i:i + (1 << 16)]
        fixed += int((np.asarray(j_s(jnp.asarray(chunk)))
                      == chunk).all(axis=1).sum())
        reps += int((np.asarray(j_rep(jnp.asarray(chunk)))
                     == chunk).all(axis=1).sum())
    assert fixed == 2 * C4_ORBITS - C4_TOTAL == 16_668
    assert reps == C4_ORBITS
